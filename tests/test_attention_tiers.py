"""PR 8: the kernel-selection and memory-policy layer.

- ``ops.tier_policy``: benchmarked attention tier selection — one
  micro-bench per shape, persistent verdict cache (restart-warm, corrupt
  file never deleted), ``PADDLE_TPU_ATTN_POLICY`` override.
- ``ops.attention``: ring attention gradients (hand-written recompute
  custom_vjp) vs the materialized core, 'auto' promotion onto a
  registered ring mesh, fallback accounting
  (``counter/attn/tier_fallbacks`` + one-shot warning).
- ``ops.remat_policy``: roofline-driven selective remat — the escalation
  ladder against a pinned HBM budget, ``remat='auto'`` end-to-end on
  jit.TrainStep / fleet.ParallelTrainStep with attribution gauges.
- ``tools/check_attribution.py``: the tier gate over bench records.
"""
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu.ops import attention as att
from paddle_tpu.ops import remat_policy, tier_policy
from paddle_tpu.profiler.telemetry import get_telemetry

_sm = att._shard_map_fn()
needs_shard_map = pytest.mark.skipif(
    _sm is None, reason="no shard_map API in this jax")


@pytest.fixture(autouse=True)
def _clean_tier_state():
    tier_policy.reset()
    att._fallback_warned.clear()
    yield
    tier_policy.reset()
    att.set_ring_context(None, None)
    att._fallback_warned.clear()


def _stub_times(monkeypatch, times, calls=None):
    """Replace the micro-bench clock with canned per-tier timings (None =
    infeasible); ``calls`` collects the tiers actually timed."""
    def fake(tier, q, k, v, causal):
        if calls is not None:
            calls.append(tier)
        return times.get(tier)

    monkeypatch.setattr(tier_policy, "_time_tier", fake)


def _qkv(rng, b=2, h=2, L=32, d=8, dtype=jnp.float32):
    mk = lambda: jnp.asarray(rng.randn(b, h, L, d), dtype)
    return mk(), mk(), mk()


# ---------------------------------------------------------------------------
# tier_policy: the verdict cache
# ---------------------------------------------------------------------------
class TestTierCache:
    def test_same_shape_benches_exactly_once(self, monkeypatch, tmp_path):
        monkeypatch.setenv("PADDLE_TPU_ATTN_POLICY", "bench")
        monkeypatch.setenv("PADDLE_TPU_ATTN_TIER_CACHE",
                           str(tmp_path / "tiers.json"))
        calls = []
        _stub_times(monkeypatch, {"xla": 1.0, "blockwise": 2.0}, calls)
        cands = ["xla", "blockwise"]
        assert tier_policy.select(4, 128, 32, jnp.float32, True, cands) == "xla"
        assert calls == ["xla", "blockwise"]  # every candidate timed once
        assert tier_policy.select(4, 128, 32, jnp.float32, True, cands) == "xla"
        assert len(calls) == 2  # pure cache hit: no re-measure
        # a DIFFERENT shape is a different key and benches again
        tier_policy.select(4, 256, 32, jnp.float32, True, cands)
        assert len(calls) == 4

    def test_cache_hit_across_process_restart(self, monkeypatch, tmp_path):
        cache = tmp_path / "tiers.json"
        monkeypatch.setenv("PADDLE_TPU_ATTN_POLICY", "bench")
        monkeypatch.setenv("PADDLE_TPU_ATTN_TIER_CACHE", str(cache))
        _stub_times(monkeypatch, {"xla": 1.0, "blockwise": 2.0})
        assert tier_policy.select(4, 128, 32, jnp.float32, True,
                                  ["xla", "blockwise"]) == "xla"
        data = json.loads(cache.read_text())
        (key, verdict), = data.items()
        assert verdict["tier"] == "xla" and "timings_ms" in verdict

        # "restart": the in-memory registry is gone, the file remains
        tier_policy.reset()

        def boom(*a):
            raise AssertionError("restart-warm select must not re-bench")

        monkeypatch.setattr(tier_policy, "_time_tier", boom)
        assert tier_policy.select(4, 128, 32, jnp.float32, True,
                                  ["xla", "blockwise"]) == "xla"

    def test_corrupt_cache_remeasures_and_deletes_nothing(
            self, monkeypatch, tmp_path):
        cache = tmp_path / "tiers.json"
        garbage = "{not json" * 3
        cache.write_text(garbage)
        monkeypatch.setenv("PADDLE_TPU_ATTN_POLICY", "bench")
        monkeypatch.setenv("PADDLE_TPU_ATTN_TIER_CACHE", str(cache))
        _stub_times(monkeypatch, {"xla": 1.0, "blockwise": 2.0})
        assert tier_policy.select(4, 128, 32, jnp.float32, True,
                                  ["xla", "blockwise"]) == "xla"
        # the unreadable file is evidence, not disposable state: its bytes
        # survive both the failed load AND later verdict persistence
        assert cache.read_text() == garbage
        tier_policy.select(4, 256, 32, jnp.float32, True,
                           ["xla", "blockwise"])
        assert cache.read_text() == garbage

    def test_env_override_wins_and_never_benches(self, monkeypatch, rng):
        monkeypatch.setenv("PADDLE_TPU_ATTN_POLICY", "blockwise")

        def boom(*a):
            raise AssertionError("forced policy must not micro-bench")

        monkeypatch.setattr(tier_policy, "_time_tier", boom)
        q, k, v = _qkv(rng)
        out = att.dot_product_attention(q, k, v, causal=True)
        ref = att.blockwise_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
        scal = get_telemetry().scalars()
        assert scal["gauge/attn/tier.L32.d8.c"] == \
            tier_policy.TIER_IDS["blockwise"]

    def test_unknown_policy_falls_back_to_heuristic(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_ATTN_POLICY", "warp-drive")
        assert tier_policy.policy_mode() == "heuristic"

    def test_restricted_candidates_never_clobber_disk_verdict(
            self, monkeypatch, tmp_path):
        """An env-restricted candidate set (e.g. PADDLE_TPU_ATTN_NO_MOSAIC
        dropping the fast tier) re-measures for its own process but must
        not overwrite the full-set verdict on disk."""
        cache = tmp_path / "tiers.json"
        monkeypatch.setenv("PADDLE_TPU_ATTN_POLICY", "bench")
        monkeypatch.setenv("PADDLE_TPU_ATTN_TIER_CACHE", str(cache))
        _stub_times(monkeypatch,
                    {"flash_tpu": 1.0, "xla": 2.0, "blockwise": 3.0})
        assert tier_policy.select(
            4, 128, 32, jnp.float32, True,
            ["flash_tpu", "xla", "blockwise"]) == "flash_tpu"
        # "restart" into a process whose env knocked flash_tpu out
        tier_policy.reset()
        assert tier_policy.select(4, 128, 32, jnp.float32, True,
                                  ["xla", "blockwise"]) == "xla"
        # the restricted winner serves THIS process (cache hit, no
        # re-bench) but the disk keeps the full-set verdict...
        (_, verdict), = json.loads(cache.read_text()).items()
        assert verdict["tier"] == "flash_tpu"
        # ...even after a later persist of a different key
        tier_policy.select(4, 256, 32, jnp.float32, True,
                           ["xla", "blockwise"])
        data = json.loads(cache.read_text())
        assert {v["tier"] for v in data.values()} == {"flash_tpu", "xla"}
        # unrestricted "restart": the fast verdict is intact and used
        tier_policy.reset()

        def boom(*a):
            raise AssertionError("full-set select must not re-bench")

        monkeypatch.setattr(tier_policy, "_time_tier", boom)
        assert tier_policy.select(
            4, 128, 32, jnp.float32, True,
            ["flash_tpu", "xla", "blockwise"]) == "flash_tpu"

    def test_bench_mode_dispatch_one_bench_across_traces(
            self, monkeypatch, rng):
        monkeypatch.setenv("PADDLE_TPU_ATTN_POLICY", "bench")
        monkeypatch.delenv("PADDLE_TPU_ATTN_TIER_CACHE", raising=False)
        monkeypatch.delenv("PADDLE_TPU_COMPILE_CACHE_DIR", raising=False)
        _stub_times(monkeypatch, {"xla": 1.0, "blockwise": 2.0})
        tel = get_telemetry()
        before = tel.counter_value("attn/tier_bench")
        q, k, v = _qkv(rng, L=64)
        f1 = jax.jit(lambda a, b, c: att.dot_product_attention(
            a, b, c, causal=True))
        f2 = jax.jit(lambda a, b, c: att.dot_product_attention(
            a, b, c, causal=True) * 2.0)
        f1(q, k, v)
        f2(q, k, v)  # second trace, same shape: verdict reused
        assert tel.counter_value("attn/tier_bench") - before == 1
        assert tel.scalars()["gauge/attn/tier.L64.d8.c"] == \
            tier_policy.TIER_IDS["xla"]


# ---------------------------------------------------------------------------
# fallback accounting: a silent reroute is counted and warned once
# ---------------------------------------------------------------------------
class TestFallbackAccounting:
    def test_heuristic_flash_misfit_counts_and_warns_once(self, monkeypatch):
        monkeypatch.setattr(att.jax, "default_backend", lambda: "tpu")
        monkeypatch.setenv("PADDLE_TPU_ATTN_POLICY", "heuristic")
        tel = get_telemetry()
        before = tel.counter_value("attn/tier_fallbacks")
        q = jnp.zeros((1, 9000, 4, 64), jnp.float32)  # 9000 % 256 != 0
        assert att._select_impl(q, q, None, True, True, True) == "blockwise"
        assert tel.counter_value("attn/tier_fallbacks") - before == 1
        assert len(att._fallback_warned) == 1
        # every occurrence COUNTS; the warning stays one-shot per shape
        assert att._select_impl(q, q, None, True, True, True) == "blockwise"
        assert tel.counter_value("attn/tier_fallbacks") - before == 2
        assert len(att._fallback_warned) == 1

    def test_flash_attention_shape_fallback_on_tpu_counts(self, monkeypatch):
        monkeypatch.setattr(att.jax, "default_backend", lambda: "tpu")
        tel = get_telemetry()
        before = tel.counter_value("attn/tier_fallbacks")
        q = jnp.zeros((1, 2, 100, 8), jnp.float32)  # 100 % 256 != 0
        out = att._flash_attention_impl(q, q, q, True, 256, 256)
        assert out.shape == q.shape
        assert tel.counter_value("attn/tier_fallbacks") - before == 1

    def test_off_tpu_blockwise_is_documented_not_a_fallback(self, rng):
        tel = get_telemetry()
        before = tel.counter_value("attn/tier_fallbacks")
        q, k, v = _qkv(rng, L=100)  # doesn't tile either
        att._flash_attention_impl(q, k, v, True, 256, 256)
        assert tel.counter_value("attn/tier_fallbacks") == before

    def test_ring_policy_without_context_counts_fallback(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_ATTN_POLICY", "ring")
        tel = get_telemetry()
        before = tel.counter_value("attn/tier_fallbacks")
        q = jnp.zeros((1, 2, 32, 8), jnp.float32)
        att._select_impl(q, q, None, True, True, False)
        assert tel.counter_value("attn/tier_fallbacks") - before == 1


# ---------------------------------------------------------------------------
# ring attention: gradients + auto promotion
# ---------------------------------------------------------------------------
@needs_shard_map
class TestRingAttentionGrad:
    def _ring(self, causal):
        mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
        spec = P(None, None, "sp", None)
        return _sm(lambda q, k, v: att.ring_attention(q, k, v, "sp",
                                                      causal, 512),
                   mesh, (spec, spec, spec), spec)

    @pytest.mark.parametrize("causal", [True, False])
    def test_fwd_and_grads_match_attention_core(self, rng, causal):
        q, k, v = _qkv(rng, b=2, h=2, L=64, d=8)
        cot = jnp.asarray(rng.randn(*q.shape), jnp.float32)
        out_r, vjp_r = jax.vjp(self._ring(causal), q, k, v)
        mask = jnp.tril(jnp.ones((64, 64), bool)) if causal else None
        out_c, vjp_c = jax.vjp(
            lambda a, b, c: att._attention_core(a, b, c, mask), q, k, v)
        np.testing.assert_allclose(np.asarray(out_r), np.asarray(out_c),
                                   rtol=2e-5, atol=2e-5)
        for gr, gc, name in zip(vjp_r(cot), vjp_c(cot), "qkv"):
            np.testing.assert_allclose(
                np.asarray(gr), np.asarray(gc), rtol=2e-5, atol=2e-5,
                err_msg=f"d{name} mismatch (recompute backward)")

    def test_grad_under_jit(self, rng):
        q, k, v = _qkv(rng, b=1, h=2, L=32, d=8)
        loss = lambda a, b, c: (self._ring(True)(a, b, c) ** 2).sum()
        g = jax.jit(jax.grad(loss))(q, k, v)
        ref = jax.grad(lambda a, b, c: (att.xla_attention(
            a, b, c, causal=True) ** 2).sum())(q, k, v)
        np.testing.assert_allclose(np.asarray(g), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)


@needs_shard_map
class TestRingAutoPromotion:
    def test_auto_promotes_on_registered_mesh(self, monkeypatch, rng):
        monkeypatch.setenv("PADDLE_TPU_ATTN_RING_MIN_SEQ", "64")
        mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
        att.set_ring_context(mesh, "sp")
        q, k, v = _qkv(rng, b=2, h=2, L=128, d=8)
        out = jax.jit(lambda a, b, c: att.dot_product_attention(
            a, b, c, causal=True))(q, k, v)
        scal = get_telemetry().scalars()
        assert scal["gauge/attn/tier.L128.d8.c"] == \
            tier_policy.TIER_IDS["ring"]
        ref = att.xla_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_below_threshold_keeps_single_device_tier(self, monkeypatch, rng):
        monkeypatch.setenv("PADDLE_TPU_ATTN_RING_MIN_SEQ", "8192")
        mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
        att.set_ring_context(mesh, "sp")
        assert not att._ring_auto_ok(128, True, None)

    def test_non_causal_and_biased_never_promote(self):
        mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
        att.set_ring_context(mesh, "sp")
        assert not att._ring_auto_ok(8192, False, None)
        assert not att._ring_auto_ok(8192, True, object())

    def test_indivisible_seq_never_promotes(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_ATTN_RING_MIN_SEQ", "64")
        mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
        att.set_ring_context(mesh, "sp")
        assert not att._ring_auto_ok(130, True, None)  # 130 % 4 != 0

    @pytest.mark.parametrize("forced", ["blockwise", "xla", "heuristic"])
    def test_explicit_policy_override_outranks_promotion(
            self, monkeypatch, rng, forced):
        """PADDLE_TPU_ATTN_POLICY must measure exactly what it names —
        the forced-blockwise bench ablation leg depends on ring NOT
        hijacking the dispatch."""
        monkeypatch.setenv("PADDLE_TPU_ATTN_RING_MIN_SEQ", "64")
        monkeypatch.setenv("PADDLE_TPU_ATTN_POLICY", forced)
        mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
        att.set_ring_context(mesh, "sp")
        assert not att._ring_auto_ok(128, True, None)
        q, k, v = _qkv(rng, L=128)
        att.dot_product_attention(q, k, v, causal=True)
        assert get_telemetry().scalars()["gauge/attn/tier.L128.d8.c"] != \
            tier_policy.TIER_IDS["ring"]

    def test_explicit_sp_axis_dispatch_publishes_ring_verdict(self, rng):
        mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
        q, k, v = _qkv(rng, L=64)
        spec = P(None, None, "sp", None)
        f = _sm(lambda a, b, c: att.dot_product_attention(
            a, b, c, causal=True, sp_axis="sp"),
            mesh, (spec, spec, spec), spec)
        out = jax.jit(f)(q, k, v)
        assert out.shape == q.shape
        # L in the gauge key is the LOCAL shard length (64 / 4 ring hops)
        assert get_telemetry().scalars()["gauge/attn/tier.L16.d8.c"] == \
            tier_policy.TIER_IDS["ring"]

    def test_plain_engine_clears_stale_ring_context(self):
        import paddle_tpu as paddle
        from paddle_tpu import nn
        from paddle_tpu.distributed.fleet.engine import ParallelTrainStep

        mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
        att.set_ring_context(mesh, "sp")
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 8))
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=net.parameters())
        ParallelTrainStep(net, loss_fn=nn.CrossEntropyLoss(), optimizer=opt,
                          mesh=Mesh(np.array(jax.devices()[:1]), ("dp",)))
        # the non-sp engine owns the trace-time global now: no trace of
        # it may promote onto the dead sp engine's mesh
        assert att._ring_ctx["axis"] is None

    def test_misspelled_sp_axis_raises(self):
        import paddle_tpu as paddle
        from paddle_tpu import nn
        from paddle_tpu.distributed.fleet.engine import ParallelTrainStep

        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 8))
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=net.parameters())
        with pytest.raises(ValueError, match="sp_axis"):
            ParallelTrainStep(net, loss_fn=nn.CrossEntropyLoss(),
                              optimizer=opt,
                              mesh=Mesh(np.array(jax.devices()[:1]), ("dp",)),
                              sp_axis="seq")


@needs_shard_map
class TestFleetSequenceParallel:
    def _build(self, sp):
        import paddle_tpu as paddle
        from paddle_tpu.distributed.fleet.engine import ParallelTrainStep
        from paddle_tpu.text.models.gpt import GPTConfig, GPTForCausalLM

        paddle.seed(0)
        cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=1,
                        num_heads=2, max_position_embeddings=64,
                        hidden_dropout=0.0, attention_dropout=0.0)
        model = GPTForCausalLM(cfg)
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=model.parameters())
        if sp:
            mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("dp", "sp"))
            return ParallelTrainStep(model, loss_fn=model.loss_fn,
                                     optimizer=opt, mesh=mesh, sp_axis="sp")
        mesh = Mesh(np.array(jax.devices()[:2]), ("dp",))
        return ParallelTrainStep(model, loss_fn=model.loss_fn,
                                 optimizer=opt, mesh=mesh)

    def test_sp_engine_matches_plain_dp(self, monkeypatch, rng):
        """Ring-sharded training (batches land pre-rotated over sp) takes
        the same loss trajectory as the plain dp engine."""
        monkeypatch.setenv("PADDLE_TPU_ATTN_RING_MIN_SEQ", "32")
        ids = rng.randint(0, 128, (2, 64)).astype(np.int32)
        labels = np.roll(ids, -1, axis=1).astype(np.int32)
        sp_engine = self._build(sp=True)
        ring_losses = [float(sp_engine((ids,), (labels,)).numpy())
                       for _ in range(3)]
        scal = get_telemetry().scalars()
        assert scal["gauge/attn/tier.L64.d16.c"] == \
            tier_policy.TIER_IDS["ring"]
        att.set_ring_context(None, None)
        dp_engine = self._build(sp=False)
        dp_losses = [float(dp_engine((ids,), (labels,)).numpy())
                     for _ in range(3)]
        np.testing.assert_allclose(ring_losses, dp_losses, rtol=2e-4,
                                   atol=2e-4)

    def test_batch_shardings_skip_indivisible_leaves(self):
        """Only leaves whose dim 1 divides the ring size take the
        (dp, sp) layout — broadcast-dim masks [b, 1, L, L], ragged class
        dims, and 1-D labels stay dp-only instead of crashing
        device_put (the ring's shard_map boundary reshards on entry, so
        dp-only landing is safe)."""
        eng = self._build(sp=True)  # ring size 4
        batch = ((np.zeros((8, 64), np.int32),         # seq leaf: (dp, sp)
                  np.zeros((8, 1, 64, 64), np.float32),  # broadcast dim 1
                  np.zeros((8, 3), np.float32)),         # 3 % 4 != 0
                 (np.zeros((8,), np.int32),))            # 1-D per-sample
        sh = eng._batch_shardings(batch)
        (s_seq, s_mask, s_ragged), (s_lab,) = sh
        assert s_seq.spec == eng._batch_sharding.spec
        dp_only = P(eng._batch_sharding.spec[0])
        assert s_mask.spec == dp_only
        assert s_ragged.spec == dp_only
        assert s_lab.spec == dp_only
        jax.device_put(batch, sh)  # must place without a divisibility error


# ---------------------------------------------------------------------------
# remat_policy: the roofline-driven escalation ladder
# ---------------------------------------------------------------------------
class TestRematPolicy:
    @pytest.fixture(autouse=True)
    def _fresh_cost_registry(self):
        from paddle_tpu.profiler import xla_cost

        xla_cost.reset()
        yield
        xla_cost.reset()

    def test_normalize_vocabulary(self):
        assert remat_policy.normalize(False) == "off"
        assert remat_policy.normalize(None) == "off"
        assert remat_policy.normalize(True) == "full"
        assert remat_policy.normalize("dots") == "dots"
        assert remat_policy.normalize("dots_no_batch") == "dots_no_batch"
        assert remat_policy.normalize("nothing") == "nothing"
        assert remat_policy.normalize("auto") == "auto"
        with pytest.raises(ValueError):
            remat_policy.normalize("everything")

    def test_apply_policy_off_is_identity(self):
        f = lambda x: x
        assert remat_policy.apply_policy(f, "off") is f
        assert remat_policy.apply_policy(f, False) is f
        assert remat_policy.apply_policy(f, "full") is not f

    def _fake_costs(self, table):
        def lower_cost(policy):
            c = table.get(policy)
            if c is None:
                return None
            peak, flops, by = c
            return {"peak_hbm_bytes": peak, "flops": flops,
                    "bytes_accessed": by}

        return lower_cost

    def test_fits_resolves_to_no_remat(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_DEVICE_HBM_BYTES", "1000")
        monkeypatch.setenv("PADDLE_TPU_REMAT_BUDGET_FRAC", "0.9")
        chosen = remat_policy.resolve("t.fits", self._fake_costs(
            {"off": (500, 1.0, 100.0)}))
        assert chosen == "off"
        scal = get_telemetry().scalars()
        assert scal["gauge/remat/t.fits"] == remat_policy.POLICY_IDS["off"]
        assert scal["gauge/remat/peak_hbm/t.fits"] == 500

    def test_memory_bound_jumps_to_nothing(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_DEVICE_HBM_BYTES", "1000")
        monkeypatch.setenv("PADDLE_TPU_REMAT_BUDGET_FRAC", "0.9")
        calls = []

        def lc(policy):
            calls.append(policy)
            # intensity 2000/2000 = 1 << CPU balance: memory-bound
            return {"off": {"peak_hbm_bytes": 2000, "flops": 2000.0,
                            "bytes_accessed": 2000.0},
                    "nothing": {"peak_hbm_bytes": 800, "flops": 2000.0,
                                "bytes_accessed": 2000.0}}.get(policy)

        assert remat_policy.resolve("t.mem", lc) == "nothing"
        assert "dots" not in calls  # memory-bound skips the dots rung
        scal = get_telemetry().scalars()
        assert scal["gauge/remat/t.mem"] == remat_policy.POLICY_IDS["nothing"]

    def test_compute_bound_tries_dots_first(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_DEVICE_HBM_BYTES", "1000")
        monkeypatch.setenv("PADDLE_TPU_REMAT_BUDGET_FRAC", "0.9")
        chosen = remat_policy.resolve("t.comp", self._fake_costs({
            # intensity 1e12/1 >> balance: compute-bound
            "off": (2000, 1e12, 1.0),
            "dots": (850, 1e12, 1.0),
            "nothing": (400, 1e12, 1.0),
        }))
        assert chosen == "dots"  # first rung that fits wins; no over-remat

    def test_nothing_fits_takes_smallest_measured(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_DEVICE_HBM_BYTES", "100")
        chosen = remat_policy.resolve("t.none", self._fake_costs({
            "off": (2000, 1.0, 100.0),
            "nothing": (1500, 1.0, 100.0),
        }))
        assert chosen == "nothing"
        scal = get_telemetry().scalars()
        assert scal["gauge/remat/peak_hbm/t.none"] == 1500

    def test_cost_analysis_off_resolves_off(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_COST_ANALYSIS", "0")

        def boom(policy):
            raise AssertionError("must not lower with cost analysis off")

        assert remat_policy.resolve("t.off", boom) == "off"

    def test_hbm_capacity_env_override(self, monkeypatch):
        from paddle_tpu.profiler.xla_cost import hbm_capacity_bytes

        monkeypatch.setenv("PADDLE_TPU_DEVICE_HBM_BYTES", "123456")
        assert hbm_capacity_bytes() == 123456
        monkeypatch.setenv("PADDLE_TPU_DEVICE_HBM_BYTES", "not-a-number")
        assert hbm_capacity_bytes() > 0  # invalid override ignored


class TestRematEndToEnd:
    def _mlp_step(self, remat="off", seed=7):
        import paddle_tpu as paddle
        from paddle_tpu import nn

        paddle.seed(seed)
        layers = []
        for _ in range(4):
            layers += [nn.Linear(64, 64), nn.ReLU()]
        layers += [nn.Linear(64, 10)]
        net = nn.Sequential(*layers)
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=net.parameters())
        return paddle.jit.TrainStep(net, loss_fn=nn.CrossEntropyLoss(),
                                    optimizer=opt, remat=remat)

    def test_train_step_auto_resolves_and_trains(self, monkeypatch, rng):
        x = rng.randn(32, 64).astype(np.float32)
        y = rng.randint(0, 10, 32).astype(np.int64)
        off_cost = self._mlp_step().lower_cost("off", (x,), (y,))
        assert off_cost is not None and off_cost["peak_hbm_bytes"] > 0
        # pin the budget below the no-remat peak: the ladder MUST engage
        monkeypatch.setenv("PADDLE_TPU_DEVICE_HBM_BYTES",
                           str(max(int(off_cost["peak_hbm_bytes"] * 0.6), 1)))
        monkeypatch.setenv("PADDLE_TPU_REMAT_BUDGET_FRAC", "1.0")
        step = self._mlp_step(remat="auto")
        losses = [float(step((x,), (y,)).numpy()) for _ in range(3)]
        assert all(np.isfinite(losses))
        assert losses[2] < losses[0]  # it still learns
        scal = get_telemetry().scalars()
        assert "gauge/remat/jit.train_step" in scal
        auto_peak = scal["gauge/remat/peak_hbm/jit.train_step"]
        assert 0 < auto_peak <= off_cost["peak_hbm_bytes"]

    def test_train_step_explicit_policies_match_off_losses(self, rng):
        # remat changes WHAT is saved, never the math: first-step losses
        # agree bitwise-ish across policies
        x = rng.randn(16, 64).astype(np.float32)
        y = rng.randint(0, 10, 16).astype(np.int64)
        base = float(self._mlp_step("off")((x,), (y,)).numpy())
        for policy in ("full", "dots", "nothing"):
            lp = float(self._mlp_step(policy)((x,), (y,)).numpy())
            assert abs(lp - base) < 1e-5, (policy, lp, base)

    def test_fleet_legacy_recompute_maps_and_lower_cost_probes(self, rng):
        import paddle_tpu as paddle
        from paddle_tpu import nn
        from paddle_tpu.distributed.fleet.engine import ParallelTrainStep

        paddle.seed(7)
        net = nn.Sequential(nn.Linear(16, 16), nn.ReLU(), nn.Linear(16, 4))
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=net.parameters())
        mesh = Mesh(np.array(jax.devices()[:1]), ("dp",))
        eng = ParallelTrainStep(net, loss_fn=nn.CrossEntropyLoss(),
                                optimizer=opt, mesh=mesh, recompute="dots")
        assert eng._remat == "dots"  # legacy vocabulary routed through
        x = rng.randn(8, 16).astype(np.float32)
        y = rng.randint(0, 4, 8).astype(np.int64)
        cost = eng.lower_cost("nothing", (x,), (y,))
        assert cost is not None and cost["peak_hbm_bytes"] > 0
        assert np.isfinite(float(eng((x,), (y,)).numpy()))

    def test_fleet_remat_auto_publishes_gauges(self, rng):
        import paddle_tpu as paddle
        from paddle_tpu import nn
        from paddle_tpu.distributed.fleet.engine import ParallelTrainStep

        paddle.seed(7)
        net = nn.Sequential(nn.Linear(16, 16), nn.ReLU(), nn.Linear(16, 4))
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=net.parameters())
        mesh = Mesh(np.array(jax.devices()[:1]), ("dp",))
        eng = ParallelTrainStep(net, loss_fn=nn.CrossEntropyLoss(),
                                optimizer=opt, mesh=mesh, remat="auto")
        x = rng.randn(8, 16).astype(np.float32)
        y = rng.randint(0, 4, 8).astype(np.int64)
        assert np.isfinite(float(eng((x,), (y,)).numpy()))
        assert np.isfinite(float(eng((x,), (y,)).numpy()))
        scal = get_telemetry().scalars()
        assert "gauge/remat/fleet.train_step" in scal
        assert scal["gauge/remat/peak_hbm/fleet.train_step"] > 0


# ---------------------------------------------------------------------------
# tools/check_attribution.py: the tier gate
# ---------------------------------------------------------------------------
def _bench_record(scalars):
    return json.dumps({"ts": 1.0, "step": 0, "tag": "bench/cfg",
                       "scalars": scalars}) + "\n"


class TestTierGate:
    BASE = {"gauge/compile/flops": 1e9, "gauge/compile/peak_hbm_bytes": 1e6,
            "gauge/mfu": 42.0}

    def test_attention_bearing_record_with_verdict_passes(self, tmp_path):
        import tools.check_attribution as gate

        p = tmp_path / "t.jsonl"
        p.write_text(_bench_record({
            **self.BASE, "counter/attn/calls": 12,
            "gauge/attn/tier.L8192.d64.c": 0,
            "counter/attn/tier_fallbacks": 0}))
        assert gate.main([str(p)]) == 0

    def test_non_attention_record_needs_no_tier(self, tmp_path):
        import tools.check_attribution as gate

        p = tmp_path / "t.jsonl"
        p.write_text(_bench_record(self.BASE))
        assert gate.main([str(p)]) == 0

    def test_missing_tier_verdict_fails(self, tmp_path):
        import tools.check_attribution as gate

        p = tmp_path / "t.jsonl"
        p.write_text(_bench_record({**self.BASE, "counter/attn/calls": 12}))
        assert gate.main([str(p)]) == 1

    def test_nonzero_fallbacks_fail(self, tmp_path):
        import tools.check_attribution as gate

        p = tmp_path / "t.jsonl"
        p.write_text(_bench_record({
            **self.BASE, "counter/attn/calls": 12,
            "gauge/attn/tier.L8192.d64.c": 3,
            "counter/attn/tier_fallbacks": 2}))
        assert gate.main([str(p)]) == 1

    def test_negative_tier_id_fails(self, tmp_path):
        import tools.check_attribution as gate

        p = tmp_path / "t.jsonl"
        p.write_text(_bench_record({
            **self.BASE, "counter/attn/calls": 1,
            "gauge/attn/tier.L64.d8.c": -1,
            "counter/attn/tier_fallbacks": 0}))
        assert gate.main([str(p)]) == 1
