"""R5 fixture: host syncs in hot paths (step-result materialization in
loops, block_until_ready per iteration, host work baked into a trace)."""
import jax
import numpy as np


def bad_loop(step, batches):
    for b in batches:
        loss = step(b)
        v = float(loss)                    # EXPECT: R5
        w = loss.numpy()                   # EXPECT: R5
        loss.block_until_ready()           # EXPECT: R5
        yield v, w


@jax.jit
def bad_traced(x):
    print("tracing", x)                    # EXPECT: R5
    s = np.sum(x)                          # EXPECT: R5
    return s


def good(step, batches):
    # deferred materialization: keep device arrays, sync once at the end
    losses = [step(b) for b in batches]
    return [float(v) for v in losses]


def good_warmup(x):
    # a single sync outside any loop is a legitimate warmup/timing fence
    return (x @ x).block_until_ready()
