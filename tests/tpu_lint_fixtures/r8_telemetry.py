"""R8 fixture: Telemetry calls under trace (silent per-step no-ops)."""
import jax

from paddle_tpu.profiler.telemetry import get_telemetry

tel = get_telemetry()


@jax.jit
def bad(x):
    tel.counter("engine/steps")            # EXPECT: R8
    tel.observe("step_ms", 1.0)            # EXPECT: R8
    get_telemetry().gauge("loss", x)       # EXPECT: R8
    return x * 2


def good(step, x):
    # record metrics OUTSIDE the jitted function, on its inputs/outputs
    out = step(x)
    tel.counter("engine/steps")
    tel.gauge("loss", out)   # deferred-coercion gauge: no sync either
    return out
