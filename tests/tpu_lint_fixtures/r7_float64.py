"""R7 fixture: float64 creation (TPU hardware computes f64 as f32, so
x64-on CPU runs silently diverge from TPU results)."""
import jax
import jax.numpy as jnp
import numpy as np


def bad():
    a = jnp.float64(3.0)                   # EXPECT: R7
    b = jnp.zeros(3, dtype="float64")      # EXPECT: R7
    c = np.ones(4).astype("double")        # EXPECT: R7
    return a, b, c


@jax.jit
def bad_traced(x):
    return x.astype(np.float64)            # EXPECT: R7


def good(vals):
    h = np.asarray(vals, np.float64)   # host-side numpy f64 is fine
    f32 = jnp.zeros(3, jnp.float32)
    if h.dtype == np.float64:          # dtype probing is not creation
        h = h.astype(np.float32)
    return h, f32
