"""R2 fixture: data-dependent Python control flow under trace."""
import jax
import jax.numpy as jnp


@jax.jit
def bad(x, y):
    if x > 0:                          # EXPECT: R2
        y = y + 1
    while y.sum() > 0:                 # EXPECT: R2
        y = y - 1
    assert x.mean() < 1e6              # EXPECT: R2
    z = x + y
    if (z * 2).max() > 0:              # EXPECT: R2
        z = -z
    return z


@jax.jit
def good(x, *rest):
    if x.shape[0] > 2:        # shape test: static under jit
        x = x * 2
    if x.dtype == jnp.float32:
        x = x.astype(jnp.bfloat16)
    if isinstance(x, tuple):  # type probing is static
        x = x[0]
    if x is None:             # identity test is Python-level
        return 0
    if rest:                  # *args emptiness is a static tuple test
        x = x + rest[0]
    tail = rest[1:]
    if tail:                  # slices of *args stay Python tuples
        x = x + tail[0]
    return x


def eager(x):
    if x > 0:  # eager define-by-run branching is legal
        return -x
    return x
