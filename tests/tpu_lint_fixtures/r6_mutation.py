"""R6 fixture: mutation of closed-over Python state under trace."""
import jax

LOG = []
COUNTER = 0


class Engine:
    def build(self, stats):
        @jax.jit
        def step(x):
            self.cache = x                 # EXPECT: R6
            LOG.append(x)                  # EXPECT: R6
            stats["last"] = x              # EXPECT: R6
            return x * 2

        return step


@jax.jit
def bad_global(x):
    global COUNTER
    COUNTER += 1                           # EXPECT: R6
    return x


@jax.jit
def good(x):
    acc = []
    acc.append(x)      # local container: rebuilt every trace, harmless
    d = {}
    d["k"] = x
    y = x * 2
    y += 1             # local augmented assign
    return acc, d, y


def eager_mutation(model, x):
    # outside jit: imperative mutation is the normal eager idiom
    model.cache = x
    LOG.append(x)
    return x
