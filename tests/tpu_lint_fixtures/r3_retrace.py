"""R3 fixture: retrace hazards in jit signatures (string params without
static markers, non-hashable defaults in static positions)."""
from functools import partial

import jax

from paddle_tpu.profiler.retrace import tracked_jit


@jax.jit
def bad_string_arg(x, mode="train"):   # EXPECT: R3
    return x if mode == "train" else -x


@partial(jax.jit, static_argnums=(1,))  # EXPECT: R3
def bad_static_default(x, opts=[]):
    return x


def step_fn(params, batch, reduction="mean"):
    return params, batch


jitted = tracked_jit(step_fn, name="step")   # EXPECT: R3


@partial(jax.jit, static_argnames=("mode",))
def good_static_string(x, mode="train"):
    return x if mode == "train" else -x


def other_step(params, batch, reduction="mean"):
    return params, batch


good_wrap = tracked_jit(other_step, static_argnames=("reduction",))


@jax.jit
def good_scalars(x, lr=0.1, steps=4):
    # Python int/float args trace as dynamic weak scalars: new VALUES do
    # not retrace, so they need no static marker
    return x * lr + steps
