"""R1 fixture: tracer concretization. Lines marked EXPECT must flag;
every other line must stay clean (negative cases)."""
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def bad(x, y):
    a = float(x)                       # EXPECT: R1
    b = int(x + y)                     # EXPECT: R1
    c = bool(x > 0)                    # EXPECT: R1
    d = np.asarray(x)                  # EXPECT: R1
    e = x.numpy()                      # EXPECT: R1
    f = y.item()                       # EXPECT: R1
    g = y.tolist()                     # EXPECT: R1
    return a, b, c, d, e, f, g


@partial(jax.jit, static_argnums=(1,))
def good_static(x, n):
    k = float(n)            # static arg: concrete at trace time
    m = int(x.shape[0])     # shapes are static under jit
    return x * k + m


@jax.jit
def good_lax(x):
    z = jax.lax.complex(x, x)   # jax.lax.complex is not builtins.complex
    cfg = float(jnp.pi)         # module constant, not a traced value
    return z, cfg


def eager(x):
    # not jit-traced: concretization is fine in eager mode
    return float(np.asarray(x).sum())


class Stepper:
    # static_argnums count the UNBOUND function's positions: self is
    # index 0 (JAX's convention), so (1,) marks `mode` static
    @partial(jax.jit, static_argnums=(1,))
    def good_method(self, mode, x):
        k = float(mode)        # mode is static: concrete at trace time
        return x * k

    @partial(jax.jit, static_argnums=(1,))
    def bad_method(self, mode, x):
        return int(x)                  # EXPECT: R1
