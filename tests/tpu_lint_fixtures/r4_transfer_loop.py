"""R4 fixture: per-item H2D transfers inside feed/batch loops."""
import jax
import jax.numpy as jnp

from paddle_tpu import to_tensor


def bad(feed):
    out = {}
    for name, v in feed.items():
        out[name] = jax.device_put(v)            # EXPECT: R4
    for name in feed:
        out[name] = jnp.asarray(feed[name])      # EXPECT: R4
    tensors = []
    for batch in feed.values():
        tensors.append(to_tensor(batch))         # EXPECT: R4
    return out, tensors


def good(feed):
    host = {k: v for k, v in feed.items()}
    return jax.device_put(host)   # ONE pytree transfer


def good_not_feed(configs):
    # loop is not over a feed/batch dict: construction-time transfers
    # (e.g. staging parameters once at init) are not the hot-loop hazard
    out = []
    for c in configs:
        out.append(jnp.asarray(c))
    return out
