"""Goodput ledger — the claim state machine (conservation by
construction, nesting without double-booking, driver-thread ownership,
the startup→unattributed flip, the drain flip, conservation-preserving
reattribution), its surfaces (gauge/goodput/* + the structured JSONL
table, both passing the schema gate's contracts; /debug/goodput), the
cross-rank/cross-restart aggregator stitching, and the end-to-end
satellite: a REAL guarded train loop fed through the prefetcher with
periodic checkpoints and one injected rollback must leave < 5%
unattributed, conserve within 1%, and compile exactly once (the ledger
costs zero retraces)."""
import json
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.jit.train_step import TrainStep
from paddle_tpu.profiler import aggregate, goodput
from paddle_tpu.profiler.goodput import CATEGORIES, GoodputLedger
from paddle_tpu.profiler.telemetry import get_telemetry


def _conserves(snap, tol=1e-6):
    booked = sum(snap["categories"].values())
    return abs(booked - snap["wall_s"]) <= tol * max(1.0, snap["wall_s"])


# ---------------------------------------------------------------------------
# The claim state machine


class TestLedgerMachine:
    def test_vocabulary_matches_aggregate_mirror(self):
        # aggregate.py must stay standalone-loadable (telemetry_agg loads
        # it by file path, no package imports), so it carries a literal
        # mirror of the vocabulary — this is the drift tripwire
        assert tuple(aggregate.GOODPUT_CATEGORIES) == tuple(CATEGORIES)

    def test_nested_claim_suspends_outer_no_double_book(self):
        led = GoodputLedger()
        with led.activity("productive_step"):
            time.sleep(0.02)
            with led.activity("input_wait"):
                time.sleep(0.03)
            time.sleep(0.01)
        snap = led.snapshot()
        cats = snap["categories"]
        # the inner claim owns its span; the outer resumes after it
        assert cats["input_wait"] >= 0.025
        assert cats["productive_step"] >= 0.025
        assert cats["productive_step"] < cats["productive_step"] \
            + cats["input_wait"]
        # conservation by construction: every second has exactly one owner
        assert _conserves(snap)

    def test_base_flips_startup_to_unattributed_at_first_step(self):
        led = GoodputLedger()
        time.sleep(0.02)
        assert led.snapshot()["current"] == "startup"
        with led.activity("productive_step"):
            time.sleep(0.01)
        time.sleep(0.02)
        snap = led.snapshot()
        assert snap["current"] == "unattributed"
        assert snap["categories"]["startup"] >= 0.015
        assert snap["categories"]["unattributed"] >= 0.015

    def test_non_driver_thread_claims_are_noops(self):
        led = GoodputLedger()
        with led.activity("productive_step"):
            pass  # this thread becomes the driver

        def bg():
            with led.activity("checkpoint_save"):
                time.sleep(0.03)

        t = threading.Thread(target=bg)
        t.start()
        t.join()
        snap = led.snapshot()
        assert snap["categories"]["checkpoint_save"] == 0.0
        assert _conserves(snap)

    def test_unknown_and_unattributed_claims_rejected(self):
        led = GoodputLedger()
        with pytest.raises(ValueError):
            led.activity("coffee_break")
        with pytest.raises(ValueError):
            # computed residual, never claimable — claiming it would
            # defeat its honesty
            led.activity("unattributed")

    def test_shutdown_begin_flips_base(self):
        led = GoodputLedger()
        time.sleep(0.01)
        led.shutdown_begin()
        led.shutdown_begin()  # idempotent
        time.sleep(0.02)
        snap = led.snapshot()
        assert snap["current"] == "drain_shutdown"
        assert snap["categories"]["drain_shutdown"] >= 0.015
        assert snap["categories"]["startup"] >= 0.005  # pre-drain stays put
        assert _conserves(snap)

    def test_reattribute_is_a_transfer_not_an_addition(self):
        led = GoodputLedger()
        time.sleep(0.05)
        moved = led.reattribute("restart_downtime", 0.02)
        assert moved == pytest.approx(0.02)
        snap = led.snapshot()
        assert snap["categories"]["restart_downtime"] == pytest.approx(0.02)
        assert _conserves(snap)
        # asking for more than the source holds moves only what exists
        moved = led.reattribute("restart_downtime", 1e9)
        snap = led.snapshot()
        assert moved <= snap["wall_s"]
        assert snap["categories"]["startup"] >= 0.0
        assert _conserves(snap)

    def test_disabled_via_env(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_GOODPUT", "0")
        led = GoodputLedger()
        with led.activity("productive_step"):
            time.sleep(0.01)
        assert led.snapshot()["categories"]["productive_step"] == 0.0

    def test_attempt_stamp_from_launch_env(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_LAUNCH_ATTEMPT", "3")
        assert GoodputLedger().attempt == 3
        monkeypatch.setenv("PADDLE_TPU_LAUNCH_ATTEMPT", "junk")
        assert GoodputLedger().attempt == 0


# ---------------------------------------------------------------------------
# Surfaces: gauges, the structured JSONL table, the debug endpoint


class TestSurfaces:
    def test_publish_and_jsonl_pass_schema_contracts(self, tmp_path):
        import tools.check_telemetry_schema as cts

        tel = get_telemetry()
        tel.reset()  # swaps in a fresh ledger too
        with goodput.activity("productive_step"):
            time.sleep(0.02)
        snap = goodput.publish(tel)
        assert snap is not None
        gauges = tel.snapshot()["gauges"]
        assert gauges["goodput/wall_s"] > 0
        assert 0 <= gauges["goodput/fraction"] <= 1
        assert gauges["goodput/productive_step_s"] >= 0.015
        # zero categories (other than the headline pair) stay unpublished
        assert "goodput/checkpoint_save_s" not in gauges
        path = tmp_path / "tel.jsonl"
        tel.to_jsonl(str(path), step=1, tag="goodput_test")
        rec = json.loads(path.read_text().splitlines()[-1])
        assert "goodput" in rec
        table = rec["goodput"]
        assert set(table["categories"]) <= set(CATEGORIES)
        assert all(v > 0 for v in table["categories"].values())
        # the record passes the schema gate's goodput name/conservation
        # contracts (closed vocabulary, seconds >= 0, sum-to-wall)
        assert cts.validate_record(rec, 1) is None
        n, err = cts.validate_file(str(path),
                                   require=["gauge/goodput/fraction"])
        assert err is None and n >= 1

    def test_schema_rejects_invented_category_and_broken_conservation(self):
        import tools.check_telemetry_schema as cts

        base = {"ts": 1.0, "step": 1, "tag": "t", "scalars": {}}
        bad_name = dict(base, scalars={"gauge/goodput/coffee_s": 1.0})
        assert "vocabulary" in cts.validate_record(bad_name, 1)
        torn = dict(base, scalars={"gauge/goodput/wall_s": 100.0,
                                   "gauge/goodput/productive_step_s": 10.0})
        assert "conserve" in cts.validate_record(torn, 1)
        bad_table = dict(base, goodput={"wall_s": 100.0, "fraction": 0.1,
                                        "attempt": 0,
                                        "categories": {"startup": 1.0}})
        assert "conserve" in cts.validate_record(bad_table, 1)

    def test_debug_goodput_endpoint(self):
        from paddle_tpu.profiler import ops_server
        import urllib.request

        tel = get_telemetry()
        tel.reset()
        with goodput.activity("productive_step"):
            time.sleep(0.01)
        srv = ops_server.start_ops_server(0, host="127.0.0.1")
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/debug/goodput",
                    timeout=5) as r:
                body = json.loads(r.read().decode())
        finally:
            ops_server.stop_ops_server()
        assert body["wall_s"] > 0
        assert 0 <= body["fraction"] <= 1
        assert set(body["categories"]) == set(CATEGORIES)
        assert body["categories"]["productive_step"] >= 0.005


# ---------------------------------------------------------------------------
# Cross-rank / cross-restart aggregation


def _rec(goodput_table=None, tag="demo", scalars=None):
    rec = {"ts": 1.0, "step": 1, "tag": tag, "scalars": scalars or {}}
    if goodput_table is not None:
        rec["goodput"] = goodput_table
    return rec


def _table(attempt, wall, productive, startup=None, **cats):
    categories = {"productive_step": productive}
    categories["startup"] = (wall - productive - sum(cats.values())
                             if startup is None else startup)
    categories.update(cats)
    return {"wall_s": wall, "fraction": productive / wall,
            "attempt": attempt, "current": "unattributed",
            "categories": categories}


class TestAggregation:
    def test_last_table_per_attempt_wins_and_launch_skipped(self):
        records = [
            _rec(_table(0, 5.0, 1.0)),          # early cumulative flush
            _rec(_table(0, 10.0, 4.0)),         # the attempt's total
            _rec(_table(1, 8.0, 6.0)),
            _rec(_table(0, 99.0, 0.0), tag="launch"),  # launcher: skip
        ]
        tables = aggregate.goodput_tables(records)
        assert set(tables) == {0, 1}
        assert tables[0]["wall_s"] == 10.0
        assert tables[1]["wall_s"] == 8.0

    def test_cross_restart_stitch_sums_attempts_adds_downtime_once(self):
        rank_records = {
            0: [_rec(_table(0, 10.0, 4.0)), _rec(_table(1, 10.0, 6.0))],
            1: [_rec(_table(0, 10.0, 2.0)), _rec(_table(1, 10.0, 4.0))],
            # the launcher's flushed record carries the dead gap — no
            # worker process existed to book it
            -1: [_rec(_table(0, 7.0, 0.0, restart_downtime=2.5),
                      tag="launch")],
        }
        s = aggregate.goodput_summary(rank_records)
        assert s is not None
        assert set(s["per_rank"]) == {0, 1}  # launch row is not a rank
        assert s["per_rank"][0]["wall_s"] == pytest.approx(20.0)
        assert s["per_rank"][0]["attempts"] == 2
        assert s["per_rank"][0]["fraction"] == pytest.approx(0.5)
        assert s["per_rank"][1]["fraction"] == pytest.approx(0.3)
        job = s["job"]
        # ranks run concurrently: job wall = mean across ranks, then the
        # launcher's downtime lands ONCE on both wall and its category
        assert job["wall_s"] == pytest.approx(22.5)
        assert job["categories"]["restart_downtime"] == pytest.approx(2.5)
        assert job["restart_downtime_s"] == pytest.approx(2.5)
        assert job["fraction"] == pytest.approx(8.0 / 22.5)
        assert s["worst_rank"] == {"rank": 1, "fraction": pytest.approx(0.3)}
        assert s["conservation_err"] < 1e-9

    def test_no_tables_returns_none(self):
        assert aggregate.goodput_summary({0: [_rec()]}) is None

    def test_conservation_err_surfaces_a_leaky_rank(self):
        leaky = _table(0, 10.0, 4.0)
        leaky["categories"] = {"productive_step": 4.0}  # 6s vanished
        s = aggregate.goodput_summary({0: [_rec(leaky)]})
        assert s["conservation_err"] == pytest.approx(0.6)


# ---------------------------------------------------------------------------
# End-to-end satellite: conservation under concurrency


class TestConservationUnderConcurrency:
    def test_guarded_loop_with_prefetch_ckpt_and_rollback(self, tmp_path):
        from paddle_tpu.io.prefetch import DevicePrefetcher
        from paddle_tpu.resilience import RecoveryPolicy, StepGuard
        from paddle_tpu.resilience.cluster import ClusterCheckpoint

        tel = get_telemetry()
        tel.reset()  # fresh ledger (this wall is the denominator),
        #              fresh retrace trackers (the zero-retrace bar)
        paddle.seed(0)
        net = nn.Linear(8, 4)
        opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                    parameters=net.parameters())
        step = TrainStep(net, lambda out, y: ((out - y) ** 2).mean(), opt,
                         guard_updates=True)
        guard = StepGuard(step, RecoveryPolicy(
            max_consecutive_bad=1,      # one NaN => a real rollback
            snapshot_every=1,
            quarantine_dir=str(tmp_path / "q")))
        ck = ClusterCheckpoint(str(tmp_path / "ckpt"))
        rng = np.random.RandomState(0)
        n = 8
        xs = rng.randn(n, 16, 8).astype("float32")
        ys = rng.randn(n, 16, 4).astype("float32")
        xs[3, 0, 0] = np.nan  # the injected bad step

        def batches():
            for i in range(n):
                time.sleep(0.005)  # real producer cost => input_wait books
                yield xs[i], ys[i]

        i = 0
        for x, y in DevicePrefetcher(batches(), depth=1):
            guard((x,), (y,))
            if (i + 1) % 3 == 0:
                ck.save(i + 1, step.snapshot_state())
            i += 1

        snap = goodput.snapshot()
        cats = snap["categories"]
        # conservation: every wall second has exactly one owner
        booked = sum(cats.values())
        assert abs(booked - snap["wall_s"]) <= 0.01 * snap["wall_s"]
        # exhaustive: the honest remainder stays under the 5% bar even
        # with the prefetch stage thread overlapping the step loop
        assert cats["unattributed"] < 0.05 * snap["wall_s"]
        # every concurrent activity booked into ITS OWN category
        assert cats["productive_step"] > 0
        assert cats["compile"] > 0          # tracked_jit claimed the trace
        assert cats["input_wait"] > 0       # consumer blocked on the queue
        assert cats["checkpoint_save"] > 0  # periodic commit claimed
        assert cats["rollback_recovery"] > 0  # quarantine + rollback
        assert cats["startup"] > 0          # model build pre-first-step
        assert cats["eval"] == 0.0
        assert cats["restart_downtime"] == 0.0
        # no double-booking: the nested claims (compile inside the step,
        # recovery inside the bad step) subtracted from their outer span,
        # so the parts cannot exceed the whole
        assert booked <= snap["wall_s"] * 1.01
        # the ledger costs zero retraces: one signature, one compile
        assert step._jitted.tracker.compiles == 1
        # and the guard genuinely rolled back (not just skipped)
        assert tel.counter_value("resilience/rollbacks") >= 1
        assert tel.counter_value("resilience/quarantined_batches") >= 1
        # satellite timers fed by the same paths
        hists = tel.snapshot()["histograms"]
        assert "resilience/rollback_ms" in hists
        assert "resilience/quarantine_ms" in hists
