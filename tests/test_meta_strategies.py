"""Meta-strategy engines (LocalSGD / DGC / fp16-allreduce / gradient merge)
on the virtual 8-device CPU mesh — the TestDistBase pattern (reference
test_dist_base.py:682): run the distributed engine and a single-process
reference on identical data and assert loss parity / convergence."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed.fleet import (
    DistributedStrategy,
    DPStrategyTrainStep,
    LocalSGDTrainStep,
    create_strategy_train_step,
)


class MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 16)
        self.fc2 = nn.Linear(16, 4)

    def forward(self, x):
        return self.fc2(nn.functional.relu(self.fc1(x)))


def make_batch(rng, b=16):
    x = rng.randn(b, 8).astype(np.float32)
    y = rng.randint(0, 4, size=(b,)).astype(np.int64)
    return x, y


def loss_fn(logits, y):
    return nn.functional.cross_entropy(logits, y)


def dp_mesh():
    return Mesh(np.array(jax.devices()[:8]), ("dp",))


def run_engine(step, rng, iters=12):
    losses = []
    for _ in range(iters):
        x, y = make_batch(rng)
        losses.append(float(step((x,), (y,)).numpy()))
    return losses


def run_engine_fixed(step, rng, iters):
    """Repeatedly fit ONE batch — a memorization target convergence tests
    can actually reach (fresh random labels every step cannot be learned)."""
    x, y = make_batch(rng)
    return [float(step((x,), (y,)).numpy()) for _ in range(iters)]


def run_reference(model, opt, rng, iters=12):
    losses = []
    for _ in range(iters):
        x, y = make_batch(rng)
        loss = loss_fn(model(paddle.to_tensor(x)), paddle.to_tensor(y))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    return losses


class TestGradientMerge:
    def test_k_step_accumulation_matches_big_batch(self):
        """k accumulation steps with avg ≡ one step on the concatenated batch."""
        paddle.seed(7)
        m1 = MLP()
        m2 = MLP()
        m2.set_state_dict(m1.state_dict())
        mesh = dp_mesh()
        opt1 = optimizer.SGD(learning_rate=0.1, parameters=m1.parameters())
        step = DPStrategyTrainStep(m1, loss_fn, opt1, mesh,
                                   gradient_merge_k=2, gradient_merge_avg=True)
        rng = np.random.RandomState(0)
        xa, ya = make_batch(rng)
        xb, yb = make_batch(rng)
        step((xa,), (ya,))
        step((xb,), (yb,))
        step.sync_to_layer()

        opt2 = optimizer.SGD(learning_rate=0.1, parameters=m2.parameters())
        x = np.concatenate([xa, xb])
        y = np.concatenate([ya, yb])
        loss = loss_fn(m2(paddle.to_tensor(x)), paddle.to_tensor(y))
        loss.backward()
        opt2.step()
        for (n1, p1), (n2, p2) in zip(sorted(m1.named_parameters()),
                                      sorted(m2.named_parameters())):
            np.testing.assert_allclose(p1.numpy(), p2.numpy(), atol=1e-5,
                                       err_msg=n1)

    def test_params_frozen_between_applies(self):
        paddle.seed(7)
        m = MLP()
        mesh = dp_mesh()
        opt = optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
        step = DPStrategyTrainStep(m, loss_fn, opt, mesh, gradient_merge_k=4)
        before = {n: np.asarray(v) for n, v in step._params.items()}
        rng = np.random.RandomState(0)
        x, y = make_batch(rng)
        step((x,), (y,))  # step 1 of 4: no apply yet
        for n, v in step._params.items():
            np.testing.assert_array_equal(np.asarray(v), before[n])


class TestFp16Allreduce:
    def test_converges_close_to_fp32(self):
        paddle.seed(3)
        m1 = MLP()
        m2 = MLP()
        m2.set_state_dict(m1.state_dict())
        mesh = dp_mesh()
        s1 = DPStrategyTrainStep(m1, loss_fn,
                                 optimizer.SGD(0.1, m1.parameters()), mesh,
                                 fp16_allreduce=True)
        losses = run_engine(s1, np.random.RandomState(0))
        ref = run_reference(m2, optimizer.SGD(0.1, m2.parameters()),
                            np.random.RandomState(0))
        assert losses[-1] < losses[0]
        # bf16 allreduce rounds the grads; trajectories stay close
        np.testing.assert_allclose(losses, ref, rtol=0.08, atol=0.05)


class TestDGC:
    def test_converges(self):
        paddle.seed(11)
        m = MLP()
        mesh = dp_mesh()
        step = DPStrategyTrainStep(
            m, loss_fn, optimizer.Momentum(0.05, momentum=0.0,
                                           parameters=m.parameters()),
            mesh, dgc=True, dgc_sparsity=0.7, dgc_rampup_begin_step=2)
        losses = run_engine_fixed(step, np.random.RandomState(1), iters=25)
        assert np.mean(losses[-5:]) < np.mean(losses[:5])

    def test_residual_accumulation_preserves_grad_mass(self):
        """Sparsified grad + residual must equal the momentum-corrected sum."""
        paddle.seed(11)
        m = MLP()
        mesh = dp_mesh()
        step = DPStrategyTrainStep(
            m, loss_fn, optimizer.SGD(0.0, parameters=m.parameters()),
            mesh, dgc=True, dgc_sparsity=0.5, dgc_momentum=0.0)
        rng = np.random.RandomState(1)
        x, y = make_batch(rng)
        step((x,), (y,))
        # after one step with momentum 0: residual v holds the unsent mass
        for n, v in step._dgc_v.items():
            resid = np.asarray(v)
            assert np.isfinite(resid).all()
        # at sparsity 0.5 roughly half the entries must have been retained
        kept = sum(float((np.asarray(v) == 0).mean())
                   for v in step._dgc_v.values()) / len(step._dgc_v)
        assert kept > 0.3  # zeros in residual = sent entries

    def test_rampup_is_dense(self):
        paddle.seed(11)
        m1, m2 = MLP(), MLP()
        m2.set_state_dict(m1.state_dict())
        mesh = dp_mesh()
        s1 = DPStrategyTrainStep(
            m1, loss_fn, optimizer.SGD(0.1, parameters=m1.parameters()),
            mesh, dgc=True, dgc_sparsity=0.99, dgc_rampup_begin_step=1000)
        rng = np.random.RandomState(2)
        x, y = make_batch(rng)
        s1((x,), (y,))
        s1.sync_to_layer()
        loss = loss_fn(m2(paddle.to_tensor(x)), paddle.to_tensor(y))
        loss.backward()
        optimizer.SGD(0.1, parameters=m2.parameters()).step()
        for (n1, p1), (_, p2) in zip(sorted(m1.named_parameters()),
                                     sorted(m2.named_parameters())):
            np.testing.assert_allclose(p1.numpy(), p2.numpy(), atol=1e-5,
                                       err_msg=n1)


class TestLocalSGD:
    def test_k1_matches_plain_dp(self):
        """k=1 LocalSGD averages params every step ⇒ ≡ plain DP with SGD."""
        paddle.seed(5)
        m1, m2 = MLP(), MLP()
        m2.set_state_dict(m1.state_dict())
        mesh = dp_mesh()
        s1 = LocalSGDTrainStep(m1, loss_fn,
                               optimizer.SGD(0.1, m1.parameters()),
                               mesh, k_steps=1)
        losses = run_engine(s1, np.random.RandomState(0))
        ref = run_reference(m2, optimizer.SGD(0.1, m2.parameters()),
                            np.random.RandomState(0))
        # per-shard batches differ from the full batch only through
        # grad-averaging order; SGD makes them identical
        np.testing.assert_allclose(losses, ref, rtol=1e-4, atol=1e-5)

    def test_k4_diverges_then_syncs_and_converges(self):
        paddle.seed(5)
        m = MLP()
        mesh = dp_mesh()
        step = LocalSGDTrainStep(m, loss_fn,
                                 optimizer.SGD(0.05, m.parameters()),
                                 mesh, k_steps=4)
        rng = np.random.RandomState(3)
        # after step 1 (no sync): replicas must differ
        x, y = make_batch(rng)
        step((x,), (y,))
        some = np.asarray(next(iter(step._params.values())))
        assert not np.allclose(some[0], some[1])
        # after step 4 (sync): replicas identical
        for _ in range(3):
            x, y = make_batch(rng)
            step((x,), (y,))
        some = np.asarray(next(iter(step._params.values())))
        np.testing.assert_allclose(some[0], some[-1], atol=1e-6)
        losses = run_engine_fixed(step, rng, iters=20)
        assert np.mean(losses[-5:]) < np.mean(losses[:5])

    def test_adaptive_k_bounded_by_loss_ratio(self):
        """k must track sqrt(loss0/loss)*k0, not compound from the current k."""
        paddle.seed(5)
        m = MLP()
        step = LocalSGDTrainStep(m, loss_fn,
                                 optimizer.SGD(0.0, m.parameters()),  # lr=0
                                 dp_mesh(), k_steps=2, adaptive=True,
                                 max_k_steps=64)
        rng = np.random.RandomState(4)
        x, y = make_batch(rng)
        for _ in range(10):  # lr=0 -> loss constant -> ratio 1 -> k stays k0
            step((x,), (y,))
        assert step._k == 2

    def test_adaptive_k_grows(self):
        paddle.seed(5)
        m = MLP()
        mesh = dp_mesh()
        step = LocalSGDTrainStep(m, loss_fn,
                                 optimizer.SGD(0.1, m.parameters()),
                                 mesh, k_steps=1, adaptive=True, max_k_steps=8)
        run_engine(step, np.random.RandomState(4), iters=30)
        assert 1 <= step._k <= 8

    def test_sync_to_layer_averages(self):
        paddle.seed(5)
        m = MLP()
        mesh = dp_mesh()
        step = LocalSGDTrainStep(m, loss_fn,
                                 optimizer.SGD(0.05, m.parameters()),
                                 mesh, k_steps=100)  # never auto-sync
        rng = np.random.RandomState(3)
        x, y = make_batch(rng)
        step((x,), (y,))
        step.sync_to_layer()
        name = next(iter(step._params))
        stacked = np.asarray(step._params[name])
        np.testing.assert_allclose(
            dict(m.named_parameters())[name].numpy(),
            stacked.mean(0), atol=1e-6)


class MultiInputNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(8, 4)

    def forward(self, a, b):
        return self.fc(a + b)


class TestMultiInputBatches:
    def test_dp_strategy_two_inputs(self):
        paddle.seed(2)
        m = MultiInputNet()
        step = DPStrategyTrainStep(m, loss_fn,
                                   optimizer.SGD(0.1, m.parameters()),
                                   dp_mesh(), gradient_merge_k=2)
        rng = np.random.RandomState(0)
        a = rng.randn(16, 8).astype(np.float32)
        b = rng.randn(16, 8).astype(np.float32)
        y = rng.randint(0, 4, 16).astype(np.int64)
        assert np.isfinite(float(step((a, b), (y,)).numpy()))

    def test_localsgd_two_inputs(self):
        paddle.seed(2)
        m = MultiInputNet()
        step = LocalSGDTrainStep(m, loss_fn,
                                 optimizer.SGD(0.1, m.parameters()),
                                 dp_mesh(), k_steps=2)
        rng = np.random.RandomState(0)
        a = rng.randn(16, 8).astype(np.float32)
        b = rng.randn(16, 8).astype(np.float32)
        y = rng.randint(0, 4, 16).astype(np.int64)
        assert np.isfinite(float(step((a, b), (y,)).numpy()))


class TestOptimizerParityAcrossEngines:
    def test_localsgd_adamw_applies_decoupled_decay(self):
        """Every rank sees identical data, so local AdamW updates are
        identical and the average is exactly one imperative AdamW step —
        catches the engine silently dropping decoupled weight decay.
        (Adam is nonlinear in the grad, so distinct per-rank shards would
        NOT reproduce the single-process trajectory even at k=1.)"""
        paddle.seed(13)
        m1, m2 = MLP(), MLP()
        m2.set_state_dict(m1.state_dict())
        step = LocalSGDTrainStep(
            m1, loss_fn,
            optimizer.AdamW(1e-2, weight_decay=0.1, parameters=m1.parameters()),
            dp_mesh(), k_steps=1)
        opt2 = optimizer.AdamW(1e-2, weight_decay=0.1,
                               parameters=m2.parameters())
        rng = np.random.RandomState(0)
        l1, ref = [], []
        for _ in range(6):
            xb = rng.randn(2, 8).astype(np.float32)
            yb = rng.randint(0, 4, size=(2,)).astype(np.int64)
            x8 = np.tile(xb, (8, 1))  # identical shard per rank
            y8 = np.tile(yb, 8)
            l1.append(float(step((x8,), (y8,)).numpy()))
            loss = loss_fn(m2(paddle.to_tensor(xb)), paddle.to_tensor(yb))
            loss.backward()
            opt2.step()
            opt2.clear_grad()
            ref.append(float(loss.numpy()))
        np.testing.assert_allclose(l1, ref, rtol=1e-4, atol=1e-5)

    def test_lamb_exclude_from_weight_decay(self):
        """Engines must honor Lamb's exclude_from_weight_decay_fn the way
        Lamb.step() does."""
        from paddle_tpu.distributed.fleet.engine import ParallelTrainStep
        paddle.seed(17)
        m1, m2 = MLP(), MLP()
        m2.set_state_dict(m1.state_dict())
        exclude = lambda p: "bias" in p.name
        s1 = ParallelTrainStep(
            m1, loss_fn,
            optimizer.Lamb(1e-2, lamb_weight_decay=0.5,
                           exclude_from_weight_decay_fn=exclude,
                           parameters=m1.parameters()),
            dp_mesh())
        opt2 = optimizer.Lamb(1e-2, lamb_weight_decay=0.5,
                              exclude_from_weight_decay_fn=exclude,
                              parameters=m2.parameters())
        rng = np.random.RandomState(0)
        x, y = make_batch(rng)
        s1((x,), (y,))
        s1.sync_to_layer()
        loss = loss_fn(m2(paddle.to_tensor(x)), paddle.to_tensor(y))
        loss.backward()
        opt2.step()
        for (n1, p1), (_, p2) in zip(sorted(m1.named_parameters()),
                                     sorted(m2.named_parameters())):
            np.testing.assert_allclose(p1.numpy(), p2.numpy(), atol=1e-5,
                                       err_msg=n1)

    def test_dp_strategy_grad_clip_applied(self):
        from paddle_tpu.nn import ClipGradByGlobalNorm
        paddle.seed(13)
        m1, m2 = MLP(), MLP()
        m2.set_state_dict(m1.state_dict())
        clip = ClipGradByGlobalNorm(0.01)
        s1 = DPStrategyTrainStep(
            m1, loss_fn,
            optimizer.SGD(0.5, parameters=m1.parameters(), grad_clip=clip),
            dp_mesh(), fp16_allreduce=False, gradient_merge_k=1)
        l1 = run_engine(s1, np.random.RandomState(0), iters=4)
        ref = run_reference(
            m2, optimizer.SGD(0.5, parameters=m2.parameters(),
                              grad_clip=ClipGradByGlobalNorm(0.01)),
            np.random.RandomState(0), iters=4)
        np.testing.assert_allclose(l1, ref, rtol=1e-4, atol=1e-5)


class TestZeroOffload:
    def test_offload_state_lives_on_host_and_matches_non_offload(self):
        from paddle_tpu.distributed.fleet.engine import ParallelTrainStep
        paddle.seed(9)
        m1, m2 = MLP(), MLP()
        m2.set_state_dict(m1.state_dict())
        mesh = dp_mesh()
        s1 = ParallelTrainStep(m1, loss_fn,
                               optimizer.Adam(1e-2, parameters=m1.parameters()),
                               mesh, zero_stage=1, offload=True)
        s2 = ParallelTrainStep(m2, loss_fn,
                               optimizer.Adam(1e-2, parameters=m2.parameters()),
                               mesh, zero_stage=1, offload=False)
        # optimizer state must be in host memory space
        any_state = next(iter(s1._opt_state.values()))
        arr = next(v for v in any_state.values() if hasattr(v, "sharding"))
        assert arr.sharding.memory_kind == "pinned_host"
        rng1, rng2 = np.random.RandomState(0), np.random.RandomState(0)
        l1 = run_engine(s1, rng1, iters=5)
        l2 = run_engine(s2, rng2, iters=5)
        np.testing.assert_allclose(l1, l2, rtol=1e-5, atol=1e-6)
        # state stays on host after stepping
        any_state = next(iter(s1._opt_state.values()))
        arr = next(v for v in any_state.values() if hasattr(v, "sharding"))
        assert arr.sharding.memory_kind == "pinned_host"

    def test_factory_passes_offload(self):
        from paddle_tpu.distributed.fleet.engine import ParallelTrainStep
        paddle.seed(9)
        m = MLP()
        strategy = DistributedStrategy()
        strategy.sharding = True
        strategy.sharding_configs = {"stage": 2, "offload": True}
        step = create_strategy_train_step(
            m, loss_fn, optimizer.Adam(1e-2, parameters=m.parameters()),
            dp_mesh(), strategy)
        assert isinstance(step, ParallelTrainStep)
        assert step._offload


class TestStrategyFactory:
    @pytest.mark.parametrize("flag,cls", [
        ("localsgd", LocalSGDTrainStep),
        ("adaptive_localsgd", LocalSGDTrainStep),
        ("dgc", DPStrategyTrainStep),
        ("fp16_allreduce", DPStrategyTrainStep),
        ("gradient_merge", DPStrategyTrainStep),
    ])
    def test_dispatch(self, flag, cls):
        paddle.seed(1)
        m = MLP()
        strategy = DistributedStrategy()
        setattr(strategy, flag, True)
        step = create_strategy_train_step(
            m, loss_fn, optimizer.SGD(0.1, m.parameters()), dp_mesh(),
            strategy)
        assert isinstance(step, cls)

    def test_default_is_gspmd_engine(self):
        from paddle_tpu.distributed.fleet.engine import ParallelTrainStep
        paddle.seed(1)
        m = MLP()
        step = create_strategy_train_step(
            m, loss_fn, optimizer.SGD(0.1, m.parameters()), dp_mesh(),
            DistributedStrategy())
        assert isinstance(step, ParallelTrainStep)


class TestFleetFacadeTrainStep:
    """fleet.init + strategy -> fleet.create_train_step builds the right
    engine on the mesh the strategy's hybrid_configs describe."""

    def test_strategy_mesh_from_hybrid_configs(self):
        from paddle_tpu.distributed.fleet.form_mesh import strategy_mesh

        s = DistributedStrategy()
        s.hybrid_configs = {"dp_degree": -1, "mp_degree": 2}
        mesh = strategy_mesh(s)
        assert mesh.axis_names == ("dp", "mp")
        assert mesh.shape["mp"] == 2 and mesh.shape["dp"] == 4

    def test_strategy_mesh_size_mismatch_raises(self):
        from paddle_tpu.distributed.fleet.form_mesh import strategy_mesh

        s = DistributedStrategy()
        s.hybrid_configs = {"dp_degree": 3, "mp_degree": 5}
        with pytest.raises(ValueError, match="devices"):
            strategy_mesh(s)

    def test_fleet_create_train_step_end_to_end(self):
        import paddle_tpu.distributed.fleet as fleet

        paddle.seed(4)
        strategy = DistributedStrategy()
        strategy.gradient_merge = True
        strategy.gradient_merge_configs = {"k_steps": 2, "avg": True}
        fleet.init(is_collective=True, strategy=strategy)
        m = MLP()
        opt = fleet.distributed_optimizer(
            optimizer.SGD(0.1, parameters=m.parameters()))
        step = fleet.fleet_base.fleet.create_train_step(m, loss_fn)
        assert isinstance(step, DPStrategyTrainStep)
        rng = np.random.RandomState(0)
        x, y = make_batch(rng)
        assert np.isfinite(float(step((x,), (y,)).numpy()))

    def test_fleet_amp_strategy_sets_compute_dtype(self):
        import jax.numpy as jnp
        import paddle_tpu.distributed.fleet as fleet
        from paddle_tpu.distributed.fleet.engine import ParallelTrainStep

        paddle.seed(4)
        strategy = DistributedStrategy()
        strategy.amp = True
        fleet.init(is_collective=True, strategy=strategy)
        m = MLP()
        fleet.distributed_optimizer(
            optimizer.SGD(0.1, parameters=m.parameters()))
        step = fleet.fleet_base.fleet.create_train_step(m, loss_fn)
        assert isinstance(step, ParallelTrainStep)
        assert step._compute_dtype == jnp.bfloat16  # amp strategy applied
        rng = np.random.RandomState(0)
        x, y = make_batch(rng)
        assert np.isfinite(float(step((x,), (y,)).numpy()))


class BNNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(8, 4)
        self.bn = nn.BatchNorm1D(4)

    def forward(self, x):
        return self.bn(self.fc(x))


class TestDPBufferSync:
    def test_batchnorm_running_stats_synced_across_dp(self):
        """Buffers computed from per-rank batch shards must be pmean'd over
        dp — otherwise every device holds different 'replicated' running
        stats and training state silently diverges (advisor finding r1)."""
        paddle.seed(11)
        m = BNNet()
        mesh = dp_mesh()
        opt = optimizer.SGD(learning_rate=0.05, parameters=m.parameters())
        step = DPStrategyTrainStep(m, loss_fn, opt, mesh)
        rng = np.random.RandomState(3)
        for _ in range(3):
            x, y = make_batch(rng)
            step((x,), (y,))
        for name, buf in step._buffers.items():
            shards = [np.asarray(s.data) for s in buf.addressable_shards]
            for s in shards[1:]:
                np.testing.assert_array_equal(
                    shards[0], s,
                    err_msg=f"buffer {name} diverged across dp ranks")
        # running_mean tracks the FULL batch mean (mean over equal shards)
        step.sync_to_layer()
        x, _ = make_batch(np.random.RandomState(9))
        pre = {n: v.numpy().copy() for n, v in m.named_buffers()}
        step((x,), (np.zeros(16, np.int64),))
        step.sync_to_layer()
        h = x @ m.fc.weight.numpy() + m.fc.bias.numpy()
        expect = pre["bn._mean"] * 0.9 + h.mean(0) * 0.1
        got = dict(m.named_buffers())["bn._mean"].numpy()
        np.testing.assert_allclose(got, expect, rtol=1e-4, atol=1e-5)
