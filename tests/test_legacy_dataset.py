"""Legacy paddle.dataset reader-creator API (python/paddle/dataset parity):
each creator returns a generator of sample tuples with the reference's
shapes, usable by legacy reader-loop training scripts."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import dataset


def take(reader, n):
    """reader creators return a CALLABLE reader (the legacy two-level
    convention); iterate by calling it."""
    out = []
    for i, s in enumerate(reader()):
        if i >= n:
            break
        out.append(s)
    return out


class TestReaders:
    def test_mnist(self):
        # the reference convention: train() -> reader; reader() -> generator
        reader = dataset.mnist.train()
        assert callable(reader)
        samples = take(reader, 3)
        img, label = samples[0]
        assert img.shape == (784,) and img.dtype == np.float32
        assert isinstance(label, int) and 0 <= label <= 9

    def test_cifar(self):
        img, label = take(dataset.cifar.train10(), 1)[0]
        assert img.shape == (3 * 32 * 32,)
        img, label = take(dataset.cifar.train100(), 1)[0]
        assert 0 <= label <= 99

    def test_uci_housing(self):
        x, y = take(dataset.uci_housing.train(), 1)[0]
        assert x.shape == (13,) and y.shape == (1,)

    def test_imdb(self):
        doc, label = take(dataset.imdb.train(), 1)[0]
        assert isinstance(doc, list) and label in (0, 1)
        assert len(dataset.imdb.word_dict()) > 0

    def test_imikolov(self):
        s = take(dataset.imikolov.train(n=5), 1)[0]
        assert len(s) == 5
        assert len(dataset.imikolov.build_dict(min_word_freq=1)) >= len(
            dataset.imikolov.build_dict(min_word_freq=3))

    def test_submodule_import(self):
        import paddle_tpu.dataset.mnist as m
        assert callable(m.train)

    def test_movielens(self):
        row = take(dataset.movielens.train(), 1)[0]
        assert len(row) == 8

    def test_conll05(self):
        s = take(dataset.conll05.test(), 1)[0]
        assert len(s) == 9
        wd, pd, ld = dataset.conll05.get_dict()
        assert len(wd) > 0

    def test_wmt(self):
        src, trg, nxt = take(dataset.wmt14.train(dict_size=64), 1)[0]
        assert trg[0] == 0
        src, trg, nxt = take(dataset.wmt16.train(64, 64), 1)[0]
        assert nxt[-1] == 1

    def test_legacy_training_loop(self):
        """The old reader-loop style trains end-to-end."""
        from paddle_tpu import nn, optimizer

        net = nn.Linear(13, 1)
        opt = optimizer.SGD(0.05, parameters=net.parameters())
        losses = []
        for epoch in range(3):
            batch = []
            for x, y in dataset.uci_housing.train()():
                batch.append((x, y))
                if len(batch) == 32:
                    xb = paddle.to_tensor(np.stack([b[0] for b in batch]))
                    yb = paddle.to_tensor(np.stack([b[1] for b in batch]))
                    loss = ((net(xb) - yb) ** 2).mean()
                    loss.backward()
                    opt.step()
                    opt.clear_grad()
                    losses.append(float(loss.numpy()))
                    batch = []
        assert losses[-1] < losses[0]
