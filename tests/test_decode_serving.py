"""Token-level LLM serving (ISSUE 12): paged KV-cache pool accounting,
paged-attention tier parity (+ int8 storage), decode-step continuous
batching with chunked-prefill admission, speculative decoding, and
drain-mid-generation with every request terminal exactly once and zero
leaked KV blocks."""
import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.inference.serving import (GenRequest, KVCacheConfig,
                                          KVCachePool, RequestStatus,
                                          TokenServeConfig,
                                          TokenServingEngine,
                                          dense_greedy_reference,
                                          run_generation_streams)
from paddle_tpu.inference.serving.loadgen import summarize_generation
from paddle_tpu.jit.functionalize import get_params
from paddle_tpu.ops import attention as att
from paddle_tpu.ops import tier_policy
from paddle_tpu.profiler.telemetry import get_telemetry
from paddle_tpu.quant import dequantize_kv, quantize_kv
from paddle_tpu.resilience.inject import clear_injector
from paddle_tpu.text.models.gpt import (GPTConfig, GPTForCausalLM,
                                        gpt_decode_fns)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean():
    clear_injector()
    get_telemetry().reset()
    yield
    clear_injector()


def tiny_model(seed=0, **kw):
    paddle.seed(seed)
    cfg = GPTConfig(vocab_size=96, hidden_size=32, num_layers=2,
                    num_heads=2, max_position_embeddings=128,
                    hidden_dropout=0.0, attention_dropout=0.0, **kw)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


def tiny_draft(seed=3):
    paddle.seed(seed)
    cfg = GPTConfig(vocab_size=96, hidden_size=16, num_layers=1,
                    num_heads=2, max_position_embeddings=128,
                    hidden_dropout=0.0, attention_dropout=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


def make_engine(model=None, draft=None, **kw):
    model = model or tiny_model()
    defaults = dict(capacity=16, decode_buckets=(1, 2, 4), prefill_chunk=8,
                    kv_blocks=48, kv_block_size=8, max_seq_len=96)
    defaults.update(kw)
    return TokenServingEngine(model, TokenServeConfig(**defaults),
                              draft_model=draft), model


# ---------------------------------------------------------------------------
# KV cache pool
# ---------------------------------------------------------------------------
class TestKVCachePool:
    def cfg(self, **kw):
        d = dict(num_layers=2, num_heads=2, head_dim=8, num_blocks=8,
                 block_size=4)
        d.update(kw)
        return KVCacheConfig(**d)

    def test_alloc_free_accounting(self):
        pool = KVCachePool(self.cfg())
        assert pool.config.usable_blocks == 7  # page 0 is scratch
        assert pool.ensure(1, 9)  # 3 blocks of 4
        assert pool.used_blocks == 3
        assert pool.ensure(1, 9)  # idempotent growth
        assert pool.used_blocks == 3
        assert pool.ensure(2, 4)
        assert pool.used_blocks == 4
        assert pool.release(1) == 3
        assert pool.release(1) == 0  # idempotent
        assert pool.release(2) == 1
        acct = pool.accounting()
        assert acct["leaked_blocks"] == 0 and acct["owners"] == []

    def test_no_partial_grab_on_exhaustion(self):
        pool = KVCachePool(self.cfg(num_blocks=4))  # 3 usable
        assert pool.ensure(1, 8)  # 2 blocks
        assert not pool.ensure(2, 8)  # needs 2, only 1 free: all-or-nothing
        assert pool.used_blocks == 2
        assert pool.ensure(2, 4)  # 1 block still fits

    def test_scratch_never_allocated(self):
        pool = KVCachePool(self.cfg())
        pool.ensure(1, 28)  # every usable block
        assert 0 not in pool.owned(1)
        table = pool.block_table(1, 7)
        assert 0 not in table

    def test_block_table_pads_with_scratch(self):
        pool = KVCachePool(self.cfg())
        pool.ensure(9, 5)  # 2 blocks
        t = pool.block_table(9, 6)
        assert t.shape == (6,)
        assert (t[2:] == 0).all()

    def test_telemetry_counters_and_occupancy(self):
        tel = get_telemetry()
        pool = KVCachePool(self.cfg())
        pool.ensure(1, 12)
        pool.release(1)
        snap = tel.snapshot()
        assert snap["counters"]["serve/kv_blocks_alloc"] == 3
        assert snap["counters"]["serve/kv_blocks_free"] == 3
        assert snap["gauges"]["serve/kv_occupancy"] == 0.0
        assert snap["gauges"]["serve/kv_blocks_total"] == 7

    def test_int8_pool_carries_scales(self):
        pool = KVCachePool(self.cfg(dtype="int8"))
        assert pool.pages["k"].dtype == jnp.int8
        assert pool.pages["k_scale"].shape == pool.pages["k"].shape[:-1]


class TestKVQuant:
    def test_roundtrip_close(self):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(4, 3, 2, 16).astype(np.float32))
        q, s = quantize_kv(x)
        back = dequantize_kv(q, s)
        assert q.dtype == jnp.int8 and s.dtype == jnp.float32
        assert s.shape == x.shape[:-1]
        # per-head absmax int8: worst-case error is scale/2 per element
        err = np.abs(np.asarray(back) - np.asarray(x))
        bound = np.asarray(s)[..., None] * 0.51
        assert (err <= bound).all()

    def test_zero_slab_safe(self):
        q, s = quantize_kv(jnp.zeros((2, 2, 4)))
        assert np.asarray(s).min() > 0  # floored scale: no div-by-zero
        assert np.asarray(dequantize_kv(q, s)).max() == 0


# ---------------------------------------------------------------------------
# Paged attention tiers
# ---------------------------------------------------------------------------
class TestPagedAttention:
    def setup_pages(self, dtype=np.float32, quantized=False):
        rng = np.random.RandomState(0)
        B, T, H, D, bs, M = 2, 3, 2, 8, 4, 5
        N = 2 * M + 1
        k = jnp.asarray(rng.randn(N, bs, H, D).astype(dtype))
        v = jnp.asarray(rng.randn(N, bs, H, D).astype(dtype))
        tables = jnp.asarray(
            np.array([[1, 2, 3, 4, 5], [6, 7, 8, 9, 10]], np.int32))
        kv_lens = jnp.asarray(np.array([11, 17], np.int32))
        q = jnp.asarray(rng.randn(B, T, H, D).astype(dtype))
        q_pos = jnp.asarray(np.stack([np.arange(8, 11),
                                      np.arange(14, 17)]).astype(np.int32))
        if quantized:
            kq, ks = quantize_kv(k)
            vq, vs = quantize_kv(v)
            return q, kq, vq, tables, q_pos, kv_lens, ks, vs
        return q, k, v, tables, q_pos, kv_lens, None, None

    def test_gather_vs_scan_parity(self):
        args = self.setup_pages()
        o1 = np.asarray(att._paged_gather_impl(*args))
        o2 = np.asarray(att._paged_scan_impl(*args))
        np.testing.assert_allclose(o1, o2, atol=1e-5)

    def test_vs_dense_reference(self):
        import math
        q, k, v, tables, q_pos, kv_lens, _, _ = self.setup_pages()
        out = np.asarray(att._paged_gather_impl(q, k, v, tables, q_pos,
                                                kv_lens))
        kd = np.asarray(k)[np.asarray(tables)[0]].reshape(20, 2, 8)
        vd = np.asarray(v)[np.asarray(tables)[0]].reshape(20, 2, 8)
        qp = int(np.asarray(q_pos)[0, 1])  # query at position 9
        s = np.einsum("hd,khd->hk", np.asarray(q)[0, 1],
                      kd[:qp + 1]) / math.sqrt(8)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        ref = np.einsum("hk,khd->hd", p, vd[:qp + 1])
        np.testing.assert_allclose(out[0, 1], ref, atol=1e-5)

    def test_int8_close_to_f32(self):
        f32 = self.setup_pages()
        i8 = self.setup_pages(quantized=True)
        o_f = np.asarray(att._paged_gather_impl(*f32))
        o_q = np.asarray(att._paged_gather_impl(*i8))
        assert np.max(np.abs(o_f - o_q)) < 0.05
        o_qs = np.asarray(att._paged_scan_impl(*i8))
        np.testing.assert_allclose(o_q, o_qs, atol=1e-5)

    def test_stale_slots_masked(self):
        """Entries past kv_len (rejected speculative writes, padded table
        slots) must not leak into the softmax."""
        q, k, v, tables, q_pos, kv_lens, _, _ = self.setup_pages()
        poisoned = k.at[np.asarray(tables)[0, 3:]].set(1e3)  # beyond len 11
        o_clean = np.asarray(att._paged_gather_impl(q, k, v, tables, q_pos,
                                                    kv_lens))
        o_pois = np.asarray(att._paged_gather_impl(q, poisoned, v, tables,
                                                   q_pos, kv_lens))
        np.testing.assert_allclose(o_clean[0], o_pois[0], atol=1e-6)

    def test_dispatch_publishes_tier_gauge(self):
        args = self.setup_pages()
        att.paged_attention(*args[:6])
        snap = get_telemetry().snapshot()
        keys = [k for k in snap["gauges"] if k.startswith("attn/tier.paged")]
        assert keys, snap["gauges"].keys()
        assert snap["gauges"][keys[0]] in (
            tier_policy.TIER_IDS["paged_gather"],
            tier_policy.TIER_IDS["paged_scan"])
        assert snap["counters"].get("attn/tier_fallbacks", 0) == 0


class TestPagedTierPolicy:
    def test_forced_tier_wins(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_ATTN_PAGED_POLICY", "paged_scan")
        assert tier_policy.select_paged(1, 2, 8, 4, 16, jnp.float32,
                                        False) == "paged_scan"

    def test_heuristic_crossover(self, monkeypatch):
        monkeypatch.delenv("PADDLE_TPU_ATTN_PAGED_POLICY", raising=False)
        # CPU default = heuristic: gather for small contexts, scan past
        # the materialization knee
        assert tier_policy.select_paged(1, 2, 8, 8, 16, jnp.float32,
                                        False) == "paged_gather"
        assert tier_policy.select_paged(1, 2, 8, 512, 16, jnp.float32,
                                        False) == "paged_scan"

    def test_bench_mode_measures_once_and_caches(self, monkeypatch,
                                                 tmp_path):
        cache = str(tmp_path / "tiers.json")
        monkeypatch.setenv("PADDLE_TPU_ATTN_PAGED_POLICY", "bench")
        monkeypatch.setenv("PADDLE_TPU_ATTN_TIER_CACHE", cache)
        tier_policy.reset()
        tel = get_telemetry()
        t1 = tier_policy.select_paged(1, 2, 8, 4, 4, jnp.float32, False)
        benches = tel.snapshot()["counters"].get("attn/tier_bench", 0)
        t2 = tier_policy.select_paged(1, 2, 8, 4, 4, jnp.float32, False)
        assert t1 == t2 and t1 in tier_policy.PAGED_TIERS
        assert tel.snapshot()["counters"].get("attn/tier_bench", 0) \
            == benches  # pure cache hit, no re-measure
        # restart-warm: a fresh registry re-reads the persisted verdict
        with open(cache) as f:
            data = json.load(f)
        assert any(":paged:" in k for k in data)
        tier_policy.reset()
        t3 = tier_policy.select_paged(1, 2, 8, 4, 4, jnp.float32, False)
        assert t3 == t1
        assert tel.snapshot()["counters"].get("attn/tier_bench", 0) \
            == benches

    def test_unknown_policy_falls_back(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_ATTN_PAGED_POLICY", "warp-drive")
        assert tier_policy.paged_policy_mode() == "heuristic"


# ---------------------------------------------------------------------------
# GPT paged forward
# ---------------------------------------------------------------------------
class TestGPTDecodeFns:
    def run_paged_prefill(self, model, prompt, kv_dtype="float32", C=8):
        mcfg = model.config
        fwd = gpt_decode_fns(mcfg, kv_dtype)
        pool = KVCachePool(KVCacheConfig(
            mcfg.num_layers, mcfg.num_heads,
            mcfg.hidden_size // mcfg.num_heads, num_blocks=16, block_size=8,
            dtype=kv_dtype))
        n = len(prompt)
        pool.ensure(1, n)
        table = jnp.asarray(pool.block_table(1, 8)[None])
        pages = pool.pages
        params = get_params(model)
        rows = []
        jfwd = jax.jit(fwd)
        for c0 in range(0, n, C):
            part = prompt[c0:c0 + C]
            pad = C - len(part)
            toks = np.concatenate([part, np.zeros(pad, np.int32)])[None]
            qpos = (c0 + np.arange(C, dtype=np.int32))[None]
            lens = np.asarray([min(c0 + C, n)], np.int32)
            logits, pages = jfwd(params, jnp.asarray(toks),
                                 jnp.asarray(qpos), pages, table,
                                 jnp.asarray(lens))
            rows.append(np.asarray(logits)[0, :C - pad if pad else C])
        return np.concatenate(rows, axis=0)

    def test_chunked_prefill_matches_dense_forward(self):
        model = tiny_model()
        rng = np.random.RandomState(1)
        prompt = rng.randint(0, 96, 19).astype(np.int32)
        paged = self.run_paged_prefill(model, prompt)
        ref = np.asarray(model(
            paddle.Tensor(prompt[None].astype(np.int64))).numpy())[0]
        np.testing.assert_allclose(paged, ref, atol=1e-4)
        assert np.array_equal(paged.argmax(-1), ref.argmax(-1))

    def test_int8_kv_close_to_bf16_reference(self):
        """ISSUE satellite: int8 KV storage parity against a wider
        reference — logits must stay close enough that greedy decisions
        survive on all but near-tie positions."""
        model = tiny_model()
        rng = np.random.RandomState(2)
        prompt = rng.randint(0, 96, 17).astype(np.int32)
        ref16 = self.run_paged_prefill(model, prompt, kv_dtype="bfloat16")
        got8 = self.run_paged_prefill(model, prompt, kv_dtype="int8")
        # int8-vs-bf16 logit drift bounded well inside the logit RANGE
        span = ref16.max() - ref16.min()
        assert np.max(np.abs(got8 - ref16)) < 0.05 * float(span)


# ---------------------------------------------------------------------------
# Engine: continuous batching, parity, chunked prefill, eviction, spec
# ---------------------------------------------------------------------------
class TestTokenEngine:
    def test_greedy_parity_with_dense_reference(self):
        eng, model = make_engine()
        eng.start()
        try:
            rng = np.random.RandomState(7)
            prompts = [rng.randint(0, 96, n).astype(np.int32)
                       for n in (5, 19, 11, 3)]
            reqs = [eng.submit(p, max_new_tokens=10) for p in prompts]
            for r in reqs:
                assert r.wait(120)
            for p, r in zip(prompts, reqs):
                assert r.status == RequestStatus.OK
                assert [int(t) for t in r.outputs[0]] \
                    == dense_greedy_reference(model, p, 10)
        finally:
            acct = eng.shutdown()
        assert acct["unaccounted"] == [] and acct["double_terminal"] == 0
        assert eng.kv_accounting()["leaked_blocks"] == 0

    def test_eos_stops_generation(self):
        eng, model = make_engine()
        eng.start()
        try:
            rng = np.random.RandomState(7)
            p = rng.randint(0, 96, 5).astype(np.int32)
            ref = dense_greedy_reference(model, p, 30)
            eos = ref[3]
            # generation stops AT the FIRST eos occurrence (inclusive) —
            # which may be before index 3 if the greedy stream repeats
            expected = ref[:ref.index(eos) + 1]
            r = eng.submit(p, max_new_tokens=30, eos_id=int(eos))
            assert r.wait(60)
            out = [int(t) for t in r.outputs[0]]
            assert out == expected
        finally:
            eng.shutdown()

    def test_ttft_tpot_stamped(self):
        eng, _ = make_engine()
        eng.start()
        try:
            r = eng.submit(np.arange(6, dtype=np.int32), max_new_tokens=8)
            assert r.wait(60)
            assert r.ttft_ms() is not None and r.ttft_ms() >= 0
            assert r.tpot_ms() is not None and r.tpot_ms() >= 0
            s = summarize_generation([r])
            assert s["tokens_generated"] == 8
            assert "ttft_p50_ms" in s and "tpot_p99_ms" in s
        finally:
            eng.shutdown()
        snap = get_telemetry().snapshot()
        assert "serve/ttft_ms" in snap["histograms"]
        assert "serve/tpot_ms" in snap["histograms"]

    def test_chunked_prefill_never_stalls_decodes(self):
        """A long prompt admitted while another sequence decodes enters
        chunk by chunk, one chunk per scheduler iteration: the running
        sequence finishes its WHOLE generation before the long prompt
        even produces a first token — decodes were never stalled behind
        the prefill."""
        eng, _ = make_engine(prefill_chunk=4, kv_blocks=64, max_seq_len=96,
                             max_running=2, decode_buckets=(1, 2))
        eng.start()
        try:
            rng = np.random.RandomState(5)
            # short first: 1 prefill chunk, then it decodes every round
            short_r = eng.submit(rng.randint(0, 96, 3).astype(np.int32),
                                 max_new_tokens=12)
            # long second: 20 prefill chunks, interleaved 1/iteration
            long_r = eng.submit(rng.randint(0, 96, 80).astype(np.int32),
                                max_new_tokens=4)
            assert long_r.wait(120) and short_r.wait(120)
            assert long_r.status == short_r.status == RequestStatus.OK
            # interleaving proof: short's 12 decode rounds all ran while
            # the long prompt was still chunking (≥ 20 iterations)
            assert short_r.finished_at < long_r.first_token_at
        finally:
            eng.shutdown()
        assert get_telemetry().counter_value("serve/prefill_chunks") >= 21

    def test_eviction_under_pool_pressure_keeps_parity(self):
        eng, model = make_engine(kv_blocks=9, kv_block_size=8,
                                 max_seq_len=48, decode_buckets=(1, 2, 4))
        eng.start()
        try:
            rng = np.random.RandomState(7)
            prompts = [rng.randint(0, 96, 20).astype(np.int32)
                       for _ in range(3)]
            reqs = [eng.submit(p, max_new_tokens=16) for p in prompts]
            for r in reqs:
                assert r.wait(180)
            for p, r in zip(prompts, reqs):
                assert r.status == RequestStatus.OK
                assert [int(t) for t in r.outputs[0]] \
                    == dense_greedy_reference(model, p, 16)
        finally:
            eng.shutdown()
        assert get_telemetry().counter_value("serve/kv_evictions") >= 1
        assert eng.kv_accounting()["leaked_blocks"] == 0

    def test_eviction_respects_batch_exclusion(self):
        """A sequence already accepted into the round's batch must never
        be evicted by a later member's allocation — its feed was decided
        from a cache cursor the eviction would zero mid-round."""
        eng, _ = make_engine(kv_blocks=5, kv_block_size=8, max_seq_len=32)
        sched = eng._scheduler
        a = GenRequest(1, np.arange(4, dtype=np.int32), 4)
        b = GenRequest(2, np.arange(4, dtype=np.int32), 4)
        assert eng._pool.ensure(a.id, 32)  # a holds every usable block
        a.ncache = 16
        sched._running.extend([a, b])
        # excluded: b cannot steal from the in-batch member — it waits
        assert not sched._ensure_blocks(b, 8, exclude=[a])
        assert a.ncache == 16 and eng._pool.owned(a.id)
        # unexcluded (a is merely running): b may evict it
        assert sched._ensure_blocks(b, 8)
        assert a.ncache == 0 and not eng._pool.owned(a.id)

    def test_tail_decode_protects_spec_group(self):
        """The plain-decode round the spec path runs for its
        near-max_seq_len tail must not evict already-ensured spec-group
        members (the cross-round variant of the exclusion above)."""
        eng, _ = make_engine(kv_blocks=5, kv_block_size=8, max_seq_len=32)
        sched = eng._scheduler
        a = GenRequest(1, np.arange(4, dtype=np.int32), 4)
        a.ncache = 16
        b = GenRequest(2, np.arange(4, dtype=np.int32), 4)
        b.ncache = 3  # pending == 1: decode-eligible tail member
        assert eng._pool.ensure(a.id, 32)  # a (the spec group) holds all
        sched._running.extend([a, b])
        sched._decode_round([b], protect=[a])
        # b could not allocate without evicting the protected member:
        # it waits a round; a's cursor and blocks are untouched
        assert a.ncache == 16 and eng._pool.owned(a.id)
        assert b.ncache == 3 and not eng._pool.owned(b.id)

    def test_submit_validation(self):
        eng, _ = make_engine()
        eng.start()
        try:
            with pytest.raises(ValueError):
                eng.submit(np.zeros((2, 2), np.int32))
            with pytest.raises(ValueError):
                eng.submit(np.asarray([1.5, 2.5]))
            with pytest.raises(ValueError):  # prompt + budget > max_seq_len
                eng.submit(np.arange(90, dtype=np.int32),
                           max_new_tokens=50)
        finally:
            eng.shutdown()

    def test_capacity_rejects_explicit(self):
        eng, _ = make_engine(capacity=1, max_running=1,
                             decode_buckets=(1,))
        eng.start()
        try:
            reqs = [eng.submit(np.arange(4, dtype=np.int32),
                               max_new_tokens=30) for _ in range(12)]
            shed = [r for r in reqs if r.status == RequestStatus.REJECTED]
            assert shed, "queue bound never shed"
            for r in reqs:
                r.wait(120)
        finally:
            acct = eng.shutdown()
        assert acct["unaccounted"] == [] and acct["double_terminal"] == 0

    def test_mid_generation_deadline_sheds_and_frees(self):
        eng, _ = make_engine()
        eng.start()
        try:
            r = eng.submit(np.arange(8, dtype=np.int32),
                           max_new_tokens=60, deadline_s=0.03)
            assert r.wait(60)
            assert r.status in (RequestStatus.DEADLINE_EXCEEDED,
                                RequestStatus.OK)
        finally:
            eng.shutdown()
        assert eng.kv_accounting()["leaked_blocks"] == 0

    def test_decode_compiles_bounded_by_buckets(self):
        eng, _ = make_engine(decode_buckets=(1, 2))
        eng.start()
        try:
            rng = np.random.RandomState(0)
            for _ in range(2):  # two waves, same shapes
                reqs = [eng.submit(rng.randint(0, 96, 4).astype(np.int32),
                                   max_new_tokens=6) for _ in range(2)]
                for r in reqs:
                    assert r.wait(60)
        finally:
            eng.shutdown()
        sched = eng._scheduler
        for b, fn in sched._decode_fns.items():
            assert fn.tracker.compiles <= 1, \
                f"decode bucket {b} recompiled: {fn.tracker.compiles}"
        if sched._prefill_fn is not None:
            assert sched._prefill_fn.tracker.compiles <= 1


class TestSpeculative:
    def test_spec_output_equals_plain_greedy(self):
        model = tiny_model()
        eng, _ = make_engine(model=model, draft=tiny_draft(), spec_k=3)
        eng.start()
        try:
            rng = np.random.RandomState(7)
            prompts = [rng.randint(0, 96, n).astype(np.int32)
                       for n in (5, 13)]
            reqs = [eng.submit(p, max_new_tokens=10) for p in prompts]
            for r in reqs:
                assert r.wait(120)
            for p, r in zip(prompts, reqs):
                assert [int(t) for t in r.outputs[0]] \
                    == dense_greedy_reference(model, p, 10)
        finally:
            eng.shutdown()
        snap = get_telemetry().snapshot()
        assert snap["counters"]["serve/spec_proposed"] > 0
        rate = snap["gauges"]["serve/spec_accept_rate"]
        assert 0.0 <= rate <= 1.0
        assert snap["counters"]["serve/spec_accepted"] \
            <= snap["counters"]["serve/spec_proposed"]
        kv = eng.kv_accounting()
        assert kv["leaked_blocks"] == 0
        assert kv["draft"]["leaked_blocks"] == 0

    def test_self_draft_accepts_everything(self):
        """Draft == target ⇒ every proposal verifies: acceptance 1.0 and
        far fewer verify steps than tokens."""
        model = tiny_model()
        eng, _ = make_engine(model=model, draft=model, spec_k=3)
        eng.start()
        try:
            r = eng.submit(np.arange(7, dtype=np.int32), max_new_tokens=12)
            assert r.wait(120)
            assert r.status == RequestStatus.OK
            assert [int(t) for t in r.outputs[0]] \
                == dense_greedy_reference(model, np.arange(7), 12)
        finally:
            eng.shutdown()
        snap = get_telemetry().snapshot()
        assert snap["gauges"]["serve/spec_accept_rate"] == 1.0
        # 12 tokens in ceil((12-1)/4)+small rounds instead of 12 steps
        assert snap["counters"]["serve/decode_steps"] <= 5

    def test_spec_requires_draft(self):
        with pytest.raises(ValueError):
            make_engine(spec_k=2)

    def test_spec_at_max_seq_len_boundary(self):
        """A request whose prompt + budget lands EXACTLY on max_seq_len:
        speculative rounds must not write k tokens past the cap (block
        table / position overflow) — the tail of the generation falls
        back to the plain decode path and the output stays greedy-exact."""
        model = tiny_model()
        eng, _ = make_engine(model=model, draft=tiny_draft(), spec_k=3,
                             max_seq_len=32, kv_blocks=16, kv_block_size=8)
        eng.start()
        try:
            prompt = np.arange(16, dtype=np.int32)
            r = eng.submit(prompt, max_new_tokens=16)  # 16 + 16 == cap
            assert r.wait(120)
            assert r.status == RequestStatus.OK, (r.status, r.detail)
            assert [int(t) for t in r.outputs[0]] \
                == dense_greedy_reference(model, prompt, 16)
        finally:
            eng.shutdown()
        assert eng.kv_accounting()["leaked_blocks"] == 0
        assert eng.kv_accounting()["draft"]["leaked_blocks"] == 0


class TestLoadgenGeneration:
    def test_run_generation_streams_summary(self):
        eng, _ = make_engine()
        eng.start()
        try:
            out = run_generation_streams(
                eng, 2, 2, lambda k: np.arange(4 + k % 3, dtype=np.int32),
                max_new_tokens=5)
        finally:
            eng.shutdown()
        assert out["by_status"] == {"ok": 4}
        assert out["tokens_generated"] == 20
        assert out["tokens_per_s"] > 0
        assert out["streams"] == 2
        assert "ttft_p99_ms" in out and out["ttft_p99_ms"] >= 0
        assert "tpot_p50_ms" in out and out["tpot_p50_ms"] >= 0


# ---------------------------------------------------------------------------
# Drain mid-generation (subprocess SIGTERM) — ISSUE satellite
# ---------------------------------------------------------------------------
_DRAIN_WORKER = textwrap.dedent("""
    import json, os, signal, sys, threading, time
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.text.models.gpt import GPTConfig, GPTForCausalLM
    from paddle_tpu.inference.serving import (TokenServeConfig,
                                              TokenServingEngine)

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=96, hidden_size=32, num_layers=2,
                    num_heads=2, max_position_embeddings=256,
                    hidden_dropout=0.0, attention_dropout=0.0)
    model = GPTForCausalLM(cfg); model.eval()
    eng = TokenServingEngine(model, TokenServeConfig(
        capacity=16, decode_buckets=(1, 2, 4), max_running=4,
        prefill_chunk=8, kv_blocks=128, kv_block_size=8, max_seq_len=240,
        drain_grace_s=0.05))
    eng.install_preemption().start()

    rng = np.random.RandomState(0)
    # N streams with LONG generations; the SIGTERM fires the moment a
    # stream is observably MID-decode (state-triggered, not a wall-clock
    # guess), so the short grace guarantees genuinely-partial DRAINED
    # requests whatever the host speed
    reqs = [eng.submit(rng.randint(0, 96, 10).astype(np.int32),
                       max_new_tokens=200) for _ in range(6)]
    def fire():
        while not any(3 <= len(r.generated) < 150 for r in reqs):
            time.sleep(0.002)
        os.kill(os.getpid(), signal.SIGTERM)
    threading.Thread(target=fire, daemon=True).start()
    for r in reqs:
        r.wait(30.0)
    eng.wait_drained(20.0)
    acct = eng.accounting()
    out = {
        "acct": acct,
        "kv": eng.kv_accounting(),
        "drain_reason": eng.drain_reason,
        "statuses": {r.id: r.status for r in reqs},
        "n_generated": {r.id: len(r.generated) for r in reqs},
        "outputs_present": {r.id: r.outputs is not None for r in reqs},
    }
    with open(os.environ["OUT"], "w") as f:
        json.dump(out, f)
    eng.exit_if_preempted()
    sys.exit(3)  # preemption drain never happened
""")


class TestRequestTracing:
    def test_sampled_generation_timeline(self, monkeypatch):
        """ISSUE 13: a sampled generation request exports ONE
        self-contained timeline — queue → prefill chunks → decode steps
        → terminal — under one trace id."""
        from paddle_tpu.profiler import spans

        monkeypatch.setenv("PADDLE_TPU_TRACE_SAMPLE", "1")
        spans.trace_store().clear()
        # one decode bucket: the timeline needs one compiled entry per
        # kind, not the full bucket ladder's compile bill
        eng, _ = make_engine(decode_buckets=(1,))
        eng.start()
        try:
            # prompt spans 2 prefill chunks (chunk=8), then decodes
            req = eng.submit(np.arange(1, 13, dtype=np.int32),
                             max_new_tokens=4)
            assert req.wait(60) and req.status == RequestStatus.OK
        finally:
            eng.shutdown()
        traces = [t for t in spans.trace_store().snapshot()
                  if t.req_id == req.id]
        assert len(traces) == 1
        names = [n for n, _t0, _d in traces[0].events]
        assert names[0] == "submit" and names[1] == "admit"
        assert "queue" in names
        assert sum(1 for n in names if n.startswith("prefill.c8")) >= 2
        assert any(n.startswith("decode.b") for n in names)
        assert names[-1] == "terminal:ok"
        # lifecycle order: all prefill slices precede the first decode
        assert max(i for i, n in enumerate(names)
                   if n.startswith("prefill.")) < \
            min(i for i, n in enumerate(names) if n.startswith("decode."))
        evs = traces[0].chrome_events(pid=1)
        assert len({e["args"]["trace_id"] for e in evs}) == 1
        spans.trace_store().clear()


class TestDrainMidGeneration:
    def test_sigterm_mid_decode_exits_77_no_leaks(self, tmp_path):
        """ISSUE satellite: subprocess SIGTERM while N streams are
        mid-decode → every request terminal exactly once (OK with partial
        text or DRAINED), exit 77, zero leaked KV blocks."""
        out_path = str(tmp_path / "out.json")
        worker = tmp_path / "worker.py"
        worker.write_text(_DRAIN_WORKER)
        env = {**os.environ, "JAX_PLATFORMS": "cpu", "OUT": out_path,
               "PYTHONPATH": _REPO + os.pathsep
               + os.environ.get("PYTHONPATH", "")}
        env.pop("PADDLE_TPU_INJECT", None)
        r = subprocess.run([sys.executable, str(worker)], env=env,
                           capture_output=True, text=True, timeout=300)
        assert r.returncode == 77, (r.returncode, r.stderr[-2000:])
        with open(out_path) as f:
            out = json.load(f)
        acct = out["acct"]
        assert out["drain_reason"] == "preempted"
        assert acct["unaccounted"] == []
        assert acct["double_terminal"] == 0
        assert acct["submitted"] == 6
        statuses = set(out["statuses"].values())
        assert statuses <= {"ok", "drained"}
        assert "drained" in statuses  # mid-decode SIGTERM + short grace
        # at least one request was drained MID-generation, and its
        # partial text was delivered, not dropped (queued-never-admitted
        # requests drain with no output — that is their contract)
        partial = [rid for rid, s in out["statuses"].items()
                   if s == "drained" and out["n_generated"][rid] > 0]
        assert partial
        for rid in partial:
            assert out["outputs_present"][rid]
            assert out["n_generated"][rid] < 200
        # the KV ledger is clean: zero leaked blocks after the drain
        assert out["kv"]["leaked_blocks"] == 0
        assert out["kv"]["owners"] == []


# ---------------------------------------------------------------------------
# Telemetry schema contracts (ISSUE satellite)
# ---------------------------------------------------------------------------
class TestSchemaContracts:
    def validate(self, scalars):
        sys.path.insert(0, os.path.join(_REPO, "tools"))
        from check_telemetry_schema import validate_record

        return validate_record({"ts": 1.0, "step": None, "tag": "t",
                                "scalars": scalars}, 1)

    def test_new_keys_accepted(self):
        assert self.validate({
            "counter/serve/kv_blocks_alloc": 12,
            "counter/serve/kv_blocks_free": 12,
            "gauge/serve/kv_blocks_total": 16,
            "gauge/serve/kv_blocks_used": 4,
            "gauge/serve/kv_occupancy": 0.25,
            "gauge/serve/spec_accept_rate": 0.8,
            "hist/serve/ttft_ms/p99": 12.5,
            "hist/serve/tpot_ms/p50": 1.5,
            "hist/serve/decode_ms.b4/p50": 3.0,
        }) is None

    def test_negative_kv_counter_rejected(self):
        assert self.validate({"counter/serve/kv_blocks_alloc": -1})

    def test_occupancy_range(self):
        assert self.validate({"gauge/serve/kv_occupancy": 1.2})
        assert self.validate({"gauge/serve/spec_accept_rate": -0.1})

    def test_negative_ttft_rejected(self):
        assert self.validate({"hist/serve/ttft_ms/p50": -3.0})
        assert self.validate({"hist/serve/tpot_ms/max": -1.0})

    def test_kv_cross_field_consistency(self):
        assert self.validate({"gauge/serve/kv_blocks_total": 8,
                              "gauge/serve/kv_blocks_used": 9})
        assert self.validate({"gauge/serve/kv_blocks_total": 8,
                              "gauge/serve/kv_blocks_used": 2,
                              "gauge/serve/kv_occupancy": 0.9})
        assert self.validate({"gauge/serve/kv_blocks_total": 8,
                              "gauge/serve/kv_blocks_used": 2,
                              "gauge/serve/kv_occupancy": 0.25}) is None

    def test_engine_telemetry_passes_schema(self, tmp_path):
        eng, _ = make_engine()
        eng.start()
        try:
            r = eng.submit(np.arange(5, dtype=np.int32), max_new_tokens=6)
            assert r.wait(60)
        finally:
            eng.shutdown()
        path = str(tmp_path / "tel.jsonl")
        get_telemetry().to_jsonl(path, tag="decode_test")
        sys.path.insert(0, os.path.join(_REPO, "tools"))
        from check_telemetry_schema import validate_file

        n, err = validate_file(path, require=[
            "counter/serve/kv_blocks_alloc",
            "counter/serve/kv_blocks_free",
            "gauge/serve/kv_occupancy",
            "counter/serve/tokens_generated"])
        assert err is None, err


# ---------------------------------------------------------------------------
# Full gate (slow)
# ---------------------------------------------------------------------------
@pytest.mark.slow
class TestDecodeGateEndToEnd:
    def test_check_decode_gate_passes(self):
        r = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools",
                                          "check_decode.py"), "--json"],
            capture_output=True, text=True, timeout=580,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert r.returncode == 0, r.stdout + r.stderr
        payload = json.loads(r.stdout)
        assert payload["gate"] == "decode"
        assert payload["status"] == "OK"
        assert payload["kv"]["leaked_blocks"] == 0
