"""Row-sparse embedding gradients (SelectedRows equivalent).

Reference: framework/selected_rows.h:1 (representation),
operators/optimizers/adam_op.h:464 (sparse/lazy Adam rows-only update),
lookup_table_v2 sparse grad. Golden criterion per VERDICT r1 item 4: the
sparse path's numerics must equal the dense path's.
"""
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.core.selected_rows import RowSparseGrad

VOCAB, DIM = 50, 8


def make_pair(seed=0):
    """Two identical embedding layers, one sparse one dense."""
    paddle.seed(seed)
    e_sp = nn.Embedding(VOCAB, DIM, sparse=True)
    e_de = nn.Embedding(VOCAB, DIM, sparse=False)
    e_de.set_state_dict(e_sp.state_dict())
    return e_sp, e_de


def run_steps(layer, opt, ids_batches):
    for ids in ids_batches:
        out = layer(paddle.to_tensor(ids))
        loss = (out * out).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    return layer.weight.numpy()


class TestRowSparseGrad:
    def test_backward_produces_sparse(self):
        e_sp, _ = make_pair()
        ids = np.array([[1, 3, 3], [7, 1, 0]], np.int64)
        out = e_sp(paddle.to_tensor(ids))
        out.sum().backward()
        g = e_sp.weight.grad
        assert isinstance(g, RowSparseGrad)
        assert g.rows.shape == (6,)
        assert g.values.shape == (6, DIM)
        assert g.num_rows == VOCAB

    def test_to_dense_matches_dense_grad(self):
        e_sp, e_de = make_pair()
        ids = np.array([[1, 3, 3], [7, 1, 0]], np.int64)
        for e in (e_sp, e_de):
            out = e(paddle.to_tensor(ids))
            (out * out).sum().backward()
        np.testing.assert_allclose(
            np.asarray(e_sp.weight.grad.to_dense()),
            e_de.weight.grad.numpy(), rtol=1e-6, atol=1e-6)

    def test_merged_combines_duplicates(self):
        rows = jnp.asarray([3, 1, 3, 9], jnp.int32)
        vals = jnp.asarray(np.arange(8, dtype=np.float32).reshape(4, 2))
        g = RowSparseGrad(rows, vals, 10)
        m = g.merged()
        dense_m = np.asarray(m.to_dense())
        np.testing.assert_allclose(dense_m, np.asarray(g.to_dense()))
        # merged has each row at most once (ignoring sentinel padding)
        real = np.asarray(m.rows)[np.asarray(m.rows) < 10]
        assert len(real) == len(set(real.tolist()))

    @pytest.mark.parametrize("opt_name", ["SGD", "Adam", "AdamW"])
    def test_sparse_matches_dense_training(self, opt_name):
        e_sp, e_de = make_pair()
        mk = getattr(optimizer, opt_name)
        kw = {"weight_decay": 0.0} if opt_name == "AdamW" else {}
        o_sp = mk(learning_rate=0.1, parameters=e_sp.parameters(), **kw)
        o_de = mk(learning_rate=0.1, parameters=e_de.parameters(), **kw)
        rng = np.random.RandomState(0)
        batches = [rng.randint(0, VOCAB, (4, 6)).astype(np.int64)
                   for _ in range(4)]
        w_sp = run_steps(e_sp, o_sp, batches)
        w_de = run_steps(e_de, o_de, batches)
        np.testing.assert_allclose(w_sp, w_de, rtol=1e-5, atol=1e-6)

    def test_adam_moments_touch_only_rows(self):
        """Lazy mode: untouched rows keep zero moments — the O(touched)
        contract (reference adam_op.h:464 lazy branch)."""
        e_sp, _ = make_pair()
        opt = optimizer.Adam(learning_rate=0.1, lazy_mode=True,
                             parameters=e_sp.parameters())
        ids = np.array([[2, 5]], np.int64)
        out = e_sp(paddle.to_tensor(ids))
        out.sum().backward()
        opt.step()
        m1 = np.asarray(opt._accumulators[id(e_sp.weight)]["moment1"])
        touched = sorted({2, 5})
        untouched = [i for i in range(VOCAB) if i not in touched]
        assert np.abs(m1[untouched]).max() == 0.0
        assert np.abs(m1[touched]).max() > 0.0

    def test_weight_decay_falls_back_dense_correctly(self):
        e_sp, e_de = make_pair()
        o_sp = optimizer.Adam(learning_rate=0.1, weight_decay=0.01,
                              parameters=e_sp.parameters())
        o_de = optimizer.Adam(learning_rate=0.1, weight_decay=0.01,
                              parameters=e_de.parameters())
        rng = np.random.RandomState(1)
        batches = [rng.randint(0, VOCAB, (3, 4)).astype(np.int64)
                   for _ in range(2)]
        np.testing.assert_allclose(run_steps(e_sp, o_sp, batches),
                                   run_steps(e_de, o_de, batches),
                                   rtol=1e-5, atol=1e-6)

    def test_global_norm_clip_with_sparse(self):
        e_sp, e_de = make_pair()
        clip = nn.ClipGradByGlobalNorm(0.01)
        o_sp = optimizer.SGD(learning_rate=0.5, grad_clip=clip,
                             parameters=e_sp.parameters())
        clip2 = nn.ClipGradByGlobalNorm(0.01)
        o_de = optimizer.SGD(learning_rate=0.5, grad_clip=clip2,
                             parameters=e_de.parameters())
        ids = np.array([[1, 1, 4]], np.int64)
        np.testing.assert_allclose(run_steps(e_sp, o_sp, [ids]),
                                   run_steps(e_de, o_de, [ids]),
                                   rtol=1e-5, atol=1e-6)

    def test_padding_idx_rows_not_updated(self):
        paddle.seed(3)
        e = nn.Embedding(VOCAB, DIM, padding_idx=0, sparse=True)
        before = e.weight.numpy()[0].copy()
        opt = optimizer.SGD(learning_rate=1.0, parameters=e.parameters())
        ids = np.array([[0, 1, 2]], np.int64)
        out = e(paddle.to_tensor(ids))
        out.sum().backward()
        opt.step()
        np.testing.assert_array_equal(e.weight.numpy()[0], before)

    def test_accumulation_two_backwards(self):
        e_sp, e_de = make_pair()
        for e in (e_sp, e_de):
            for ids in (np.array([[1, 2]], np.int64),
                        np.array([[2, 3]], np.int64)):
                out = e(paddle.to_tensor(ids))
                out.sum().backward()
        np.testing.assert_allclose(np.asarray(e_sp.weight.grad.to_dense()),
                                   e_de.weight.grad.numpy(),
                                   rtol=1e-6, atol=1e-6)

    def test_dense_then_sparse_accumulation_tied_use(self):
        """wte used densely (matmul) AND sparsely (lookup) in one graph:
        grads from both uses must combine to a proper dense Tensor grad."""
        e_sp, e_de = make_pair(seed=5)
        for e in (e_sp, e_de):
            ids = np.array([[1, 2, 3]], np.int64)
            emb = e(paddle.to_tensor(ids))
            dense_use = (e.weight * 0.5).sum()
            (emb.sum() + dense_use).backward()
        g_sp = e_sp.weight.grad
        assert not isinstance(g_sp, RowSparseGrad)  # densified Tensor
        np.testing.assert_allclose(g_sp.numpy(), e_de.weight.grad.numpy(),
                                   rtol=1e-6, atol=1e-6)

    @pytest.mark.parametrize("clip_cls", ["ClipGradByValue", "ClipGradByNorm"])
    def test_other_clips_with_sparse(self, clip_cls):
        e_sp, e_de = make_pair(seed=6)
        mk = getattr(nn, clip_cls)
        o_sp = optimizer.SGD(learning_rate=0.5, grad_clip=mk(0.01),
                             parameters=e_sp.parameters())
        o_de = optimizer.SGD(learning_rate=0.5, grad_clip=mk(0.01),
                             parameters=e_de.parameters())
        ids = np.array([[1, 1, 4]], np.int64)
        np.testing.assert_allclose(run_steps(e_sp, o_sp, [ids]),
                                   run_steps(e_de, o_de, [ids]),
                                   rtol=1e-5, atol=1e-6)

    def test_global_norm_clip_ignores_padding_rows(self):
        paddle.seed(8)
        e_sp = nn.Embedding(VOCAB, DIM, padding_idx=0, sparse=True)
        e_de = nn.Embedding(VOCAB, DIM, padding_idx=0, sparse=False)
        e_de.set_state_dict(e_sp.state_dict())
        for e in (e_sp, e_de):
            out = e(paddle.to_tensor(np.array([[0, 1, 2]], np.int64)))
            (out * 3.0).sum().backward()
        sq_sp = float(np.asarray(e_sp.weight.grad.sq_l2norm()))
        sq_de = float((e_de.weight.grad.numpy().astype(np.float64) ** 2).sum())
        np.testing.assert_allclose(sq_sp, sq_de, rtol=1e-5)
