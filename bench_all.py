"""Measure BASELINE.md configs beyond the headline (bench.py = config #4).

Writes one JSON object per config to stdout and the full list to
``BENCH_extra.json``. Mirrors the reference's relative-CI approach
(tools/test_model_benchmark.sh): absolute numbers are recorded per commit
and tracked regression-style, since the reference publishes none.

Configs (BASELINE.md table):
  #1 MNIST LeNet, dygraph, host batches           -> samples/sec
  #2 ResNet-50, static-graph Executor, one chip   -> samples/sec
  #3 BERT-base pretrain, fleet DP engine, one chip-> samples/sec + tok/sec
  #4 long-context GPT-small, L=8192, q-chunked causal XLA attention,
     no recompute (net-new vs the reference)       -> tokens/sec
(#5 ERNIE pp+tp needs a pod slice; its sharding path is validated by
 dryrun_multichip on the virtual mesh.)
  #6 input-pipeline: feed-bound MLP step, DevicePrefetcher on vs off
     -> samples/sec + speedup (net-new; any backend)
  #7 serving: inference.serving closed-loop at N concurrent streams
     -> tokens/sec + p50/p99 latency (net-new; any backend)
  #8 decode: token-level LLM serving (paged KV + continuous batching +
     speculative ablation) vs the one-shot recompute-the-prefix
     Predictor baseline at N=8 streams -> tokens/sec + TTFT/TPOT
     p50/p99 (net-new; any backend)

Usage: python bench_all.py [--smoke]
         [lenet|resnet50|bert|longctx|pipeline|serving|decode]
  (--smoke: tiny shapes, any backend; names select a subset)
"""
from __future__ import annotations

import json
import os
import sys
import time

# full attribution for bench runs: lowered.compile() memory_analysis
# gives the EXACT peak-HBM (argument+output+temp-alias) at the price of
# a second XLA compile per fresh signature — amortized over the ritual,
# and absorbed entirely by the persistent compilation cache where
# configured. The env wins if the rig already set a mode.
os.environ.setdefault("PADDLE_TPU_COST_ANALYSIS", "full")
# bench runs also lint every compiled program (analysis.hlo H-rules):
# the counter/hlolint/findings.* counters ride each config's telemetry
# record, and the HLO_SNAPSHOTS/ dump below feeds the offline
# tools/hlo_lint.py ratchet gate in bench_ritual.sh
os.environ.setdefault("PADDLE_TPU_HLO_LINT", "1")

import jax
import jax.numpy as jnp
import numpy as np

SMOKE = "--smoke" in sys.argv

# v5e bf16 systolic peak; MFU numbers assume the conv/matmul path runs bf16
_PEAK_TFLOPS = {"tpu": 197.0}


def _mfu(samples_per_sec, flops_per_sample):
    peak = _PEAK_TFLOPS.get(jax.default_backend())
    if peak is None:
        return None
    return round(100.0 * samples_per_sec * flops_per_sample / (peak * 1e12), 2)


def _block(out):
    # materialize, don't jax.block_until_ready: on the remote axon
    # platform block_until_ready returns before execution finishes
    # (measured: 30-step windows "completed" in dispatch-only time),
    # while a host transfer genuinely drains the queue
    np.asarray(getattr(out, "_value", out))


def _rate(fn, n_warm, n_iter, reps=3):
    """Median samples/sec of `reps` windows; fn(i) runs one step and
    returns an object to block on."""
    for i in range(n_warm):
        out = fn(i)
    _block(out)
    rates = []
    for _ in range(reps):
        t0 = time.perf_counter()
        for i in range(n_iter):
            out = fn(i)
        _block(out)
        rates.append(n_iter / (time.perf_counter() - t0))
    return sorted(rates)[len(rates) // 2]


def bench_lenet():
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.vision.models import LeNet

    paddle.seed(0)
    net = LeNet()
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=net.parameters())
    b = 64
    rng = np.random.RandomState(0)
    xs = paddle.to_tensor(rng.randn(b, 1, 28, 28).astype(np.float32))
    ys = paddle.to_tensor(rng.randint(0, 10, b).astype(np.int64))

    step = paddle.jit.TrainStep(net, loss_fn=nn.CrossEntropyLoss(),
                                optimizer=opt)

    def one(i):
        return step((xs,), (ys,))

    sps = _rate(one, 3, 5 if SMOKE else 30) * b
    return {"metric": "lenet_mnist_dygraph_samples_per_sec",
            "value": round(sps, 2), "unit": "samples/sec"}


def build_resnet50_train(smoke=False, window=None):
    """BENCH config #2's step, shared with tools/profile_model.py so the
    profiler measures EXACTLY the benchmarked program. Returns
    ``(step, batch_size)``; ``step(_)`` runs one Executor iteration and
    returns the loss fetch (``return_numpy=False``: a numpy fetch would
    block the device every step). With ``window=W`` the step runs W
    training steps as ONE compiled program via ``Executor.run_steps`` —
    per-dispatch latency through the rig's tunnel is ~5-6 ms, a fifth of
    the whole ResNet step, so the window amortization is part of the
    measured config (real long trainings run windows too)."""
    import paddle_tpu as paddle
    from paddle_tpu import static
    from paddle_tpu.vision.models import resnet50

    paddle.seed(0)
    # b=128: stage-1 convs (C<=64) underfill the 128-wide MXU contraction at
    # b=64; doubling the batch improves their occupancy (measured 2518 vs
    # 2281 samples/s at w=10) and still fits HBM with room
    b = 8 if smoke else 128
    size = 32 if smoke else 224
    main = static.Program()
    start = static.Program()
    with static.program_guard(main, start):
        x = static.data("x", [None, 3, size, size], "float32")
        y = static.data("y", [None, 1], "int64")
        model = resnet50(num_classes=100 if smoke else 1000)
        # static AMP O1: convs/matmuls recorded bf16, BN/softmax fp32
        # (the reference decorates the static optimizer with
        # mixed_precision.decorate; recording under auto_cast bakes the
        # same casts into the program). bf16 needs no loss scaling.
        with paddle.amp.auto_cast(enable=not smoke, dtype="bfloat16"):
            logits = model(x)
            loss = paddle.nn.functional.cross_entropy(
                logits, y.reshape([-1]))
        opt = paddle.optimizer.Momentum(learning_rate=0.01, momentum=0.9)
        opt.minimize(loss)
    exe = static.Executor()
    exe.run(start)
    rng = np.random.RandomState(0)
    # device-resident feed: on this rig host->device rides an HTTP tunnel
    # (~40MB of images/step would measure the tunnel, not the chip); real
    # input pipelines keep batches device-side via double-buffered device_put
    xv = paddle.to_tensor(rng.randn(b, 3, size, size).astype(np.float32))
    yv = paddle.to_tensor(
        rng.randint(0, 100 if smoke else 1000, (b, 1)).astype(np.int64))

    if window:
        def step(_i=None):
            return exe.run_steps(main, feed={"x": xv, "y": yv},
                                 fetch_list=[loss], n_steps=window,
                                 return_numpy=False)[0]
    else:
        def step(_i=None):
            return exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss],
                           return_numpy=False)[0]

    return step, b


def bench_resnet50():
    window = None if SMOKE else 20
    one, b = build_resnet50_train(smoke=SMOKE, window=window)
    sps = _rate(one, 2, 3) * b * (window or 1)
    out = {"metric": "resnet50_static_executor_samples_per_sec_per_chip",
           "value": round(sps, 2), "unit": "samples/sec"}
    if not SMOKE:
        # ResNet-50 @224²: ~4.1 GFLOP forward, ~3x for fwd+bwd
        mfu = _mfu(sps, 3 * 4.1e9)
        if mfu is not None:
            out["mfu_pct"] = mfu
    return out


def bench_bert_dp():
    import paddle_tpu as paddle
    from jax.sharding import Mesh
    from paddle_tpu.distributed.fleet.engine import ParallelTrainStep
    from paddle_tpu.text.models.bert import (BertForPretraining, bert_base,
                                             bert_tiny)

    paddle.seed(0)
    config = bert_tiny() if SMOKE else bert_base(hidden_dropout=0.0,
                                                 attention_dropout=0.0)
    b, L = (4, 64) if SMOKE else (32, 128)  # phase-1 pretrain shape
    # fleet DP engine; one chip here = dp world of 1, the same compiled
    # path the 8-device CPU-mesh parity tests exercise with dp=8
    mesh = Mesh(np.array(jax.devices()[:1]), ("dp",))
    rng = np.random.RandomState(0)
    ids = rng.randint(0, config.vocab_size, (b, L)).astype(np.int32)
    mlm = np.where(rng.rand(b, L) < 0.15, ids, -100).astype(np.int32)
    nsp = rng.randint(0, 2, b).astype(np.int64)

    # silent-corruption defense cost (resilience.integrity): the same
    # config built with in-jit state fingerprinting, measured with the
    # fold firing on EVERY timed step (fingerprint_every=1) — at the
    # production interval of 100 the due step would land inside _rate's
    # warmup and the timed window (<100 steps) would price only the
    # cond-false branch, never the tree reduction the column exists to
    # bound. The per-fold cost divided by the production interval is the
    # amortized overhead the "<1% step time at fingerprint_every=100"
    # acceptance bar is judged on. Measured BEFORE the headline leg so
    # (a) the fp engine pays any process cold-start tax (conservative
    # bias) and (b) a telemetry reset leaves the headline record
    # carrying ONLY the main engine's attribution. FRESH model +
    # optimizer per engine: the jitted step donates the arrays the
    # layer handed it, so a second engine over the same objects would
    # read deleted buffers.
    _FP_PRODUCTION_EVERY = 100
    paddle.seed(0)
    model_fp = BertForPretraining(config)
    opt_fp = paddle.optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01,
                                    parameters=model_fp.parameters())
    step_fp = ParallelTrainStep(
        model_fp, loss_fn=model_fp.loss_fn, optimizer=opt_fp, mesh=mesh,
        compute_dtype=None if SMOKE else jnp.bfloat16,
        fingerprint_every=1)
    # 20 smoke iters (not the usual 3): this column is a RATIO of two
    # rates, so per-leg noise doubles — 3-iter CPU rates swing ±11%
    sps_fp = _rate(lambda i: step_fp((ids,), (mlm, nsp)),
                   2, 20 if SMOKE else 30) * b
    del step_fp, model_fp, opt_fp
    from paddle_tpu.profiler import get_telemetry

    get_telemetry().reset()

    paddle.seed(0)
    model = BertForPretraining(config)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01,
                                 parameters=model.parameters())
    step = ParallelTrainStep(
        model, loss_fn=model.loss_fn, optimizer=opt, mesh=mesh,
        compute_dtype=None if SMOKE else jnp.bfloat16)

    def one(i):
        return step((ids,), (mlm, nsp))

    sps = _rate(one, 2, 20 if SMOKE else 30) * b
    fold_pct = (sps / sps_fp - 1.0) * 100  # fold cost as % of a step
    out = {"metric": "bert_base_dp_pretrain_samples_per_sec_per_chip",
           "value": round(sps, 2), "unit": "samples/sec",
           "tokens_per_sec": round(sps * L, 2),
           "fingerprint_samples_per_sec": round(sps_fp, 2),
           "fingerprint_fold_overhead_pct": round(fold_pct, 3),
           "fingerprint_overhead_pct": round(
               fold_pct / _FP_PRODUCTION_EVERY, 4)}
    if not SMOKE:
        # 6·N FLOP/token with N = transformer params (BERT-base ~86M
        # non-embedding) + MLM head matmul 2·h·V fwd ·3
        n_tr = 86e6
        flops_tok = 6 * n_tr + 6 * config.hidden_size * config.vocab_size
        mfu = _mfu(sps * L, flops_tok)
        if mfu is not None:
            out["mfu_pct"] = mfu
    return out


def bench_gpt_long_context():
    """Long-context end-to-end: GPT-small at L=8192 on ONE chip. Net-new
    vs the reference (SURVEY §5: long-context absent there).

    r5 configuration (each measured): the causal-chunked XLA attention
    tier + NO step-level recompute — 46.5-47.0k tok/s vs r4's 27.5k
    (flash_tpu Mosaic + full recompute); dots-policy remat measured
    36.4k, full remat 35.8k, manual attention VJP (O(L) remat residuals)
    46.2k. The chunked tier's autodiff residuals are the ~0.53·L² bf16
    exp weights (~0.85 GB/layer, ~10 GB total) — they fit v5e HBM at
    b=1; b=2 OOMs in every variant, so b=1 is the measured shape.

    r5 second pass: chunk size c=256 (32 chunks, now the tier default at
    this L) measured 58.5-60.0k tok/s (+24-27%; c=512/128/64 all worse —
    the attention here is HBM-bound on ~4 mandatory passes over the
    score-space tiles, and c=256 balances tile-size against causal-stair
    waste). The official pallas flash kernel measured 58.7 ms/layer
    fwd+bwd vs this tier's 8.3 at the same shape (Mosaic via this rig's
    remote compile service is ~7x off the pace — same wall as r4's own
    kernels), so the XLA-level tier stands.
    MFU/vs_baseline framing follows bench.py's A100 methodology with the
    causal-attention term included (at L=8192 attention is ~38% of model
    FLOPs).

    PR 8 additions: (1) the attention tier is now chosen by MEASUREMENT —
    the config runs under ``PADDLE_TPU_ATTN_POLICY=bench`` (the TPU
    default, forced here so CPU CI exercises the same path) with the
    persistent tier cache wired, so the first trace micro-benches the
    feasible tiers and every later run is a cache hit; (2) a
    ``tokens_per_sec_forced_blockwise`` ablation column records what the
    pre-policy streaming floor measures, so the tier win is a recorded
    number, not a claim; (3) a remat control-loop probe pins the HBM
    budget to 60% of the no-remat peak and records which checkpoint
    policy ``remat='auto'`` escalates to and the peak it measured —
    attribution-gauge proof that the ladder lowers peak HBM on THIS
    config when capacity demands it."""
    import tempfile

    import paddle_tpu as paddle
    from jax.sharding import Mesh
    from paddle_tpu.distributed.fleet.engine import ParallelTrainStep
    from paddle_tpu.ops import remat_policy, tier_policy
    from paddle_tpu.profiler import get_telemetry
    from paddle_tpu.text.models.gpt import GPTConfig, GPTForCausalLM

    if SMOKE:
        config = GPTConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                           num_heads=4, max_position_embeddings=512,
                           hidden_dropout=0.0, attention_dropout=0.0)
        b, L, iters = 1, 512, 2
    else:
        config = GPTConfig(hidden_size=768, num_layers=12, num_heads=12,
                           max_position_embeddings=8192,
                           hidden_dropout=0.0, attention_dropout=0.0)
        b, L, iters = 1, 8192, 10

    # no recompute on the real config: the chunked tier's exp-weight
    # residuals (~10 GB, see docstring) fit HBM at this b=1 shape, and
    # remat would trade ~25% throughput for capacity that isn't needed.
    # Smoke keeps full remat ON deliberately — it is the only place the
    # remat × longctx-model compose is exercised off-TPU (the real
    # config's remat-off program is compiled by the full run itself).
    def build_engine(remat=None):
        paddle.seed(0)
        model = GPTForCausalLM(config)
        opt = paddle.optimizer.Adam(learning_rate=1e-4,
                                    parameters=model.parameters())
        mesh = Mesh(np.array(jax.devices()[:1]), ("dp",))
        return ParallelTrainStep(
            model, loss_fn=model.loss_fn, optimizer=opt, mesh=mesh,
            remat=("full" if SMOKE else "off") if remat is None else remat,
            compute_dtype=None if SMOKE else jnp.bfloat16)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, config.vocab_size, (b, L)).astype(np.int32)
    labels = np.roll(ids, -1, axis=1).astype(np.int32)
    ids = paddle.to_tensor(ids)
    labels = paddle.to_tensor(labels)

    def measure(engine, n_iter):
        return _rate(lambda i: engine((ids,), (labels,)), 1, n_iter) * b * L

    tel = get_telemetry()
    saved_env = {k: os.environ.get(k) for k in
                 ("PADDLE_TPU_ATTN_POLICY", "PADDLE_TPU_ATTN_TIER_CACHE",
                  "PADDLE_TPU_DEVICE_HBM_BYTES")}
    try:
        # -- tier ablation leg: the forced streaming floor ---------------
        os.environ["PADDLE_TPU_ATTN_POLICY"] = "blockwise"
        engine = build_engine()
        abl_tps = measure(engine, max(2, iters // 2))
        del engine

        # -- measured tier selection for the remaining legs --------------
        if saved_env["PADDLE_TPU_ATTN_POLICY"] is None:
            os.environ["PADDLE_TPU_ATTN_POLICY"] = "bench"
        else:
            os.environ["PADDLE_TPU_ATTN_POLICY"] = \
                saved_env["PADDLE_TPU_ATTN_POLICY"]
        if tier_policy.cache_path() is None:
            # no compile-cache dir on this rig: still exercise the
            # persistent verdict cache end-to-end via a scratch file
            os.environ["PADDLE_TPU_ATTN_TIER_CACHE"] = os.path.join(
                tempfile.mkdtemp(prefix="paddle_tpu_bench_"),
                "attn_tiers.json")
        tier_policy.reset()  # in-memory verdicts; the disk cache decides

        # -- remat control-loop probe ------------------------------------
        probe = build_engine(remat="auto")  # deferred build; probed by hand
        remat_cols = {}
        off = probe.lower_cost("off", (ids,), (labels,))
        if off is not None:
            os.environ["PADDLE_TPU_DEVICE_HBM_BYTES"] = str(
                max(int(off["peak_hbm_bytes"] * 0.6), 1))
            chosen = remat_policy.resolve(
                "fleet.train_step",
                lambda p: probe.lower_cost(p, (ids,), (labels,)))
            auto_peak = tel.scalars().get(
                "gauge/remat/peak_hbm/fleet.train_step")
            remat_cols = {
                "remat_off_peak_hbm_bytes": off["peak_hbm_bytes"],
                "remat_auto_policy": chosen,
                "remat_auto_peak_hbm_bytes": auto_peak,
            }
            del os.environ["PADDLE_TPU_DEVICE_HBM_BYTES"]
        del probe

        # -- the headline leg: measured tier selection, clean telemetry --
        tel.reset()  # the record must carry ONLY this leg's attribution
        engine = build_engine()
        tps = measure(engine, iters)
    finally:
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    tier_id = tel.scalars().get(
        f"gauge/attn/tier.{tier_policy.gauge_key(L, config.hidden_size // config.num_heads, True)}")
    id_to_name = {v: k for k, v in tier_policy.TIER_IDS.items()}
    out = {"metric": "gpt_small_L8192_longctx_train_tokens_per_sec",
           "value": round(tps, 1), "unit": "tokens/sec",
           "seq_len": L,
           "tokens_per_sec_forced_blockwise": round(abl_tps, 1),
           "tier_ablation_speedup": round(tps / abl_tps, 3),
           "attn_tier_selected": id_to_name.get(tier_id, "unknown")}
    out.update(remat_cols)
    if not SMOKE:
        # 6·N_matmul + causal attention 6·L·h·n_layers per token
        n_mat = (12 * config.num_layers * config.hidden_size ** 2
                 + config.vocab_size * config.hidden_size)
        flops_tok = 6 * n_mat + 6 * L * config.hidden_size * config.num_layers
        mfu = _mfu(tps, flops_tok)
        if mfu is not None:
            out["mfu_pct"] = mfu
        # bench.py's A100 north-star methodology: 90% of an A100 chip at a
        # typical 45% training MFU (312 TF/s bf16 peak)
        out["vs_baseline"] = round(tps / (0.9 * 0.45 * 312e12 / flops_tok), 4)
    return out


def bench_input_pipeline():
    """Device-resident input pipeline (io.DevicePrefetcher): steady-state
    train throughput with the background prefetch pipeline ON vs OFF.

    The config models the streaming-loader shape the prefetcher exists
    for: each batch costs a fixed ACQUISITION latency (30 ms sleep — the
    stand-in for a disk/GCS/feature-store read; pure wait, no CPU) plus
    real decode work (uint8 → f32 + per-row normalize), and the train
    loop fetches the loss scalar every step (the hapi fit/logging
    pattern — that host sync is exactly what stops the inline loop from
    hiding source latency behind JAX's async dispatch). OFF pays
    acquire+decode+step serially; ON overlaps acquire/decode/H2D with
    the in-flight step, so the steady-state step time collapses toward
    max(source, compute). The headline value is the ON rate;
    ``prefetch_off_samples_per_sec``/``speedup`` record the contrast.
    Sleep-based source latency keeps the contrast stable on a small-host
    rig where compute already saturates the cores (a pure CPU-overlap
    formulation measures core contention there, not the pipeline)."""
    import paddle_tpu as paddle
    from paddle_tpu import nn

    paddle.seed(0)
    b, d = (32, 64) if SMOKE else (256, 1024)
    n_batches = 6 if SMOKE else 30
    acquire_s = 0.003 if SMOKE else 0.030
    net = nn.Sequential(nn.Linear(d, d), nn.ReLU(), nn.Linear(d, d),
                        nn.ReLU(), nn.Linear(d, 10))
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=net.parameters())
    step = paddle.jit.TrainStep(net, loss_fn=nn.CrossEntropyLoss(),
                                optimizer=opt)
    rng = np.random.RandomState(0)
    payloads = [rng.randint(0, 256, (b, d)).astype(np.uint8)
                for _ in range(8)]
    ys = rng.randint(0, 10, b).astype(np.int64)

    def batches():
        for i in range(n_batches):
            time.sleep(acquire_s)  # source latency (I/O wait, no CPU)
            raw = payloads[i % len(payloads)]
            x = raw.astype(np.float32) / 255.0
            x = (x - x.mean(axis=1, keepdims=True)) / (
                x.std(axis=1, keepdims=True) + 1e-6)
            yield (x,), (ys,)

    def epoch(prefetch):
        it = step.prefetch(batches(), depth=2) if prefetch else batches()
        tot = 0.0
        for inp, lab in it:
            tot += float(step(inp, lab).numpy())  # per-step loss logging
        return tot

    epoch(False)  # warmup: compile the step off the clock

    def rate(prefetch, reps=3):
        vals = []
        for _ in range(reps):
            t0 = time.perf_counter()
            epoch(prefetch)
            vals.append(n_batches * b / (time.perf_counter() - t0))
        return sorted(vals)[len(vals) // 2]

    off = rate(False)
    on = rate(True)
    return {"metric": "input_pipeline_prefetch_samples_per_sec",
            "value": round(on, 2), "unit": "samples/sec",
            "prefetch_off_samples_per_sec": round(off, 2),
            "speedup": round(on / off, 3)}


def bench_serving():
    """Serving runtime (inference.serving): closed-loop request latency
    and throughput at N concurrent synchronous streams — the deployment
    twin of the training configs. Each request carries L "tokens" (an
    [L, d] activation through a 3-layer MLP), so tokens/s is comparable
    across request sizes. ONE batch bucket sized to the concurrency
    (every dispatch pads to it): a single compiled executable, and the
    attribution headline (serve.step.b<N> + serve/batch_ms.b<N>) is the
    bucket every batch actually hit — per-bucket MFU is the denominator,
    occupancy the packing efficiency. The closed loop never sheds (no
    deadline, capacity ≥ streams): any non-OK status here is a bug, and
    the record carries the full serve/* telemetry for the schema gate.
    The OVERLOAD side (2x offered load, injected stragglers, SIGTERM
    drain) is tools/check_serving.py's job, not a latency bench's."""
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.inference import Config, create_predictor
    from paddle_tpu.inference.serving import (ServeConfig, ServingEngine,
                                              run_streams)
    from paddle_tpu.profiler import get_telemetry

    paddle.seed(0)
    L, d = (16, 64) if SMOKE else (128, 512)
    streams = 2 if SMOKE else 16
    per_stream = 4 if SMOKE else 40
    net = nn.Sequential(nn.Linear(d, d), nn.ReLU(), nn.Linear(d, d),
                        nn.ReLU(), nn.Linear(d, d))
    net.eval()
    cfg = Config()
    cfg.set_layer(net, [paddle.jit.InputSpec([None, L, d], "float32", "x")])
    engine = ServingEngine(create_predictor(cfg), ServeConfig(
        capacity=4 * streams, buckets=(streams,)))
    engine.start()  # warmup: the bucket compiles before the clock starts
    rng = np.random.RandomState(0)
    xs = rng.randn(32, L, d).astype(np.float32)
    try:
        run_streams(engine, streams, 2, lambda k: [xs[k % len(xs)]])  # warm
        out = run_streams(engine, streams, per_stream,
                          lambda k: [xs[k % len(xs)]])
    finally:
        acct = engine.shutdown()
    n = streams * per_stream
    if acct["unaccounted"] or acct["double_terminal"] \
            or out["by_status"].get("ok", 0) != n:
        raise AssertionError(
            f"closed-loop serving shed or lost requests: {out['by_status']}, "
            f"unaccounted={acct['unaccounted']}, "
            f"double_terminal={acct['double_terminal']}")
    occ = get_telemetry().hist_summary("serve/batch_occupancy") or {}
    return {"metric": "serving_closed_loop_tokens_per_sec",
            "value": round(out["ok_per_s"] * L, 1), "unit": "tokens/sec",
            "streams": streams, "tokens_per_request": L,
            "requests_per_sec": round(out["ok_per_s"], 2),
            "p50_ms": round(out["p50_ms"], 3),
            "p99_ms": round(out["p99_ms"], 3),
            "batch_occupancy_p50": round(occ.get("p50", 0.0), 3),
            "warmup_compile_ms": round(engine.warmup_ms[streams], 1)}


def bench_decode():
    """Token-level LLM serving (inference.serving.decode): greedy
    generation at N=8 concurrent streams through decode-step continuous
    batching over the paged KV cache, against the ONE-SHOT baseline the
    runtime replaces — a Predictor recomputing the full prefix every
    token (PR 7's serving shape). Same workload both legs (8 streams x
    identical prompts x same token budget), tokens/s = generated tokens
    / wall.

    Ablation columns: the one-shot baseline (`oneshot_tokens_per_sec`,
    `continuous_batching_speedup`) and speculative decoding
    (`spec_tokens_per_sec`, `spec_accept_rate` — a tiny draft model
    proposing k=3). TTFT/TPOT p50/p99 come from the request objects'
    own stamps; decode-step MFU attribution comes from the per-entry
    cost records (serve.decode.b<N> entries own serve/decode_ms.b<N>).
    The spec and baseline legs run FIRST so the headline record's
    last-compiled entry is the main leg's decode executable."""
    import paddle_tpu as paddle
    from paddle_tpu import nn  # noqa: F401  (predictor path imports)
    from paddle_tpu.inference import Config, create_predictor
    from paddle_tpu.inference.serving import (TokenServeConfig,
                                              TokenServingEngine,
                                              run_generation_streams)
    from paddle_tpu.profiler import get_telemetry
    from paddle_tpu.text.models.gpt import GPTConfig, GPTForCausalLM

    streams = 8
    if SMOKE:
        P, T, per_stream = 48, 16, 2
        mcfg = dict(vocab_size=512, hidden_size=128, num_layers=2,
                    num_heads=4)
    else:
        P, T, per_stream = 256, 64, 4
        mcfg = dict(vocab_size=2048, hidden_size=256, num_layers=4,
                    num_heads=8)
    Lmax = P + T
    paddle.seed(0)
    model = GPTForCausalLM(GPTConfig(
        max_position_embeddings=Lmax, hidden_dropout=0.0,
        attention_dropout=0.0, **mcfg))
    model.eval()
    paddle.seed(3)
    draft = GPTForCausalLM(GPTConfig(
        vocab_size=mcfg["vocab_size"], hidden_size=mcfg["hidden_size"] // 2,
        num_layers=1, num_heads=2, max_position_embeddings=Lmax,
        hidden_dropout=0.0, attention_dropout=0.0))
    draft.eval()
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, mcfg["vocab_size"], P).astype(np.int32)
               for _ in range(streams)]
    bs = 16
    kv_blocks = streams * (Lmax // bs + 1) + 8

    def serve_cfg(spec_k=0):
        return TokenServeConfig(
            capacity=4 * streams, decode_buckets=(1, 2, 4, 8),
            max_running=streams, prefill_chunk=min(P, 32),
            kv_blocks=kv_blocks, kv_block_size=bs, max_seq_len=Lmax,
            spec_k=spec_k)

    def run_leg(engine):
        engine.start()
        try:
            run_generation_streams(  # warm: every entry compiled
                engine, streams, 1,
                lambda k: prompts[k % streams], max_new_tokens=4)
            out = run_generation_streams(
                engine, streams, per_stream,
                lambda k: prompts[k % streams], max_new_tokens=T)
        finally:
            acct = engine.shutdown()
        want_ok = streams * (per_stream + 1)  # warm + timed rounds
        if acct["unaccounted"] or acct["double_terminal"] \
                or engine.kv_accounting()["leaked_blocks"] \
                or acct["by_status"].get("ok", 0) != want_ok:
            raise AssertionError(f"decode bench lost requests or blocks: "
                                 f"{acct}, {engine.kv_accounting()}")
        return out

    tel = get_telemetry()

    # leg 1 (first — its compiles must not be the headline entry):
    # speculative ablation
    spec = run_leg(TokenServingEngine(model, serve_cfg(spec_k=3),
                                      draft_model=draft))
    accept = tel.snapshot()["gauges"].get("serve/spec_accept_rate", 0.0)

    # leg 2: one-shot baseline — a Predictor over the full padded
    # context, recomputing the whole prefix for every generated token
    # (all 8 streams batched per step, which FAVORS the baseline: it
    # gets perfect batching for free)
    cfg = Config()
    cfg.set_layer(model, [paddle.jit.InputSpec([None, Lmax], "int64",
                                               "ids")])
    predictor = create_predictor(cfg)
    raw_fn = predictor.serving_fn()

    def serving_logits(arr):  # serving_fn returns a tuple of outputs
        out = raw_fn(jnp.asarray(arr))
        return np.asarray(out[0] if isinstance(out, (list, tuple)) else out)

    ids = np.zeros((streams, Lmax), np.int64)
    for s in range(streams):
        ids[s, :P] = prompts[s]
    serving_logits(ids)  # warm the compile off the clock
    t0 = time.perf_counter()
    n_base_tokens = 0
    for rep in range(per_stream):
        cur = ids.copy()
        ln = P
        for _ in range(T):
            logits = serving_logits(cur)
            nxt = logits[:, ln - 1].argmax(-1)
            cur[:, ln] = nxt
            ln += 1
            n_base_tokens += streams
    oneshot_tps = n_base_tokens / (time.perf_counter() - t0)

    # leg 3 (last — the headline attribution entry): plain continuous
    # batching. kv_evictions is reported as THIS leg's delta — counters
    # are process-cumulative and the spec leg's double pool pressure
    # must not masquerade as headline-config thrash
    ev0 = tel.counter_value("serve/kv_evictions")
    out = run_leg(TokenServingEngine(model, serve_cfg()))
    evictions = tel.counter_value("serve/kv_evictions") - ev0
    return {"metric": "decode_serving_tokens_per_sec",
            "value": round(out["tokens_per_s"], 1), "unit": "tokens/sec",
            "streams": streams, "prompt_len": P, "max_new_tokens": T,
            "oneshot_tokens_per_sec": round(oneshot_tps, 1),
            "continuous_batching_speedup":
                round(out["tokens_per_s"] / max(oneshot_tps, 1e-9), 3),
            "spec_tokens_per_sec": round(spec["tokens_per_s"], 1),
            "spec_accept_rate": round(float(accept), 4),
            "ttft_p50_ms": round(out.get("ttft_p50_ms", 0.0), 3),
            "ttft_p99_ms": round(out.get("ttft_p99_ms", 0.0), 3),
            "tpot_p50_ms": round(out.get("tpot_p50_ms", 0.0), 3),
            "tpot_p99_ms": round(out.get("tpot_p99_ms", 0.0), 3),
            "kv_evictions": int(evictions)}


def _dump_hlo_snapshots(config_name):
    """Write every program this config compiled to
    ``HLO_SNAPSHOTS/<config>/<entry>.hlo.txt`` plus a ``MANIFEST.json``
    carrying the compile-time context (registered mesh, amp policy) —
    the corpus tools/hlo_lint.py self-runs over in bench_ritual.sh.
    Free under PADDLE_TPU_COST_ANALYSIS=full (the text was stashed at
    compile time); best-effort like every attribution surface."""
    import shutil

    from paddle_tpu.profiler import collective_attrib, xla_cost

    try:
        texts = xla_cost.hlo_texts()
        if not texts:
            return
        bf16 = False
        try:
            from paddle_tpu.amp.auto_cast import amp_state

            st = amp_state()
            bf16 = bool(st.enabled) and "float16" in str(st.dtype)
        except Exception:
            pass
        d = os.path.join("HLO_SNAPSHOTS", config_name)
        shutil.rmtree(d, ignore_errors=True)  # no stale entries linger
        os.makedirs(d, exist_ok=True)
        for entry, text in sorted(texts.items()):
            safe = entry.replace("/", "_")
            with open(os.path.join(d, safe + ".hlo.txt"), "w") as f:
                f.write(text)
        with open(os.path.join(d, "MANIFEST.json"), "w") as f:
            json.dump({"config": config_name,
                       "mesh": collective_attrib.registered_axes(),
                       "bf16_policy": bf16,
                       "entries": sorted(texts)}, f, indent=1)
            f.write("\n")
    except Exception as e:
        print(f"hlo snapshot dump failed for {config_name}: {e}",
              file=sys.stderr)


def _merge_telemetry_record(tel, tag, extra, step):
    """Replace THIS config's record in TELEMETRY.jsonl, keeping every
    other config's — a subset run (`bench_all.py serving`) must not
    truncate the other configs' recorded telemetry (twin of the
    per-metric BENCH_extra.json merge in main)."""
    kept = []
    try:
        with open("TELEMETRY.jsonl") as f:
            for ln in f:
                if not ln.strip():
                    continue
                try:
                    if json.loads(ln).get("tag") == tag:
                        continue
                except Exception:
                    pass  # drop ONLY the unparseable line (torn write)
                else:
                    kept.append(ln)
    except OSError:
        pass
    with open("TELEMETRY.jsonl", "w") as f:
        f.writelines(kept)
    tel.to_jsonl("TELEMETRY.jsonl", step=step, tag=tag, extra=extra,
                 append=True)


def main():
    only = [a.lstrip("-") for a in sys.argv[1:] if a.lstrip("-") in
            ("lenet", "resnet50", "bert", "longctx", "pipeline", "serving",
             "decode")]
    table = {"lenet": bench_lenet, "resnet50": bench_resnet50,
             "bert": bench_bert_dp, "longctx": bench_gpt_long_context,
             "pipeline": bench_input_pipeline, "serving": bench_serving,
             "decode": bench_decode}
    from paddle_tpu.profiler import (bottleneck, collective_attrib,
                                     device_profile, get_telemetry,
                                     xla_cost)

    tel = get_telemetry()
    results = []
    for name, fn in table.items():
        if only and name not in only:
            continue
        # per-config isolation: configs share entry names (lenet and
        # pipeline both drive jit.train_step) and histograms accumulate,
        # so without a reset a config's MFU would blend the previous
        # config's step times — and a config whose attribution silently
        # broke would inherit the previous one's sticky gauges, defeating
        # check_attribution. reset() also zeroes retrace trackers and the
        # cost registry, so every record carries ONLY its own config.
        tel.reset()
        r = fn()
        r["backend"] = jax.default_backend()
        r["smoke"] = SMOKE
        # attribution columns (profiler.xla_cost): XLA's own FLOPs/HBM
        # accounting for the entry this config just compiled, and the
        # MEASURED MFU from its step-latency histogram — the denominator
        # the hand-derived mfu_pct estimates above are checked against
        row = xla_cost.headline(tel)
        if row is not None:
            r["attribution_entry"] = row["entry"]
            r["compile_flops"] = row["flops"]
            r["compile_bytes_accessed"] = row["bytes_accessed"]
            r["compile_peak_hbm_bytes"] = row["peak_hbm_bytes"]
            if row.get("verdict"):
                r["roofline"] = row["verdict"]
            if "mfu_pct" in row:
                r["mfu_measured_pct"] = round(row["mfu_pct"], 3)
                r["hbm_gbps_achieved"] = round(row["hbm_gbps"], 3)
        # automated bottleneck verdict (profiler.bottleneck): folds any
        # device-profile decomposition captured during this config with
        # the roofline/MFU gauges into one word per entry. The headline
        # entry's verdict and its dominating numbers become columns —
        # check_bench_trajectory names the suspect from exactly these on
        # a regression.
        # per-axis collective attribution (profiler.collective_attrib):
        # the compiled HLO's collectives mapped onto the registered mesh
        # axes — on multi-dev configs the headline entry grows
        # collective_<axis>_{bytes,count}[,_ms] columns (bytes/count are
        # static per-step inventory; ms appears when a device capture
        # ran). These are attribution movers for check_bench_trajectory:
        # a regression whose collective_dp_ms doubled names its suspect.
        # Published BEFORE the verdicts so comm_bound refines per-axis.
        head_entry = row["entry"] if row is not None else None
        try:
            collective_attrib.publish_static(tel)
            if head_entry is not None:
                for axis, crow in sorted(
                        collective_attrib.entry_summary(head_entry)
                        .items()):
                    r[f"collective_{axis}_bytes"] = crow.get("bytes", 0.0)
                    r[f"collective_{axis}_count"] = crow.get("count", 0.0)
                    if "ms" in crow:
                        r[f"collective_{axis}_ms"] = round(crow["ms"], 4)
        except Exception:
            pass  # attribution must never fail a bench record
        verdicts = bottleneck.publish(tel)
        if head_entry in verdicts:
            r["bottleneck"] = verdicts[head_entry]["verdict"]
            for k, v in verdicts[head_entry]["evidence"].items():
                if isinstance(v, (int, float)) and k.endswith("_frac"):
                    r[f"profile_{k}"] = round(float(v), 4)
        fracs = device_profile.publish(tel).get(head_entry or "", {})
        for cat, v in fracs.items():
            r.setdefault(f"profile_{cat}", round(float(v), 4))
        # hlo-lint: the compile-time hook counted findings per rule as
        # this config's programs compiled; the total is an attribution
        # mover for check_bench_trajectory (a regression that arrived
        # with new lint findings names them as the suspect), and the
        # snapshot dump feeds the offline ratchet gate in bench_ritual
        r["hlolint_findings"] = sum(
            v for k, v in tel.counter_scalars().items()
            if k.startswith("counter/hlolint/findings."))
        # goodput columns (profiler.goodput): tel.reset() above swapped
        # in a fresh ledger, so this snapshot attributes ONLY this
        # config's wall clock — the fraction and per-category seconds
        # become trajectory movers (a config whose input_wait_s doubled
        # names its suspect without a profiler run)
        try:
            from paddle_tpu.profiler import goodput as _goodput

            gsnap = _goodput.snapshot()
            if gsnap["wall_s"] > 0:
                r["goodput_fraction"] = round(gsnap["fraction"], 4)
                for cat, secs in gsnap["categories"].items():
                    if secs > 0:
                        r[f"goodput_{cat}_s"] = round(secs, 3)
        except Exception:
            pass  # attribution must never fail a bench record
        _dump_hlo_snapshots(name)
        print(json.dumps(r), flush=True)
        # machine-readable telemetry, one record per config written the
        # moment the config finishes — its gauge/compile/* and gauge/mfu
        # reflect THIS config's compiles/steps (headline = last-compiled
        # entry), so tools/check_attribution.py genuinely gates every
        # config rather than re-validating the final snapshot N times
        extra = {k: v for k, v in r.items()
                 if isinstance(v, (int, float)) and not isinstance(v, bool)}
        _merge_telemetry_record(tel, f"bench/{r['metric']}", extra,
                                step=len(results))
        results.append(r)
    if not SMOKE:
        # merge with any previously recorded configs (per-config runs)
        try:
            with open("BENCH_extra.json") as f:
                old = {r["metric"]: r for r in json.load(f)}
        except Exception:
            old = {}
        for r in results:
            old[r["metric"]] = r
        with open("BENCH_extra.json", "w") as f:
            json.dump(list(old.values()), f, indent=1)


if __name__ == "__main__":
    main()
