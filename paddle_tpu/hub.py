"""paddle.hub — re-export shim (parity:
/root/reference/python/paddle/hub.py)."""
from .hapi.hub import help  # noqa: F401
from .hapi.hub import list  # noqa: F401
from .hapi.hub import load  # noqa: F401

__all__ = ["list", "help", "load"]
