"""paddle.batch — the fluid-era reader batcher (parity:
/root/reference/python/paddle/batch.py). Legacy training loops wrap sample
readers with it before feeding Executor/DataFeeder."""
from __future__ import annotations

__all__ = ["batch"]


def batch(reader, batch_size, drop_last=False):
    """Transform a sample-level reader creator into a batch-level one.

    ``reader``: callable returning an iterable of samples. Returns a
    reader creator whose iterator yields lists of ``batch_size`` samples
    (the trailing partial batch is kept unless ``drop_last``).
    """
    if batch_size <= 0:
        raise ValueError(f"batch_size must be positive, got {batch_size}")

    def batch_reader():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batch_reader
