"""paddle.dataset reader-API compat — parity with
python/paddle/dataset/ (mnist.py, cifar.py, imdb.py, imikolov.py,
uci_housing.py, movielens.py, conll05.py, wmt14.py, wmt16.py, flowers.py).

The reference's legacy data layer exposes *reader creators*:
``paddle.dataset.mnist.train()`` returns a zero-arg callable (the reader),
and calling THAT yields sample tuples — the two-level convention the old
``fluid.io``/``paddle.batch`` pipeline composes over. Each creator here is a
thin adapter over the map-style Datasets in ``paddle_tpu.vision/.text``
(which already handle local files + zero-egress synthetic fallback), so
legacy training scripts port unchanged while new code uses
``paddle_tpu.io.DataLoader``. Submodules are registered in ``sys.modules``
so ``import paddle_tpu.dataset.mnist`` works like the reference.
"""
from __future__ import annotations

import sys
import types

import numpy as np


def _reader_from(dataset_factory, transform=None):
    """Build a reader: a zero-arg callable yielding transformed samples."""

    def reader():
        ds = dataset_factory()
        for i in range(len(ds)):
            sample = ds[i]
            if transform is not None:
                yield transform(sample)
            elif isinstance(sample, (list, tuple)):
                yield tuple(sample)
            else:
                yield sample

    return reader


def _creator(dataset_factory, transform=None):
    """Reader *creator*: calling it returns the reader callable (the
    reference's ``mnist.train()`` convention)."""

    def create(*_a, **_k):
        return _reader_from(dataset_factory, transform)

    return create


def _module(name, **attrs):
    m = types.ModuleType(f"{__name__}.{name}")
    for k, v in attrs.items():
        setattr(m, k, v)
    sys.modules[m.__name__] = m
    return m


def _flat_sample(sample):
    """(image, label) → (1-D float32 image, int label) — the legacy layout."""
    img, label = sample
    return (np.asarray(img, np.float32).reshape(-1),
            int(np.asarray(label).ravel()[0]))


def _make_mnist():
    from ..vision.datasets import MNIST

    return _module(
        "mnist",
        train=_creator(lambda: MNIST(mode="train"), _flat_sample),
        test=_creator(lambda: MNIST(mode="test"), _flat_sample),
    )


def _make_cifar():
    from ..vision.datasets import Cifar10, Cifar100

    return _module(
        "cifar",
        train10=_creator(lambda: Cifar10(mode="train"), _flat_sample),
        test10=_creator(lambda: Cifar10(mode="test"), _flat_sample),
        train100=_creator(lambda: Cifar100(mode="train"), _flat_sample),
        test100=_creator(lambda: Cifar100(mode="test"), _flat_sample),
    )


def _make_uci_housing():
    from ..text.datasets import UCIHousing

    return _module(
        "uci_housing",
        train=_creator(lambda: UCIHousing(mode="train")),
        test=_creator(lambda: UCIHousing(mode="test")),
    )


def _make_imdb():
    from ..text.datasets import Imdb

    def pair(sample):
        doc, label = sample
        return list(np.asarray(doc)), int(label)

    return _module(
        "imdb",
        train=_creator(lambda: Imdb(mode="train"), pair),
        test=_creator(lambda: Imdb(mode="test"), pair),
        word_dict=lambda: Imdb(mode="train").word_idx,
    )


def _make_imikolov():
    from ..text.datasets import Imikolov

    def build_dict(min_word_freq=50):
        return Imikolov(mode="train", min_word_freq=min_word_freq).word_idx

    def train(word_idx=None, n=5, data_type="NGRAM"):
        return _reader_from(
            lambda: Imikolov(mode="train", data_type=data_type, window_size=n))

    def test(word_idx=None, n=5, data_type="NGRAM"):
        return _reader_from(
            lambda: Imikolov(mode="test", data_type=data_type, window_size=n))

    return _module("imikolov", build_dict=build_dict, train=train, test=test)


def _make_movielens():
    from ..text.datasets import Movielens

    return _module(
        "movielens",
        train=_creator(lambda: Movielens(mode="train")),
        test=_creator(lambda: Movielens(mode="test")),
    )


def _make_conll05():
    from ..text.datasets import Conll05st

    return _module(
        "conll05",
        test=_creator(lambda: Conll05st()),
        get_dict=lambda: Conll05st().get_dict(),
    )


def _make_wmt14():
    from ..text.datasets import WMT14

    return _module(
        "wmt14",
        train=lambda dict_size=1000: _reader_from(
            lambda: WMT14(mode="train", dict_size=dict_size)),
        test=lambda dict_size=1000: _reader_from(
            lambda: WMT14(mode="test", dict_size=dict_size)),
    )


def _make_wmt16():
    from ..text.datasets import WMT16

    return _module(
        "wmt16",
        train=lambda src_dict_size=1000, trg_dict_size=1000: _reader_from(
            lambda: WMT16(mode="train", src_dict_size=src_dict_size,
                          trg_dict_size=trg_dict_size)),
        test=lambda src_dict_size=1000, trg_dict_size=1000: _reader_from(
            lambda: WMT16(mode="test", src_dict_size=src_dict_size,
                          trg_dict_size=trg_dict_size)),
    )


def _make_flowers():
    from ..vision.datasets import Flowers

    return _module(
        "flowers",
        train=_creator(lambda: Flowers(), _flat_sample),
        test=_creator(lambda: Flowers(), _flat_sample),
    )


mnist = _make_mnist()
cifar = _make_cifar()
uci_housing = _make_uci_housing()
imdb = _make_imdb()
imikolov = _make_imikolov()
movielens = _make_movielens()
conll05 = _make_conll05()
wmt14 = _make_wmt14()
wmt16 = _make_wmt16()
flowers = _make_flowers()

__all__ = ["mnist", "cifar", "uci_housing", "imdb", "imikolov", "movielens",
           "conll05", "wmt14", "wmt16", "flowers"]
