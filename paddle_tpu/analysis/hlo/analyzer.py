"""hlo-lint driver: findings, analysis context, and the rule loop.

:class:`HloFinding` deliberately subclasses the AST linter's ``Finding``
so the shared ratchet (``analysis.baseline``) and renderers
(``analysis.report``) work unchanged — only the field *semantics* shift:
``path`` is the compiled entry's label (or a snapshot file), ``line`` is
the 1-based line in the HLO text, and ``context`` is the instruction's
name stem (trailing SSA counter stripped — ``%dot.3`` and ``%dot.17``
are the same program point across recompiles, which is what keeps
baseline keys stable).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Optional

from ..analyzer import Finding
from .parsing import HloInstr, HloModule, parse_module

__all__ = ["HloFinding", "AnalysisContext", "analyze_hlo_text",
           "analyze_module"]


class HloFinding(Finding):
    """One finding over one compiled entry's optimized HLO.

    Same surface as the AST ``Finding`` (``key()`` / ``to_dict()`` /
    ``path``/``line``/``col``/``context``), so ``baseline.compare`` and
    ``report.render_*`` need no second implementation.
    """


@dataclasses.dataclass
class AnalysisContext:
    """What the rules need to know beyond the HLO text itself.

    ``entry`` labels every finding's ``path``. ``mesh_axes`` (ordered
    ``{axis: size}``) arms the mesh-aware rules H6/H7 — empty means "no
    mesh registered", and those rules stay silent rather than guess.
    ``bf16_policy`` arms H2's f32-matmul check (an f32 dot is only a
    hazard when the program was *supposed* to be bf16). Thresholds keep
    the byte/FLOP rules quiet on trivia; the CLI and the compile-time
    hook both construct one of these.
    """

    entry: str = "<hlo>"
    mesh_axes: Dict[str, int] = dataclasses.field(default_factory=dict)
    bf16_policy: bool = False
    h1_min_waste: float = 0.10   # flag dots wasting >= 10% of MXU FLOPs
    h1_min_flops: float = 1e6    # ... but only ops worth >= 1 MFLOP
    h3_min_bytes: float = float(1 << 20)   # copies/transposes >= 1 MiB
    h7_min_bytes: float = float(4 << 20)   # replicated params >= 4 MiB

    def mesh_desc(self) -> str:
        return "{" + ", ".join(f"{k}:{v}"
                               for k, v in self.mesh_axes.items()) + "}"


def make_finding(rule, ctx: AnalysisContext, instr: Optional[HloInstr],
                 message: str, line: int = 0,
                 context: Optional[str] = None) -> HloFinding:
    """One finding anchored at ``instr`` (or an explicit line for
    module-level findings). Rules funnel through here so severity/hint
    stay in the rule metadata and the key stays (entry, rule, stem)."""
    if instr is not None:
        line = instr.line
        if context is None:
            context = instr.stem
        src = instr.source_src()
        if src != "?":
            message = f"{message} [{src}]"
    return HloFinding(
        rule=rule.id, severity=rule.severity, path=ctx.entry, line=line,
        col=0, message=message, hint=rule.hint,
        context=context if context is not None else "<module>")


def analyze_module(module: HloModule,
                   ctx: Optional[AnalysisContext] = None,
                   select: Optional[Iterable[str]] = None
                   ) -> List[HloFinding]:
    """Run every (selected) rule over one parsed module."""
    from .hlo_rules import HLO_RULES  # late: rules import this module

    ctx = ctx or AnalysisContext()
    chosen = set(select) if select else None
    findings: List[HloFinding] = []
    for rule in HLO_RULES.values():
        if chosen is not None and rule.id not in chosen:
            continue
        findings.extend(rule.check(module, ctx))
    findings.sort(key=lambda f: (f.line, f.rule, f.context))
    return findings


def analyze_hlo_text(text: str,
                     ctx: Optional[AnalysisContext] = None,
                     select: Optional[Iterable[str]] = None
                     ) -> List[HloFinding]:
    """Parse one optimized-HLO text and run the H-rules over it — the
    single entry point the CLI, the compile-time hook, and the tests
    share."""
    return analyze_module(parse_module(text), ctx, select)
