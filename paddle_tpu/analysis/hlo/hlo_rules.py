"""hlo-lint rules H1–H8: compiled-program hazards the AST linter cannot
see, keyed to the regressions the ROADMAP chases (padding waste and
missed sharding for the layout planner, collective anti-patterns from
the PR 13 axis work, the static-executor host gap).

Each rule is metadata (id, severity, title, fix hint) plus a whole-
module check over the parsed :class:`~.parsing.HloModule`. Adding a
rule = one ``Rule`` entry with its check function. Checks are
best-effort by contract: an instruction whose operands or attributes
don't resolve is skipped, never guessed at.
"""
from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from . import axes as _axes
from .analyzer import AnalysisContext, HloFinding, make_finding
from .parsing import (COLLECTIVE_OPCODES, DONE_OPCODES, HloComputation,
                      HloInstr, HloModule)

__all__ = ["HLO_RULES", "Rule"]


@dataclass(frozen=True)
class Rule:
    id: str
    severity: str
    title: str
    hint: str
    check: Callable[[HloModule, AnalysisContext], List[HloFinding]]


# MXU/VPU tiling (pallas guide): lane dim is always 128; the sublane
# minimum depends on dtype width — f32 tiles (8,128), bf16 (16,128),
# int8/fp8 (32,128). A dot whose M/N/K sit between tile multiples is
# silently padded up and the padding FLOPs are real wall-clock.
_SUBLANE = {"f32": 8, "f16": 16, "bf16": 16, "s8": 32, "u8": 32,
            "f8e4m3fn": 32, "f8e5m2": 32}
_LANE = 128

_INDEX_RE = re.compile(r"\bindex=(\d+)")
_DIM_LABELS_RE = re.compile(r"dim_labels=([\w?]+)_([\w?]+)->([\w?]+)")
_HOST_TARGET_RE = re.compile(r"host|callback|py_func|cpu_", re.IGNORECASE)


def _pad(n: int, mult: int) -> int:
    return ((n + mult - 1) // mult) * mult


def _prod(vals) -> int:
    out = 1
    for v in vals:
        out *= int(v)
    return out


def _operand_shape(comp: HloComputation, name: str
                   ) -> Optional[Tuple[str, Tuple[int, ...]]]:
    instr = comp.by_name().get(name)
    if instr is None:
        return None
    shapes = instr.shapes()
    return shapes[0] if shapes else None


def _dot_mnk(comp: HloComputation, instr: HloInstr
             ) -> Optional[Tuple[int, int, int, int]]:
    """(B, M, N, K) of one dot, from its operand shapes and
    contracting/batch dim attributes; None when anything is missing."""
    if len(instr.operands) < 2:
        return None
    lhs = _operand_shape(comp, instr.operands[0])
    rhs = _operand_shape(comp, instr.operands[1])
    if lhs is None or rhs is None:
        return None
    ldims, rdims = lhs[1], rhs[1]
    lcd = instr.attr_dims("lhs_contracting_dims") or ()
    rcd = instr.attr_dims("rhs_contracting_dims") or ()
    lbd = instr.attr_dims("lhs_batch_dims") or ()
    rbd = instr.attr_dims("rhs_batch_dims") or ()
    if not lcd or max(lcd, default=-1) >= len(ldims) \
            or max(rcd, default=-1) >= len(rdims) \
            or max(lbd, default=-1) >= len(ldims) \
            or max(rbd, default=-1) >= len(rdims):
        return None
    k = _prod(ldims[d] for d in lcd)
    b = _prod(ldims[d] for d in lbd)
    m = _prod(d for i, d in enumerate(ldims) if i not in lcd and i not in lbd)
    n = _prod(d for i, d in enumerate(rdims) if i not in rcd and i not in rbd)
    return b, m, n, k


def _conv_mnk(comp: HloComputation, instr: HloInstr
              ) -> Optional[Tuple[int, int, int, int]]:
    """(B=1, M, N, K) of one convolution viewed as the implicit GEMM the
    MXU runs: M = batch x output spatial, K = Cin x kernel spatial,
    N = Cout — dims located via the dim_labels attribute."""
    m = _DIM_LABELS_RE.search(instr.body)
    if not m or len(instr.operands) < 2:
        return None
    kernel_labels, out_labels = m.group(2), m.group(3)
    kernel = _operand_shape(comp, instr.operands[1])
    out_shapes = instr.shapes()
    if kernel is None or not out_shapes:
        return None
    kdims, odims = kernel[1], out_shapes[0][1]
    if len(kdims) != len(kernel_labels) or len(odims) != len(out_labels):
        return None
    try:
        cin = kdims[kernel_labels.index("i")]
        cout = kdims[kernel_labels.index("o")]
        f_out = out_labels.index("f")
    except ValueError:
        return None
    k_spatial = _prod(d for i, d in enumerate(kdims)
                      if kernel_labels[i] not in ("i", "o"))
    m_out = _prod(d for i, d in enumerate(odims) if i != f_out)
    return 1, m_out, cout, cin * k_spatial


def _check_h1(module: HloModule, ctx: AnalysisContext) -> List[HloFinding]:
    out: List[HloFinding] = []
    rule = HLO_RULES["H1"]
    for comp in module.computations.values():
        for instr in comp.instrs:
            if instr.opcode == "dot":
                mnk = _dot_mnk(comp, instr)
            elif instr.opcode == "convolution":
                mnk = _conv_mnk(comp, instr)
            else:
                continue
            if mnk is None:
                continue
            b, m, n, k = mnk
            if min(m, n, k) <= 0:
                continue
            shapes = instr.shapes()
            dtype = shapes[0][0] if shapes else "f32"
            sub = _SUBLANE.get(dtype, 8)
            pm, pn, pk = _pad(m, sub), _pad(n, _LANE), _pad(k, _LANE)
            flops = 2.0 * b * m * n * k
            waste = 1.0 - (m * n * k) / float(pm * pn * pk)
            if flops < ctx.h1_min_flops or waste < ctx.h1_min_waste:
                continue
            out.append(make_finding(
                rule, ctx, instr,
                f"{instr.opcode} M×N×K = {m}×{n}×{k} "
                f"pads to {pm}×{pn}×{pk} "
                f"({dtype} tile {sub}×{_LANE}): "
                f"~{waste:.0%} of MXU FLOPs are padding"))
    return out


def _check_h2(module: HloModule, ctx: AnalysisContext) -> List[HloFinding]:
    out: List[HloFinding] = []
    rule = HLO_RULES["H2"]
    for comp in module.computations.values():
        for instr in comp.instrs:
            wide = sorted({dt for dt, _ in instr.shapes()
                           if dt in ("f64", "c128")})
            if wide:
                out.append(make_finding(
                    rule, ctx, instr,
                    f"{instr.opcode} produces {'/'.join(wide)} — TPU has "
                    f"no f64 units, this runs emulated or downcast"))
                continue
            if ctx.bf16_policy and instr.opcode in ("dot", "convolution"):
                shapes = instr.shapes()
                if shapes and shapes[0][0] == "f32":
                    out.append(make_finding(
                        rule, ctx, instr,
                        f"f32 {instr.opcode} compiled while a bf16 "
                        f"autocast policy is active — this matmul "
                        f"escaped the policy"))
    return out


def _check_h3(module: HloModule, ctx: AnalysisContext) -> List[HloFinding]:
    out: List[HloFinding] = []
    rule = HLO_RULES["H3"]
    for comp in module.computations.values():
        for instr in comp.instrs:
            if instr.opcode not in ("copy", "transpose"):
                continue
            nbytes = instr.result_bytes()
            if nbytes >= ctx.h3_min_bytes:
                out.append(make_finding(
                    rule, ctx, instr,
                    f"layout-change {instr.opcode} moves "
                    f"{nbytes / (1 << 20):.1f} MiB"))
    return out


_HOST_OPCODES = {"infeed", "outfeed", "send", "recv", "send-done",
                 "recv-done"}


def _check_h4(module: HloModule, ctx: AnalysisContext) -> List[HloFinding]:
    out: List[HloFinding] = []
    rule = HLO_RULES["H4"]
    flagged = set()
    for comp in module.computations.values():
        for instr in comp.instrs:
            if instr.opcode != "while":
                continue
            for called in instr.called_computations():
                for sub in module.reachable_from(called):
                    for si in sub.instrs:
                        is_host = si.opcode in _HOST_OPCODES
                        if not is_host and si.opcode == "custom-call":
                            target = si.custom_call_target() or ""
                            is_host = bool(_HOST_TARGET_RE.search(target))
                        if not is_host or (sub.name, si.name) in flagged:
                            continue
                        flagged.add((sub.name, si.name))
                        what = (si.custom_call_target()
                                if si.opcode == "custom-call"
                                else si.opcode)
                        out.append(make_finding(
                            rule, ctx, si,
                            f"{what} inside while body %{sub.name} — "
                            f"one host round-trip per loop iteration"))
    return out


def _axis_of(instr: HloInstr, mesh: Dict[str, int]) -> str:
    """The mapped mesh-axis label of one collective instruction (the
    pure-math twin of collective_attrib's mapping, taking the mesh
    explicitly)."""
    if instr.opcode.startswith("collective-permute"):
        from .parsing import parse_pairs

        return _axes.map_pairs_to_axis(parse_pairs(instr.body) or [], mesh)
    groups = _axes.expand_world(instr.replica_groups(), mesh)
    return _axes.map_groups_to_axes(groups or [], mesh)


def _check_h5(module: HloModule, ctx: AnalysisContext) -> List[HloFinding]:
    out: List[HloFinding] = []
    rule = HLO_RULES["H5"]
    for comp in module.computations.values():
        users = comp.users()
        # (a) all-gather immediately consumed by dynamic-slice: each
        # device gathers everything then keeps a slice — a reduce-scatter
        # (or no gather at all) moves 1/shard of the bytes
        for instr in comp.instrs:
            if instr.opcode not in ("all-gather", "all-gather-start"):
                continue
            consumers = []
            for u in users.get(instr.name, []):
                if u.opcode in DONE_OPCODES:
                    consumers.extend(users.get(u.name, []))
                else:
                    consumers.append(u)
            ds = next((u for u in consumers
                       if u.opcode == "dynamic-slice"), None)
            if ds is not None:
                out.append(make_finding(
                    rule, ctx, instr,
                    f"all-gather result is consumed by dynamic-slice "
                    f"%{ds.name} — a reduce-scatter (or sharded consumer) "
                    f"would move 1/shard of the bytes"))
        # (b) same-group all-reduces that could be bucketed into one
        by_groups: Dict[frozenset, List[HloInstr]] = {}
        for instr in comp.instrs:
            if instr.opcode not in ("all-reduce", "all-reduce-start"):
                continue
            groups = instr.replica_groups()
            if groups is None:
                continue
            key = frozenset(frozenset(g) for g in groups) or frozenset({()})
            by_groups.setdefault(key, []).append(instr)
        for instrs in by_groups.values():
            if len(instrs) < 2:
                continue
            first = instrs[0]
            axis = (_axis_of(first, ctx.mesh_axes)
                    if ctx.mesh_axes else None)
            label = f" on axis {axis}" if axis and axis != _axes.UNMAPPED \
                else ""
            out.append(make_finding(
                rule, ctx, first,
                f"{len(instrs)} all-reduces over identical replica "
                f"groups{label} in %{comp.name} — bucket them into one "
                f"launch (latency is per-launch, not per-byte)"))
        # (c) a collective inside a while body whose operand is passed
        # through the loop unchanged recomputes the same result every
        # iteration — hoist it above the loop
        for instr in comp.instrs:
            if instr.opcode != "while":
                continue
            for called in instr.called_computations():
                body = module.computations.get(called)
                if body is None:
                    continue
                out.extend(_invariant_collectives(rule, ctx, body))
    return out


def _invariant_collectives(rule: Rule, ctx: AnalysisContext,
                           body: HloComputation) -> List[HloFinding]:
    params = body.params()
    root = body.root
    if len(params) != 1 or root is None or root.opcode != "tuple":
        return []
    param_name = params[0].name
    # tuple element j is invariant when the root's j-th operand is a
    # get-tuple-element(param) of index j — the value rides the loop
    # carry untouched
    invariant = set()
    for instr in body.instrs:
        if instr.opcode != "get-tuple-element" \
                or param_name not in instr.operands:
            continue
        m = _INDEX_RE.search(instr.body)
        if not m:
            continue
        j = int(m.group(1))
        if j < len(root.operands) and root.operands[j] == instr.name:
            invariant.add(instr.name)
    out = []
    for instr in body.instrs:
        if instr.opcode in DONE_OPCODES \
                or instr.opcode not in COLLECTIVE_OPCODES:
            continue
        inv = next((op for op in instr.operands if op in invariant), None)
        if inv is not None:
            out.append(make_finding(
                rule, ctx, instr,
                f"{instr.opcode} operand %{inv} is loop-invariant "
                f"(carried through %{body.name} unchanged) — hoist the "
                f"collective out of the while"))
    return out


def _check_h6(module: HloModule, ctx: AnalysisContext) -> List[HloFinding]:
    if not ctx.mesh_axes:
        return []
    out: List[HloFinding] = []
    rule = HLO_RULES["H6"]
    for comp in module.computations.values():
        for instr in comp.instrs:
            if instr.opcode in DONE_OPCODES \
                    or instr.opcode not in COLLECTIVE_OPCODES:
                continue
            if _axis_of(instr, ctx.mesh_axes) == _axes.UNMAPPED:
                out.append(make_finding(
                    rule, ctx, instr,
                    f"{instr.opcode} replica groups match no axis of the "
                    f"registered mesh {ctx.mesh_desc()} — the layout "
                    f"planner cannot price this collective"))
    return out


def _check_h7(module: HloModule, ctx: AnalysisContext) -> List[HloFinding]:
    if not any(size > 1 for size in ctx.mesh_axes.values()):
        return []
    entry = module.entry_computation()
    if entry is None:
        return []
    out: List[HloFinding] = []
    rule = HLO_RULES["H7"]
    for p in entry.params():
        if p.sharding() != "replicated":
            continue
        nbytes = p.result_bytes()
        if nbytes < ctx.h7_min_bytes:
            continue
        out.append(make_finding(
            rule, ctx, p,
            f"parameter {p.type_text} ({nbytes / (1 << 20):.1f} MiB) is "
            f"replicated on every device of mesh {ctx.mesh_desc()} — "
            f"shard it along a mesh axis"))
    return out


def _check_h8(module: HloModule, ctx: AnalysisContext) -> List[HloFinding]:
    entry = module.entry_computation()
    if entry is None:
        return []
    root = entry.root
    if root is None or root.opcode != "tuple":
        return []
    out: List[HloFinding] = []
    rule = HLO_RULES["H8"]
    by_name = entry.by_name()
    param_names = {p.name for p in entry.params()}

    def passthrough_of(name: str) -> Optional[str]:
        """The parameter this output returns unchanged (possibly through
        the copy XLA inserts for aliased outputs), else None."""
        if name in param_names:
            return name
        instr = by_name.get(name)
        if instr is not None and instr.opcode == "copy" \
                and len(instr.operands) == 1 \
                and instr.operands[0] in param_names:
            return instr.operands[0]
        return None

    seen: Dict[str, int] = {}
    for i, op in enumerate(root.operands):
        src = passthrough_of(op)
        if src is not None:
            out.append(make_finding(
                rule, ctx, root,
                f"entry output #{i} returns parameter %{src} unchanged — "
                f"drop it from the fetch list (it is fetched, transferred "
                f"and never produced)", context=f"{root.stem}#{i}"))
        elif op in seen:
            out.append(make_finding(
                rule, ctx, root,
                f"entry output #{i} duplicates output #{seen[op]} "
                f"(%{op}) — fetch it once", context=f"{root.stem}#{i}"))
        else:
            seen[op] = i
    return out


HLO_RULES: Dict[str, Rule] = {r.id: r for r in [
    Rule("H1", "warning", "MXU padding waste",
         "pad-aware sizing: pick batch/feature dims that are multiples "
         "of the dtype tile (f32 8×128, bf16 16×128, MXU "
         "128×128) — or fold the ragged dim into a padded bucket "
         "(io.ShapeBuckets) so XLA pads once, not per step.",
         _check_h1),
    Rule("H2", "error", "dtype hazard",
         "f64 never runs natively on TPU; an f32 dot under a bf16 "
         "policy means an input bypassed amp.auto_cast (a constant, a "
         "loaded buffer, or an op outside the policy's op set) — cast "
         "the operand or extend the policy.",
         _check_h2),
    Rule("H3", "warning", "large layout-change copy",
         "a multi-MiB copy/transpose is XLA repairing a layout mismatch "
         "— keep producers and consumers in one layout (donate buffers, "
         "avoid host-round-trips that reset layouts, check "
         "dimension_order of custom kernels).",
         _check_h3),
    Rule("H4", "error", "host round-trip inside device loop",
         "an infeed/outfeed/host callback inside a compiled while body "
         "stalls the loop on the host every iteration — move host I/O "
         "outside the loop, or replace the callback with an in-graph op.",
         _check_h4),
    Rule("H5", "warning", "collective anti-pattern",
         "gather-then-slice wants reduce-scatter; same-group all-reduces "
         "want one bucketed launch; a collective over a loop-invariant "
         "operand wants hoisting above the while.",
         _check_h5),
    Rule("H6", "warning", "collective unmapped to mesh",
         "the replica groups match no registered mesh axis (and no axis "
         "product) — re-express the sharding over the mesh axes, or "
         "register the real mesh, so per-axis attribution and the "
         "layout planner can price it.",
         _check_h6),
    Rule("H7", "warning", "large replicated parameter",
         "a mesh axis exists but this parameter is materialized fully "
         "on every device — shard it (NamedSharding over a mesh axis) "
         "or mark it intentionally replicated in the baseline with a "
         "comment.",
         _check_h7),
    Rule("H8", "info", "dead computation output",
         "every entry output is fetched and transferred each step — "
         "returning an input unchanged (or the same value twice) pays "
         "D2H bandwidth for nothing; prune the fetch list.",
         _check_h8),
]}
