"""hlo-lint — post-compile static analysis of optimized HLO programs.

The runtime analogue of tpu-lint's AST pass: where tpu-lint reads the
*Python source* for tracer hazards before anything compiles, this
package reads the *compiled artifact* — the optimized HLO text
``profiler.xla_cost.capture`` already stashes per ``tracked_jit`` entry
(never a second lowering) — for the hazards only the compiled program
can show: MXU padding waste, dtype downgrades, layout-change copies,
host round-trips inside device loops, collective anti-patterns,
unmapped/missed sharding, and dead fetch outputs (rules H1–H8).

Layout (mirrors the AST side one directory up):

- ``parsing``   — the structured HLO text parser (modules /
  computations / instructions), the ONE home of the low-level helpers
  ``profiler.hlo_attrib`` and ``profiler.collective_attrib`` also use;
- ``axes``      — the pure replica-group → mesh-axis mapper (the
  framework-facing wrapper with the registered-mesh default lives in
  ``profiler.collective_attrib``);
- ``hlo_rules`` — rule metadata + checks (H1–H8);
- ``analyzer``  — :class:`HloFinding` and :func:`analyze_hlo_text`.

The ratchet store and renderers are shared with tpu-lint
(``..baseline`` / ``..report``): an :class:`HloFinding` exposes the
same ``key()`` / ``path`` / ``context`` surface, so the Infer-style
baseline mechanics needed no second implementation. CLI front end:
``tools/hlo_lint.py``; opt-in compile-time hook:
``PADDLE_TPU_HLO_LINT=1`` (see ``profiler.xla_cost``).

Like the rest of ``paddle_tpu/analysis``, this package imports no
framework and no jax — ``tools/hlo_lint.py`` loads it standalone.
"""
from .analyzer import AnalysisContext, HloFinding, analyze_hlo_text
from .hlo_rules import HLO_RULES
from .parsing import (HloComputation, HloInstr, HloModule, parse_module,
                      shape_bytes)

__all__ = [
    "AnalysisContext", "HloFinding", "analyze_hlo_text", "HLO_RULES",
    "HloComputation", "HloInstr", "HloModule", "parse_module",
    "shape_bytes",
]
