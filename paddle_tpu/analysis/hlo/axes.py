"""Pure replica-group → mesh-axis mapping.

The math behind PR 13's per-axis collective attribution, hoisted out of
``profiler.collective_attrib`` so the standalone linter (rules H5/H6)
can name axes without importing the framework. These functions take the
mesh explicitly as an ordered ``{axis_name: size}`` dict; the
framework-facing wrappers in ``profiler.collective_attrib`` keep their
``registered_axes()`` default on top of these.

Partition ids are assumed row-major over the mesh axis order — jax's
own device-array layout, which is how GSPMD numbers them. Matching is
exact set equality: attribution (and lint) never guesses, anything
non-canonical degrades to :data:`UNMAPPED`.
"""
from __future__ import annotations

import itertools
from typing import Dict, List, Tuple

__all__ = ["UNMAPPED", "strides", "expected_groups",
           "map_groups_to_axes", "map_pairs_to_axis", "expand_world"]

UNMAPPED = "unmapped"


def strides(sizes: List[int]) -> List[int]:
    st = [1] * len(sizes)
    for i in range(len(sizes) - 2, -1, -1):
        st[i] = st[i + 1] * sizes[i + 1]
    return st


def expected_groups(axes: Dict[str, int],
                    subset: Tuple[str, ...]) -> frozenset:
    """The canonical group set of a collective over ``subset`` of the
    mesh axes: members vary along the subset, everything else fixed."""
    names = list(axes)
    sizes = [axes[n] for n in names]
    stride = dict(zip(names, strides(sizes)))
    complement = [n for n in names if n not in subset]
    groups = []
    for fixed in itertools.product(*[range(axes[n]) for n in complement]):
        base = sum(f * stride[n] for n, f in zip(complement, fixed))
        members = []
        for var in itertools.product(*[range(axes[n]) for n in subset]):
            members.append(base + sum(v * stride[n]
                                      for n, v in zip(subset, var)))
        groups.append(frozenset(members))
    return frozenset(groups)


def map_groups_to_axes(groups: List[Tuple[int, ...]],
                       axes: Dict[str, int]) -> str:
    """The axis label of a replica-group set: the MINIMAL subset of
    mesh axes whose expected grouping matches exactly ("dp", or "dp+tp"
    for a flattened multi-axis group), else ``unmapped``."""
    if not axes or not groups:
        return UNMAPPED
    canonical = frozenset(frozenset(g) for g in groups)
    names = list(axes)
    # smallest subsets first; ties broken by mesh axis order so a
    # degenerate (size-1) axis match is deterministic
    for k in range(1, len(names) + 1):
        for subset in itertools.combinations(names, k):
            if expected_groups(axes, subset) == canonical:
                return "+".join(subset)
    return UNMAPPED


def map_pairs_to_axis(pairs: List[Tuple[int, int]],
                      axes: Dict[str, int]) -> str:
    """The axis of a ``collective-permute``: every (source, target) pair
    must differ along exactly one non-trivial mesh axis — the ring axis
    of PR 8's sp rotation. Anything else is ``unmapped``."""
    if not axes or not pairs:
        return UNMAPPED
    names = list(axes)
    sizes = [axes[n] for n in names]
    stride = strides(sizes)

    def coords(idx: int) -> Tuple[int, ...]:
        return tuple((idx // stride[i]) % sizes[i]
                     for i in range(len(names)))

    for i, name in enumerate(names):
        if sizes[i] <= 1:
            continue
        ok = True
        for s, t in pairs:
            cs, ct = coords(s), coords(t)
            if cs[i] == ct[i] or any(cs[j] != ct[j]
                                     for j in range(len(names)) if j != i):
                ok = False
                break
        if ok:
            return name
    return UNMAPPED


def expand_world(groups, axes: Dict[str, int]):
    """XLA's ``replica_groups={}`` is shorthand for ONE group of ALL
    devices — expand it against the mesh so the global reduction maps to
    the full axis product instead of degrading to unmapped."""
    if groups == [] and axes:
        world = 1
        for size in axes.values():
            world *= size
        return [tuple(range(world))]
    return groups
