"""Structured parser over optimized HLO text.

One home for the HLO-text primitives that used to live as private
helpers in ``profiler.hlo_attrib`` (instruction/opcode split) and
``profiler.collective_attrib`` (shape bytes, replica-group forms) —
both now import from here, and the hlo-lint rules get the structure
they need (computations, operands, users, called computations) from
the same single parse.

Scope and tolerance match the profiler layer: this is a *line* parser
for the text ``Compiled.as_text()`` emits (`name = type opcode(...),
attrs, metadata={...}`), not a full HLO grammar. Unrecognized lines are
skipped; instructions missing attributes simply report them absent.
Everything here is framework-free (stdlib; numpy only lazily, for the
iota replica-group form) so ``tools/hlo_lint.py`` can load it without
importing jax.
"""
from __future__ import annotations

import dataclasses
import re
from typing import Dict, Iterator, List, Optional, Tuple

__all__ = [
    "DTYPE_BYTES", "COLLECTIVE_OPCODES", "DONE_OPCODES",
    "HloInstr", "HloComputation", "HloModule",
    "iter_instruction_lines", "opcode_of", "opcode_and_type",
    "parse_shapes", "shape_bytes", "parse_group_sets", "parse_pairs",
    "parse_module",
]

# every opcode the collective inventory claims (async halves map to
# their base op); kept aligned with hlo_attrib's category vocabulary
COLLECTIVE_OPCODES = {
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute", "collective-broadcast",
    "all-reduce-start", "all-gather-start", "collective-permute-start",
}
# the *-done halves carry no replica_groups; the start half owns the
# instance (counting both would double every async collective)
DONE_OPCODES = {"all-reduce-done", "all-gather-done",
                "collective-permute-done"}

# dtype token -> bytes per element (token/opaque types carry no payload)
DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "f8e4m3b11fnuz": 1, "f8e4m3fnuz": 1, "f8e5m2fnuz": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

NAME_RE = re.compile(r"^(?:ROOT\s+)?%?([\w.\-]+)\s+=\s+(.*)$")
_SHAPE_RE = re.compile(r"([a-z]\d*[a-z0-9]*)\[([\d,]*)\]")
_GROUPS_LITERAL_RE = re.compile(
    r"replica_groups=\{(\{[\d,\s]*\}(?:,\s*\{[\d,\s]*\})*)?\}")
_GROUPS_IOTA_RE = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?")
_PAIRS_RE = re.compile(
    r"source_target_pairs=\{(\{[\d,\s]*\}(?:,\s*\{[\d,\s]*\})*)?\}")
_INNER_GROUP_RE = re.compile(r"\{([\d,\s]*)\}")
# the comma continuation serves branch_computations={a, b}; each name
# must NOT be followed by "=" or the list would swallow the next
# attribute's keyword ("condition=%c, body=%b" is two attributes)
_CALLED_RE = re.compile(
    r"\b(to_apply|body|condition|calls|branch_computations)="
    r"\{?%?([\w.\-]+\b(?!=)(?:,\s*%?[\w.\-]+\b(?!=))*)\}?")
_SHARDING_RE = re.compile(r"sharding=\{([^}]*)\}")
_CUSTOM_TARGET_RE = re.compile(r'custom_call_target="([^"]+)"')
_META_BODY_RE = re.compile(r"metadata=\{([^}]*)\}")
_SRC_FILE_RE = re.compile(r'source_file="([^"]+)"')
_SRC_LINE_RE = re.compile(r"source_line=(\d+)")
_OP_NAME_RE = re.compile(r'op_name="([^"]+)"')
_DIMS_ATTR_RE = re.compile(r"\b(\w+_dims|dimensions)=\{([\d,\s]*)\}")
_COMP_HEADER_RE = re.compile(
    r"^(ENTRY\s+)?%?([\w.\-]+)\s*\(.*\)\s*->\s*.+\{\s*$")


def opcode_of(body: str) -> str:
    """The opcode of one instruction body (everything right of ``= ``):
    skip the result type — one token, or a parenthesized tuple type —
    then the next identifier before ``(`` is the opcode."""
    body = body.lstrip()
    if body.startswith("("):
        depth = 0
        for i, ch in enumerate(body):
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    body = body[i + 1:].lstrip()
                    break
        else:
            return "?"
    else:
        parts = body.split(None, 1)
        if len(parts) < 2:
            return "?"
        body = parts[1]
    m = re.match(r"([A-Za-z][\w\-]*)\(", body)
    return m.group(1).lower() if m else "?"


def opcode_and_type(body: str) -> Tuple[str, str]:
    """(opcode, result-type text) of one instruction body. The result
    type is everything left of the opcode token (one shape, or a
    parenthesized tuple of shapes)."""
    stripped = body.lstrip()
    m = re.match(r"^(\([^)]*\)|\S+)\s+([a-z][\w\-]*)\(", stripped)
    if not m:
        return "?", ""
    return m.group(2).lower(), m.group(1)


def parse_shapes(type_text: str) -> List[Tuple[str, Tuple[int, ...]]]:
    """``[(dtype, dims)]`` for every array shape in a result-type text
    (one element for a plain shape, several for a tuple type).
    ``f32[]`` is a scalar: ``("f32", ())``."""
    out = []
    for dtype, dims in _SHAPE_RE.findall(type_text):
        shape = tuple(int(d) for d in dims.split(",") if d.strip())
        out.append((dtype, shape))
    return out


def shape_bytes(type_text: str) -> float:
    """Byte size of one HLO result type (scalar, array, or tuple): sum
    over every ``dtype[dims]`` token. ``f32[]`` is a scalar (4 bytes)."""
    total = 0.0
    for dtype, shape in parse_shapes(type_text):
        size = DTYPE_BYTES.get(dtype)
        if size is None:
            continue  # token/opaque types carry no payload
        n = 1
        for d in shape:
            n *= d
        total += n * size
    return total


def parse_group_sets(body: str) -> Optional[List[Tuple[int, ...]]]:
    """The instruction's replica groups as explicit member tuples, from
    either the literal or the iota form; None when absent."""
    m = _GROUPS_IOTA_RE.search(body)
    if m:
        n_groups, group_size = int(m.group(1)), int(m.group(2))
        dims = [int(d) for d in m.group(3).split(",")]
        total = 1
        for d in dims:
            total *= d
        # iota semantics: arange(prod(dims)).reshape(dims).transpose(perm)
        # .reshape(n_groups, group_size) — each row is one group
        import numpy as np

        arr = np.arange(total).reshape(dims)
        if m.group(4):
            perm = [int(p) for p in m.group(4).split(",")]
            arr = arr.transpose(perm)
        arr = arr.reshape(n_groups, group_size)
        return [tuple(int(v) for v in row) for row in arr]
    m = _GROUPS_LITERAL_RE.search(body)
    if m:
        inner = m.group(1) or ""
        groups = []
        for g in _INNER_GROUP_RE.findall(inner):
            members = tuple(int(v) for v in g.split(",") if v.strip())
            if members:
                groups.append(members)
        return groups
    return None


def parse_pairs(body: str) -> Optional[List[Tuple[int, int]]]:
    """A ``collective-permute``'s source_target_pairs, None when absent."""
    m = _PAIRS_RE.search(body)
    if not m:
        return None
    pairs = []
    for g in _INNER_GROUP_RE.findall(m.group(1) or ""):
        members = [int(v) for v in g.split(",") if v.strip()]
        if len(members) == 2:
            pairs.append((members[0], members[1]))
    return pairs


def iter_instruction_lines(text: str) -> Iterator[Tuple[str, str, int]]:
    """``(name, body, lineno)`` for every instruction-shaped line —
    the flat view ``profiler.hlo_attrib.parse_hlo_text`` consumes."""
    for lineno, line in enumerate(text.splitlines(), 1):
        m = NAME_RE.match(line.strip())
        if m:
            yield m.group(1), m.group(2), lineno


# -- the structured view ------------------------------------------------------

@dataclasses.dataclass
class HloInstr:
    """One instruction of one computation, with the attributes the lint
    rules read. ``body`` keeps the raw text so ad-hoc attributes stay
    greppable without growing this class per rule."""

    name: str
    opcode: str
    type_text: str              # result-type text ("f32[64,64]{1,0}" / tuple)
    body: str                   # everything right of "= "
    line: int                   # 1-based line in the module text
    computation: str
    operands: Tuple[str, ...] = ()
    is_root: bool = False

    @property
    def stem(self) -> str:
        """Instruction name minus the trailing SSA counter — the stable
        identity baselines key on (``%dot.3`` and ``%dot.17`` are the
        same program point across recompiles)."""
        return re.sub(r"[.\d]+$", "", self.name)

    def shapes(self) -> List[Tuple[str, Tuple[int, ...]]]:
        return parse_shapes(self.type_text)

    def result_bytes(self) -> float:
        return shape_bytes(self.type_text)

    def called_computations(self) -> List[str]:
        """Computations this instruction invokes (``to_apply=``,
        ``body=``/``condition=`` of a while, ``calls=`` of a fusion,
        ``branch_computations={..}`` of a conditional)."""
        out = []
        for _kw, names in _CALLED_RE.findall(self.body):
            for n in names.split(","):
                n = n.strip().lstrip("%")
                if n:
                    out.append(n)
        return out

    def attr_dims(self, key: str) -> Optional[Tuple[int, ...]]:
        """An integer-set attribute (``lhs_contracting_dims``,
        ``dimensions``, ...), None when absent."""
        for k, vals in _DIMS_ATTR_RE.findall(self.body):
            if k == key:
                return tuple(int(v) for v in vals.split(",") if v.strip())
        return None

    def sharding(self) -> Optional[str]:
        m = _SHARDING_RE.search(self.body)
        return m.group(1).strip() if m else None

    def custom_call_target(self) -> Optional[str]:
        m = _CUSTOM_TARGET_RE.search(self.body)
        return m.group(1) if m else None

    def replica_groups(self) -> Optional[List[Tuple[int, ...]]]:
        return parse_group_sets(self.body)

    def source_src(self) -> str:
        """``file.py:123`` (basename) from the metadata, or "?"."""
        mm = _META_BODY_RE.search(self.body)
        if not mm:
            return "?"
        md = mm.group(1)
        f = _SRC_FILE_RE.search(md)
        ln = _SRC_LINE_RE.search(md)
        if not f and not ln:
            return "?"
        return ((f.group(1).split("/")[-1] if f else "?")
                + ":" + (ln.group(1) if ln else "?"))

    def op_name(self) -> str:
        mm = _META_BODY_RE.search(self.body)
        if mm:
            o = _OP_NAME_RE.search(mm.group(1))
            if o:
                return o.group(1)
        return "?"


@dataclasses.dataclass
class HloComputation:
    name: str
    instrs: List[HloInstr] = dataclasses.field(default_factory=list)
    is_entry: bool = False

    @property
    def root(self) -> Optional[HloInstr]:
        for i in self.instrs:
            if i.is_root:
                return i
        return self.instrs[-1] if self.instrs else None

    def params(self) -> List[HloInstr]:
        return [i for i in self.instrs if i.opcode == "parameter"]

    def by_name(self) -> Dict[str, HloInstr]:
        return {i.name: i for i in self.instrs}

    def users(self) -> Dict[str, List[HloInstr]]:
        """operand name -> instructions consuming it (within this
        computation — HLO operands never cross computation scopes)."""
        out: Dict[str, List[HloInstr]] = {}
        for i in self.instrs:
            for op in i.operands:
                out.setdefault(op, []).append(i)
        return out


@dataclasses.dataclass
class HloModule:
    name: str
    computations: Dict[str, HloComputation]
    entry: Optional[str] = None
    header: str = ""

    def entry_computation(self) -> Optional[HloComputation]:
        if self.entry and self.entry in self.computations:
            return self.computations[self.entry]
        return None

    def all_instrs(self) -> Iterator[HloInstr]:
        for comp in self.computations.values():
            yield from comp.instrs

    def reachable_from(self, comp_name: str) -> List[HloComputation]:
        """``comp_name`` plus every computation transitively called from
        it (fusion bodies, reducers, nested whiles)."""
        seen: List[HloComputation] = []
        names = [comp_name]
        visited = set()
        while names:
            n = names.pop()
            if n in visited or n not in self.computations:
                continue
            visited.add(n)
            comp = self.computations[n]
            seen.append(comp)
            for instr in comp.instrs:
                names.extend(instr.called_computations())
        return seen


def _operands_of(body: str, opcode: str) -> Tuple[str, ...]:
    """Operand instruction names from the opcode's argument list.
    Each top-level comma-separated argument contributes its trailing
    identifier token (``%tanh.4`` or bare ``tanh.4``; a leading shape
    like ``f32[8]{0}`` is skipped); literal arguments (``constant(0)``)
    contribute nothing."""
    idx = body.find(opcode + "(")
    if idx < 0:
        return ()
    i = idx + len(opcode)
    depth = 0
    sq = br = 0  # [..] / {..} nesting: commas inside a shape's dims or
    # layout ("f32[32,16]{1,0} %x") do NOT separate arguments
    args: List[str] = []
    cur: List[str] = []
    for ch in body[i:]:
        if ch == "(":
            depth += 1
            if depth == 1:
                continue
        elif ch == ")":
            depth -= 1
            if depth == 0:
                args.append("".join(cur))
                break
        elif ch == "[":
            sq += 1
        elif ch == "]":
            sq -= 1
        elif ch == "{":
            br += 1
        elif ch == "}":
            br -= 1
        elif ch == "," and depth == 1 and sq == 0 and br == 0:
            args.append("".join(cur))
            cur = []
            continue
        if depth >= 1:
            cur.append(ch)
    out = []
    for a in args:
        a = a.strip()
        if not a:
            continue
        name = None
        for tok in re.findall(r"%([\w.\-]+)", a):
            name = tok
        if name is None:
            # bare (un-%-prefixed) operand form: the last identifier
            # token that is not a shape ("f32[8]" / "(f32[8], s32[])");
            # the trailing lookahead must reject mid-token stops too, or
            # "f32[..." would yield its prefix "f3" as a phantom operand
            for tok in re.findall(
                    r"(?<![\w\[{])([A-Za-z_][\w.\-]*)(?![\w.\-\[])", a):
                name = tok
        if name is not None:
            out.append(name)
    return tuple(out)


def parse_module(text: str) -> HloModule:
    """Parse one optimized-HLO module text into computations and
    instructions. Tolerant by contract: lines that match nothing are
    skipped, so truncated or annotated dumps still parse."""
    module_name = "?"
    header = ""
    comps: Dict[str, HloComputation] = {}
    entry: Optional[str] = None
    current: Optional[HloComputation] = None
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line:
            continue
        if line.startswith("HloModule"):
            header = line
            parts = line.split(None, 2)
            if len(parts) > 1:
                module_name = parts[1].rstrip(",")
            continue
        if line == "}" or line == "})":
            current = None
            continue
        m = _COMP_HEADER_RE.match(line)
        if m and "=" not in line.split("(", 1)[0]:
            name = m.group(2)
            current = comps.setdefault(name, HloComputation(name=name))
            if m.group(1):
                current.is_entry = True
                entry = name
            continue
        m = NAME_RE.match(line)
        if m and current is not None:
            name, body = m.group(1), m.group(2)
            opcode, type_text = opcode_and_type(body)
            if opcode == "?":
                opcode = opcode_of(body)
            current.instrs.append(HloInstr(
                name=name, opcode=opcode, type_text=type_text, body=body,
                line=lineno, computation=current.name,
                operands=_operands_of(body, opcode),
                is_root=line.startswith("ROOT ")))
    if entry is None and comps:
        # single-computation dumps without an ENTRY keyword: the last
        # computation is the entry by XLA's printing convention
        entry = list(comps)[-1]
    return HloModule(name=module_name, computations=comps, entry=entry,
                     header=header)
