"""AST visitor core of tpu-lint: trace-context and taint tracking.

JAX's trace-then-compile model turns a family of runtime disasters
(tracer concretization, silent per-step retraces, host syncs in the hot
loop) into *source-level patterns*. This module provides the machinery
the rules in ``rules.py`` run on:

- **trace-context detection** — which function bodies execute under
  ``jax.jit`` tracing. Understands this framework's own idioms, not just
  decorators: ``tracked_jit(step_fn, ...)`` / ``jax.jit(fn)`` wrap calls
  that reference a locally-defined function (the dominant pattern in
  ``jit.TrainStep`` / ``static.Executor`` / ``fleet.ParallelTrainStep``),
  ``@jax.jit`` / ``@tracked_jit(...)`` / ``@partial(jax.jit, ...)``
  decorators, callables handed to ``lax.scan/cond/while_loop``,
  ``jax.grad/value_and_grad/vmap/checkpoint``, and op fns registered
  through ``core.tensor.apply_op``. Functions *defined inside* a traced
  function are traced too (grad closures, scan bodies).
- **taint tracking** — which names inside a traced body hold traced
  values: parameters (minus ``static_argnums``/``static_argnames``),
  anything assigned from an expression over tainted names, loop targets
  of tainted iterables (with ``.items()``/``.keys()`` key-vs-value
  refinement: dict keys are static Python values). Shape/dtype
  attributes (``x.shape`` etc.) are static under jit and break the
  taint chain, as do ``isinstance``/``type``/``is None`` tests.

Deliberate limits (documented, not bugs): the analysis is
intra-procedural — a helper *called from* a traced body is only analyzed
if it is itself wrapped/marked (e.g. ``fleet.apply_optimizer_update`` is
not descended into), and ``Layer.forward`` bodies are NOT treated as
traced because every layer here is dual-mode (define-by-run eager AND
staged) — data-dependent Python control flow is legal in eager mode.
"""
from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

__all__ = ["Finding", "Analyzer", "analyze_source", "parse_suppressions"]


@dataclass
class Finding:
    rule: str
    severity: str  # "error" | "warning" | "info"
    path: str
    line: int
    col: int
    message: str
    hint: str
    context: str  # enclosing function qualname ("<module>" at top level)

    def key(self) -> Tuple[str, str, str]:
        """Baseline identity: line numbers drift on unrelated edits, so
        the ratchet store keys on (file, rule, enclosing function)."""
        return (self.path, self.rule, self.context)

    def to_dict(self) -> dict:
        return {
            "rule": self.rule, "severity": self.severity, "path": self.path,
            "line": self.line, "col": self.col, "message": self.message,
            "hint": self.hint, "context": self.context,
        }


# jit-entry wrappers: a call/decorator whose terminal name is one of
# these traces its first callable argument
JIT_WRAPPERS = {"jit", "tracked_jit", "pjit"}

# transform/control callees that trace callable args at these positions
TRACING_CALLEES = {
    "scan": (0,), "cond": (1, 2), "switch": (1,), "while_loop": (0, 1),
    "fori_loop": (2,), "grad": (0,), "value_and_grad": (0,), "vmap": (0,),
    "pmap": (0,), "checkpoint": (0,), "remat": (0,), "apply_op": (0,),
    "custom_vjp": (0,), "custom_jvp": (0,),
}

# attributes that are STATIC under jit tracing (reading them off a tracer
# yields a concrete Python value) — they break the taint chain
STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "weak_type", "sharding",
                "aval", "name"}

# calls whose result is always a concrete Python value. The trace-probe
# helpers (core.tensor._is_tracer and friends) are how this framework
# legitimately branches on "am I being traced" — their result is a
# concrete bool by construction
STATIC_CALLS = {"isinstance", "type", "hasattr", "callable", "len", "id",
                "repr", "str", "issubclass", "_is_tracer", "_is_concrete",
                "_recording"}

_SUPPRESS_RE = re.compile(
    r"#\s*tpu-lint:\s*(disable|disable-next)\s*=\s*"
    r"([A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)")


def parse_suppressions(source: str) -> Dict[int, Set[str]]:
    """``{line: {rule, ...}}`` from inline ``# tpu-lint: disable=R1,R5``
    (same line) and ``# tpu-lint: disable-next=R1`` (following line)
    comments. The rule name ``all`` suppresses every rule."""
    out: Dict[int, Set[str]] = {}
    for lineno, line in enumerate(source.splitlines(), 1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        rules = {r.strip() for r in m.group(2).split(",") if r.strip()}
        target = lineno + 1 if m.group(1) == "disable-next" else lineno
        out.setdefault(target, set()).update(rules)
    return out


def call_name(node: ast.Call) -> Optional[str]:
    """Terminal name of a call: ``jax.lax.scan(...)`` → ``scan``."""
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def dotted(node) -> Optional[str]:
    """Dotted name of an expression, e.g. ``jax.device_put`` — None when
    any segment is not a plain Name/Attribute."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _const_str(node) -> Optional[str]:
    return node.value if (isinstance(node, ast.Constant)
                          and isinstance(node.value, str)) else None


def _static_spec(call: Optional[ast.Call]):
    """(static_argnums, static_argnames) sets from a wrap call's kwargs."""
    nums: Set[int] = set()
    names: Set[str] = set()
    if call is None:
        return nums, names
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, int) \
                        and not isinstance(n.value, bool):
                    nums.add(n.value)
        elif kw.arg == "static_argnames":
            for n in ast.walk(kw.value):
                s = _const_str(n)
                if s:
                    names.add(s)
    return nums, names


class Scope:
    def __init__(self, node, qualname: str, traced: bool,
                 parent: Optional["Scope"]):
        self.node = node
        self.qualname = qualname
        self.traced = traced
        self.parent = parent
        self.locals: Set[str] = set()
        self.tainted: Set[str] = set()
        self.step_results: Set[str] = set()  # names holding jitted-step outputs
        self.py_tuples: Set[str] = set()  # vararg tuples: emptiness is static
        if parent is not None and traced:
            # closure visibility: names traced in the enclosing traced
            # scope stay traced inside nested defs (grad/scan bodies)
            self.tainted |= parent.tainted
            self.py_tuples |= parent.py_tuples


def _function_locals(fn) -> Set[str]:
    """Names bound in a function body (params, assignment/loop/with
    targets, nested def names). ``global``/``nonlocal`` declarations are
    removed — mutating those under trace is exactly rule R6's business."""
    names: Set[str] = set()
    a = fn.args
    for p in a.posonlyargs + a.args + a.kwonlyargs:
        names.add(p.arg)
    if a.vararg:
        names.add(a.vararg.arg)
    if a.kwarg:
        names.add(a.kwarg.arg)
    nonlocals: Set[str] = set()
    body = fn.body if isinstance(fn.body, list) else [fn.body]  # Lambda
    stack = list(body)
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            names.add(n.name)
            continue  # nested defs own their locals
        if isinstance(n, ast.Lambda):
            continue
        if isinstance(n, ast.ClassDef):
            names.add(n.name)
            continue
        if isinstance(n, (ast.Global, ast.Nonlocal)):
            nonlocals.update(n.names)
        elif isinstance(n, ast.Assign):
            for t in n.targets:
                names.update(_target_names(t))
        elif isinstance(n, (ast.AnnAssign, ast.AugAssign)):
            names.update(_target_names(n.target))
        elif isinstance(n, (ast.For, ast.AsyncFor)):
            names.update(_target_names(n.target))
        elif isinstance(n, (ast.With, ast.AsyncWith)):
            for item in n.items:
                if item.optional_vars is not None:
                    names.update(_target_names(item.optional_vars))
        elif isinstance(n, ast.NamedExpr):
            names.update(_target_names(n.target))
        elif isinstance(n, ast.ExceptHandler) and n.name:
            names.add(n.name)
        elif isinstance(n, ast.comprehension):
            names.update(_target_names(n.target))
        stack.extend(ast.iter_child_nodes(n))
    return names - nonlocals


def _target_names(t) -> Set[str]:
    """Plain names bound by an assignment target (subscript/attribute
    targets mutate an existing object — they bind nothing)."""
    out: Set[str] = set()
    if isinstance(t, ast.Name):
        out.add(t.id)
    elif isinstance(t, (ast.Tuple, ast.List)):
        for e in t.elts:
            out.update(_target_names(e))
    elif isinstance(t, ast.Starred):
        out.update(_target_names(t.value))
    return out


class Analyzer(ast.NodeVisitor):
    """One pass over one module. ``run()`` returns raw findings —
    suppression filtering and baseline comparison happen in the CLI."""

    def __init__(self, path: str, source: str, select: Optional[Set[str]] = None):
        from . import rules  # late import: rules imports Finding from here

        self.path = path
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.select = select
        self.findings: List[Finding] = []
        self.scope: Optional[Scope] = None
        self.loop_stack: List[dict] = []
        self._qual: List[str] = []
        self._rules = rules
        # nodes marked jit-traced by the pre-pass, with wrap metadata
        self._marks: Dict[ast.AST, dict] = {}
        self._parents: Dict[ast.AST, ast.AST] = {}
        self._wrap_sites: List[dict] = []  # for R3

    # -- public ------------------------------------------------------------
    def run(self) -> List[Finding]:
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self._parents[child] = node
        self._prepass()
        for site in self._wrap_sites:
            self._rules.check_wrap_site(self, site)
        self.visit(self.tree)
        return self.findings

    def emit(self, rule: str, node, message: str, hint: Optional[str] = None):
        if self.select is not None and rule not in self.select:
            return
        meta = self._rules.RULES[rule]
        self.findings.append(Finding(
            rule=rule, severity=meta.severity, path=self.path,
            line=getattr(node, "lineno", 0), col=getattr(node, "col_offset", 0),
            message=message, hint=hint if hint is not None else meta.hint,
            context=self.qualname()))

    def qualname(self) -> str:
        return ".".join(self._qual) if self._qual else "<module>"

    def in_traced(self) -> bool:
        return self.scope is not None and self.scope.traced

    def in_loop(self) -> bool:
        return bool(self.loop_stack)

    def in_feedish_loop(self) -> bool:
        return any(l["feedish"] for l in self.loop_stack)

    # -- trace-context pre-pass --------------------------------------------
    def _prepass(self):
        """Mark every function node whose body executes under tracing."""
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                name = call_name(node)
                if name in JIT_WRAPPERS and node.args:
                    target = self._resolve_callable(node.args[0], node)
                    if target is not None:
                        nums, names = _static_spec(node)
                        self._mark(target, nums, names)
                        self._wrap_sites.append(
                            {"call": node, "fn": target,
                             "static_argnums": nums,
                             "static_argnames": names})
                elif name in TRACING_CALLEES:
                    for pos in TRACING_CALLEES[name]:
                        if pos < len(node.args):
                            t = self._resolve_callable(node.args[pos], node)
                            if t is not None:
                                self._mark(t, set(), set())
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    spec = self._decorator_wrap(dec)
                    if spec is not None:
                        nums, names = spec
                        self._mark(node, nums, names)
                        self._wrap_sites.append(
                            {"call": dec if isinstance(dec, ast.Call) else node,
                             "fn": node, "static_argnums": nums,
                             "static_argnames": names})

    def _decorator_wrap(self, dec):
        """(static_argnums, static_argnames) when the decorator is a jit
        wrapper (bare, factory-called, or via functools.partial)."""
        if isinstance(dec, (ast.Name, ast.Attribute)):
            name = dec.id if isinstance(dec, ast.Name) else dec.attr
            return (set(), set()) if name in JIT_WRAPPERS else None
        if isinstance(dec, ast.Call):
            name = call_name(dec)
            if name in JIT_WRAPPERS:
                return _static_spec(dec)
            if name == "partial" and dec.args:
                inner = dotted(dec.args[0]) or ""
                if inner.split(".")[-1] in JIT_WRAPPERS:
                    return _static_spec(dec)
        return None

    def _mark(self, node, nums, names):
        info = self._marks.setdefault(
            node, {"static_argnums": set(), "static_argnames": set()})
        info["static_argnums"] |= nums
        info["static_argnames"] |= names

    def _resolve_callable(self, arg, at_node):
        """A wrap call's callable argument → its def node. Lambdas mark
        themselves; a Name resolves to a FunctionDef in the enclosing
        scope chain (innermost first, shallow per scope)."""
        if isinstance(arg, ast.Lambda):
            return arg
        if not isinstance(arg, ast.Name):
            return None
        scope_node = self._parents.get(at_node)
        while scope_node is not None:
            if isinstance(scope_node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                       ast.Module)):
                for d in self._shallow_defs(scope_node):
                    if d.name == arg.id:
                        return d
            scope_node = self._parents.get(scope_node)
        return None

    @staticmethod
    def _shallow_defs(scope_node):
        body = scope_node.body
        out = []
        stack = list(body)
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append(n)
                continue  # don't descend into nested scopes
            if isinstance(n, (ast.Lambda, ast.ClassDef)):
                continue
            stack.extend(ast.iter_child_nodes(n))
        return out

    # -- taint -------------------------------------------------------------
    def tainted(self, node) -> bool:
        """Does evaluating this expression touch a traced value?"""
        if self.scope is None or not self.scope.traced:
            return False
        return self._taint(node)

    def _taint(self, node) -> bool:
        s = self.scope
        if isinstance(node, ast.Name):
            return node.id in s.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in STATIC_ATTRS:
                return False  # x.shape/.dtype are static under jit
            return self._taint(node.value)
        if isinstance(node, ast.Subscript):
            return self._taint(node.value) or self._taint(node.slice)
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name in STATIC_CALLS:
                return False
            if name in ("float", "int", "bool", "complex"):
                return False  # concretizers: result is host-side (R1 flags them)
            return (any(self._taint(a) for a in node.args)
                    or any(self._taint(k.value) for k in node.keywords)
                    or self._taint(node.func))
        if isinstance(node, ast.BinOp):
            return self._taint(node.left) or self._taint(node.right)
        if isinstance(node, ast.UnaryOp):
            return self._taint(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self._taint(v) for v in node.values)
        if isinstance(node, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return False  # identity tests are Python-level (x is None)
            if all(isinstance(op, (ast.In, ast.NotIn)) for op in node.ops):
                # `k in d` tests keys — static for dicts of traced values;
                # only a traced LEFT operand is data-dependent
                return self._taint(node.left)
            return (self._taint(node.left)
                    or any(self._taint(c) for c in node.comparators))
        if isinstance(node, ast.IfExp):
            return (self._taint(node.test) or self._taint(node.body)
                    or self._taint(node.orelse))
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self._taint(e) for e in node.elts)
        if isinstance(node, ast.Dict):
            return (any(self._taint(v) for v in node.values)
                    or any(k is not None and self._taint(k)
                           for k in node.keys))
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            return (any(self._taint(g.iter) for g in node.generators)
                    or self._taint(node.elt))
        if isinstance(node, ast.DictComp):
            return (any(self._taint(g.iter) for g in node.generators)
                    or self._taint(node.key) or self._taint(node.value))
        if isinstance(node, ast.Starred):
            return self._taint(node.value)
        if isinstance(node, ast.NamedExpr):
            return self._taint(node.value)
        if isinstance(node, ast.JoinedStr):
            return any(self._taint(v) for v in node.values)
        if isinstance(node, ast.FormattedValue):
            return self._taint(node.value)
        return False

    def _bind(self, target, is_tainted: bool):
        for name in _target_names(target):
            if is_tainted:
                self.scope.tainted.add(name)
            else:
                self.scope.tainted.discard(name)

    def _bind_for_target(self, target, it):
        """Loop-target taint with the dict-iteration refinement: keys of
        a dict of traced values are static Python objects."""
        t = self._taint(it)
        if (isinstance(it, ast.Call) and isinstance(it.func, ast.Attribute)
                and not it.args):
            base_tainted = self._taint(it.func.value)
            meth = it.func.attr
            if meth == "keys":
                self._bind(target, False)
                return
            if meth == "items" and base_tainted and \
                    isinstance(target, (ast.Tuple, ast.List)) \
                    and len(target.elts) == 2:
                self._bind(target.elts[0], False)
                self._bind(target.elts[1], True)
                return
        if isinstance(it, ast.Call) and call_name(it) == "enumerate" \
                and isinstance(target, (ast.Tuple, ast.List)) \
                and len(target.elts) == 2:
            self._bind(target.elts[0], False)
            self._bind(target.elts[1], t)
            return
        if isinstance(it, ast.Call) and call_name(it) == "range":
            self._bind(target, False)
            return
        self._bind(target, t)

    # -- scope/visit machinery ---------------------------------------------
    def _enter_function(self, node, name: str):
        mark = self._marks.get(node)
        traced = (mark is not None
                  or (self.scope is not None and self.scope.traced))
        scope = Scope(node, name, traced, self.scope)
        scope.locals = _function_locals(node)
        a = node.args
        if a.vararg:
            # a *args tuple is a Python tuple even under trace — its
            # emptiness/length is static (rules exempt `if rest:` tests)
            scope.py_tuples.add(a.vararg.arg)
        # Only functions EXPLICITLY handed to a tracing entry (jit wrap,
        # grad/scan/cond/apply_op, decorator) get tainted params. A plain
        # helper defined inside a traced body inherits the traced CONTEXT
        # (closure taint, trace-time print/telemetry checks) but its own
        # params are frequently called with static values (shape ints) —
        # auto-tainting them is the analyzer's main false-positive source.
        if mark is not None:
            nums = mark["static_argnums"]
            names = mark["static_argnames"]
            params = [p.arg for p in a.posonlyargs + a.args]
            # static_argnums indices follow JAX's convention: they count
            # the wrapped function's own positions, INCLUDING a leading
            # self/cls (jit sees the unbound function)
            for idx, pname in enumerate(params):
                if pname in ("self", "cls"):
                    continue
                if idx in nums or pname in names:
                    continue
                scope.tainted.add(pname)
            for p in a.kwonlyargs:
                if p.arg not in names:
                    scope.tainted.add(p.arg)
            if a.vararg:
                scope.tainted.add(a.vararg.arg)
            if a.kwarg:
                scope.tainted.add(a.kwarg.arg)
        return scope

    def visit_FunctionDef(self, node):
        self._qual.append(node.name)
        outer, self.scope = self.scope, self._enter_function(node, node.name)
        outer_loops, self.loop_stack = self.loop_stack, []
        for d in node.decorator_list:
            self.visit(d)
        for stmt in node.body:
            self.visit(stmt)
        self.scope = outer
        self.loop_stack = outer_loops
        self._qual.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        outer = self.scope
        self.scope = self._enter_function(node, "<lambda>")
        self.visit(node.body)
        self.scope = outer

    def visit_ClassDef(self, node):
        self._qual.append(node.name)
        self.generic_visit(node)
        self._qual.pop()

    def visit_Assign(self, node):
        self.visit(node.value)
        t = self.tainted(node.value)
        self._rules.check_assign(self, node)
        for target in node.targets:
            if self.scope is not None:
                self._bind(target, t)
                # a slice of a *args tuple is still a Python tuple —
                # its emptiness stays static (`inits = flat[k:]`)
                if (isinstance(node.value, ast.Subscript)
                        and isinstance(node.value.slice, ast.Slice)
                        and isinstance(node.value.value, ast.Name)
                        and node.value.value.id in self.scope.py_tuples):
                    for n in _target_names(target):
                        self.scope.py_tuples.add(n)
            if not isinstance(target, ast.Name):
                self.visit(target)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self.visit(node.value)
            if self.scope is not None:
                self._bind(node.target, self.tainted(node.value))

    def visit_AugAssign(self, node):
        self.visit(node.value)
        self._rules.check_augassign(self, node)
        if self.scope is not None and isinstance(node.target, ast.Name):
            if self.tainted(node.value):
                self.scope.tainted.add(node.target.id)

    def visit_NamedExpr(self, node):
        self.visit(node.value)
        if self.scope is not None:
            self._bind(node.target, self.tainted(node.value))

    def visit_For(self, node):
        self.visit(node.iter)
        if self.scope is not None:
            self._bind_for_target(node.target, node.iter)
        self.loop_stack.append({"node": node, "feedish": self._feedish(node)})
        for stmt in node.body:
            self.visit(stmt)
        self.loop_stack.pop()
        for stmt in node.orelse:
            self.visit(stmt)

    visit_AsyncFor = visit_For

    def visit_While(self, node):
        self._rules.check_branch(self, node, kind="while")
        self.visit(node.test)
        self.loop_stack.append({"node": node, "feedish": False})
        for stmt in node.body:
            self.visit(stmt)
        self.loop_stack.pop()
        for stmt in node.orelse:
            self.visit(stmt)

    def visit_If(self, node):
        self._rules.check_branch(self, node, kind="if")
        self.generic_visit(node)

    def visit_Assert(self, node):
        self._rules.check_branch(self, node, kind="assert")
        self.generic_visit(node)

    def visit_Call(self, node):
        self._rules.check_call(self, node)
        self.generic_visit(node)

    def visit_Attribute(self, node):
        self._rules.check_attribute(self, node)
        self.generic_visit(node)

    @staticmethod
    def _feedish(node: ast.For) -> bool:
        """Does this loop iterate a feed/batch-like mapping? (the shape
        of the per-leaf H2D dispatch regression PR 2 eliminated)"""
        it = node.iter
        if isinstance(it, ast.Call) and isinstance(it.func, ast.Attribute) \
                and it.func.attr in ("items", "values"):
            return True
        names = []
        for n in list(ast.walk(it)) + list(ast.walk(node.target)):
            if isinstance(n, ast.Name):
                names.append(n.id.lower())
            elif isinstance(n, ast.Attribute):
                names.append(n.attr.lower())
        return any(k in name for name in names
                   for k in ("feed", "batch", "slot"))


def analyze_source(path: str, source: str,
                   select: Optional[Set[str]] = None) -> List[Finding]:
    """Analyze one module's source; returns findings with inline
    ``# tpu-lint: disable=`` suppressions already applied."""
    analyzer = Analyzer(path, source, select=select)
    findings = analyzer.run()
    supp = parse_suppressions(source)
    return [f for f in findings
            if not (supp.get(f.line) and
                    ({f.rule, "all"} & supp[f.line]))]
