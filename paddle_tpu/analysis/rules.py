"""tpu-lint rules R1–R8: TPU/JAX hazard patterns keyed to failures this
framework has actually hit (PR 1 built the *runtime* retrace tracker;
PR 2 hand-hunted per-leaf H2D dispatch loops — both classes are caught
here statically, before a step executes).

Each rule is metadata (id, severity, title, fix hint) plus a check
hooked into the analyzer's visit events. Adding a rule = adding a Rule
entry and extending one of the ``check_*`` dispatchers below.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict

from .analyzer import call_name, dotted

__all__ = ["RULES", "Rule"]


@dataclass(frozen=True)
class Rule:
    id: str
    severity: str
    title: str
    hint: str


RULES: Dict[str, Rule] = {r.id: r for r in [
    Rule("R1", "error", "tracer concretization",
         "float()/int()/bool()/np.asarray()/.numpy() force a traced value "
         "to a host constant: under jax.jit this raises "
         "ConcretizationTypeError (or silently bakes in a trace-time "
         "constant). Keep the math in jnp, or mark the argument static."),
    Rule("R2", "error", "data-dependent Python control flow",
         "a Python if/while on a traced value branches at TRACE time, not "
         "run time — route it through static.control_flow (cond/while_loop) "
         "or jax.lax.cond/while_loop; shape/dtype tests are static and fine."),
    Rule("R3", "warning", "retrace hazard in jit signature",
         "string-valued parameters retrace (or fail) per value — list them "
         "in static_argnames/static_argnums; static args must have hashable "
         "defaults (no list/dict/set)."),
    Rule("R4", "warning", "per-item H2D transfer in feed loop",
         "one device_put/jnp.asarray per dict entry dispatches one transfer "
         "per leaf (the regression class PR 2 eliminated) — build the host "
         "pytree first and issue ONE jax.device_put over it."),
    Rule("R5", "warning", "host sync in hot path",
         "block_until_ready()/.numpy()/np-reductions on step outputs force "
         "a device sync every iteration and stall the async dispatch "
         "pipeline — defer materialization (deferred gauges, periodic "
         "fetch) or move the reduction into the jitted program."),
    Rule("R6", "warning", "Python state mutation under trace",
         "mutating closed-over state (self.x = .., list.append, dict[k] = "
         "..) inside a jitted function runs ONCE at trace time and may "
         "leak tracers — return new values instead, or compute outside."),
    Rule("R7", "warning", "float64 on TPU",
         "TPU hardware has no f64 units: float64 arrays are silently "
         "computed as float32 there, so x64-on CPU runs diverge from TPU "
         "— use jnp.float32 (or int dtypes for index math / host-side np "
         "for true f64) so both backends agree."),
    Rule("R8", "error", "telemetry call under trace",
         "Telemetry counters/gauges inside a jitted body execute only at "
         "trace time (silent no-op per step) — record metrics outside the "
         "jitted function, on its inputs/outputs."),
]}

# R1: direct concretizers --------------------------------------------------
_CONCRETIZE_BUILTINS = {"float", "int", "bool", "complex"}
_CONCRETIZE_METHODS = {"numpy", "item", "tolist", "__array__"}
_NP_HOST_CALLS = {"asarray", "array", "sum", "mean", "prod", "max", "min",
                  "any", "all", "median", "percentile"}

# R5: step-result detection
_STEP_ATTRS = {"train_batch", "eval_batch", "run_steps"}
_TELEMETRY_METHODS = {"counter", "gauge", "observe", "observe_interval",
                      "timer", "to_jsonl"}
_TELEMETRY_BASES = {"tel", "telemetry", "_telemetry"}


def _np_call(node: ast.Call):
    """('np'|'jnp', method) for numpy/jax.numpy module calls, else None."""
    d = dotted(node.func)
    if not d:
        return None
    parts = d.split(".")
    if parts[0] in ("np", "numpy") and len(parts) == 2:
        return "np", parts[1]
    if parts[0] in ("jnp",) and len(parts) == 2:
        return "jnp", parts[1]
    if d.startswith("jax.numpy.") and len(parts) == 3:
        return "jnp", parts[2]
    return None


def _is_steplike_call(node: ast.Call) -> bool:
    """A call that runs one jitted training/eval step."""
    f = node.func
    if isinstance(f, ast.Name):
        return f.id == "step" or f.id.endswith("_step")
    if isinstance(f, ast.Attribute):
        return (f.attr in _STEP_ATTRS or f.attr.endswith("_step")
                or f.attr in ("_jitted", "_jitted_multi"))
    return False


# -- event dispatchers ------------------------------------------------------

def check_call(a, node: ast.Call) -> None:
    name = call_name(node)
    npc = _np_call(node)

    if a.in_traced():
        check_mutating_call(a, node)  # R6 via .append()/.update()/...
        # R1 — concretizing a traced value (bare-builtin calls only:
        # jax.lax.complex's terminal name is also "complex")
        if isinstance(node.func, ast.Name) and name in _CONCRETIZE_BUILTINS \
                and node.args \
                and any(a.tainted(arg) for arg in node.args):
            a.emit("R1", node,
                   f"{name}() concretizes a traced value inside a "
                   f"jit-traced function")
        elif isinstance(node.func, ast.Attribute) \
                and node.func.attr in _CONCRETIZE_METHODS \
                and a.tainted(node.func.value):
            a.emit("R1", node,
                   f".{node.func.attr}() concretizes a traced value inside "
                   f"a jit-traced function")
        elif npc and npc[0] == "np" and npc[1] in ("asarray", "array") \
                and any(a.tainted(arg) for arg in node.args):
            a.emit("R1", node,
                   f"np.{npc[1]}() materializes a traced value on the host "
                   f"inside a jit-traced function")
        # R5(a) — host-side work baked into the trace
        elif npc and npc[0] == "np" and npc[1] in _NP_HOST_CALLS \
                and any(a.tainted(arg) for arg in node.args):
            a.emit("R5", node,
                   f"np.{npc[1]}() on a traced value runs on the host at "
                   f"trace time — use jnp.{npc[1]} so it stays in the "
                   f"compiled program")
        elif name == "print" and (any(a.tainted(arg) for arg in node.args)
                                  or not node.args):
            a.emit("R5", node,
                   "print() inside a jit-traced function executes at trace "
                   "time only (once), not per step — use jax.debug.print "
                   "or log outside the step")
        # R8 — telemetry no-ops under trace
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _TELEMETRY_METHODS:
            base = node.func.value
            base_name = base.id if isinstance(base, ast.Name) else (
                base.attr if isinstance(base, ast.Attribute) else None)
            if base_name in _TELEMETRY_BASES or (
                    isinstance(base, ast.Call)
                    and call_name(base) == "get_telemetry"):
                a.emit("R8", node,
                       f"Telemetry.{node.func.attr}() inside a jit-traced "
                       f"function records only at trace time")
        elif name == "get_telemetry":
            a.emit("R8", node,
                   "get_telemetry() inside a jit-traced function — any "
                   "metric recorded here is a silent per-step no-op")
        return

    # outside traced code ---------------------------------------------------
    # R4 — per-item H2D transfers in a feed/batch loop
    d = dotted(node.func)
    if a.in_feedish_loop():
        if d in ("jax.device_put", "device_put") or name == "to_tensor" \
                or (npc and npc[0] == "jnp" and npc[1] in ("asarray", "array")):
            a.emit("R4", node,
                   f"{d or name}() issues one H2D transfer per loop "
                   f"iteration over a feed/batch dict")
    # R5(b) — explicit device syncs in hot loops / on step results
    if isinstance(node.func, ast.Attribute):
        if node.func.attr == "block_until_ready" and a.in_loop():
            a.emit("R5", node,
                   ".block_until_ready() inside a loop forces a device "
                   "sync per iteration")
        elif node.func.attr == "numpy" and a.scope is not None \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id in a.scope.step_results:
            a.emit("R5", node,
                   f".numpy() on '{node.func.value.id}' (a jitted step "
                   f"result) blocks on the device every step")
    elif name in ("float", "int") and node.args \
            and a.scope is not None:
        arg = node.args[0]
        if isinstance(arg, ast.Name) and arg.id in a.scope.step_results:
            a.emit("R5", node,
                   f"{name}() on '{arg.id}' (a jitted step result) blocks "
                   f"on the device every step")

    _check_float64_call(a, node)


def _check_float64_call(a, node: ast.Call) -> None:
    """R7 via dtype= kwargs/astype with a 'float64'/'double' string."""
    for kw in node.keywords:
        if kw.arg == "dtype" and isinstance(kw.value, ast.Constant) \
                and kw.value.value in ("float64", "double"):
            a.emit("R7", node,
                   f"dtype={kw.value.value!r} creates a float64 array")
    if isinstance(node.func, ast.Attribute) \
            and node.func.attr in ("astype", "cast") and node.args:
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and arg.value in ("float64",
                                                           "double"):
            a.emit("R7", node,
                   f".{node.func.attr}({arg.value!r}) casts to float64")


def check_attribute(a, node: ast.Attribute) -> None:
    # R7 — jnp.float64 anywhere (silently f32 with x64 off); np.float64
    # only under trace (host-side numpy float64 is legitimate)
    if node.attr != "float64":
        return
    d = dotted(node)
    if d in ("jnp.float64", "jax.numpy.float64"):
        a.emit("R7", node, "jnp.float64 is silently computed as float32 "
                           "on TPU hardware")
    elif d in ("np.float64", "numpy.float64") and a.in_traced():
        # only as a dtype ARGUMENT — `x.dtype == np.float64` comparisons
        # are legitimate host-side dtype probing
        parent = a._parents.get(node)
        is_dtype_arg = (isinstance(parent, ast.Call) and node in parent.args) \
            or (isinstance(parent, ast.keyword) and parent.arg == "dtype")
        if is_dtype_arg:
            a.emit("R7", node, "np.float64 inside a jit-traced function "
                               "requests an x64 dtype TPU will not honor")


def _static_truthiness(a, test) -> bool:
    """`if rest:` on a *args tuple (or a slice of one) tests Python tuple
    emptiness — static under trace, not data-dependent."""
    while isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        test = test.operand
    return (isinstance(test, ast.Name) and a.scope is not None
            and test.id in a.scope.py_tuples)


def check_branch(a, node, kind: str) -> None:
    """R2 — Python branching on traced values inside a traced body."""
    if not a.in_traced():
        return
    if _static_truthiness(a, node.test):
        return
    if a.tainted(node.test):
        stmt = {"if": "if", "while": "while", "assert": "assert"}[kind]
        a.emit("R2", node,
               f"`{stmt}` on a traced value inside a jit-traced function "
               f"branches at trace time")


def check_assign(a, node: ast.Assign) -> None:
    # R5 bookkeeping: remember names holding jitted-step outputs
    if a.scope is not None and isinstance(node.value, ast.Call) \
            and _is_steplike_call(node.value):
        for t in node.targets:
            for n in ast.walk(t):
                if isinstance(n, ast.Name):
                    a.scope.step_results.add(n.id)
    # R6 — assignment mutating non-local state under trace
    if not a.in_traced():
        return
    for t in node.targets:
        _check_mutation_target(a, t)


def check_augassign(a, node: ast.AugAssign) -> None:
    if not a.in_traced():
        return
    _check_mutation_target(a, node.target, aug=True)


def _check_mutation_target(a, target, aug=False) -> None:
    """R6: writing through an attribute/subscript whose base is not a
    local of the traced function mutates Python state at trace time."""
    if isinstance(target, (ast.Tuple, ast.List)):
        for e in target.elts:
            _check_mutation_target(a, e, aug)
        return
    base = None
    if isinstance(target, ast.Attribute):
        base = target.value
    elif isinstance(target, ast.Subscript):
        base = target.value
    elif aug and isinstance(target, ast.Name) \
            and target.id not in a.scope.locals:
        a.emit("R6", target,
               f"augmented assignment to closed-over '{target.id}' inside "
               f"a jit-traced function mutates state at trace time")
        return
    if base is None:
        return
    root = base
    while isinstance(root, (ast.Attribute, ast.Subscript)):
        root = root.value
    if isinstance(root, ast.Name):
        if root.id == "self" or root.id not in a.scope.locals:
            a.emit("R6", target,
                   f"writing to '{root.id}.{getattr(target, 'attr', '[..]')}'"
                   f" inside a jit-traced function mutates closed-over "
                   f"Python state at trace time"
                   if isinstance(target, ast.Attribute) else
                   f"subscript write into closed-over '{root.id}' inside a "
                   f"jit-traced function mutates state at trace time")


_MUTATING_METHODS = {"append", "extend", "insert", "add", "update",
                     "setdefault", "pop", "clear", "remove"}


def check_mutating_call(a, node: ast.Call) -> None:
    """R6 via mutating method calls on closed-over containers."""
    if not a.in_traced():
        return
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in _MUTATING_METHODS \
            and isinstance(f.value, ast.Name) \
            and f.value.id not in a.scope.locals:
        a.emit("R6", node,
               f"'{f.value.id}.{f.attr}()' inside a jit-traced function "
               f"mutates closed-over Python state at trace time")


def check_wrap_site(a, site: dict) -> None:
    """R3 — signature hazards at a jit wrap site (call or decorator)."""
    fn = site["fn"]
    if isinstance(fn, ast.Lambda):
        return
    call, nums, names = site["call"], site["static_argnums"], \
        site["static_argnames"]
    args = fn.args
    params = args.posonlyargs + args.args
    defaults = args.defaults
    # map trailing defaults onto params
    pad = [None] * (len(params) - len(defaults))
    p_defaults = pad + list(defaults)
    for idx, (p, default) in enumerate(zip(params, p_defaults)):
        if p.arg in ("self", "cls"):
            continue
        # static_argnums count the unbound function's positions (a
        # leading self/cls occupies index 0 — JAX's convention)
        is_static = idx in nums or p.arg in names
        if default is not None and isinstance(default, ast.Constant) \
                and isinstance(default.value, str) and not is_static:
            a.emit("R3", call,
                   f"jit-wrapped '{fn.name}' takes string parameter "
                   f"'{p.arg}' without marking it static — every distinct "
                   f"value fails (or retraces) at trace time")
        if is_static and isinstance(default, (ast.List, ast.Dict, ast.Set)):
            a.emit("R3", call,
                   f"static parameter '{p.arg}' of jit-wrapped '{fn.name}' "
                   f"has a non-hashable default — jit's cache key will "
                   f"raise TypeError")
    for p, default in zip(args.kwonlyargs, args.kw_defaults):
        is_static = p.arg in names
        if default is not None and isinstance(default, ast.Constant) \
                and isinstance(default.value, str) and not is_static:
            a.emit("R3", call,
                   f"jit-wrapped '{fn.name}' takes string parameter "
                   f"'{p.arg}' without marking it static — every distinct "
                   f"value fails (or retraces) at trace time")
        if is_static and isinstance(default, (ast.List, ast.Dict, ast.Set)):
            a.emit("R3", call,
                   f"static parameter '{p.arg}' of jit-wrapped '{fn.name}' "
                   f"has a non-hashable default — jit's cache key will "
                   f"raise TypeError")
