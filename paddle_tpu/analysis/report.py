"""Finding emitters: compiler-style text and machine-readable JSON."""
from __future__ import annotations

from collections import Counter
from typing import List, Optional, TextIO

from .analyzer import Finding

__all__ = ["render_text", "render_json", "summary_line"]

_SEV_ORDER = {"error": 0, "warning": 1, "info": 2}


def sort_findings(findings: List[Finding]) -> List[Finding]:
    return sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule))


def render_text(findings: List[Finding], stream: TextIO,
                show_hints: bool = True) -> None:
    """``file:line:col: RULE severity: message`` (+ indented fix hint),
    the clickable compiler convention."""
    for f in sort_findings(findings):
        stream.write(f"{f.path}:{f.line}:{f.col}: {f.rule} "
                     f"{f.severity}: {f.message} [{f.context}]\n")
        if show_hints and f.hint:
            stream.write(f"    hint: {f.hint}\n")


def render_json(findings: List[Finding], stale: Optional[List[dict]] = None,
                n_baselined: int = 0) -> dict:
    by_rule = Counter(f.rule for f in findings)
    return {
        "findings": [f.to_dict() for f in sort_findings(findings)],
        "by_rule": dict(sorted(by_rule.items())),
        "baselined": n_baselined,
        "stale_baseline_entries": stale or [],
    }


def summary_line(n_new: int, n_baselined: int, n_stale: int,
                 n_files: int) -> str:
    parts = [f"{n_files} files", f"{n_new} new finding(s)"]
    if n_baselined:
        parts.append(f"{n_baselined} baselined")
    if n_stale:
        parts.append(f"{n_stale} stale baseline entr"
                     + ("y" if n_stale == 1 else "ies"))
    return ", ".join(parts)
