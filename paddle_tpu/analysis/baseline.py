"""Baseline / ratchet store for tpu-lint (the Infer/RacerD landing
strategy): pre-existing findings are recorded in a committed JSON file
and tolerated; anything NEW fails CI; a FIXED finding makes its baseline
entry stale, prompting a regenerate — so the debt can only shrink.

Entries key on ``(file, rule, enclosing-function)`` with a count, never
on line numbers — unrelated edits must not invalidate the baseline.
"""
from __future__ import annotations

import json
from collections import Counter
from typing import Dict, List, Tuple

from .analyzer import Finding

__all__ = ["load_baseline", "make_baseline", "save_baseline", "compare"]

_VERSION = 1


def make_baseline(findings: List[Finding]) -> dict:
    counts = Counter(f.key() for f in findings)
    entries = [
        {"file": path, "rule": rule, "context": ctx, "count": n}
        for (path, rule, ctx), n in sorted(counts.items())
    ]
    return {"version": _VERSION, "entries": entries}


def save_baseline(path: str, baseline: dict) -> None:
    with open(path, "w") as f:
        json.dump(baseline, f, indent=2, sort_keys=True)
        f.write("\n")


def load_baseline(path: str) -> dict:
    with open(path) as f:
        data = json.load(f)
    if not isinstance(data, dict) or "entries" not in data:
        raise ValueError(f"{path}: not a tpu-lint baseline file")
    return data


def compare(findings: List[Finding], baseline: dict
            ) -> Tuple[List[Finding], List[dict], int]:
    """(new_findings, stale_entries, n_baselined).

    - *new*: findings over their key's baselined count (all of a key's
      findings are reported when it exceeds budget — line numbers inside
      one function aren't stable enough to pick "the new one");
    - *stale*: baseline entries whose key now has FEWER findings than
      recorded (burned down — regenerate to ratchet the budget down);
    - *n_baselined*: findings absorbed by the baseline.
    """
    allowed: Dict[Tuple[str, str, str], int] = {
        (e["file"], e["rule"], e["context"]): int(e.get("count", 0))
        for e in baseline.get("entries", [])
    }
    observed = Counter(f.key() for f in findings)
    new: List[Finding] = []
    n_baselined = 0
    for key, n in observed.items():
        budget = allowed.get(key, 0)
        if n > budget:
            new.extend(f for f in findings if f.key() == key)
        else:
            n_baselined += n
    stale = [
        {"file": k[0], "rule": k[1], "context": k[2], "count": budget,
         "observed": observed.get(k, 0)}
        for k, budget in sorted(allowed.items())
        if observed.get(k, 0) < budget
    ]
    return new, stale, n_baselined
