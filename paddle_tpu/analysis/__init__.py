"""tpu-lint — AST-based tracer-safety & retrace-hazard analysis.

Static companion to the *runtime* retrace tracker (profiler.tracked_jit):
eight rules (R1–R8) catch tracer concretization, data-dependent Python
control flow, jit-signature retrace hazards, per-leaf H2D dispatch
loops, host syncs in hot paths, trace-time state mutation, float64 on
TPU, and telemetry calls under trace — all before a single step runs.
CLI front end: ``tools/tpu_lint.py`` (with a ratcheting baseline gate).
"""
from .analyzer import Analyzer, Finding, analyze_source, parse_suppressions
from .baseline import compare, load_baseline, make_baseline, save_baseline
from .report import render_json, render_text, summary_line
from .rules import RULES

__all__ = [
    "Analyzer", "Finding", "analyze_source", "parse_suppressions",
    "compare", "load_baseline", "make_baseline", "save_baseline",
    "render_json", "render_text", "summary_line", "RULES",
]
