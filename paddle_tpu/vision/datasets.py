"""Vision datasets — parity with python/paddle/vision/datasets/ (MNIST,
FashionMNIST, Cifar10/100) + python/paddle/dataset builtins.

Zero-egress environment: datasets load from local files when present
(``image_path``/``label_path``/``data_file``); otherwise ``mode='synthetic'``
or the FakeData dataset provides deterministic synthetic samples so the full
training pipeline (bench, tests, examples) runs without network access.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ..io.dataset import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "FakeData", "Flowers"]


class FakeData(Dataset):
    """Deterministic synthetic dataset for pipelines without local data."""

    def __init__(self, num_samples=1024, image_shape=(1, 28, 28), num_classes=10,
                 transform=None, seed=0):
        self.num_samples = num_samples
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self.seed = seed

    def __getitem__(self, idx):
        rng = np.random.RandomState(self.seed + idx)
        img = rng.rand(*self.image_shape).astype(np.float32)
        label = np.array([rng.randint(0, self.num_classes)], np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return self.num_samples


class MNIST(Dataset):
    NUM_CLASSES = 10
    _shape = (1, 28, 28)

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        self.mode = mode
        self.transform = transform
        if image_path and os.path.exists(image_path):
            self.images = self._read_images(image_path)
            self.labels = self._read_labels(label_path)
        else:
            # zero-egress fallback: deterministic synthetic digits
            n = 2048 if mode == "train" else 512
            rng = np.random.RandomState(42 if mode == "train" else 7)
            self.labels = rng.randint(0, 10, size=(n, 1)).astype(np.int64)
            self.images = np.zeros((n, 28, 28), np.float32)
            for i, lab in enumerate(self.labels[:, 0]):
                img = rng.rand(28, 28).astype(np.float32) * 0.1
                img[2 + lab : 26, 4 : 6 + lab] += 0.8  # label-correlated pattern
                self.images[i] = np.clip(img, 0, 1)

    @staticmethod
    def _read_images(path):
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            data = np.frombuffer(f.read(), dtype=np.uint8)
        return (data.reshape(n, rows, cols).astype(np.float32) / 255.0)

    @staticmethod
    def _read_labels(path):
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            magic, n = struct.unpack(">II", f.read(8))
            data = np.frombuffer(f.read(), dtype=np.uint8)
        return data.reshape(-1, 1).astype(np.int64)

    def __getitem__(self, idx):
        img = self.images[idx]
        label = self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img[None].astype(np.float32) if img.ndim == 2 else img
        return img, label

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    NUM_CLASSES = 10

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.mode = mode
        self.transform = transform
        if data_file and os.path.exists(data_file):
            self.data = self._load_tar(data_file, mode)
        else:
            n = 1024 if mode == "train" else 256
            rng = np.random.RandomState(11 if mode == "train" else 13)
            self.data = [
                (rng.rand(3, 32, 32).astype(np.float32),
                 np.int64(rng.randint(self.NUM_CLASSES)))
                for _ in range(n)
            ]

    def _load_tar(self, path, mode):
        out = []
        names = (
            [f"data_batch_{i}" for i in range(1, 6)] if mode == "train"
            else ["test_batch"]
        )
        with tarfile.open(path) as tf:
            for m in tf.getmembers():
                if any(m.name.endswith(n) for n in names):
                    d = pickle.load(tf.extractfile(m), encoding="bytes")
                    imgs = d[b"data"].reshape(-1, 3, 32, 32).astype(np.float32) / 255.0
                    labels = d.get(b"labels", d.get(b"fine_labels"))
                    out.extend(
                        (img, np.int64(lab)) for img, lab in zip(imgs, labels)
                    )
        return out

    def __getitem__(self, idx):
        img, label = self.data[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, np.array([label], np.int64)

    def __len__(self):
        return len(self.data)


class Cifar100(Cifar10):
    NUM_CLASSES = 100


class Flowers(FakeData):
    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend=None):
        super().__init__(num_samples=512, image_shape=(3, 64, 64), num_classes=102,
                         transform=transform)
