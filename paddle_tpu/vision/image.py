"""Image IO backend — parity with python/paddle/vision/image.py
(set_image_backend / get_image_backend / image_load). 'pil' and 'cv2'
mirror the reference backends; 'tensor' decodes to a paddle Tensor via
numpy (no torch/cv2 dependency needed for the common path)."""
from __future__ import annotations

__all__ = ["set_image_backend", "get_image_backend", "image_load"]

_image_backend = "pil"


def set_image_backend(backend):
    global _image_backend
    if backend not in ("pil", "cv2", "tensor"):
        raise ValueError(
            f"Expected backend are one of ['pil', 'cv2', 'tensor'], "
            f"but got {backend}")
    _image_backend = backend


def get_image_backend():
    return _image_backend


def image_load(path, backend=None):
    """Load an image; returns a PIL Image ('pil'), ndarray ('cv2') or
    Tensor ('tensor')."""
    backend = backend or _image_backend
    if backend not in ("pil", "cv2", "tensor"):
        raise ValueError(
            f"Expected backend are one of ['pil', 'cv2', 'tensor'], "
            f"but got {backend}")
    if backend == "pil":
        from PIL import Image

        return Image.open(path)
    import numpy as np
    from PIL import Image

    img = Image.open(path)
    if backend == "cv2":
        # cv2.imread default decodes EVERY format to 3-channel BGR
        # (palette expanded, alpha dropped) — reversing raw PIL output
        # would produce ABGR for RGBA and index maps for 'P' images
        arr = np.asarray(img.convert("RGB"))
        return arr[..., ::-1]
    arr = np.asarray(img)
    from ..core.tensor import to_tensor

    return to_tensor(arr)
