"""paddle_tpu.vision — models/transforms/datasets (parity python/paddle/vision)."""
from . import datasets, transforms  # noqa: F401
from . import image  # noqa: F401
from . import models  # noqa: F401
from . import ops  # noqa: F401
from .models import *  # noqa: F401,F403
