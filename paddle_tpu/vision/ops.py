"""Detection ops — TPU-first re-design of the reference's
operators/detection/ family (yolo_box_op.cc, prior_box_op.cc,
box_coder_op.cc, multiclass_nms_op.cc, roi_align_op.cc,
iou_similarity_op.cc).

Every op is STATIC-SHAPED (XLA requirement): NMS returns a fixed
[keep_top_k] padded detection block plus a valid count instead of the
reference's LoD output (same content as its multiclass_nms2 variant), and
suppression runs as a ``lax.scan`` over score-sorted candidates rather than
data-dependent loops.
"""
from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..core.enforce import enforce
from ..core.tensor import Tensor, apply_op

__all__ = [
    "yolo_box", "prior_box", "box_coder", "multiclass_nms", "roi_align",
    "iou_similarity", "box_iou", "psroi_pool", "deform_conv2d", "spp",
    "space_to_depth_stem_conv",
]


def _t(x):
    from ..core.tensor import to_tensor

    return x if isinstance(x, Tensor) else to_tensor(x)


# ---------------------------------------------------------------------------
# yolo_box (reference: operators/detection/yolo_box_op.h GetYoloBox)
# ---------------------------------------------------------------------------
def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, name=None, scale_x_y=1.0):
    """Decode one YOLOv3 head.

    x: [N, an*(5+class_num), H, W]; img_size: [N, 2] (h, w).
    Returns (boxes [N, an*H*W, 4] in x1y1x2y2 image coords,
    scores [N, an*H*W, class_num]). Predictions whose objectness confidence
    is below ``conf_thresh`` produce zero boxes and scores (the reference
    skips them, leaving zeros)."""
    anchors = np.asarray(anchors, np.float32).reshape(-1, 2)
    an = anchors.shape[0]
    scale = float(scale_x_y)
    bias = -0.5 * (scale - 1.0)

    def f(xr, img):
        n, c, h, w = xr.shape
        xr = xr.reshape(n, an, 5 + class_num, h, w)
        img_h = img[:, 0].astype(jnp.float32)[:, None, None, None]
        img_w = img[:, 1].astype(jnp.float32)[:, None, None, None]
        in_h = float(downsample_ratio * h)
        in_w = float(downsample_ratio * w)
        gx = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
        gy = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
        aw = jnp.asarray(anchors[:, 0])[None, :, None, None]
        ah = jnp.asarray(anchors[:, 1])[None, :, None, None]

        cx = (gx + jax.nn.sigmoid(xr[:, :, 0]) * scale + bias) * img_w / w
        cy = (gy + jax.nn.sigmoid(xr[:, :, 1]) * scale + bias) * img_h / h
        bw = jnp.exp(xr[:, :, 2]) * aw * img_w / in_w
        bh = jnp.exp(xr[:, :, 3]) * ah * img_h / in_h
        x1, y1 = cx - bw / 2, cy - bh / 2
        x2, y2 = cx + bw / 2, cy + bh / 2
        if clip_bbox:
            x1 = jnp.clip(x1, 0.0, img_w - 1.0)
            y1 = jnp.clip(y1, 0.0, img_h - 1.0)
            x2 = jnp.clip(x2, 0.0, img_w - 1.0)
            y2 = jnp.clip(y2, 0.0, img_h - 1.0)
        conf = jax.nn.sigmoid(xr[:, :, 4])  # [n, an, h, w]
        keep = (conf >= conf_thresh).astype(xr.dtype)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1) * keep[..., None]
        cls = jax.nn.sigmoid(xr[:, :, 5:])  # [n, an, C, h, w]
        scores = cls * (conf * keep)[:, :, None]
        boxes = boxes.reshape(n, an * h * w, 4)
        scores = scores.transpose(0, 1, 3, 4, 2).reshape(
            n, an * h * w, class_num)
        return boxes, scores

    return apply_op(f, _t(x), _t(img_size).detach(), multi_out=True)


# ---------------------------------------------------------------------------
# prior_box (reference: operators/detection/prior_box_op.h)
# ---------------------------------------------------------------------------
def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None,
              min_max_aspect_ratios_order=False):
    """SSD prior (anchor) boxes for one feature map.

    Returns (boxes [H, W, P, 4] normalized x1y1x2y2,
    variances [H, W, P, 4]). Prior order per cell matches the reference:
    for each min_size — ar=1 box, extra aspect-ratio boxes, then the
    sqrt(min·max) box (or the min/max-first order when
    ``min_max_aspect_ratios_order=True``)."""
    min_sizes = [float(s) for s in np.atleast_1d(min_sizes)]
    max_sizes = [float(s) for s in np.atleast_1d(max_sizes)] if max_sizes \
        else []
    # ExpandAspectRatios: 1.0 first, dedup, flip adds reciprocals
    ars = [1.0]
    for ar in aspect_ratios:
        ar = float(ar)
        if not any(abs(ar - e) < 1e-6 for e in ars):
            ars.append(ar)
            if flip:
                ars.append(1.0 / ar)

    def f(feat, img):
        h, w = feat.shape[2], feat.shape[3]
        img_h, img_w = float(img.shape[2]), float(img.shape[3])
        step_w = float(steps[0]) or img_w / w
        step_h = float(steps[1]) or img_h / h
        cx = (jnp.arange(w, dtype=jnp.float32) + offset) * step_w
        cy = (jnp.arange(h, dtype=jnp.float32) + offset) * step_h
        cxg, cyg = jnp.meshgrid(cx, cy)  # [h, w]
        whs = []
        for k, ms in enumerate(min_sizes):
            per = []
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    per.append((ms, ms))
                else:
                    per.append((ms * math.sqrt(ar), ms / math.sqrt(ar)))
            if max_sizes:
                s = math.sqrt(ms * max_sizes[k])
                sq = (s, s)
                if min_max_aspect_ratios_order:
                    per = [per[0], sq] + per[1:]
                else:
                    per = per + [sq]
            whs.extend(per)
        bw = jnp.asarray([p[0] for p in whs], jnp.float32) / img_w / 2
        bh = jnp.asarray([p[1] for p in whs], jnp.float32) / img_h / 2
        ncx = (cxg / img_w)[..., None]
        ncy = (cyg / img_h)[..., None]
        boxes = jnp.stack([ncx - bw, ncy - bh, ncx + bw, ncy + bh], axis=-1)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32),
                               boxes.shape)
        return boxes, var

    return apply_op(f, _t(input).detach(), _t(image).detach(),
                    multi_out=True)


# ---------------------------------------------------------------------------
# box_coder (reference: operators/detection/box_coder_op.h)
# ---------------------------------------------------------------------------
def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, name=None,
              axis=0):
    """Encode targets against priors / decode deltas with priors.

    encode: target [N, 4], prior [M, 4] → [N, M, 4].
    decode: target [N, M, 4], prior broadcast on ``axis`` → [N, M, 4].
    ``prior_box_var`` may be None, a [M, 4] tensor, or 4 floats."""
    norm = 0.0 if box_normalized else 1.0
    var_is_list = isinstance(prior_box_var, (list, tuple))
    var_list = [float(v) for v in prior_box_var] if var_is_list else None

    def split_prior(p):
        pw = p[..., 2] - p[..., 0] + norm
        ph = p[..., 3] - p[..., 1] + norm
        px = p[..., 0] + pw / 2
        py = p[..., 1] + ph / 2
        return px, py, pw, ph

    def f(prior, target, *maybe_var):
        var = maybe_var[0] if maybe_var else (
            jnp.asarray(var_list, jnp.float32) if var_list is not None
            else None)
        px, py, pw, ph = split_prior(prior)
        if code_type == "encode_center_size":
            tw = target[:, 2] - target[:, 0] + norm
            th = target[:, 3] - target[:, 1] + norm
            tx = target[:, 0] + tw / 2
            ty = target[:, 1] + th / 2
            ox = (tx[:, None] - px[None, :]) / pw[None, :]
            oy = (ty[:, None] - py[None, :]) / ph[None, :]
            ow = jnp.log(jnp.abs(tw[:, None] / pw[None, :]))
            oh = jnp.log(jnp.abs(th[:, None] / ph[None, :]))
            out = jnp.stack([ox, oy, ow, oh], axis=-1)
            if var is not None:
                out = out / jnp.broadcast_to(var, out.shape)
            return out
        # decode_center_size: target [N, M, 4]; prior broadcasts on `axis`
        # (axis=0: prior per column [1, M]; axis=1: prior per row [N, 1])
        bc = (lambda a: a[None, :]) if axis == 0 else (lambda a: a[:, None])
        px, py, pw, ph = (bc(v) for v in (px, py, pw, ph))
        t = target
        if var is not None:
            if var.ndim == 1:  # 4 floats
                v = var[None, None, :]
            else:  # [M, 4] or [N, 4] aligned with the prior axis
                v = bc(var)
            t = t * v
        ox = pw * t[..., 0] + px
        oy = ph * t[..., 1] + py
        ow = jnp.exp(t[..., 2]) * pw
        oh = jnp.exp(t[..., 3]) * ph
        return jnp.stack([ox - ow / 2, oy - oh / 2,
                          ox + ow / 2 - norm, oy + oh / 2 - norm], axis=-1)

    args = [_t(prior_box), _t(target_box)]
    if prior_box_var is not None and not var_is_list:
        args.append(_t(prior_box_var))
    return apply_op(f, *args)


# ---------------------------------------------------------------------------
# IOU
# ---------------------------------------------------------------------------
def _iou_matrix(a, b, normalized=True):
    """a [..., A, 4], b [..., B, 4] → [..., A, B]."""
    norm = 0.0 if normalized else 1.0
    ax1, ay1, ax2, ay2 = (a[..., :, None, i] for i in range(4))
    bx1, by1, bx2, by2 = (b[..., None, :, i] for i in range(4))
    iw = jnp.clip(jnp.minimum(ax2, bx2) - jnp.maximum(ax1, bx1) + norm,
                  0.0, None)
    ih = jnp.clip(jnp.minimum(ay2, by2) - jnp.maximum(ay1, by1) + norm,
                  0.0, None)
    inter = iw * ih
    area_a = jnp.clip(ax2 - ax1 + norm, 0.0, None) * \
        jnp.clip(ay2 - ay1 + norm, 0.0, None)
    area_b = jnp.clip(bx2 - bx1 + norm, 0.0, None) * \
        jnp.clip(by2 - by1 + norm, 0.0, None)
    union = area_a + area_b - inter
    return jnp.where(union > 0, inter / jnp.maximum(union, 1e-10), 0.0)


def iou_similarity(x, y, box_normalized=True, name=None):
    """Pairwise IoU (reference: iou_similarity_op): x [N,4], y [M,4] →
    [N, M]."""
    return apply_op(partial(_iou_matrix, normalized=box_normalized),
                    _t(x), _t(y))


box_iou = iou_similarity


# ---------------------------------------------------------------------------
# multiclass_nms (reference: operators/detection/multiclass_nms_op.cc)
# ---------------------------------------------------------------------------
def _nms_class(boxes, scores, score_threshold, nms_top_k, nms_threshold,
               nms_eta, normalized):
    """One class, one image: returns (keep mask [K], scores [K], idx [K])
    for the nms_top_k score-sorted candidates."""
    k = nms_top_k
    order = jnp.argsort(-scores)[:k]
    s = scores[order]
    b = boxes[order]
    valid = s > score_threshold
    iou = _iou_matrix(b, b, normalized=normalized)  # [K, K]

    def step(carry, i):
        keep, thr = carry
        # suppressed if any already-kept earlier candidate overlaps > thr
        earlier = jnp.arange(k) < i
        sup = jnp.any(earlier & keep & (iou[i] > thr))
        ki = valid[i] & ~sup
        keep = keep.at[i].set(ki)
        thr = jnp.where(ki & (nms_eta < 1.0) & (thr > 0.5), thr * nms_eta,
                        thr)
        return (keep, thr), None

    keep0 = jnp.zeros((k,), bool)
    (keep, _), _ = jax.lax.scan(step, (keep0, jnp.float32(nms_threshold)),
                                jnp.arange(k))
    return keep, s, order


def multiclass_nms(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                   nms_threshold=0.3, normalized=True, nms_eta=1.0,
                   background_label=0, name=None, return_index=False):
    """Static-shape multiclass NMS.

    bboxes: [N, M, 4]; scores: [N, C, M]. Returns
    (out [N, keep_top_k, 6] rows = (label, score, x1, y1, x2, y2) padded
    with label -1, nms_rois_num [N]) — the fixed-size form of the
    reference's LoD output (content matches multiclass_nms2, which also
    returns per-image counts). Suppression is a ``lax.scan`` over the
    nms_top_k score-sorted candidates per class — fully batched on the
    accelerator, no host loop."""
    def f(bb, sc):
        n, m, _ = bb.shape
        c = sc.shape[1]
        ktk = min(int(nms_top_k), m)
        # keep_top_k = -1 (reference: keep everything) → all candidates
        kt = c * ktk if int(keep_top_k) < 0 else int(keep_top_k)

        def per_image(boxes, scores_ci):
            keeps, ss, idxs = jax.vmap(
                lambda s_c: _nms_class(boxes, s_c, score_threshold, ktk,
                                       nms_threshold, nms_eta, normalized)
            )(scores_ci)  # [C, K] each
            labels = jnp.broadcast_to(jnp.arange(c)[:, None],
                                      keeps.shape)
            if background_label >= 0:
                keeps = keeps & (labels != background_label)
            flat_keep = keeps.reshape(-1)
            flat_s = jnp.where(flat_keep, ss.reshape(-1), -jnp.inf)
            flat_lab = labels.reshape(-1)
            flat_idx = idxs.reshape(-1)
            top = jnp.argsort(-flat_s)[:kt]
            sel_valid = flat_keep[top]
            sel_s = ss.reshape(-1)[top]
            sel_lab = flat_lab[top].astype(jnp.float32)
            sel_box = boxes[flat_idx[top]]
            row = jnp.concatenate(
                [jnp.where(sel_valid, sel_lab, -1.0)[:, None],
                 jnp.where(sel_valid, sel_s, 0.0)[:, None],
                 sel_box * sel_valid[:, None].astype(boxes.dtype)], axis=1)
            return row, sel_valid.sum().astype(jnp.int32), flat_idx[top]

        rows, counts, indices = jax.vmap(per_image)(bb, sc)
        return rows, counts, indices

    out, counts, idx = apply_op(f, _t(bboxes).detach(), _t(scores).detach(),
                                multi_out=True)
    if return_index:
        return out, counts, idx
    return out, counts


# ---------------------------------------------------------------------------
# roi_align (reference: operators/detection/roi_align_op.cc)
# ---------------------------------------------------------------------------
def roi_align(input, boxes, output_size, spatial_scale=1.0,
              sampling_ratio=-1, boxes_num=None, aligned=True, name=None):
    """RoIAlign: input [N, C, H, W], boxes [R, 4] (x1, y1, x2, y2),
    boxes_num [N] (rois per image, in order) → [R, C, ph, pw].

    TPU-first: ``sampling_ratio=-1`` uses a FIXED 2×2 sample grid per bin
    (the detectron default) instead of the reference's per-roi adaptive
    count — XLA needs static shapes; pass an explicit ratio for parity
    with adaptive cases. ``aligned=True`` applies the -0.5 half-pixel
    offset (roi_align_op.cc's continuous coordinate mode)."""
    if isinstance(output_size, int):
        ph = pw = int(output_size)
    else:
        ph, pw = int(output_size[0]), int(output_size[1])
    sr = int(sampling_ratio) if int(sampling_ratio) > 0 else 2

    def f(feat, rois, rois_n):
        n, ch, h, w = feat.shape
        r = rois.shape[0]
        # rois_n -> per-roi batch index (static total length R)
        cum = jnp.cumsum(rois_n)
        batch_idx = jnp.searchsorted(cum, jnp.arange(r), side="right")
        off = 0.5 if aligned else 0.0
        x1 = rois[:, 0] * spatial_scale - off
        y1 = rois[:, 1] * spatial_scale - off
        x2 = rois[:, 2] * spatial_scale - off
        y2 = rois[:, 3] * spatial_scale - off
        rw = x2 - x1
        rh = y2 - y1
        if not aligned:
            rw = jnp.maximum(rw, 1.0)
            rh = jnp.maximum(rh, 1.0)
        bin_w = rw / pw
        bin_h = rh / ph
        # sample points: y = y1 + (iy + (s + .5)/sr) * bin_h
        gy = (jnp.arange(ph)[:, None] +
              (jnp.arange(sr)[None, :] + 0.5) / sr).reshape(-1)  # [ph*sr]
        gx = (jnp.arange(pw)[:, None] +
              (jnp.arange(sr)[None, :] + 0.5) / sr).reshape(-1)
        sy = y1[:, None] + gy[None, :] * bin_h[:, None]  # [R, ph*sr]
        sx = x1[:, None] + gx[None, :] * bin_w[:, None]

        def bilinear(img, yy, xx):
            # img [C, H, W]; yy [P], xx [Q] -> [C, P, Q]
            y0 = jnp.clip(jnp.floor(yy), 0, h - 1)
            x0 = jnp.clip(jnp.floor(xx), 0, w - 1)
            y0i = y0.astype(jnp.int32)
            x0i = x0.astype(jnp.int32)
            y1i = jnp.clip(y0i + 1, 0, h - 1)
            x1i = jnp.clip(x0i + 1, 0, w - 1)
            wy1 = jnp.clip(yy - y0, 0.0, 1.0)
            wx1 = jnp.clip(xx - x0, 0.0, 1.0)
            wy0, wx0 = 1.0 - wy1, 1.0 - wx1
            # out-of-range samples contribute 0 (reference: empty when
            # y < -1 or y > H)
            oob_y = (yy < -1.0) | (yy > h)
            oob_x = (xx < -1.0) | (xx > w)
            g = lambda yi, xi: img[:, yi][:, :, xi]
            out = (g(y0i, x0i) * (wy0[:, None] * wx0[None, :])[None]
                   + g(y0i, x1i) * (wy0[:, None] * wx1[None, :])[None]
                   + g(y1i, x0i) * (wy1[:, None] * wx0[None, :])[None]
                   + g(y1i, x1i) * (wy1[:, None] * wx1[None, :])[None])
            mask = (~oob_y)[:, None] & (~oob_x)[None, :]
            return out * mask[None]

        def per_roi(bi, yy, xx):
            img = feat[bi]
            samples = bilinear(img, yy, xx)  # [C, ph*sr, pw*sr]
            samples = samples.reshape(ch, ph, sr, pw, sr)
            return samples.mean(axis=(2, 4))

        return jax.vmap(per_roi)(batch_idx, sy, sx)

    if boxes_num is None:
        if _t(input).shape[0] != 1:
            raise ValueError(
                "roi_align: boxes_num is required when the input batch has "
                "more than one image (otherwise every RoI would silently "
                "pool from image 0)")
        bn = jnp.asarray([_t(boxes).shape[0]], jnp.int32)
        return apply_op(lambda ft, ro: f(ft, ro, bn), _t(input), _t(boxes))
    return apply_op(f, _t(input), _t(boxes), _t(boxes_num).detach())


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI average pooling (R-FCN).

    Parity with the reference's psroi_pool op
    (/root/reference/paddle/fluid/operators/psroi_pool_op.h CPUPSROIPoolOpKernel):
    ``x`` [N, C, H, W] with C = out_channels·ph·pw, ``boxes`` [R, 4]
    (x1, y1, x2, y2), ``boxes_num`` [N] → [R, out_channels, ph, pw]. Roi
    coords are rounded then scaled, bins use floor/ceil edges, empty bins
    yield 0 — matching the kernel exactly.

    TPU-first: instead of per-roi scalar loops over dynamic [hstart, hend)
    ranges, each bin is a MASKED mean over the full H/W extent — row/col
    membership masks [R, ph, H] / [R, pw, W] contracted against the
    (c, i, j)-factorized feature map in one einsum. Static shapes,
    vectorized over rois, differentiable.
    """
    if isinstance(output_size, int):
        ph = pw = int(output_size)
    else:
        ph, pw = int(output_size[0]), int(output_size[1])

    def f(feat, rois, rois_n):
        n, cin, h, w = feat.shape
        r = rois.shape[0]
        enforce(cin % (ph * pw) == 0,
                f"psroi_pool: C={cin} must be out_channels*{ph}*{pw}")
        cout = cin // (ph * pw)
        cum = jnp.cumsum(rois_n)
        batch_idx = jnp.searchsorted(cum, jnp.arange(r), side="right")

        x1 = jnp.round(rois[:, 0]) * spatial_scale
        y1 = jnp.round(rois[:, 1]) * spatial_scale
        x2 = (jnp.round(rois[:, 2]) + 1.0) * spatial_scale
        y2 = (jnp.round(rois[:, 3]) + 1.0) * spatial_scale
        rh = jnp.maximum(y2 - y1, 0.1)
        rw = jnp.maximum(x2 - x1, 0.1)
        bh = rh / ph
        bw = rw / pw

        ivec = jnp.arange(ph, dtype=feat.dtype)
        jvec = jnp.arange(pw, dtype=feat.dtype)
        hstart = jnp.clip(jnp.floor(ivec[None] * bh[:, None] + y1[:, None]),
                          0, h).astype(jnp.int32)          # [R, ph]
        hend = jnp.clip(jnp.ceil((ivec[None] + 1) * bh[:, None] + y1[:, None]),
                        0, h).astype(jnp.int32)
        wstart = jnp.clip(jnp.floor(jvec[None] * bw[:, None] + x1[:, None]),
                          0, w).astype(jnp.int32)          # [R, pw]
        wend = jnp.clip(jnp.ceil((jvec[None] + 1) * bw[:, None] + x1[:, None]),
                        0, w).astype(jnp.int32)

        ys = jnp.arange(h)
        xs = jnp.arange(w)
        mask_y = ((ys[None, None, :] >= hstart[..., None])
                  & (ys[None, None, :] < hend[..., None])).astype(feat.dtype)
        mask_x = ((xs[None, None, :] >= wstart[..., None])
                  & (xs[None, None, :] < wend[..., None])).astype(feat.dtype)

        # channel axis factorizes as (c, i, j): input_channel = (c*ph+i)*pw+j
        featr = feat[batch_idx].reshape(r, cout, ph, pw, h, w)
        s = jnp.einsum("rcijhw,rih,rjw->rcij", featr, mask_y, mask_x)
        area = ((hend - hstart)[:, None, :, None]
                * (wend - wstart)[:, None, None, :]).astype(feat.dtype)
        return jnp.where(area > 0, s / jnp.maximum(area, 1.0), 0.0)

    return apply_op(f, _t(x), _t(boxes), _t(boxes_num).detach())


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable convolution v1 (``mask=None``) / v2 (modulated).

    Parity with the reference's deformable_conv ops
    (/root/reference/paddle/fluid/operators/deformable_conv_op.cc, v1 op and
    python/paddle/vision/ops.py:397 deform_conv2d): ``x`` [N, Cin, H, W],
    ``offset`` [N, dg·2·kh·kw, Ho, Wo] with per-kernel-position (Δh, Δw)
    channel pairs, ``mask`` [N, dg·kh·kw, Ho, Wo], ``weight``
    [Cout, Cin/g, kh, kw] → [N, Cout, Ho, Wo].

    TPU-first: the reference's deformable_im2col CUDA kernel becomes a
    batched bilinear GATHER building sampled columns [N, K, Cin, Ho·Wo]
    (vectorized over kernel positions and rois via take + arithmetic — no
    scalar loops), followed by ONE grouped MXU contraction with the weight.
    Differentiable in x, offset, mask, and weight through jax autodiff —
    the hand-written col2im/col2im_coord backward kernels are subsumed.
    """
    sh, sw = (stride, stride) if isinstance(stride, int) else map(int, stride)
    ph_, pw_ = (padding, padding) if isinstance(padding, int) else map(int, padding)
    dh, dw = (dilation, dilation) if isinstance(dilation, int) else map(int, dilation)

    def f(xv, off, wv, *rest):
        rest = list(rest)
        bv = rest.pop(0) if bias is not None else None
        mv = rest.pop(0) if mask is not None else None
        n, cin, h, w = xv.shape
        cout, cin_g, kh, kw = wv.shape
        dg = deformable_groups
        K = kh * kw
        ho = (h + 2 * ph_ - (dh * (kh - 1) + 1)) // sh + 1
        wo = (w + 2 * pw_ - (dw * (kw - 1) + 1)) // sw + 1

        # base sampling grid per kernel position k and output location p
        oy = jnp.arange(ho) * sh - ph_
        ox = jnp.arange(wo) * sw - pw_
        ky, kx = jnp.meshgrid(jnp.arange(kh) * dh, jnp.arange(kw) * dw,
                              indexing="ij")
        base_y = oy[None, :, None] + ky.reshape(-1)[:, None, None]  # [K,Ho,1]
        base_x = ox[None, None, :] + kx.reshape(-1)[:, None, None]  # [K,1,Wo]

        off = off.reshape(n, dg, K, 2, ho, wo)
        sy = base_y + off[:, :, :, 0]                    # [N,dg,K,Ho,Wo]
        sx = base_x + off[:, :, :, 1]

        def bilinear(img, yy, xx):
            # img [C_dg, H, W]; yy/xx [K, Ho, Wo] -> [C_dg, K, Ho, Wo]
            y0 = jnp.floor(yy)
            x0 = jnp.floor(xx)
            wy1 = (yy - y0).astype(img.dtype)
            wx1 = (xx - x0).astype(img.dtype)
            out = 0.0
            for iy, wyy in ((y0, 1.0 - wy1), (y0 + 1, wy1)):
                for ix, wxx in ((x0, 1.0 - wx1), (x0 + 1, wx1)):
                    inside = ((iy >= 0) & (iy <= h - 1)
                              & (ix >= 0) & (ix <= w - 1))
                    yi = jnp.clip(iy, 0, h - 1).astype(jnp.int32)
                    xi = jnp.clip(ix, 0, w - 1).astype(jnp.int32)
                    v = img[:, yi, xi]                   # [C_dg, K, Ho, Wo]
                    wgt = (wyy * wxx * inside.astype(img.dtype))[None]
                    out = out + v * wgt
            return out

        # vmap over batch and deformable groups
        xg = xv.reshape(n, dg, cin // dg, h, w)
        cols = jax.vmap(jax.vmap(bilinear))(xg, sy, sx)  # [N,dg,C/dg,K,Ho,Wo]
        if mv is not None:
            cols = cols * mv.reshape(n, dg, 1, K, ho, wo)
        cols = cols.reshape(n, cin, K, ho, wo)

        # grouped contraction: out[n,m,p] = sum_{c_g,k} w[m,c_g,k]·cols
        cols = cols.reshape(n, groups, cin // groups, K, ho, wo)
        wg = wv.reshape(groups, cout // groups, cin_g, K)
        out = jnp.einsum("ngckhw,gmck->ngmhw", cols, wg)
        out = out.reshape(n, cout, ho, wo)
        if bv is not None:
            out = out + bv.reshape(1, -1, 1, 1).astype(out.dtype)
        return out

    args = [_t(x), _t(offset), _t(weight)]
    if bias is not None:
        args.append(_t(bias))
    if mask is not None:
        args.append(_t(mask))
    return apply_op(f, *args)


def spp(x, pyramid_height=3, pooling_type="max", name=None):
    """Spatial pyramid pooling — parity with the reference spp op
    (/root/reference/paddle/fluid/operators/spp_op.cc, spp_op.h): level p
    adaptively pools x:[N, C, H, W] to a [2^p, 2^p] grid, levels are
    flattened and concatenated to [N, C * (4^height - 1) / 3]. Every level
    is a static-shape adaptive pool, so the whole pyramid compiles to one
    fused XLA program."""
    from ..core.enforce import InvalidArgumentError, enforce
    from ..nn import functional as F
    from ..tensor.manipulation import concat, flatten

    enforce(pooling_type in ("max", "avg"),
            f"spp: unknown pooling_type {pooling_type!r}")
    pool = (F.adaptive_max_pool2d if pooling_type == "max"
            else F.adaptive_avg_pool2d)
    x = _t(x)
    outs = []
    for p in range(int(pyramid_height)):
        bins = 2 ** p
        outs.append(flatten(pool(x, bins), start_axis=1))
    return concat(outs, axis=1)


def space_to_depth_stem_conv(x, weight):
    """EXACT space-to-depth reformulation of the ResNet stem conv
    (7x7/stride-2/pad-3) — the standard TPU trick for C_in=3 stems, whose
    tiny contraction badly under-fills the 128-wide MXU:

    pad the 7x7 kernel to 8x8 with zeros, split every spatial index into
    (2a+p), and the stride-2 conv becomes a STRIDE-1 4x4 conv over the
    2x2-space-to-depth input (channels C_in*4 = 12) with the kernel taps
    regrouped — bit-for-bit the same sum, better MXU mapping. x: [N, 3,
    H, W] (H, W even), weight: [C_out, 3, 7, 7]; returns [N, C_out, H/2,
    W/2]. Checkpoint-compatible: the PARAMETER keeps its [C_out,3,7,7]
    shape; the regrouping happens at trace time.
    """
    def f(a, w):
        n, ci, H, W = a.shape
        co = w.shape[0]
        # pad input 3 each side (as the stride-2 conv would), then s2d
        ap = jnp.pad(a, ((0, 0), (0, 0), (3, 3), (3, 3)))
        Hp, Wp = H + 6, W + 6
        z = ap.reshape(n, ci, Hp // 2, 2, Wp // 2, 2)
        z = z.transpose(0, 1, 3, 5, 2, 4).reshape(n, ci * 4, Hp // 2, Wp // 2)
        # kernel: zero-pad 7->8, split taps (2a+p, 2b+q) -> [co, ci*4, 4, 4]
        wp = jnp.pad(w, ((0, 0), (0, 0), (0, 1), (0, 1)))
        w2 = wp.reshape(co, ci, 4, 2, 4, 2)
        w2 = w2.transpose(0, 1, 3, 5, 2, 4).reshape(co, ci * 4, 4, 4)
        out = jax.lax.conv_general_dilated(
            z, w2, window_strides=(1, 1), padding="VALID",
            dimension_numbers=jax.lax.conv_dimension_numbers(
                z.shape, w2.shape, ("NCHW", "OIHW", "NCHW")))
        return out[:, :, :H // 2, :W // 2]

    return apply_op(f, _t(x), weight)
