"""Vision transforms — parity with python/paddle/vision/transforms/ (numpy
backend; HWC uint8/float in, paddle-style CHW float out via ToTensor)."""
from __future__ import annotations

import numbers
import random as pyrandom

import numpy as np

from ..core.tensor import Tensor, to_tensor as _to_tensor_fn

__all__ = [
    "Compose", "ToTensor", "Normalize", "Resize", "RandomCrop", "CenterCrop",
    "RandomHorizontalFlip", "RandomVerticalFlip", "Transpose", "Pad",
    "BrightnessTransform", "RandomRotation", "Grayscale",
    "to_tensor", "normalize", "resize", "hflip", "vflip", "center_crop", "crop",
]


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


def _as_np(img):
    if isinstance(img, Tensor):
        return img.numpy()
    return np.asarray(img)


def to_tensor(pic, data_format="CHW"):
    arr = _as_np(pic)
    if arr.dtype == np.uint8:
        arr = arr.astype(np.float32) / 255.0
    else:
        arr = arr.astype(np.float32)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if data_format == "CHW":
        arr = np.transpose(arr, (2, 0, 1))
    return _to_tensor_fn(arr)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    arr = _as_np(img).astype(np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        arr = (arr - mean.reshape(-1, 1, 1)) / std.reshape(-1, 1, 1)
    else:
        arr = (arr - mean) / std
    return arr if not isinstance(img, Tensor) else _to_tensor_fn(arr)


def resize(img, size, interpolation="bilinear"):
    arr = _as_np(img)
    import jax
    import jax.numpy as jnp

    if isinstance(size, int):
        h, w = arr.shape[:2]
        if h < w:
            size = (size, int(size * w / h))
        else:
            size = (int(size * h / w), size)
    target = tuple(size) + arr.shape[2:]
    method = {"bilinear": "linear", "nearest": "nearest", "bicubic": "cubic"}[interpolation]
    out = np.asarray(jax.image.resize(jnp.asarray(arr, jnp.float32), target, method=method))
    return out.astype(arr.dtype) if arr.dtype == np.uint8 else out


def hflip(img):
    return _as_np(img)[:, ::-1].copy()


def vflip(img):
    return _as_np(img)[::-1].copy()


def crop(img, top, left, height, width):
    return _as_np(img)[top : top + height, left : left + width].copy()


def center_crop(img, output_size):
    arr = _as_np(img)
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    h, w = arr.shape[:2]
    th, tw = output_size
    top = max((h - th) // 2, 0)
    left = max((w - tw) // 2, 0)
    return crop(arr, top, left, th, tw)


class ToTensor:
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def __call__(self, img):
        return to_tensor(img, self.data_format)


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False, keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean, mean, mean]
        if isinstance(std, numbers.Number):
            std = [std, std, std]
        self.mean = mean
        self.std = std
        self.data_format = data_format

    def __call__(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


class Resize:
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = size
        self.interpolation = interpolation

    def __call__(self, img):
        return resize(img, self.size, self.interpolation)


class RandomCrop:
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        if isinstance(size, numbers.Number):
            size = (int(size), int(size))
        self.size = size
        self.padding = padding

    def __call__(self, img):
        arr = _as_np(img)
        if self.padding:
            p = self.padding if not isinstance(self.padding, int) else [self.padding] * 4
            arr = np.pad(arr, [(p[1], p[3]), (p[0], p[2])] + [(0, 0)] * (arr.ndim - 2))
        h, w = arr.shape[:2]
        th, tw = self.size
        top = pyrandom.randint(0, max(h - th, 0))
        left = pyrandom.randint(0, max(w - tw, 0))
        return crop(arr, top, left, th, tw)


class CenterCrop:
    def __init__(self, size, keys=None):
        self.size = size

    def __call__(self, img):
        return center_crop(img, self.size)


class RandomHorizontalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        if pyrandom.random() < self.prob:
            return hflip(img)
        return _as_np(img)


class RandomVerticalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        if pyrandom.random() < self.prob:
            return vflip(img)
        return _as_np(img)


class Transpose:
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def __call__(self, img):
        arr = _as_np(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return np.transpose(arr, self.order)


class Pad:
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        if isinstance(padding, int):
            padding = [padding] * 4
        self.padding = padding
        self.fill = fill

    def __call__(self, img):
        arr = _as_np(img)
        p = self.padding
        return np.pad(
            arr, [(p[1], p[3]), (p[0], p[2])] + [(0, 0)] * (arr.ndim - 2),
            constant_values=self.fill,
        )


class BrightnessTransform:
    def __init__(self, value, keys=None):
        self.value = float(value)

    def __call__(self, img):
        arr = _as_np(img).astype(np.float32)
        factor = 1.0 + pyrandom.uniform(-self.value, self.value)
        return np.clip(arr * factor, 0, 255 if arr.max() > 1 else 1.0)


class RandomRotation:
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees

    def __call__(self, img):
        import scipy.ndimage as ndi

        arr = _as_np(img)
        angle = pyrandom.uniform(*self.degrees)
        return ndi.rotate(arr, angle, reshape=False, order=1)


class Grayscale:
    def __init__(self, num_output_channels=1, keys=None):
        self.num_output_channels = num_output_channels

    def __call__(self, img):
        arr = _as_np(img).astype(np.float32)
        if arr.ndim == 3 and arr.shape[2] == 3:
            g = arr @ np.asarray([0.299, 0.587, 0.114], np.float32)
        else:
            g = arr.squeeze()
        if self.num_output_channels == 3:
            return np.stack([g] * 3, axis=-1)
        return g[..., None]
