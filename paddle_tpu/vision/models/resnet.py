"""ResNet family — parity with python/paddle/vision/models/resnet.py
(resnet18/34/50/101/152). Conv+BN blocks lower to MXU convs with XLA-fused
batchnorm (replacing the reference's fused_bn_activation_op.cu path).
"""
from __future__ import annotations

import numpy as np

from ... import nn

__all__ = ["ResNet", "resnet18", "resnet34", "resnet50", "resnet101", "resnet152"]


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None, groups=1,
                 base_width=64, dilation=1, norm_layer=None):
        super().__init__()
        norm_layer = norm_layer or nn.BatchNorm2D
        self.conv1 = nn.Conv2D(inplanes, planes, 3, padding=1, stride=stride,
                               bias_attr=False)
        self.bn1 = norm_layer(planes)
        self.relu = nn.ReLU()
        self.conv2 = nn.Conv2D(planes, planes, 3, padding=1, bias_attr=False)
        self.bn2 = norm_layer(planes)
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None, groups=1,
                 base_width=64, dilation=1, norm_layer=None):
        super().__init__()
        norm_layer = norm_layer or nn.BatchNorm2D
        width = int(planes * (base_width / 64.0)) * groups
        self.conv1 = nn.Conv2D(inplanes, width, 1, bias_attr=False)
        self.bn1 = norm_layer(width)
        self.conv2 = nn.Conv2D(width, width, 3, padding=dilation, stride=stride,
                               groups=groups, dilation=dilation, bias_attr=False)
        self.bn2 = norm_layer(width)
        self.conv3 = nn.Conv2D(width, planes * self.expansion, 1, bias_attr=False)
        self.bn3 = norm_layer(planes * self.expansion)
        self.relu = nn.ReLU()
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class ResNet(nn.Layer):
    def __init__(self, block, depth=50, width=64, num_classes=1000, with_pool=True,
                 groups=1):
        super().__init__()
        layer_cfg = {
            18: [2, 2, 2, 2], 34: [3, 4, 6, 3], 50: [3, 4, 6, 3],
            101: [3, 4, 23, 3], 152: [3, 8, 36, 3],
        }
        layers = layer_cfg[depth]
        self.groups = groups
        self.base_width = width
        self.num_classes = num_classes
        self.with_pool = with_pool
        self._norm_layer = nn.BatchNorm2D
        self.inplanes = 64
        self.dilation = 1
        self.conv1 = nn.Conv2D(3, self.inplanes, 7, stride=2, padding=3,
                               bias_attr=False)
        self.bn1 = self._norm_layer(self.inplanes)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(3, 2, 1)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], stride=2)
        self.layer3 = self._make_layer(block, 256, layers[2], stride=2)
        self.layer4 = self._make_layer(block, 512, layers[3], stride=2)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _stem(self, x):
        """The 7x7/2 stem; PADDLE_TPU_S2D_STEM=1 opts into the exact
        space-to-depth reformulation (vision.ops.space_to_depth_stem_conv
        — C_in=3 under-fills the MXU; s2d quadruples the contraction).
        Default OFF: measured ~5% SLOWER end-to-end on v5e (1492 vs 1564
        samples/s, b=64 bf16) — this rig's XLA already handles the stem
        well and the pad/regroup reshapes cost more than the conv saves;
        the classic trick is kept as a knob for topologies where it pays."""
        import os

        import jax

        w = getattr(self.conv1, "weight", None)
        if (os.environ.get("PADDLE_TPU_S2D_STEM", "0") == "1"
                and jax.default_backend() == "tpu"
                and x.ndim == 4 and x.shape[2] % 2 == 0
                and x.shape[3] % 2 == 0
                # the reformulation encodes EXACTLY 7x7/stride-2/pad-3
                # bias-free semantics: a customized stem (CIFAR 3x3 etc.)
                # must take the generic conv
                and w is not None and tuple(w.shape[2:]) == (7, 7)
                and self._stem_attr_is(self.conv1, "_stride", 2)
                and self._stem_attr_is(self.conv1, "_dilation", 1)
                and getattr(self.conv1, "_groups", 1) == 1
                and self._stem_attr_is(self.conv1, "_padding", 3)
                and getattr(self.conv1, "bias", None) is None):
            from ..ops import space_to_depth_stem_conv

            return space_to_depth_stem_conv(x, w)
        return self.conv1(x)

    @staticmethod
    def _stem_attr_is(conv, name, value):
        """True iff conv's attr equals ``value`` in every spatial position
        — accepts int, list, tuple, or nested forms; anything unparseable
        safely fails the guard (generic conv path)."""
        v = getattr(conv, name, None)
        if isinstance(v, (int, np.integer)):
            return int(v) == value
        try:
            arr = np.ravel(np.asarray(v))
            return arr.size > 0 and all(int(p) == value for p in arr)
        except Exception:
            return False

    def _make_layer(self, block, planes, blocks, stride=1):
        norm_layer = self._norm_layer
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2D(self.inplanes, planes * block.expansion, 1, stride=stride,
                          bias_attr=False),
                norm_layer(planes * block.expansion),
            )
        layers = [block(self.inplanes, planes, stride, downsample, self.groups,
                        self.base_width, self.dilation, norm_layer)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes,
                                groups=self.groups, base_width=self.base_width,
                                norm_layer=norm_layer))
        return nn.Sequential(*layers)

    def forward(self, x):
        x = self.relu(self.bn1(self._stem(x)))
        x = self.maxpool(x)
        x = self.layer1(x)
        x = self.layer2(x)
        x = self.layer3(x)
        x = self.layer4(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            from ...tensor.manipulation import flatten

            x = flatten(x, 1, -1)
            x = self.fc(x)
        return x


def _resnet(block, depth, pretrained=False, **kwargs):
    model = ResNet(block, depth, **kwargs)
    if pretrained:
        raise NotImplementedError(
            "pretrained weights require network access; load a local "
            "checkpoint with model.set_state_dict(paddle_tpu.load(path))"
        )
    return model


def resnet18(pretrained=False, **kwargs):
    return _resnet(BasicBlock, 18, pretrained, **kwargs)


def resnet34(pretrained=False, **kwargs):
    return _resnet(BasicBlock, 34, pretrained, **kwargs)


def resnet50(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 50, pretrained, **kwargs)


def resnet101(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 101, pretrained, **kwargs)


def resnet152(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 152, pretrained, **kwargs)
