"""paddle.flops — model complexity profiler.

Parity with python/paddle/hapi/dynamic_flops.py:24: per-layer FLOPs
(multiply-add counts, matching the reference's conventions exactly) via
forward post-hooks on leaf layers, a custom_ops override dict keyed by
layer class, an optional per-layer detail table, and an integer total
return. Works on any ``nn.Layer``; static ``Program`` complexity is the
recorded op list's job (static_flops is the reference's separate path).
"""
from __future__ import annotations

import numpy as np

from ..nn import layer_base

__all__ = ["flops", "dynamic_flops"]


def _numel(t):
    return int(np.prod(t.shape)) if hasattr(t, "shape") else 0


def count_convNd(m, x, y):
    x = x[0]
    kernel_ops = int(np.prod(m.weight.shape[2:]))
    bias_ops = 1 if getattr(m, "bias", None) is not None else 0
    groups = getattr(m, "_groups", 1)
    m.total_ops += abs(int(
        _numel(y) * (x.shape[1] / groups * kernel_ops + bias_ops)))


def count_leaky_relu(m, x, y):
    m.total_ops += _numel(x[0])


def count_bn(m, x, y):
    nelements = _numel(x[0])
    if not getattr(m, "training", False):
        m.total_ops += abs(int(2 * nelements))


def count_linear(m, x, y):
    m.total_ops += abs(int(m.weight.shape[0] * _numel(y)))


def count_avgpool(m, x, y):
    m.total_ops += _numel(y)


def count_adap_avgpool(m, x, y):
    kernel = np.array(x[0].shape[2:]) // np.array(y.shape[2:])
    total_add = int(np.prod(kernel))
    m.total_ops += abs(int((total_add + 1) * _numel(y)))


def count_zero_ops(m, x, y):
    m.total_ops += 0


def count_parameters(m, x, y):
    m.total_params = sum(_numel(p) for p in m.parameters(include_sublayers=False))


def count_io_info(m, x, y):
    m.input_shape = list(x[0].shape)
    out = y[0] if isinstance(y, (list, tuple)) else y
    m.output_shape = list(out.shape)


def _register_hooks():
    from .. import nn

    table = {
        nn.Conv1D: count_convNd, nn.Conv2D: count_convNd,
        nn.Conv3D: count_convNd,
        nn.ReLU: count_zero_ops, nn.ReLU6: count_zero_ops,
        nn.LeakyReLU: count_leaky_relu,
        nn.Linear: count_linear,
        nn.Dropout: count_zero_ops,
        nn.AvgPool1D: count_avgpool, nn.AvgPool2D: count_avgpool,
        nn.AvgPool3D: count_avgpool,
        nn.AdaptiveAvgPool1D: count_adap_avgpool,
        nn.AdaptiveAvgPool2D: count_adap_avgpool,
        nn.AdaptiveAvgPool3D: count_adap_avgpool,
    }
    for name, fn in (("Conv1DTranspose", count_convNd),
                     ("Conv2DTranspose", count_convNd),
                     ("Conv3DTranspose", count_convNd),
                     ("BatchNorm", count_bn), ("BatchNorm1D", count_bn),
                     ("BatchNorm2D", count_bn), ("BatchNorm3D", count_bn)):
        cls = getattr(nn, name, None)
        if cls is not None:
            table[cls] = fn
    return table


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Count a network's FLOPs (reference hapi/dynamic_flops.py:24).

    ``net``: an ``nn.Layer`` (static Programs record their op list — use
    the executor's compiled cost model there). ``input_size``: shape of a
    single input batch, e.g. ``[1, 3, 224, 224]``. ``custom_ops``: dict
    mapping layer CLASSES to ``fn(layer, inputs, output)`` that adds into
    ``layer.total_ops``. Returns the integer total; optionally prints the
    per-layer table."""
    from ..core.tensor import to_tensor

    if isinstance(net, layer_base.Layer):
        inputs = to_tensor(np.random.rand(*input_size).astype("float32"))
        return dynamic_flops(net, inputs, custom_ops=custom_ops,
                             print_detail=print_detail)
    raise TypeError(
        "flops expects an nn.Layer instance (static Program complexity "
        "rides the recorded op list; see static executor)")


def dynamic_flops(model, inputs, custom_ops=None, print_detail=False):
    handlers = []
    custom_ops = custom_ops or {}
    register_hooks = _register_hooks()
    seen_types = set()

    def add_hooks(m):
        if len(list(m.children())) > 0:
            return
        m.total_ops = 0
        m.total_params = 0
        m_type = type(m)
        fn = custom_ops.get(m_type, register_hooks.get(m_type))
        if m_type not in seen_types:
            if m_type in custom_ops:
                print(f"Customize Function has been applied to {m_type}")
            elif fn is None:
                print(f"Cannot find suitable count function for {m_type}. "
                      "Treat it as zero FLOPs.")
            seen_types.add(m_type)
        if fn is not None:
            handlers.append(m.register_forward_post_hook(fn))
        handlers.append(m.register_forward_post_hook(count_parameters))
        handlers.append(m.register_forward_post_hook(count_io_info))

    training = model.training
    model.eval()
    model.apply(add_hooks)
    model(inputs)
    if training:
        model.train()
    for h in handlers:
        h.remove()

    rows, total_ops, total_params = [], 0, 0
    for name, m in model.named_sublayers():
        if len(list(m.children())) > 0 or not hasattr(m, "input_shape"):
            continue
        rows.append((m.full_name(), m.input_shape, m.output_shape,
                     int(m.total_params), int(m.total_ops)))
        total_ops += m.total_ops
        total_params += m.total_params
        for attr in ("total_ops", "total_params", "input_shape",
                     "output_shape"):
            delattr(m, attr)

    if print_detail:
        header = ("Layer Name", "Input Shape", "Output Shape",
                  "Params", "Flops")
        all_rows = [tuple(str(c) for c in r) for r in rows]
        widths = [max(len(h), *(len(r[i]) for r in all_rows)) if all_rows
                  else len(h) for i, h in enumerate(header)]
        line = "+" + "+".join("-" * (w + 2) for w in widths) + "+"
        print(line)
        print("|" + "|".join(f" {h:<{w}} " for h, w in zip(header, widths))
              + "|")
        print(line)
        for r in all_rows:
            print("|" + "|".join(f" {c:<{w}} " for c, w in zip(r, widths))
                  + "|")
        print(line)
    print(f"Total Flops: {int(total_ops)}     "
          f"Total Params: {int(total_params)}")
    return int(total_ops)
