"""hapi callbacks — parity with python/paddle/hapi/callbacks.py (Callback,
ProgBarLogger, ModelCheckpoint, EarlyStopping, LRScheduler, VisualDL stub)."""
from __future__ import annotations

import numbers
import time

import numpy as np

__all__ = [
    "Callback", "CallbackList", "ProgBarLogger", "ModelCheckpoint",
    "EarlyStopping", "LRScheduler", "TelemetryLogger", "config_callbacks",
]


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params or {}

    def on_begin(self, mode, logs=None):
        getattr(self, f"on_{mode}_begin", lambda l=None: None)(logs)

    def on_end(self, mode, logs=None):
        getattr(self, f"on_{mode}_end", lambda l=None: None)(logs)

    def on_batch_begin(self, mode, step, logs=None):
        getattr(self, f"on_{mode}_batch_begin", lambda s, l=None: None)(step, logs)

    def on_batch_end(self, mode, step, logs=None):
        getattr(self, f"on_{mode}_batch_end", lambda s, l=None: None)(step, logs)

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def append(self, cb):
        self.callbacks.append(cb)

    def set_model(self, model):
        for cb in self.callbacks:
            cb.set_model(model)

    def set_params(self, params):
        for cb in self.callbacks:
            cb.set_params(params)

    def on_begin(self, mode, logs=None):
        for cb in self.callbacks:
            cb.on_begin(mode, logs)

    def on_end(self, mode, logs=None):
        for cb in self.callbacks:
            cb.on_end(mode, logs)

    def on_epoch_begin(self, epoch, logs=None):
        for cb in self.callbacks:
            cb.on_epoch_begin(epoch, logs)

    def on_epoch_end(self, epoch, logs=None):
        for cb in self.callbacks:
            cb.on_epoch_end(epoch, logs)

    def on_batch_begin(self, mode, step, logs=None):
        for cb in self.callbacks:
            cb.on_batch_begin(mode, step, logs)

    def on_batch_end(self, mode, step, logs=None):
        for cb in self.callbacks:
            cb.on_batch_end(mode, step, logs)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self._t0 = time.time()
        if self.verbose:
            print(f"Epoch {epoch + 1}/{self.params.get('epochs', '?')}")

    def on_train_batch_end(self, step, logs=None):
        if self.verbose >= 2 and step % self.log_freq == 0:
            msg = " - ".join(
                f"{k}: {v:.4f}" if isinstance(v, numbers.Number) else f"{k}: {v}"
                for k, v in (logs or {}).items() if k != "step"
            )
            print(f"  step {step}: {msg}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._t0
            msg = " - ".join(
                f"{k}: {v:.4f}" if isinstance(v, numbers.Number) else f"{k}: {v}"
                for k, v in (logs or {}).items() if k != "step"
            )
            print(f"  epoch {epoch + 1} done in {dt:.1f}s: {msg}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            self.model.save(f"{self.save_dir}/{epoch}")

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(f"{self.save_dir}/final")


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.wait = 0
        self.best = None

    def _better(self, cur, best):
        if best is None:
            return True
        if self.mode == "min":
            return cur < best - self.min_delta
        return cur > best + self.min_delta

    def on_epoch_end(self, epoch, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            cur = (logs or {}).get(f"eval_{self.monitor}")
        if cur is None:
            return
        if self._better(cur, self.best):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if hasattr(lr, "step") else None

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            s = self._sched()
            if s:
                s.step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            s = self._sched()
            if s:
                s.step()


class ReduceLROnPlateau(Callback):
    """Shrink the LR when a monitored metric plateaus — parity with
    hapi/callbacks.py ReduceLROnPlateau in the reference."""

    def __init__(self, monitor="loss", factor=0.1, patience=10, mode="min",
                 min_delta=1e-4, min_lr=0.0, verbose=1, cooldown=0):
        super().__init__()
        self.monitor = monitor
        self.factor = float(factor)
        self.patience = patience
        self.mode = mode
        self.min_delta = min_delta
        self.min_lr = min_lr
        self.verbose = verbose
        self.cooldown = cooldown
        self._cooldown_counter = 0
        self._wait = 0
        self._best = None

    def _better(self, cur):
        if self._best is None:
            return True
        if self.mode == "min":
            return cur < self._best - self.min_delta
        return cur > self._best + self.min_delta

    def on_eval_end(self, logs=None):
        self._check(logs or {})

    def on_epoch_end(self, epoch, logs=None):
        self._check(logs or {})

    def _check(self, logs):
        # fit() reports eval metrics in the epoch logs as 'eval_<name>'
        # (same fallback EarlyStopping uses): prefer the eval metric over
        # the noisy last-train-batch value when both exist
        cur = logs.get(f"eval_{self.monitor}", logs.get(self.monitor))
        if cur is None:
            return
        try:
            cur = float(np.asarray(cur).ravel()[0])
        except Exception:
            return
        if self._cooldown_counter > 0:
            self._cooldown_counter -= 1
            self._wait = 0
        if self._better(cur):
            self._best = cur
            self._wait = 0
            return
        self._wait += 1
        if self._wait >= self.patience:
            opt = getattr(self.model, "_optimizer", None)
            if opt is None:
                return
            old = float(opt.get_lr())
            new = max(old * self.factor, self.min_lr)
            if new < old:
                opt.set_lr(new)
                if self.verbose:
                    print(f"ReduceLROnPlateau: lr {old:.3e} -> {new:.3e}")
            self._cooldown_counter = self.cooldown
            self._wait = 0


class VisualDL(Callback):
    """Scalar logging callback. The reference streams to the VisualDL
    service; that package isn't in this image, so scalars append to a
    JSONL file any dashboard (or `jq`) can tail — same hook points."""

    def __init__(self, log_dir="./vdl_log"):
        super().__init__()
        self.log_dir = log_dir
        self._step = 0

    def _write(self, tag, logs):
        import json as _json
        import os as _os

        _os.makedirs(self.log_dir, exist_ok=True)
        rec = {"step": self._step, "tag": tag}
        for k, v in (logs or {}).items():
            try:
                rec[k] = float(np.asarray(v).ravel()[0])
            except Exception:
                continue
        with open(_os.path.join(self.log_dir, "scalars.jsonl"), "a") as f:
            f.write(_json.dumps(rec) + "\n")

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        self._write("train", logs)

    def on_eval_end(self, logs=None):
        self._write("eval", logs)


class TelemetryLogger(Callback):
    """Stream the runtime telemetry during ``Model.fit`` (the VisualDL-
    parity scalar surface over ``paddle_tpu.profiler``): every
    ``log_freq`` train batches, one JSONL record — the batch's logs
    (loss, metrics), per-batch latency/throughput, and the global
    ``Telemetry`` snapshot (counters, gauges, histogram percentiles) —
    lands in ``<log_dir>/<filename>`` in the schema
    ``tools/check_telemetry_schema.py`` validates. A record is also
    written at every eval end and at train end, so short runs always
    produce at least one row."""

    def __init__(self, log_dir="./telemetry", filename="scalars.jsonl",
                 log_freq=1, sample_memory=False):
        super().__init__()
        import os

        self.path = os.path.join(log_dir, filename)
        self.log_freq = max(int(log_freq), 1)
        self.sample_memory = sample_memory
        self._step = 0
        self._t0 = None

    def _telemetry(self):
        from ..profiler.telemetry import get_telemetry

        return get_telemetry()

    def _write(self, tag, logs=None):
        tel = self._telemetry()
        if self.sample_memory:
            from ..profiler.telemetry import sample_device_memory

            sample_device_memory(tel)
        extra = {}
        for k, v in (logs or {}).items():
            if k != "step":
                extra[str(k)] = v  # to_jsonl drops non-coercible values
        tel.to_jsonl(self.path, step=self._step, tag=tag, extra=extra)

    def on_train_begin(self, logs=None):
        self._write("train_begin", logs)

    def on_train_batch_begin(self, step, logs=None):
        self._t0 = time.perf_counter()

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        tel = self._telemetry()
        if self._t0 is not None:
            dt = time.perf_counter() - self._t0
            tel.observe("hapi/step_ms", dt * 1e3)
            if dt > 0:
                # steps/s, not samples/s: fit's nominal batch_size param
                # is a lie when train_data arrives pre-batched (list or
                # DataLoader) — scaling by it would misreport throughput
                # by the real batch-size factor
                tel.gauge("hapi/steps_per_s", 1.0 / dt)
        if self._step % self.log_freq == 0:
            self._write("train", logs)

    def on_eval_end(self, logs=None):
        self._write("eval", logs)

    def on_train_end(self, logs=None):
        self._write("train_end", logs)


def config_callbacks(callbacks=None, model=None, batch_size=None, epochs=None,
                     steps=None, log_freq=2, verbose=2, save_freq=1,
                     save_dir=None, metrics=None, mode="train"):
    cbks = callbacks if isinstance(callbacks, (list, tuple)) else (
        [callbacks] if callbacks else []
    )
    cbks = list(cbks)
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks = [ProgBarLogger(log_freq, verbose=verbose)] + cbks
    cl = CallbackList(cbks)
    cl.set_model(model)
    cl.set_params({
        "batch_size": batch_size, "epochs": epochs, "steps": steps,
        "verbose": verbose, "metrics": metrics or ["loss"],
    })
    return cl
