"""paddle.hub — load models from a hubconf.py entry-point directory
(parity: /root/reference/python/paddle/hapi/hub.py). The reference also
fetches github/gitee archives; this environment is zero-egress, so
``source='local'`` (a directory containing ``hubconf.py``) is the
supported path and the remote sources raise with that guidance.

hubconf contract (same as the reference): a ``hubconf.py`` whose public
callables are the model entry points; ``dependencies = [...]`` is an
optional list of importable module names checked before load.
"""
from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]

_HUBCONF = "hubconf.py"


def _import_hubconf(repo_dir: str):
    if not os.path.isdir(repo_dir):
        raise ValueError(f"hub: {repo_dir!r} is not a directory")
    path = os.path.join(repo_dir, _HUBCONF)
    if not os.path.exists(path):
        raise FileNotFoundError(f"hub: no {_HUBCONF} in {repo_dir!r}")
    spec = importlib.util.spec_from_file_location("paddle_tpu_hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.path.insert(0, repo_dir)
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.path.remove(repo_dir)
    for dep in getattr(mod, "dependencies", []):
        if importlib.util.find_spec(dep) is None:
            raise RuntimeError(f"hub: missing dependency {dep!r} required "
                               f"by {path}")
    return mod


def _check_source(source: str):
    if source not in ("local",):
        raise ValueError(
            f"hub source {source!r} is unavailable in this zero-egress "
            "environment; clone the repo yourself and use source='local'")


def list(repo_dir, source="local", force_reload=False):
    """Names of the model entry points exported by the repo's hubconf."""
    _check_source(source)
    mod = _import_hubconf(repo_dir)
    return [n for n, v in vars(mod).items()
            if callable(v) and not n.startswith("_")]


def help(repo_dir, model, source="local", force_reload=False):
    """Docstring of one entry point."""
    _check_source(source)
    mod = _import_hubconf(repo_dir)
    fn = getattr(mod, model, None)
    if fn is None or not callable(fn):
        raise ValueError(f"hub: no entry point {model!r} in {repo_dir!r}")
    return fn.__doc__


def load(repo_dir, model, source="local", force_reload=False, **kwargs):
    """Call the entry point and return the constructed model."""
    _check_source(source)
    mod = _import_hubconf(repo_dir)
    fn = getattr(mod, model, None)
    if fn is None or not callable(fn):
        raise ValueError(f"hub: no entry point {model!r} in {repo_dir!r}")
    return fn(**kwargs)
