"""Model summary — parity with python/paddle/hapi/model_summary.py."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from ..nn.layer_base import Layer

__all__ = ["summary"]


def summary(net: Layer, input_size=None, dtypes=None, input=None):
    """Print per-layer output shapes and parameter counts; returns totals."""
    from .. import tensor as T

    hooks = []
    rows = []

    def register(layer, name):
        def hook(l, inputs, outputs):
            out = outputs[0] if isinstance(outputs, (list, tuple)) else outputs
            shape = list(out.shape) if isinstance(out, Tensor) else "?"
            n_params = sum(int(np.prod(p.shape)) for p in l._parameters.values() if p is not None)
            rows.append((name or type(l).__name__, str(shape), n_params))

        hooks.append(layer.register_forward_post_hook(hook))

    for name, sub in net.named_sublayers():
        if not sub._sub_layers:  # leaves only
            register(sub, name)

    if input is not None:
        x = input if isinstance(input, (list, tuple)) else [input]
    else:
        if input_size is None:
            raise ValueError("summary needs input_size or input")
        sizes = [input_size] if isinstance(input_size, tuple) else list(input_size)
        sizes = [list(s) for s in (sizes if isinstance(sizes[0], (list, tuple)) else [sizes])]
        x = [
            T.zeros([1 if (d is None or d == -1) else d for d in s],
                    dtypes if isinstance(dtypes, str) else "float32")
            for s in sizes
        ]
    was_training = net.training
    net.eval()
    try:
        net(*x)
    finally:
        if was_training:
            net.train()
        for h in hooks:
            h.remove()

    total = sum(int(np.prod(p.shape)) for p in net.parameters())
    trainable = sum(
        int(np.prod(p.shape)) for p in net.parameters() if p.trainable
    )
    width = 70
    print("-" * width)
    print(f"{'Layer (type)':35s} {'Output Shape':20s} {'Param #':>12s}")
    print("=" * width)
    for name, shape, n in rows:
        print(f"{name:35.35s} {shape:20.20s} {n:12,d}")
    print("=" * width)
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    print(f"Non-trainable params: {total - trainable:,}")
    print("-" * width)
    return {"total_params": total, "trainable_params": trainable}
