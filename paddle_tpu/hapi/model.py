"""hapi Model — Keras-style fit/evaluate/predict, parity with
python/paddle/hapi/model.py:876,1519 (Model + DynamicGraphAdapter).

TPU-first: ``prepare`` stages the whole train step through
paddle_tpu.jit.TrainStep (one XLA program per step) instead of per-op eager
dispatch; metrics run host-side on fetched outputs like the reference.
"""
from __future__ import annotations

import jax
import numpy as np

from ..core.tensor import Tensor, no_grad, to_tensor
from ..metric import Metric
from ..nn.layer_base import Layer
from ..profiler import spans as _spans
from ..resilience import preemption as _preempt
from . import callbacks as callbacks_mod

__all__ = ["Model"]


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._train_step = None
        self.stop_training = False

    # ------------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is not None:
            self._metrics = metrics if isinstance(metrics, (list, tuple)) else [metrics]
        self._train_step = None  # rebuilt lazily
        return self

    def _ensure_train_step(self):
        if self._train_step is None and self._optimizer is not None and self._loss is not None:
            from ..jit.train_step import TrainStep

            loss_layer = self._loss

            def loss_fn(out, *labels):
                return loss_layer(Tensor(out) if not isinstance(out, Tensor) else out,
                                  *[Tensor(l) for l in labels])

            self._train_step = TrainStep(self.network, loss_fn, self._optimizer)
        return self._train_step

    # ------------------------------------------------------------------
    def train_batch(self, inputs, labels=None, update=True):
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if labels is None or isinstance(labels, (list, tuple)) else [labels]
        step = self._ensure_train_step()
        loss = step(tuple(inputs), tuple(labels or ()))
        metrics_out = []
        if self._metrics:
            step.sync_to_layer()
            with no_grad():
                self.network.eval()
                outs = self.network(*inputs)
                self.network.train()
            for m in self._metrics:
                res = m.update(m.compute(outs, *labels)) if labels else None
                metrics_out.append(res)
            step.refresh_from_layer()
        # train_batch's contract (reference hapi Model.train_batch) returns a
        # host float per call — the sync is the API, not an accident; fit()
        # users who need async steps go through prefetch_depth + callbacks
        # tpu-lint: disable-next=R5
        return (float(loss.numpy()), metrics_out) if metrics_out else float(loss.numpy())

    def eval_batch(self, inputs, labels=None):
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if labels is None or isinstance(labels, (list, tuple)) else [labels]
        self.network.eval()
        with no_grad():
            outs = self.network(*inputs)
            loss = self._loss(outs, *labels) if self._loss and labels else None
        self.network.train()
        metrics_out = []
        for m in self._metrics:
            metrics_out.append(m.update(m.compute(outs, *labels)))
        return (float(loss.numpy()) if loss is not None else None), metrics_out

    def predict_batch(self, inputs):
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        self.network.eval()
        with no_grad():
            outs = self.network(*inputs)
        self.network.train()
        return outs

    # ------------------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None, prefetch_depth=0,
            prefetch_buckets=None):
        """See ``_fit_impl`` for the behavior docs. This boundary owns
        the root "fit" span of the structured-span hierarchy
        (fit → epoch → step → h2d/compute/callback/checkpoint) so every
        exit path — normal, exception, preemption — closes it; the full
        keyword signature stays here for introspection/IDE surfaces."""
        with _spans.span("fit", cat="fit"):
            return self._fit_impl(
                train_data=train_data, eval_data=eval_data,
                batch_size=batch_size, epochs=epochs, eval_freq=eval_freq,
                log_freq=log_freq, save_dir=save_dir, save_freq=save_freq,
                verbose=verbose, drop_last=drop_last, shuffle=shuffle,
                num_workers=num_workers, callbacks=callbacks,
                accumulate_grad_batches=accumulate_grad_batches,
                num_iters=num_iters, prefetch_depth=prefetch_depth,
                prefetch_buckets=prefetch_buckets)

    def _fit_impl(self, train_data=None, eval_data=None, batch_size=1,
                  epochs=1, eval_freq=1, log_freq=10, save_dir=None,
                  save_freq=1, verbose=2, drop_last=False, shuffle=True,
                  num_workers=0, callbacks=None, accumulate_grad_batches=1,
                  num_iters=None, prefetch_depth=0, prefetch_buckets=None):
        """``prefetch_depth`` > 0 stages batches through an
        ``io.DevicePrefetcher``: a background pipeline that many batches
        ahead pads into ``prefetch_buckets`` (fixed compile shapes for
        ragged data) and issues one async pytree device transfer per
        batch, overlapping H2D with the in-flight train step."""
        from ..io import DataLoader, Dataset

        loader = train_data if not isinstance(train_data, Dataset) else DataLoader(
            train_data, batch_size=batch_size, shuffle=shuffle,
            drop_last=drop_last, num_workers=num_workers,
        )
        eval_loader = None
        if eval_data is not None:
            eval_loader = eval_data if not isinstance(eval_data, Dataset) else DataLoader(
                eval_data, batch_size=batch_size, num_workers=num_workers,
            )
        import os

        if save_dir and os.path.exists(f"{save_dir}/preempt.pdparams"):
            # relaunched after a preemption exit: consume the emergency
            # checkpoint the preempted attempt wrote below, so the
            # relaunch continues from its weights/optimizer state
            # instead of burning the restart budget on epoch-0 restarts
            # (step-cursor resume is resilience.StepGuard's domain).
            # Consume-ONCE: the files are removed after loading so a
            # stale emergency state can never silently override a later,
            # unrelated run pointed at the same save_dir
            self.load(f"{save_dir}/preempt")
            for suffix in (".pdparams", ".pdopt"):
                try:
                    os.remove(f"{save_dir}/preempt{suffix}")
                except OSError:
                    pass
        cbks = callbacks_mod.config_callbacks(
            callbacks, model=self, batch_size=batch_size, epochs=epochs,
            verbose=verbose, log_freq=log_freq, save_dir=save_dir,
            save_freq=save_freq,
            metrics=["loss"] + [n for m in self._metrics for n in _as_list(m.name())],
        )
        cbks.on_begin("train")
        it_count = 0
        for epoch in range(epochs):
            if self.stop_training:
                break
            # epoch span: explicit enter, exited after the epoch-end
            # checkpoint below. An exception path may skip the exit —
            # the span stack self-heals (and the dangling "B" in the
            # flight recorder is correct forensics: the epoch WAS open).
            _epoch_span = _spans.span("epoch", cat="epoch").__enter__()
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs = {}
            data_iter = loader
            if prefetch_depth:
                from ..io.prefetch import DevicePrefetcher

                # one prefetcher per epoch: it is a one-shot pipeline and
                # close() below guarantees no worker outlives the epoch
                data_iter = DevicePrefetcher(loader, depth=prefetch_depth,
                                             buckets=prefetch_buckets)
            try:
                for step_i, batch in enumerate(data_iter):
                    # preemption boundary (resilience): with the handler
                    # installed, SIGTERM lands here between steps — save
                    # an emergency checkpoint and exit with the relaunch
                    # code the distributed.launch watcher recognizes
                    if _preempt.preemption_requested():
                        _preempt.exit_for_relaunch(
                            (lambda: self.save(f"{save_dir}/preempt"))
                            if save_dir else None)
                    if prefetch_depth:
                        # leaves come back as device jax.Arrays; re-wrap so
                        # metrics/eager paths see Tensors like loader output
                        batch = jax.tree_util.tree_map(
                            lambda a: Tensor(a) if isinstance(a, jax.Array)
                            else a, batch)
                    inputs, labels = _split_batch(batch)
                    with _spans.span("step", cat="step", step=it_count):
                        cbks.on_batch_begin("train", step_i, logs)
                        out = self.train_batch(inputs, labels)
                        loss_v, metr = out if isinstance(out, tuple) else (out, [])
                        logs = {"loss": loss_v, "step": step_i}
                        for m in self._metrics:
                            for n, v in zip(_as_list(m.name()), _as_list(m.accumulate())):
                                logs[n] = v
                        with _spans.span("callback", cat="callback"):
                            cbks.on_batch_end("train", step_i, logs)
                    it_count += 1
                    if num_iters is not None and it_count >= num_iters:
                        break
            finally:
                if prefetch_depth:
                    data_iter.close()
            if self._train_step is not None:
                self._train_step.sync_to_layer()
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_loader, verbose=0)
                logs.update({f"eval_{k}": v for k, v in eval_logs.items()})
            cbks.on_epoch_end(epoch, logs)
            if isinstance(self._optimizer, object) and hasattr(self._optimizer, "_learning_rate"):
                lr = self._optimizer._learning_rate
                if hasattr(lr, "step"):
                    lr.step()
            if save_dir and (epoch + 1) % save_freq == 0:
                with _spans.span("checkpoint", cat="checkpoint"):
                    self.save(f"{save_dir}/{epoch}")
            _epoch_span.__exit__(None, None, None)
        cbks.on_end("train", logs)
        return self

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_iters=None):
        from ..io import DataLoader, Dataset

        if self._train_step is not None:
            self._train_step.sync_to_layer()
        loader = eval_data if not isinstance(eval_data, Dataset) else DataLoader(
            eval_data, batch_size=batch_size, num_workers=num_workers,
        )
        for m in self._metrics:
            m.reset()
        losses = []
        for i, batch in enumerate(loader):
            inputs, labels = _split_batch(batch)
            loss_v, _ = self.eval_batch(inputs, labels)
            if loss_v is not None:
                losses.append(loss_v)
            if num_iters is not None and i + 1 >= num_iters:
                break
        logs = {}
        if losses:
            logs["loss"] = float(np.mean(losses))
        for m in self._metrics:
            for n, v in zip(_as_list(m.name()), _as_list(m.accumulate())):
                logs[n] = v
        if verbose:
            print("Eval:", logs)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                verbose=1, callbacks=None):
        from ..io import DataLoader, Dataset

        if self._train_step is not None:
            self._train_step.sync_to_layer()
        loader = test_data if not isinstance(test_data, Dataset) else DataLoader(
            test_data, batch_size=batch_size, num_workers=num_workers,
        )
        outputs = []
        for batch in loader:
            # labeled datasets: drop the trailing label like fit/evaluate do
            inputs, _ = _split_batch(batch)
            outs = self.predict_batch(inputs)
            outputs.append(outs)
        if stack_outputs and outputs:
            first = outputs[0]
            if isinstance(first, Tensor):
                return [np.concatenate([o.numpy() for o in outputs])]
            return [
                np.concatenate([o[i].numpy() for o in outputs])
                for i in range(len(first))
            ]
        return outputs

    # ------------------------------------------------------------------
    def save(self, path, training=True):
        from ..framework.io import save

        if self._train_step is not None:
            self._train_step.sync_to_layer()
        save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io import load
        import os

        state = load(path + ".pdparams") if not path.endswith(".pdparams") else load(path)
        self.network.set_state_dict(state)
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None and os.path.exists(opt_path):
            self._optimizer.set_state_dict(load(opt_path))
        if self._train_step is not None:
            self._train_step.refresh_from_layer()

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        from .summary import summary as summary_fn

        return summary_fn(self.network, input_size, dtypes=dtype)


def _as_list(v):
    return v if isinstance(v, (list, tuple)) else [v]


def _split_batch(batch, has_labels=True):
    if isinstance(batch, (list, tuple)):
        if len(batch) >= 2 and has_labels:
            *ins, lab = batch
            if len(ins) == 1:
                return [ins[0]], [lab]
            return list(ins), [lab]
        return list(batch), []
    return [batch], []
