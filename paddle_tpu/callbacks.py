"""paddle.callbacks — re-export of the hapi callbacks (parity:
/root/reference/python/paddle/callbacks.py, which is the same shim)."""
from .hapi.callbacks import Callback  # noqa: F401
from .hapi.callbacks import EarlyStopping  # noqa: F401
from .hapi.callbacks import LRScheduler  # noqa: F401
from .hapi.callbacks import ModelCheckpoint  # noqa: F401
from .hapi.callbacks import ProgBarLogger  # noqa: F401
from .hapi.callbacks import ReduceLROnPlateau  # noqa: F401
from .hapi.callbacks import TelemetryLogger  # noqa: F401
from .hapi.callbacks import VisualDL  # noqa: F401

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "VisualDL",
           "LRScheduler", "EarlyStopping", "ReduceLROnPlateau",
           "TelemetryLogger"]
