"""Checkpoint/auto-resume (parity fluid/incubate/checkpoint/
auto_checkpoint.py:71,265,598 + checkpoint_saver.py).

Two layers:
- ``CheckpointSaver`` — numbered snapshots with retention (keep_max), atomic
  via temp-dir rename. Payload storage is orbax PyTreeCheckpointer, the
  TPU-native answer to the reference's per-process save_persistables files:
  jax.Arrays save with their ShardingMetadata, so a mesh-sharded train state
  checkpoints and restores without gathering to one host (SURVEY.md §5
  "TPU-equiv: sharded array checkpointing keyed by mesh sharding").
- ``train_epoch_range`` — the auto-checkpoint epoch loop: resumes from the
  last completed epoch for a job id, saving state at every epoch end.
"""
from __future__ import annotations

import json
import os
import shutil
from typing import Any, Callable, Dict, List, Optional

__all__ = ["CheckpointSaver", "train_epoch_range", "save_train_state",
           "restore_train_state"]


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def _io_retry(fn, *args, **kwargs):
    """Checkpoint reads/writes behind deterministic exponential backoff
    (resilience retry layer): transient filesystem/GCS OSErrors — the
    blips that throw away hours of state when a save dies — get
    ``PADDLE_TPU_CKPT_RETRIES`` (default 3) extra attempts, counted in
    ``resilience/io_retries``."""
    from ...resilience.retry import retry_call

    return retry_call(
        fn, *args,
        retries=int(os.environ.get("PADDLE_TPU_CKPT_RETRIES", 3)),
        base=float(os.environ.get("PADDLE_TPU_CKPT_RETRY_BASE", 0.2)),
        retry_on=(OSError,), **kwargs)


def save_train_state(state: Dict[str, Any], path: str):
    """Save a pytree of (possibly mesh-sharded) arrays atomically: write to a
    temp sibling, then swap — a crash mid-save never loses the previous
    checkpoint (it survives at ``path`` or ``path + '.tmp-old'``, and
    ``restore_train_state`` checks both)."""
    path = os.path.abspath(path)
    tmp = path + ".tmp-save"
    old = path + ".tmp-old"
    # crash leftovers from a previous save: a stale tmp is always garbage
    # (orbax refuses to write into an existing dir); old may only be removed
    # while the committed path exists — otherwise it is the sole survivor
    # restore_train_state falls back to
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    if os.path.exists(old) and os.path.exists(path):
        shutil.rmtree(old)

    def _write():
        # a retried attempt must clear its own partial tmp first (orbax
        # refuses to write into an existing dir)
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        _checkpointer().save(tmp, state)

    _io_retry(_write)
    # flush the tree BEFORE the commit rename (shared durability contract
    # with framework.io.atomic_replace): the rename must never point at
    # data still sitting in the page cache when a preemption lands
    from ...framework.io import fsync_dir, fsync_tree

    fsync_tree(tmp)
    if os.path.exists(path):
        if os.path.exists(old):
            shutil.rmtree(old)
        os.rename(path, old)
    os.rename(tmp, path)
    fsync_dir(os.path.dirname(path))
    if os.path.exists(old):
        shutil.rmtree(old)


def _resolve_ckpt_path(path: str) -> str:
    """The committed checkpoint, or the .tmp-old survivor of a mid-swap crash."""
    path = os.path.abspath(path)
    if os.path.exists(path):
        return path
    old = path + ".tmp-old"
    if os.path.exists(old):
        return old
    return path


def restore_train_state(path: str):
    return _io_retry(_checkpointer().restore, _resolve_ckpt_path(path))


class CheckpointSaver:
    """Numbered checkpoints under a root dir with retention.

    Layout: <root>/ckpt-<n>/{payload orbax tree}, <root>/LATEST (json:
    number + user meta). Save is atomic: orbax writes to a temp name then
    this class renames and updates LATEST last.
    """

    def __init__(self, root: str, keep_max: int = 3):
        self.root = os.path.abspath(root)
        self.keep_max = keep_max
        os.makedirs(self.root, exist_ok=True)

    def _ckpt_dir(self, n: int) -> str:
        return os.path.join(self.root, f"ckpt-{n}")

    def latest(self) -> Optional[int]:
        f = os.path.join(self.root, "LATEST")
        if not os.path.exists(f):
            return None
        with open(f) as fh:
            return json.load(fh)["number"]

    def latest_meta(self) -> Optional[dict]:
        f = os.path.join(self.root, "LATEST")
        if not os.path.exists(f):
            return None
        with open(f) as fh:
            return json.load(fh).get("meta", {})

    def numbers(self) -> List[int]:
        out = []
        for name in os.listdir(self.root):
            if name.startswith("ckpt-"):
                try:
                    out.append(int(name.split("-", 1)[1]))
                except ValueError:
                    pass
        return sorted(out)

    def save(self, number: int, state: Dict[str, Any],
             meta: Optional[dict] = None):
        tmp = self._ckpt_dir(number) + ".tmp"
        final = self._ckpt_dir(number)
        def _write():
            for p in (tmp, final):
                if os.path.exists(p):
                    shutil.rmtree(p)
            _checkpointer().save(tmp, state)

        _io_retry(_write)
        from ...framework.io import atomic_replace, fsync_dir, fsync_tree

        fsync_tree(tmp)
        os.rename(tmp, final)
        fsync_dir(self.root)

        def _write_latest(tmp_path):
            with open(tmp_path, "w") as fh:
                json.dump({"number": number, "meta": meta or {}}, fh)

        atomic_replace(os.path.join(self.root, "LATEST"), _write_latest)
        self._gc()

    def restore(self, number: Optional[int] = None):
        number = self.latest() if number is None else number
        if number is None:
            return None
        return _io_retry(_checkpointer().restore, self._ckpt_dir(number))

    def _gc(self):
        nums = self.numbers()
        latest = self.latest()
        while len(nums) > self.keep_max:
            n = nums.pop(0)
            if n == latest:
                continue
            shutil.rmtree(self._ckpt_dir(n), ignore_errors=True)


def train_epoch_range(max_epoch: int, root: str,
                      get_state: Callable[[], Dict[str, Any]],
                      set_state: Callable[[Dict[str, Any]], None],
                      keep_max: int = 2, save_every: int = 1):
    """Auto-checkpoint epoch loop (auto_checkpoint.py:265
    _train_epoch_range parity):

        for epoch in train_epoch_range(10, dir, get_state, set_state):
            ...train one epoch...

    On a fresh run yields 0..max_epoch-1 saving state each epoch; on restart
    restores the snapshot and resumes from the next epoch.
    """
    saver = CheckpointSaver(root, keep_max=keep_max)
    last = saver.latest()
    start = 0
    if last is not None:
        set_state(saver.restore(last))
        start = last + 1
    for epoch in range(start, max_epoch):
        yield epoch
        if (epoch + 1) % save_every == 0 or epoch == max_epoch - 1:
            saver.save(epoch, get_state(), meta={"epoch": epoch})
