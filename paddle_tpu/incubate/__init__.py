"""paddle_tpu.incubate — incubating subsystems (parity fluid/incubate)."""
from . import checkpoint  # noqa: F401
from . import moe  # noqa: F401
