"""Mixture-of-Experts with expert parallelism over an 'ep' mesh axis.

The reference snapshot predates its MoE work (SURVEY.md §2: EP-precursor —
none), so this is net-new capability, designed TPU-first rather than ported:
the Mesh-TensorFlow/GShard dense-dispatch formulation — gate → top-k →
dispatch einsum → per-expert FFN on stacked weights → combine einsum — which
XLA partitions cleanly: sharding the expert axis of the stacked weights and
dispatched activations over 'ep' makes the dispatch/combine einsums lower to
all-to-alls on ICI, with no hand-written routing code.

Components:
- ``top_k_gating``      — softmax gate, top-k selection, capacity dropping,
                          load-balance aux loss (GShard eq. 4).
- ``moe_dispatch``      — build dispatch/combine tensors.
- ``ExpertMLP``         — stacked per-expert FFN ([E, ...] weights carrying
                          tp_spec ('ep', ...) so fleet engines shard them).
- ``MoELayer``          — drop-in FFN replacement (eager Layer API).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from .. import nn
from ..core.tensor import Tensor, _is_tracer, apply_op
from ..nn import initializer as I

__all__ = ["top_k_gating", "moe_dispatch", "ExpertMLP", "MoELayer"]


def top_k_gating(gate_logits, top_k: int, capacity: int):
    """Returns (combine_weights [T, E, C], dispatch_mask [T, E, C], aux_loss).

    GShard-style: softmax over experts, top-k per token, position-in-expert
    by cumulative sum, tokens beyond ``capacity`` dropped (their combine
    weight is 0 → the residual connection carries them). Pure jnp; vmappable
    and shardable.
    """
    t, e = gate_logits.shape
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)

    combine = jnp.zeros((t, e, capacity), jnp.float32)
    dispatch = jnp.zeros((t, e, capacity), bool)
    # occupancy per expert accumulates across the k routing rounds
    occupancy = jnp.zeros((e,), jnp.int32)
    masked = probs
    density_frac = jnp.zeros((e,), jnp.float32)

    for _ in range(top_k):
        idx = jnp.argmax(masked, axis=-1)                      # [T]
        onehot = jax.nn.one_hot(idx, e, dtype=jnp.int32)       # [T, E]
        # position of each token inside its chosen expert's buffer
        pos_in_round = jnp.cumsum(onehot, axis=0) - onehot      # [T, E]
        pos = (pos_in_round + occupancy[None, :]) * onehot
        pos_tok = jnp.sum(pos, axis=-1)                        # [T]
        keep = pos_tok < capacity
        w = jnp.take_along_axis(probs, idx[:, None], axis=-1)[:, 0]  # [T]
        w = jnp.where(keep, w, 0.0)
        pos_clip = jnp.minimum(pos_tok, capacity - 1)
        cap_onehot = jax.nn.one_hot(pos_clip, capacity, dtype=jnp.float32)
        contrib = (onehot.astype(jnp.float32)[:, :, None]
                   * cap_onehot[:, None, :]) * w[:, None, None]
        combine = combine + contrib
        dispatch = dispatch | (contrib > 0)
        occupancy = occupancy + jnp.sum(onehot * keep[:, None].astype(jnp.int32),
                                        axis=0)
        density_frac = density_frac + jnp.mean(onehot.astype(jnp.float32),
                                               axis=0)
        masked = jnp.where(onehot.astype(bool), -jnp.inf, masked)

    # renormalize the k selected weights per token (top2 gating convention)
    denom = jnp.maximum(combine.sum(axis=(1, 2)), 1e-9)
    combine = combine / denom[:, None, None]
    dispatch = combine > 0

    # load-balance loss: E * mean_e(density * mean-gate-prob) (GShard eq. 4)
    density = density_frac / top_k
    mean_prob = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(density * mean_prob)
    return combine, dispatch, aux


def moe_dispatch(x, dispatch):
    """x: [T, D], dispatch: [T, E, C] → expert inputs [E, C, D]."""
    return jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), x)


class ExpertMLP(nn.Layer):
    """E parallel FFNs as stacked weights [E, d, ff] / [E, ff, d] with
    tp_spec ('ep', …): fleet engines shard the expert axis, so each ep rank
    holds E/ep experts and the dispatch/combine einsums become all-to-alls."""

    def __init__(self, num_experts: int, d_model: int, d_ff: int,
                 activation: str = "gelu"):
        super().__init__()
        std = 0.02
        init = I.Normal(0.0, std)
        self.w_in = self.create_parameter(
            [num_experts, d_model, d_ff], default_initializer=init)
        self.b_in = self.create_parameter(
            [num_experts, 1, d_ff], default_initializer=I.Constant(0.0))
        self.w_out = self.create_parameter(
            [num_experts, d_ff, d_model], default_initializer=init)
        self.b_out = self.create_parameter(
            [num_experts, 1, d_model], default_initializer=I.Constant(0.0))
        for p in (self.w_in, self.b_in, self.w_out, self.b_out):
            p.tp_spec = ("ep",) + (None,) * (len(p.shape) - 1)
        self._act = activation

    def forward(self, expert_in):
        """expert_in: [E, C, D] → [E, C, D]; one batched MXU matmul pair."""

        def f(xe, wi, bi, wo, bo):
            h = jnp.einsum("ecd,edf->ecf", xe, wi) + bi
            h = jax.nn.gelu(h, approximate=True) if self._act == "gelu" else (
                jnp.maximum(h, 0))
            return jnp.einsum("ecf,efd->ecd", h, wo) + bo

        return apply_op(f, expert_in, self.w_in, self.b_in, self.w_out,
                        self.b_out, op_name="expert_mlp")


class MoELayer(nn.Layer):
    """Drop-in FFN replacement: ``y = combine(experts(dispatch(x)))``.

    Aux (load-balance) loss: in eager mode it lands on ``self.aux_loss``
    after each forward — add ``layer.aux_loss * coeff`` to the loss. Under
    jit/fleet engines a side-effect attribute cannot carry a traced value
    out (it would leak the tracer), so ``self.aux_loss`` stays None there;
    jitted training must call :meth:`forward_with_aux` and fold the returned
    aux into the loss functionally.
    """

    def __init__(self, d_model: int, d_ff: int, num_experts: int,
                 top_k: int = 2, capacity_factor: float = 1.25,
                 activation: str = "gelu", gate_noise: float = 0.0):
        super().__init__()
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.gate = nn.Linear(d_model, num_experts, bias_attr=False)
        self.experts = ExpertMLP(num_experts, d_model, d_ff, activation)
        self.aux_loss = None

    def forward(self, x):
        """x: [B, L, D] (or [T, D]) → same shape."""
        out, aux = self.forward_with_aux(x)
        # only a concrete value may live on the layer (a tracer stored here
        # would escape its trace and error on any later access)
        self.aux_loss = None if _is_tracer(aux._value) else aux
        return out

    def forward_with_aux(self, x):
        """Functional form for jitted training: returns (out, aux_loss)."""
        orig_shape = x.shape
        d = orig_shape[-1]
        t = int(np.prod(orig_shape[:-1]))
        cap = max(1, int(math.ceil(
            self.capacity_factor * self.top_k * t / self.num_experts)))
        flat = x.reshape([t, d])
        logits = self.gate(flat)

        def route(flat_raw, logits_raw):
            combine, dispatch, aux = top_k_gating(
                logits_raw, self.top_k, cap)
            expert_in = moe_dispatch(flat_raw, dispatch)
            return expert_in, combine.astype(flat_raw.dtype), aux

        expert_in, combine, aux = apply_op(route, flat, logits,
                                           multi_out=True, op_name="moe_route")
        expert_out = self.experts(expert_in)

        def unroute(eo, comb):
            return jnp.einsum("ecd,tec->td", eo, comb)

        out = apply_op(unroute, expert_out, combine, op_name="moe_combine")
        return out.reshape(list(orig_shape)), aux
