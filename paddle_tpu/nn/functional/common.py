"""Common functionals: linear, dropout, embedding, one_hot, interpolate, pad,
normalize, cosine_similarity — parity with python/paddle/nn/functional/common.py
and input.py in the reference.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core import dtype as dtype_mod
from ...core import rng as rng_mod
from ...core.tensor import Tensor, apply_op, to_tensor

__all__ = [
    "linear", "dropout", "dropout2d", "dropout3d", "alpha_dropout", "embedding",
    "one_hot", "label_smooth", "pad", "interpolate", "upsample", "normalize",
    "cosine_similarity", "pixel_shuffle", "pixel_unshuffle", "channel_shuffle",
    "unfold", "fold", "bilinear",
]

from ...tensor.manipulation import pad  # re-export (paddle exposes under F.pad)


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b with paddle's [in, out] weight layout — lowers to a
    single MXU matmul; XLA fuses the bias add.

    NOTE (profiled, v5e GPT-2 345M): leave the bias grad to jax's native
    vjp. A custom_vjp that reformulates db as a rank-1 MXU dot measured
    3k tok/s SLOWER end-to-end — the custom_vjp boundary breaks XLA's
    dW-matmul+Adam kOutput fusions, which outweighs the faster reduce."""
    from ...amp.auto_cast import maybe_cast_inputs

    if bias is None:
        return apply_op(
            lambda a, w: jnp.matmul(*maybe_cast_inputs("linear", a, w)), _t(x), weight
        )

    def f(a, w, b):
        a, w = maybe_cast_inputs("linear", a, w)
        out = jnp.matmul(a, w)
        return out + b.astype(out.dtype)

    return apply_op(f, _t(x), weight, bias)


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    x = _t(x)
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return apply_op(lambda a: a * (1.0 - p), x)
        return x
    if p == 1.0:
        return apply_op(lambda a: jnp.zeros_like(a), x)
    key = rng_mod.next_key()
    shape = tuple(x.shape)
    if axis is not None:
        axes = [axis] if isinstance(axis, int) else list(axis)
        shape = tuple(s if i in axes else 1 for i, s in enumerate(x.shape))
    keep = jax.random.bernoulli(key, 1.0 - p, shape)

    def f(a):
        m = keep.astype(a.dtype)
        if mode == "upscale_in_train":
            return a * m / (1.0 - p)
        return a * m  # downscale_in_infer mode: plain mask at train time

    return apply_op(f, x)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    ch_axis = 1 if data_format == "NCHW" else 3
    return dropout(x, p, axis=[0, ch_axis], training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    ch_axis = 1 if data_format == "NCDHW" else 4
    return dropout(x, p, axis=[0, ch_axis], training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    x = _t(x)
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772848170429916717
    scale = 1.0507009873554804934193349852946
    alpha_p = -alpha * scale
    key = rng_mod.next_key()
    keep = jax.random.bernoulli(key, 1.0 - p, tuple(x.shape))
    a_coef = (1.0 - p + p * alpha_p**2) ** -0.5
    b_coef = -a_coef * p * alpha_p

    def f(v):
        m = keep.astype(v.dtype)
        return a_coef * (v * m + alpha_p * (1 - m)) + b_coef

    return apply_op(f, x)


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """Gather rows of ``weight``.

    ``sparse=True`` in eager mode produces a ``RowSparseGrad`` for the
    weight — the TPU-native SelectedRows equivalent
    (framework/selected_rows.h:1, operators/lookup_table_v2_op.*): the
    gradient stays (rows, values) through the optimizer, whose sparse path
    updates only touched rows (O(batch·seq·dim), not O(vocab·dim)).
    Under jit/tracing the dense gather + XLA scatter-add vjp is the fast
    path (the engines consume dense grads)."""

    def f(idx, w):
        out = jnp.take(w, idx, axis=0)
        if padding_idx is not None and padding_idx >= 0:
            mask = (idx != padding_idx)[..., None].astype(w.dtype)
            out = out * mask
        return out

    xt = _t(x).detach()
    if sparse:
        from ...core import tensor as tensor_mod
        from ...core.selected_rows import RowSparseGrad

        eager = not tensor_mod._is_tracer(xt._value)
        # leaf weights only: a RowSparseGrad cotangent cannot flow through
        # an upstream jax vjp (e.g. weight.astype(...) under AMP) — those
        # take the dense path
        record = (tensor_mod._grad_mode.enabled and eager
                  and isinstance(weight, Tensor) and not weight.stop_gradient
                  and weight._node is None
                  and tensor_mod._op_recorder is None)
        if record:
            idx_raw = xt._value
            w_raw = weight._value
            num_rows, dim = w_raw.shape
            out_raw = f(idx_raw, w_raw)

            def vjp_fn(ct):
                rows = idx_raw.reshape(-1).astype(jnp.int32)
                vals = ct.reshape(-1, dim)
                if padding_idx is not None and padding_idx >= 0:
                    # mask padded positions out of the sparse update
                    rows = jnp.where(rows == padding_idx,
                                     jnp.int32(num_rows), rows)
                return (RowSparseGrad(rows, vals, num_rows),)

            node = tensor_mod.Node([weight], vjp_fn,
                                   [(out_raw.shape, out_raw.dtype)],
                                   name="embedding_sparse_grad")
            out = Tensor(out_raw, stop_gradient=False)
            out._node = node
            out._idx = 0
            return out

    return apply_op(lambda idx, w: f(idx, w), xt, weight)


def one_hot(x, num_classes, name=None):
    return apply_op(
        lambda idx: jax.nn.one_hot(idx, num_classes, dtype=dtype_mod.get_default_dtype()),
        _t(x).detach(),
    )


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def f(l):
        k = l.shape[-1]
        if prior_dist is not None:
            pd = prior_dist._value if isinstance(prior_dist, Tensor) else jnp.asarray(prior_dist)
            return (1.0 - epsilon) * l + epsilon * pd
        return (1.0 - epsilon) * l + epsilon / k

    return apply_op(f, _t(label))


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def f(a):
        nrm = jnp.sum(jnp.abs(a) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return a / jnp.maximum(nrm, epsilon)

    return apply_op(f, _t(x))


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def f(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.sqrt(jnp.sum(a * a, axis=axis))
        nb = jnp.sqrt(jnp.sum(b * b, axis=axis))
        return dot / jnp.maximum(na * nb, eps)

    return apply_op(f, _t(x1), _t(x2))


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = int(upscale_factor)

    def f(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            oc = c // (r * r)
            a = a.reshape(n, oc, r, r, h, w)
            a = a.transpose(0, 1, 4, 2, 5, 3)
            return a.reshape(n, oc, h * r, w * r)
        n, h, w, c = a.shape
        oc = c // (r * r)
        a = a.reshape(n, h, w, r, r, oc)
        a = a.transpose(0, 1, 3, 2, 4, 5)
        return a.reshape(n, h * r, w * r, oc)

    return apply_op(f, _t(x))


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = int(downscale_factor)

    def f(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            oh, ow = h // r, w // r
            a = a.reshape(n, c, oh, r, ow, r)
            a = a.transpose(0, 1, 3, 5, 2, 4)
            return a.reshape(n, c * r * r, oh, ow)
        n, h, w, c = a.shape
        oh, ow = h // r, w // r
        a = a.reshape(n, oh, r, ow, r, c)
        a = a.transpose(0, 2, 4, 5, 1, 3)
        return a.reshape(n, oh, ow, c * r * r)

    return apply_op(f, _t(x))


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def f(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, groups, c // groups, h, w)
            a = a.transpose(0, 2, 1, 3, 4)
            return a.reshape(n, c, h, w)
        n, h, w, c = a.shape
        a = a.reshape(n, h, w, groups, c // groups)
        a = a.transpose(0, 1, 2, 4, 3)
        return a.reshape(n, h, w, c)

    return apply_op(f, _t(x))


def interpolate(
    x,
    size=None,
    scale_factor=None,
    mode="nearest",
    align_corners=False,
    align_mode=0,
    data_format="NCHW",
    name=None,
):
    x = _t(x)
    spatial = x.shape[2:] if data_format.startswith("NC") else x.shape[1:-1]
    if size is None:
        if isinstance(scale_factor, (int, float)):
            scale_factor = [scale_factor] * len(spatial)
        size = [int(s * f) for s, f in zip(spatial, scale_factor)]
    else:
        if isinstance(size, Tensor):
            size = [int(v) for v in size.numpy()]
        size = [int(s.item()) if isinstance(s, Tensor) else int(s) for s in size]

    method = {
        "nearest": "nearest",
        "bilinear": "linear",
        "trilinear": "linear",
        "linear": "linear",
        "bicubic": "cubic",
        "area": "linear",
    }[mode.lower()]

    def f(a):
        if data_format.startswith("NC"):
            target = list(a.shape[:2]) + size
        else:
            target = [a.shape[0]] + size + [a.shape[-1]]
        if method == "nearest":
            return _nearest_resize(a, target, data_format)
        if align_corners:
            # jax.image.resize has no align_corners; emulate via linear scale
            return _align_corners_resize(a, target, data_format, method)
        return jax.image.resize(a, tuple(target), method=method)

    return apply_op(f, x)


def _nearest_resize(a, target, data_format):
    # floor-index nearest (paddle semantics with align_corners=False)
    idxs = []
    src_spatial_axes = range(2, a.ndim) if data_format.startswith("NC") else range(1, a.ndim - 1)
    out = a
    for ax in src_spatial_axes:
        in_s = a.shape[ax]
        out_s = target[ax]
        idx = jnp.clip(jnp.floor(jnp.arange(out_s) * (in_s / out_s)).astype(jnp.int32), 0, in_s - 1)
        out = jnp.take(out, idx, axis=ax)
    return out


def _align_corners_resize(a, target, data_format, method):
    axes = list(range(2, a.ndim)) if data_format.startswith("NC") else list(range(1, a.ndim - 1))
    out = a
    for ax in axes:
        in_s = out.shape[ax]
        out_s = target[ax]
        if out_s == 1 or in_s == 1:
            pos = jnp.zeros(out_s)
        else:
            pos = jnp.arange(out_s) * ((in_s - 1) / (out_s - 1))
        lo = jnp.floor(pos).astype(jnp.int32)
        hi = jnp.clip(lo + 1, 0, in_s - 1)
        w = (pos - lo).astype(out.dtype)
        shape = [1] * out.ndim
        shape[ax] = out_s
        w = w.reshape(shape)
        out = jnp.take(out, lo, axis=ax) * (1 - w) + jnp.take(out, hi, axis=ax) * w
    return out


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
             align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    ks = [kernel_sizes] * 2 if isinstance(kernel_sizes, int) else list(kernel_sizes)
    st = [strides] * 2 if isinstance(strides, int) else list(strides)
    pd = [paddings] * 2 if isinstance(paddings, int) else list(paddings)
    dl = [dilations] * 2 if isinstance(dilations, int) else list(dilations)
    if len(pd) == 2:
        pd = [pd[0], pd[0], pd[1], pd[1]]

    def f(a):
        n, c, h, w = a.shape
        patches = jax.lax.conv_general_dilated_patches(
            a,
            filter_shape=ks,
            window_strides=st,
            padding=((pd[0], pd[1]), (pd[2], pd[3])),
            rhs_dilation=dl,
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )
        # patches: [n, c*kh*kw, oh, ow] -> [n, c*kh*kw, oh*ow]
        return patches.reshape(n, patches.shape[1], -1)

    return apply_op(f, _t(x))


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    os = [output_sizes] * 2 if isinstance(output_sizes, int) else list(output_sizes)
    ks = [kernel_sizes] * 2 if isinstance(kernel_sizes, int) else list(kernel_sizes)
    st = [strides] * 2 if isinstance(strides, int) else list(strides)
    pd = [paddings] * 2 if isinstance(paddings, int) else list(paddings)
    dl = [dilations] * 2 if isinstance(dilations, int) else list(dilations)
    if len(pd) == 2:
        pd = [pd[0], pd[0], pd[1], pd[1]]

    def f(a):
        n, ckk, l = a.shape
        c = ckk // (ks[0] * ks[1])
        oh = (os[0] + pd[0] + pd[1] - dl[0] * (ks[0] - 1) - 1) // st[0] + 1
        ow = (os[1] + pd[2] + pd[3] - dl[1] * (ks[1] - 1) - 1) // st[1] + 1
        cols = a.reshape(n, c, ks[0], ks[1], oh, ow)
        out = jnp.zeros((n, c, os[0] + pd[0] + pd[1], os[1] + pd[2] + pd[3]), a.dtype)
        for i in range(ks[0]):
            for j in range(ks[1]):
                hi = i * dl[0]
                wj = j * dl[1]
                out = out.at[
                    :, :, hi : hi + oh * st[0] : st[0], wj : wj + ow * st[1] : st[1]
                ].add(cols[:, :, i, j])
        return out[:, :, pd[0] : out.shape[2] - pd[1], pd[2] : out.shape[3] - pd[3]]

    return apply_op(f, _t(x))


def bilinear(x1, x2, weight, bias=None, name=None):
    def f(a, b, w, *rest):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if rest:
            out = out + rest[0]
        return out

    if bias is not None:
        return apply_op(f, _t(x1), _t(x2), weight, bias)
    return apply_op(f, _t(x1), _t(x2), weight)


def diag_embed(input, offset=0, dim1=-2, dim2=-1):
    """Create batched matrices whose (dim1, dim2) planes carry ``input``'s
    last axis on the ``offset`` diagonal — parity with
    python/paddle/nn/functional/extension.py:29 (diag_embed op). One
    scatter-free construction: place on the trailing [n, n] plane via a
    static index set, then moveaxis to (dim1, dim2)."""
    x = _t(input)

    def f(a):
        m = a.shape[-1]
        n = m + abs(offset)
        out_ndim = a.ndim + 1
        d1 = dim1 % out_ndim
        d2 = dim2 % out_ndim
        if d1 == d2:
            raise ValueError("diag_embed: dim1 and dim2 must differ")
        idx = jnp.arange(m)
        rows = idx + max(-offset, 0)
        cols = idx + max(offset, 0)
        plane = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
        plane = plane.at[..., rows, cols].set(a)
        # trailing (r, c) plane -> the requested (dim1, dim2) positions
        # (moveaxis handles d1 > d2 — the row axis simply lands after the
        # column axis, which IS the reference's transposed-diagonal
        # behavior; verified against torch.diag_embed for dim1 > dim2)
        return jnp.moveaxis(plane, (-2, -1), (d1, d2))

    return apply_op(f, x)


__all__.append("diag_embed")
