"""Pooling functionals — parity with python/paddle/nn/functional/pooling.py.
Built on ``lax.reduce_window``, XLA's native windowed reduction (replaces the
reference's pool_op.cu / cuDNN pooling).
"""
from __future__ import annotations

import functools
import itertools
import os

import jax
import jax.numpy as jnp
import numpy as np

from ...core.enforce import enforce
from ...core.tensor import Tensor, apply_op, to_tensor

__all__ = [
    "avg_pool1d", "avg_pool2d", "avg_pool3d", "max_pool1d", "max_pool2d",
    "max_pool3d", "adaptive_avg_pool1d", "adaptive_avg_pool2d",
    "adaptive_avg_pool3d", "adaptive_max_pool1d", "adaptive_max_pool2d",
    "adaptive_max_pool3d",
]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _norm(v, n):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    return tuple(int(i) for i in v)


def _pool(x, kernel, stride, padding, n, op, channel_last, ceil_mode=False,
          exclusive=True):
    kernel = _norm(kernel, n)
    stride = _norm(stride if stride is not None else kernel, n)
    if isinstance(padding, str):
        pad_str = padding.upper()
        pads = None
    else:
        p = _norm(padding, n) if not isinstance(padding, (list,)) or all(
            isinstance(i, (int, np.integer)) for i in padding
        ) else None
        if p is None:
            pads = [tuple(int(i) for i in pr) for pr in padding]
        else:
            pads = [(int(i), int(i)) for i in p]
        pad_str = None

    def f(a):
        nd = a.ndim
        if channel_last:
            window = (1,) + kernel + (1,)
            strides = (1,) + stride + (1,)
            spatial = list(range(1, nd - 1))
        else:
            window = (1, 1) + kernel
            strides = (1, 1) + stride
            spatial = list(range(2, nd))
        if pad_str is not None:
            padding_cfg = pad_str
        else:
            full = [(0, 0)] * nd
            for i, ax in enumerate(spatial):
                lo, hi = pads[i]
                if ceil_mode:
                    in_s = a.shape[ax]
                    out_ceil = -(-(in_s + lo + hi - kernel[i]) // stride[i]) + 1
                    needed = (out_ceil - 1) * stride[i] + kernel[i] - in_s - lo
                    hi = max(hi, needed)
                full[ax] = (lo, hi)
            padding_cfg = full
        if op == "max":
            if (jnp.issubdtype(a.dtype, jnp.floating)
                    and isinstance(padding_cfg, list)
                    and os.environ.get("PADDLE_TPU_MANUAL_MAXPOOL", "0") == "1"):
                return _manual_maxpool(window, strides,
                                       tuple(padding_cfg))(a)
            init = -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating) else jnp.iinfo(a.dtype).min
            return jax.lax.reduce_window(a, init, jax.lax.max, window, strides, padding_cfg)
        # avg: sum then divide by count (exclusive=True divides by valid count)
        s = jax.lax.reduce_window(a, 0.0, jax.lax.add, window, strides, padding_cfg)
        if exclusive and (pad_str is None and any(p != (0, 0) for p in (padding_cfg if isinstance(padding_cfg, list) else []))):
            ones = jnp.ones_like(a)
            cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides, padding_cfg)
            return s / cnt
        return s / float(np.prod(kernel))

    return apply_op(f, _t(x))


@functools.lru_cache(maxsize=None)
def _manual_maxpool(window, strides, pads):
    """Floating max-pool with a value-equality backward. NEGATIVE RESULT —
    default OFF (opt in via PADDLE_TPU_MANUAL_MAXPOOL=1).

    Motivation: XLA differentiates ``reduce_window(max)`` into
    select-and-scatter — 1.43 ms/step of the ResNet-50 profile
    (tools/profiles/r4_resnet.txt). This rule instead routes gradients by
    VALUE EQUALITY: eq_u = (view_u == y) over the prod(window) strided
    views, dx accumulated either by dilated-pad scatter-back or by
    gathering the dilated y/scale grids. Ties split the gradient evenly
    (sum-preserving; XLA and the reference's cuDNN kernel pick one winner —
    identical on tie-free continuous inputs).

    Measured on v5e at the ResNet stem shape ([64,64,112,112] bf16, k3 s2
    p1), fwd+bwd chained 10× in one jit: XLA select-and-scatter ≈ 9 ms/iter
    incl. harness, pad-scatter formulation 76 ms, single-dilation gather
    formulation 52 ms — the shifted-window equality passes do NOT fuse into
    the two elementwise loops the arithmetic suggests on this emitter, so
    the manual rule loses 6-8× and end-to-end ResNet-50 dropped
    1581→1205 samples/s. Kept as an opt-in record of the experiment.

    Forward is the same ``reduce_window`` either way; when no gradient is
    taken the custom_vjp adds nothing.
    """

    @jax.custom_vjp
    def mp(a):
        return jax.lax.reduce_window(a, -jnp.inf, jax.lax.max, window,
                                     strides, list(pads))

    def fwd(a):
        y = mp(a)
        return y, (a, y)

    def bwd(res, dy):
        a, y = res
        nd = a.ndim
        ap = jax.lax.pad(a, jnp.asarray(-jnp.inf, a.dtype),
                         [(lo, hi, 0) for lo, hi in pads])
        dyf = dy.astype(jnp.float32)
        offsets = list(itertools.product(*(range(w) for w in window)))

        def view(u):
            limit = [u[d] + strides[d] * (y.shape[d] - 1) + 1
                     for d in range(nd)]
            return jax.lax.slice(ap, u, limit, strides)

        eqs = [view(u) == y for u in offsets]
        cnt = functools.reduce(
            jnp.add, (e.astype(jnp.float32) for e in eqs))
        scale = dyf / cnt
        dxp = None
        for u, eq in zip(offsets, eqs):
            part = jnp.where(eq, scale, 0.0)
            cfg = [(u[d],
                    ap.shape[d] - (u[d] + strides[d] * (y.shape[d] - 1) + 1),
                    strides[d] - 1) for d in range(nd)]
            scattered = jax.lax.pad(part, jnp.asarray(0.0, jnp.float32), cfg)
            dxp = scattered if dxp is None else dxp + scattered
        dx = jax.lax.slice(
            dxp, [lo for lo, _ in pads],
            [lo + s for (lo, _), s in zip(pads, a.shape)])
        return (dx.astype(a.dtype),)

    mp.defvjp(fwd, bwd)
    return mp


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    out = _pool(x, kernel_size, stride, padding, 1, "max", data_format == "NLC", ceil_mode)
    return out


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    out = _pool(x, kernel_size, stride, padding, 2, "max", data_format == "NHWC", ceil_mode)
    if return_mask:
        idx = _max_pool_indices(_t(x), kernel_size, stride, padding, 2, data_format)
        return out, idx
    return out


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, "max", data_format == "NDHWC", ceil_mode)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    return _pool(x, kernel_size, stride, padding, 1, "avg", data_format == "NLC",
                 ceil_mode, exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, "avg", data_format == "NHWC",
                 ceil_mode, exclusive)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, "avg", data_format == "NDHWC",
                 ceil_mode, exclusive)


def _max_pool_indices(x, kernel, stride, padding, n, data_format):
    """Flat spatial argmax indices for return_mask (paddle semantics)."""
    kernel_t = _norm(kernel, n)
    stride_t = _norm(stride if stride is not None else kernel, n)
    pad_t = _norm(padding, n)

    def f(a):
        spatial = a.shape[2:]
        # int32 indices: exact to 2^31 elements and TPU-native — float
        # carriers are either inexact past 2^24 (f32) or silently
        # degraded to f32 on TPU hardware (f64; tpu-lint R7)
        flat_idx = jnp.arange(int(np.prod(spatial)), dtype=jnp.int32).reshape(spatial)
        flat_idx = jnp.broadcast_to(flat_idx, a.shape)
        window = (1, 1) + kernel_t
        strides = (1, 1) + stride_t
        pads = [(0, 0), (0, 0)] + [(p, p) for p in pad_t]

        def reducer(acc, cur):
            av, ai = acc
            cv, ci = cur
            take_cur = cv > av
            return jnp.where(take_cur, cv, av), jnp.where(take_cur, ci, ai)

        init_v = jnp.asarray(-jnp.inf, a.dtype)
        init_i = jnp.asarray(-1, jnp.int32)
        vals, idxs = jax.lax.reduce_window(
            (a, flat_idx), (init_v, init_i),
            lambda xa, xb: reducer((xa[0], xa[1]), (xb[0], xb[1])),
            window, strides, pads,
        )
        return idxs.astype(jnp.int64)

    return apply_op(f, x)


def _adaptive(x, output_size, n, op, channel_last):
    if isinstance(output_size, (int, np.integer)):
        output_size = (int(output_size),) * n
    output_size = tuple(
        int(o) if o is not None else None for o in output_size
    )

    def f(a):
        spatial_axes = list(range(1, 1 + n)) if channel_last else list(range(2, 2 + n))
        out = a
        for i, ax in enumerate(spatial_axes):
            tgt = output_size[i]
            if tgt is None:
                continue
            in_s = out.shape[ax]
            # adaptive pooling: bin b covers [floor(b*in/out), ceil((b+1)*in/out))
            pieces = []
            for b in range(tgt):
                lo = (b * in_s) // tgt
                hi = -(-((b + 1) * in_s) // tgt)
                seg = jax.lax.slice_in_dim(out, lo, hi, axis=ax)
                red = jnp.max(seg, axis=ax, keepdims=True) if op == "max" else jnp.mean(
                    seg, axis=ax, keepdims=True
                )
                pieces.append(red)
            out = jnp.concatenate(pieces, axis=ax) if len(pieces) > 1 else pieces[0]
        return out

    return apply_op(f, _t(x))


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive(x, output_size, 1, "avg", False)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive(x, output_size, 2, "avg", data_format == "NHWC")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive(x, output_size, 3, "avg", data_format == "NDHWC")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 1, "max", False)


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 2, "max", False)


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 3, "max", False)
