"""paddle_tpu.nn.functional — parity with python/paddle/nn/functional/."""
from .activation import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .conv import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .pooling import *  # noqa: F401,F403
from .vision import *  # noqa: F401,F403
from ...tensor.sequence import sequence_mask  # noqa: F401

from . import activation, common, conv, loss, norm, pooling, vision  # noqa: F401

from ..layer.decode import gather_tree  # noqa: F401  (F.gather_tree parity)
