"""Loss functionals — parity with python/paddle/nn/functional/loss.py."""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from ...core.enforce import InvalidArgumentError, enforce
from ...core.tensor import Tensor, apply_op, to_tensor

__all__ = [
    "cross_entropy", "softmax_with_cross_entropy", "mse_loss", "l1_loss",
    "nll_loss", "binary_cross_entropy", "binary_cross_entropy_with_logits",
    "kl_div", "smooth_l1_loss", "margin_ranking_loss", "hinge_embedding_loss",
    "cosine_embedding_loss", "square_error_cost", "log_loss", "sigmoid_focal_loss",
    "triplet_margin_loss", "ctc_loss", "edit_distance", "hsigmoid_loss",
    "dice_loss", "npair_loss",
]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    if reduction == "none":
        return out
    raise InvalidArgumentError(f"unknown reduction {reduction!r}")


def _hard_ce_fwd_impl(logits, lbl_i, ax, ignore_index):
    m2 = jax.lax.stop_gradient(jnp.max(logits, axis=ax, keepdims=True))
    # exp stays in the input dtype, the SUM accumulates f32 (see the
    # rationale in cross_entropy's fast path)
    lse = jnp.log(jnp.sum(jnp.exp(logits - m2), axis=ax,
                          dtype=jnp.float32)) \
        + jnp.squeeze(m2, axis=ax).astype(jnp.float32)
    lbl_exp = jnp.expand_dims(lbl_i, ax)
    picked = jnp.take_along_axis(logits, jnp.clip(lbl_exp, 0, None), axis=ax)
    loss = (lse - jnp.squeeze(picked, axis=ax).astype(jnp.float32)
            ).astype(logits.dtype)
    mask = (lbl_i != ignore_index).astype(logits.dtype)
    return loss * mask, mask, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _hard_ce(logits, lbl_i, ax, ignore_index):
    """Hard-label CE (lse − picked logit) with a hand-written backward.

    Autodiff of the lse form saves the full [N, V] exp(logits − m)
    intermediate as a residual — for an LM head that is an extra
    0.8 GB bf16 HBM write+read per step (GPT-2 345M, V=50257) on top of
    the logits the head matmul already keeps. The manual rule saves only
    the f32 per-row lse: backward recomputes softmax = exp(l − lse) from
    the logits residual and emits dlogits = (softmax − onehot)·dy·mask in
    ONE fused elementwise pass (the onehot subtract rides the same pass
    via a broadcasted-iota compare, no scatter). Replaces the reference's
    fused softmax_with_cross_entropy grad kernel
    (operators/softmax_with_cross_entropy_op.cu) at the XLA level."""
    loss, mask, _ = _hard_ce_fwd_impl(logits, lbl_i, ax, ignore_index)
    return loss, mask


def _hard_ce_fwd(logits, lbl_i, ax, ignore_index):
    loss, mask, lse = _hard_ce_fwd_impl(logits, lbl_i, ax, ignore_index)
    return (loss, mask), (logits, lbl_i, lse)


def _hard_ce_bwd(ax, ignore_index, res, ct):
    dloss, _dmask = ct  # mask is label-only — no logits cotangent
    logits, lbl_i, lse = res
    nd = logits.ndim
    axp = ax % nd
    maskf = (lbl_i != ignore_index).astype(jnp.float32)
    g = jnp.expand_dims(dloss.astype(jnp.float32) * maskf, axp)
    # softmax recomputed in f32 inside the fusion (a bf16 cast of lse
    # would cost ~8 mantissa bits ON the exponent scale)
    p = jnp.exp(logits.astype(jnp.float32) - jnp.expand_dims(lse, axp))
    idx = jax.lax.broadcasted_iota(jnp.int32, logits.shape, axp)
    onehot = (idx == jnp.clip(jnp.expand_dims(lbl_i, axp), 0, None))
    dlogits = ((p - onehot) * g).astype(logits.dtype)
    return dlogits, np.zeros(lbl_i.shape, dtype=jax.dtypes.float0)


_hard_ce.defvjp(_hard_ce_fwd, _hard_ce_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_linear_hard_ce(h2, wT, lbl_i, ignore_index=-100):
    """LM-head matmul + hard-label CE with a hand-written joint backward.

    Splitting linear (autodiff) from _hard_ce (custom_vjp) leaves XLA a
    [N, V] ``dlogits`` with TWO dot consumers (dW and dh) — it materializes
    dlogits once (~0.8 GB bf16 at GPT-2 345M) and re-reads it for each dot.
    The joint rule instead hands each dot its own algebraically distinct
    dlogits expression ((p − y)·g vs p·g − y·g — different HLO, so CSE
    cannot re-merge them), letting each fuse into its consumer dot's
    operand: the softmax recompute reads the saved logits residual
    directly and dlogits never exists in HBM. Replaces the reference's
    fused softmax_with_cross_entropy grad + matmul grad pair
    (operators/softmax_with_cross_entropy_op.cu, matmul_v2_op) at the XLA
    level. Returns (per-row loss·mask, mask)."""
    logits = jnp.matmul(h2, wT)
    loss, mask, _ = _hard_ce_fwd_impl(logits, lbl_i, -1, ignore_index)
    return loss, mask


def _flce_fwd(h2, wT, lbl_i, ignore_index):
    logits = jnp.matmul(h2, wT)
    loss, mask, lse = _hard_ce_fwd_impl(logits, lbl_i, -1, ignore_index)
    return (loss, mask), (h2, wT, lbl_i, logits, lse)


def _flce_bwd(ignore_index, res, ct):
    dloss, _dmask = ct
    h2, wT, lbl_i, logits, lse = res
    maskf = (lbl_i != ignore_index).astype(jnp.float32)
    g = jnp.expand_dims(dloss.astype(jnp.float32) * maskf, -1)
    shifted = logits.astype(jnp.float32) - jnp.expand_dims(lse, -1)
    idx = jax.lax.broadcasted_iota(jnp.int32, logits.shape, logits.ndim - 1)
    onehot = (idx == jnp.clip(jnp.expand_dims(lbl_i, -1), 0, None))
    # two NON-CSE-able forms of the same dlogits, one per consumer dot
    d_for_w = ((jnp.exp(shifted) - onehot) * g).astype(logits.dtype)
    d_for_h = (jnp.exp(shifted) * g
               - jnp.where(onehot, g, jnp.zeros((), jnp.float32))
               ).astype(logits.dtype)
    dw = jnp.einsum("nh,nv->hv", h2, d_for_w)
    dh = jnp.matmul(d_for_h, wT.T)
    return dh, dw.astype(wT.dtype), np.zeros(lbl_i.shape,
                                             dtype=jax.dtypes.float0)


fused_linear_hard_ce.defvjp(_flce_fwd, _flce_bwd)


def cross_entropy(
    input,
    label,
    weight=None,
    ignore_index=-100,
    reduction="mean",
    soft_label=False,
    axis=-1,
    use_softmax=True,
    label_smoothing=0.0,
    name=None,
):
    """Fused logits→softmax→NLL — replaces the reference's
    softmax_with_cross_entropy CUDA kernel (operators/softmax_with_cross_entropy_op.cu);
    XLA fuses the log-softmax with the gather."""
    input = _t(input)
    label = _t(label)
    w = weight

    def f(logits, lbl, *wa):
        # hard-label fast path FIRST, before any full log-softmax exists to
        # be materialized (in eager mode nothing dead-code-eliminates it)
        if (not soft_label and label_smoothing == 0.0 and use_softmax
                and not wa):
            lbl_i = lbl.astype(jnp.int32)
            if lbl_i.ndim == logits.ndim and lbl_i.shape[axis] == 1:
                lbl_i = jnp.squeeze(lbl_i, axis=axis)
            # loss = logsumexp - picked logit. Avoids materializing the full
            # [N, V] log-probs the log_softmax+gather form writes (for an LM
            # head V is 50k+ — that tensor is HBM bandwidth, not compute).
            # The SUM accumulates in f32 (a bf16 sum over a 50k vocab
            # carries ~2 digits) while the exp values stay in the input
            # dtype — upcasting them would double the saved residual's HBM
            # bytes (measured -8% end-to-end on the GPT bench).
            # _hard_ce adds the manual backward (no [N,V] exp residual);
            # PADDLE_TPU_MANUAL_CE=0 falls back to autodiff of the same
            # forward.
            if os.environ.get("PADDLE_TPU_MANUAL_CE", "1") == "1":
                return _hard_ce(logits, lbl_i, axis, ignore_index)
            loss, mask, _ = _hard_ce_fwd_impl(logits, lbl_i, axis,
                                              ignore_index)
            return loss, mask
        logp = jax.nn.log_softmax(logits, axis=axis) if use_softmax else jnp.log(
            jnp.clip(logits, 1e-30, None)
        )
        if soft_label:
            soft = lbl
            if label_smoothing > 0.0:
                k = logits.shape[axis]
                soft = soft * (1.0 - label_smoothing) + label_smoothing / k
            loss = -jnp.sum(soft * logp, axis=axis)
            if wa:
                loss = loss * jnp.sum(soft * wa[0], axis=axis)
            return loss
        lbl_i = lbl.astype(jnp.int32)
        squeeze = lbl_i.ndim == logp.ndim and lbl_i.shape[axis] == 1
        if squeeze:
            lbl_i = jnp.squeeze(lbl_i, axis=axis)
        if label_smoothing > 0.0:
            k = logp.shape[axis]
            onehot = jax.nn.one_hot(lbl_i, k, axis=axis, dtype=logp.dtype)
            soft = onehot * (1.0 - label_smoothing) + label_smoothing / k
            loss = -jnp.sum(soft * logp, axis=axis)
        else:
            lbl_exp = jnp.expand_dims(lbl_i, axis)
            picked = jnp.take_along_axis(logp, jnp.clip(lbl_exp, 0, None), axis=axis)
            loss = -jnp.squeeze(picked, axis=axis)
        mask = (lbl_i != ignore_index).astype(logp.dtype)
        loss = loss * mask
        if wa:
            loss = loss * jnp.take(wa[0], jnp.clip(lbl_i, 0, None))
        return loss, mask

    def g(logits, lbl, *wa):
        res = f(logits, lbl, *wa)
        loss, mask = res if isinstance(res, tuple) else (res, None)
        if reduction == "none":
            return loss
        if reduction == "sum":
            return jnp.sum(loss)
        if soft_label or mask is None:
            return jnp.mean(loss)
        # hard labels: mean over non-ignored positions
        return jnp.sum(loss) / jnp.maximum(jnp.sum(mask), 1.0)

    args = [input, label.detach() if not soft_label else label]
    if w is not None:
        args.append(w)
    return apply_op(g, *args)


def softmax_with_cross_entropy(
    logits, label, soft_label=False, ignore_index=-100, numeric_stable_mode=True,
    return_softmax=False, axis=-1,
):
    loss = cross_entropy(
        logits, label, soft_label=soft_label, ignore_index=ignore_index,
        reduction="none", axis=axis,
    )
    from .activation import softmax as softmax_fn

    loss = loss.unsqueeze(axis) if not soft_label else loss
    if return_softmax:
        return loss, softmax_fn(logits, axis=axis)
    return loss


def mse_loss(input, label, reduction="mean", name=None):
    return apply_op(lambda a, b: _reduce((a - b) ** 2, reduction), _t(input), _t(label))


def l1_loss(input, label, reduction="mean", name=None):
    return apply_op(lambda a, b: _reduce(jnp.abs(a - b), reduction), _t(input), _t(label))


def square_error_cost(input, label):
    return apply_op(lambda a, b: (a - b) ** 2, _t(input), _t(label))


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    def f(logp, lbl, *wa):
        lbl_i = lbl.astype(jnp.int32)
        picked = jnp.take_along_axis(logp, jnp.expand_dims(jnp.clip(lbl_i, 0, None), 1), axis=1)
        loss = -jnp.squeeze(picked, axis=1)
        mask = (lbl_i != ignore_index).astype(logp.dtype)
        wgt = mask
        if wa:
            wgt = wgt * jnp.take(wa[0], jnp.clip(lbl_i, 0, None))
        loss = loss * wgt
        if reduction == "none":
            return loss
        if reduction == "sum":
            return jnp.sum(loss)
        return jnp.sum(loss) / jnp.maximum(jnp.sum(wgt), 1e-12)

    args = [_t(input), _t(label).detach()]
    if weight is not None:
        args.append(weight)
    return apply_op(f, *args)


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def f(p, t, *wa):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-12)
        loss = -(t * jnp.log(p) + (1.0 - t) * jnp.log(1.0 - p))
        if wa:
            loss = loss * wa[0]
        return _reduce(loss, reduction)

    args = [_t(input), _t(label)]
    if weight is not None:
        args.append(_t(weight))
    return apply_op(f, *args)


def binary_cross_entropy_with_logits(
    logit, label, weight=None, reduction="mean", pos_weight=None, name=None
):
    def f(z, t, *extra):
        i = 0
        w = None
        pw = None
        if weight is not None:
            w = extra[i]
            i += 1
        if pos_weight is not None:
            pw = extra[i]
        # numerically stable: max(z,0) - z*t + log(1+exp(-|z|)), with pos_weight
        log_sig = jax.nn.log_sigmoid(z)
        log_sig_neg = jax.nn.log_sigmoid(-z)
        if pw is not None:
            loss = -(pw * t * log_sig + (1.0 - t) * log_sig_neg)
        else:
            loss = -(t * log_sig + (1.0 - t) * log_sig_neg)
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)

    args = [_t(logit), _t(label)]
    if weight is not None:
        args.append(_t(weight))
    if pos_weight is not None:
        args.append(_t(pos_weight))
    return apply_op(f, *args)


def kl_div(input, label, reduction="mean", name=None):
    def f(logp, t):
        loss = t * (jnp.log(jnp.clip(t, 1e-12, None)) - logp)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)

    return apply_op(f, _t(input), _t(label))


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def f(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d, delta * (d - 0.5 * delta))
        return _reduce(loss, reduction)

    return apply_op(f, _t(input), _t(label))


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    return apply_op(
        lambda a, b, t: _reduce(jnp.maximum(-t * (a - b) + margin, 0.0), reduction),
        _t(input), _t(other), _t(label),
    )


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    return apply_op(
        lambda a, t: _reduce(
            jnp.where(t == 1.0, a, jnp.maximum(margin - a, 0.0)), reduction
        ),
        _t(input), _t(label),
    )


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    def f(a, b, t):
        cos = jnp.sum(a * b, axis=-1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12
        )
        loss = jnp.where(t == 1, 1.0 - cos, jnp.maximum(cos - margin, 0.0))
        return _reduce(loss, reduction)

    return apply_op(f, _t(input1), _t(input2), _t(label))


def log_loss(input, label, epsilon=1e-4, name=None):
    return apply_op(
        lambda p, t: -t * jnp.log(p + epsilon) - (1.0 - t) * jnp.log(1.0 - p + epsilon),
        _t(input), _t(label),
    )


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def f(z, t, *nrm):
        p = jax.nn.sigmoid(z)
        ce = -(t * jax.nn.log_sigmoid(z) + (1 - t) * jax.nn.log_sigmoid(-z))
        p_t = p * t + (1 - p) * (1 - t)
        a_t = alpha * t + (1 - alpha) * (1 - t)
        loss = a_t * ((1 - p_t) ** gamma) * ce
        if nrm:
            loss = loss / nrm[0]
        return _reduce(loss, reduction)

    args = [_t(logit), _t(label)]
    if normalizer is not None:
        args.append(_t(normalizer))
    return apply_op(f, *args)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    def f(a, pos, neg):
        dp = jnp.sum(jnp.abs(a - pos) ** p, axis=-1) ** (1.0 / p)
        dn = jnp.sum(jnp.abs(a - neg) ** p, axis=-1) ** (1.0 / p)
        if swap:
            dsn = jnp.sum(jnp.abs(pos - neg) ** p, axis=-1) ** (1.0 / p)
            dn = jnp.minimum(dn, dsn)
        return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)

    return apply_op(f, _t(input), _t(positive), _t(negative))


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC via optax's implementation (replaces the reference's warpctc vendored
    dep, cmake/external/warpctc.cmake)."""
    import optax

    def f(lp, lbl, il, ll):
        # paddle layout: [T, B, C] logits; optax expects [B, T, C]
        logits = jnp.transpose(lp, (1, 0, 2))
        b, t, c = logits.shape
        logit_pad = (jnp.arange(t)[None, :] >= il[:, None]).astype(logits.dtype)
        lbl_b = lbl if lbl.ndim == 2 else lbl.reshape(b, -1)
        lbl_pad = (
            jnp.arange(lbl_b.shape[1])[None, :] >= ll[:, None]
        ).astype(logits.dtype)
        loss = optax.ctc_loss(logits, logit_pad, lbl_b, lbl_pad, blank_id=blank)
        if reduction == "none":
            return loss
        if reduction == "sum":
            return jnp.sum(loss)
        return jnp.mean(loss / jnp.maximum(ll.astype(loss.dtype), 1.0))

    return apply_op(
        f, _t(log_probs), _t(labels).detach(), _t(input_lengths).detach(),
        _t(label_lengths).detach(),
    )


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None):
    """Levenshtein distance between batched token sequences.

    Parity with the reference's edit_distance op
    (/root/reference/paddle/fluid/operators/edit_distance_op.cc, python API
    fluid/layers/loss.py:360): returns ``(distance [B, 1] float32,
    sequence_num [1] float32)``; ``normalized`` divides by the reference
    (label) length; ``ignored_tokens`` are removed from both sides first.

    TPU-first: instead of the reference's per-sequence O(L1·L2) scalar DP
    loop, each DP row update is vectorized — the in-row insertion chain
    ``new[j] = min(new[j-1]+1, cand[j])`` is a min-plus prefix scan, i.e.
    ``j + cummin(cand - j)`` (jax.lax.cummin), so one lax.scan over input
    positions does O(L1) vector steps of width L2+1, batched over B.
    Token removal for ``ignored_tokens`` is a stable argsort compaction
    (static shapes; lengths shrink instead of the buffer).
    """
    inp, lab = _t(input), _t(label)
    B, L1 = inp.shape
    L2 = lab.shape[1]
    il = _t(input_length) if input_length is not None else None
    ll = _t(label_length) if label_length is not None else None

    def f(inp, lab, *rest):
        rest = list(rest)
        li = (rest.pop(0).reshape(-1) if input_length is not None
              else jnp.full((B,), L1)).astype(jnp.int32)
        lj = (rest.pop(0).reshape(-1) if label_length is not None
              else jnp.full((B,), L2)).astype(jnp.int32)

        def compact(seq, length, ignored):
            keep = jnp.ones(seq.shape, bool)
            for tok in ignored:
                keep &= seq != tok
            keep &= jnp.arange(seq.shape[1])[None, :] < length[:, None]
            order = jnp.argsort(~keep, axis=1, stable=True)
            return jnp.take_along_axis(seq, order, axis=1), keep.sum(axis=1)

        if ignored_tokens:
            inp, li = compact(inp, li, ignored_tokens)
            lab, lj = compact(lab, lj, ignored_tokens)

        def row_update(carry, x_i):
            # prev: [B, L2+1] distances for input prefix i-1; cap holds each
            # row's DP row at its own input length (O(B·L2) memory — the
            # full [L1+1, B, L2+1] table is never materialized)
            prev, cap = carry
            tok, i = x_i
            cost = (tok[:, None] != lab).astype(prev.dtype)       # [B, L2]
            cand = jnp.minimum(prev[:, 1:] + 1, prev[:, :-1] + cost)
            cand = jnp.concatenate(
                [(prev[:, :1] + 1), cand], axis=1)                # [B, L2+1]
            arange = jnp.arange(L2 + 1)[None, :].astype(prev.dtype)
            new = arange + jax.lax.cummin(cand - arange, axis=1)
            cap = jnp.where((i == li)[:, None], new, cap)
            return (new, cap), None

        row0 = jnp.broadcast_to(
            jnp.arange(L2 + 1, dtype=jnp.float32)[None], (B, L2 + 1))
        cap0 = row0  # li == 0 → distance is the label length itself
        (_, cap), _ = jax.lax.scan(
            row_update, (row0, cap0), (inp.T, jnp.arange(1, L1 + 1)))
        dist = jnp.take_along_axis(cap, lj[:, None], axis=1)[:, 0]  # [B]
        # empty-reference convention (edit_distance_op.h): d(x, "") = len(x)
        # is already in the DP; normalization guards /0 like the reference
        if normalized:
            dist = dist / jnp.maximum(lj.astype(dist.dtype), 1.0)
        return dist[:, None].astype(jnp.float32)

    args = [inp, lab] + [t.detach() for t in (il, ll) if t is not None]
    out = apply_op(f, *args)
    return out, to_tensor(np.array([float(B)], np.float32))


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid loss — parity with
    python/paddle/nn/functional/loss.py:312 (kernel
    paddle/fluid/operators/hierarchical_sigmoid_op.h).

    Default tree: complete binary tree over ``num_classes`` leaves via the
    reference's SimpleCode (matrix_bit_code.h:106): for leaf ``l`` the code
    is ``c = l + num_classes``; step ``j`` classifies against internal node
    ``(c >> (j+1)) - 1`` with binary target ``(c >> j) & 1``; the path
    length is ``floor(log2(c))``. Loss per sample is the summed
    sigmoid-BCE over its path: Σ_j log(1+exp(p_j)) − Σ_{bit_j=1} p_j with
    pre-activation clipped to ±40 like the kernel.

    TPU-first shape: the variable-length path is computed at a STATIC
    max length with a per-sample mask (no data-dependent loops under jit);
    the per-step weight rows ride one gather + batched dot.
    ``is_sparse`` selects the reference's sparse row update — under XLA
    gathers/scatters are already sparse at the lattice level, so it is
    accepted and ignored.
    """
    input = _t(input)
    label = _t(label)
    weight = _t(weight)

    custom = path_table is not None and path_code is not None

    def f(x, lbl, w, *rest):
        rest = list(rest)
        b = rest.pop(0) if bias is not None else None
        if custom:
            table, code_bits = rest[0], rest[1]
            mask = (table >= 0)
            idx = jnp.clip(table, 0, None).astype(jnp.int32)
            bits = (code_bits > 0) & mask
        else:
            lbl_i = lbl.reshape((lbl.shape[0],)).astype(jnp.uint32)
            c = lbl_i + jnp.uint32(num_classes)
            max_len = int(np.floor(np.log2(2 * num_classes - 1)))
            j = jnp.arange(max_len, dtype=jnp.uint32)[None, :]
            length = jnp.floor(
                jnp.log2(c.astype(jnp.float32)))[:, None]  # per-sample
            mask = j.astype(jnp.float32) < length
            idx = ((c[:, None] >> (j + 1)) - 1).astype(jnp.int32)
            idx = jnp.clip(idx, 0, num_classes - 2)
            bits = ((c[:, None] >> j) & 1).astype(bool) & (mask > 0)
        rows = jnp.take(w, idx, axis=0)             # [N, L, D]
        pre = jnp.einsum("nld,nd->nl", rows, x)
        if b is not None:
            pre = pre + jnp.take(b.reshape(-1), idx, axis=0)
        pre = jnp.clip(pre, -40.0, 40.0)
        maskf = mask.astype(pre.dtype)
        loss = jnp.sum(jnp.log1p(jnp.exp(pre)) * maskf, axis=1) \
            - jnp.sum(jnp.where(bits, pre, 0.0), axis=1)
        return loss[:, None]

    args = [input, label.detach(), weight]
    if bias is not None:
        args.append(_t(bias))
    if custom:
        args.append(_t(path_table).detach())
        args.append(_t(path_code).detach())
    return apply_op(f, *args)


def dice_loss(input, label, epsilon=0.00001, name=None):
    """Dice loss — parity with
    python/paddle/fluid/layers/nn.py:7060 (one-hot over the trailing class
    axis, per-sample dice score over all non-batch dims, mean-reduced)."""
    input = _t(input)
    label = _t(label)

    def f(x, lbl):
        lbl_i = lbl.astype(jnp.int32)
        if lbl_i.shape[-1] == 1:
            lbl_i = lbl_i[..., 0]
        onehot = jax.nn.one_hot(lbl_i, x.shape[-1], dtype=x.dtype)
        reduce_dim = tuple(range(1, x.ndim))
        inse = jnp.sum(x * onehot, axis=reduce_dim)
        denom = jnp.sum(x, axis=reduce_dim) + jnp.sum(onehot, axis=reduce_dim)
        return jnp.mean(1.0 - 2.0 * inse / (denom + epsilon))

    return apply_op(f, input, label.detach())


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """N-pair metric loss — parity with
    python/paddle/fluid/layers/loss.py:1653: soft-label CE over the
    anchor·positiveᵀ similarity matrix plus a 0.25·l2_reg embedding
    regularizer."""
    anchor = _t(anchor)
    positive = _t(positive)
    labels = _t(labels)

    def f(a, p, lbl):
        beta = 0.25
        bsz = lbl.shape[0]
        l2 = lbl.reshape((bsz, 1))
        eq = (l2 == l2.T).astype(a.dtype)
        soft = eq / jnp.sum(eq, axis=1, keepdims=True)
        l2loss = (jnp.mean(jnp.sum(a * a, axis=1))
                  + jnp.mean(jnp.sum(p * p, axis=1))) * beta * l2_reg
        sim = a @ p.T
        logp = jax.nn.log_softmax(sim, axis=-1)
        ce_rows = -jnp.sum(soft * logp, axis=1)       # [B]
        # reference quirk: reduce_sum(labels * ce, 0) then mean — the
        # soft-label CE rows are re-weighted by the soft labels
        celoss = jnp.mean(jnp.sum(soft * ce_rows[:, None], axis=0))
        return l2loss + celoss

    return apply_op(f, anchor, positive, labels.detach())
