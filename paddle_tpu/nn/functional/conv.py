"""Convolution functionals — parity with python/paddle/nn/functional/conv.py.

All convs lower to ``jax.lax.conv_general_dilated``, which XLA maps onto the
MXU (replacing the reference's cuDNN dispatch in operators/conv_op.cc /
conv_cudnn_op.cu). Weight layout follows paddle: [out_c, in_c/groups, *k].
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.enforce import InvalidArgumentError, enforce
from ...core.tensor import Tensor, apply_op, to_tensor

__all__ = [
    "conv1d", "conv2d", "conv3d", "conv1d_transpose", "conv2d_transpose",
    "conv3d_transpose",
]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _norm_tuple(v, n, name):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    v = tuple(int(i) for i in v)
    enforce(len(v) == n, f"{name} must have {n} elements, got {len(v)}")
    return v


def _norm_padding(padding, n):
    """Returns jax-style padding: string or [(lo, hi)] * n."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, (int, np.integer)):
        return [(int(padding), int(padding))] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, (int, np.integer)) for p in padding):
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    if all(isinstance(p, (list, tuple)) for p in padding):
        # NCHW-style per-dim padding incl. batch/channel dims: strip to spatial
        sp = [p for p in padding if tuple(p) != (0, 0)] or padding[-n:]
        return [tuple(int(i) for i in p) for p in padding[-n:]]
    raise InvalidArgumentError(f"cannot interpret conv padding {padding!r}")


def _dim_numbers(n, channel_last):
    if n == 1:
        return ("NWC", "WIO", "NWC") if channel_last else ("NCW", "OIW", "NCW")
    if n == 2:
        return ("NHWC", "HWIO", "NHWC") if channel_last else ("NCHW", "OIHW", "NCHW")
    return ("NDHWC", "DHWIO", "NDHWC") if channel_last else ("NCDHW", "OIDHW", "NCDHW")


def _conv(x, weight, bias, stride, padding, dilation, groups, n, data_format):
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    stride = _norm_tuple(stride, n, "stride")
    dilation = _norm_tuple(dilation, n, "dilation")
    pad = _norm_padding(padding, n)
    dn = _dim_numbers(n, channel_last)

    def f(a, w, *rest):
        from ...amp.auto_cast import maybe_cast_inputs

        a, w = maybe_cast_inputs(f"conv{n}d", a, w)
        if channel_last:
            # paddle weights are always [O, I/g, *k]; jax channel-last wants [*k, I/g, O]
            w = jnp.moveaxis(w, (0, 1), (-1, -2))
        out = jax.lax.conv_general_dilated(
            a,
            w,
            window_strides=stride,
            padding=pad,
            rhs_dilation=dilation,
            feature_group_count=groups,
            dimension_numbers=dn,
        )
        if rest:
            b = rest[0]
            shape = [1] * out.ndim
            shape[-1 if channel_last else 1] = b.shape[0]
            out = out + b.reshape(shape).astype(out.dtype)
        return out

    args = (_t(x), weight) + ((bias,) if bias is not None else ())
    return apply_op(f, *args)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    fmt = "NWC" if data_format in ("NLC", "NWC") else "NCW"
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1, fmt)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2, data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3, data_format)


def _conv_transpose(
    x, weight, bias, stride, padding, output_padding, dilation, groups, n,
    data_format, output_size,
):
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    stride = _norm_tuple(stride, n, "stride")
    dilation = _norm_tuple(dilation, n, "dilation")
    out_pad = _norm_tuple(output_padding, n, "output_padding")
    pad = _norm_padding(padding, n)
    dn = _dim_numbers(n, channel_last)

    def f(a, w, *rest):
        # paddle transpose-conv weight layout: [in_c, out_c/groups, *k]
        # grad-of-conv formulation: lhs_dilation=stride implements fractional
        # stride; padding is adjusted per standard transpose-conv algebra.
        if isinstance(pad, str):
            if pad == "SAME":
                raise InvalidArgumentError("SAME padding unsupported for conv_transpose")
            base_pad = [(0, 0)] * n
        else:
            base_pad = pad
        k = w.shape[2:]
        eff_k = [dilation[i] * (k[i] - 1) + 1 for i in range(n)]
        tpad = [
            (
                eff_k[i] - 1 - base_pad[i][0],
                eff_k[i] - 1 - base_pad[i][1] + out_pad[i],
            )
            for i in range(n)
        ]
        # weight: [I, O/g, *k] -> flip spatial, swap I/O -> [O/g*g? ...]
        w_flip = jnp.flip(w, axis=tuple(range(2, 2 + n)))
        if groups > 1:
            # [I, O/g, *k] with I = g * (I/g): split groups into output dim
            i_c, og = w_flip.shape[0], w_flip.shape[1]
            w_flip = w_flip.reshape((groups, i_c // groups, og) + k)
            w_flip = jnp.moveaxis(w_flip, 2, 1)  # [g, O/g, I/g, *k]
            w_t = w_flip.reshape((groups * og, i_c // groups) + k)
        else:
            w_t = jnp.swapaxes(w_flip, 0, 1)
        if channel_last:
            w_t = jnp.moveaxis(w_t, (0, 1), (-1, -2))
        out = jax.lax.conv_general_dilated(
            a,
            w_t,
            window_strides=(1,) * n,
            padding=tpad,
            lhs_dilation=stride,
            rhs_dilation=dilation,
            feature_group_count=groups,
            dimension_numbers=dn,
        )
        if output_size is not None:
            tgt = [int(s) for s in output_size]
            sl = [slice(None)] * out.ndim
            axes = range(2, 2 + n) if not channel_last else range(1, 1 + n)
            for i, ax in enumerate(axes):
                sl[ax] = slice(0, tgt[i])
            out = out[tuple(sl)]
        if rest:
            b = rest[0]
            shape = [1] * out.ndim
            shape[-1 if channel_last else 1] = b.shape[0]
            out = out + b.reshape(shape)
        return out

    args = (_t(x), weight) + ((bias,) if bias is not None else ())
    return apply_op(f, *args)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCL", name=None):
    fmt = "NWC" if data_format in ("NLC", "NWC") else "NCW"
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 1, fmt, output_size)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, data_format="NCHW", output_size=None, name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 2, data_format, output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCDHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 3, data_format, output_size)
