"""Activation functionals — parity with python/paddle/nn/functional/activation.py.
XLA fuses these into adjacent matmuls/convs, replacing the reference's fused
activation CUDA kernels (operators/fused/fused_bn_activation_op.cu etc.).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor, apply_op, to_tensor

__all__ = [
    "elu_",
    "relu", "relu_", "relu6", "elu", "selu", "celu", "gelu", "sigmoid",
    "hardsigmoid", "hardswish", "hardtanh", "hardshrink", "leaky_relu",
    "log_sigmoid", "log_softmax", "maxout", "mish", "prelu", "rrelu",
    "silu", "swish", "softmax", "softmax_", "softplus", "softshrink",
    "softsign", "tanh", "tanh_", "tanhshrink", "thresholded_relu", "glu",
    "gumbel_softmax",
]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def relu(x, name=None):
    return apply_op(jax.nn.relu, _t(x))


def relu_(x, name=None):
    x._rebind(relu(x))
    return x


def relu6(x, name=None):
    return apply_op(jax.nn.relu6, _t(x))


def elu_(x, alpha=1.0, name=None):
    x._rebind(elu(x, alpha))
    return x


def elu(x, alpha=1.0, name=None):
    return apply_op(lambda a: jax.nn.elu(a, alpha), _t(x))


def selu(
    x,
    scale=1.0507009873554804934193349852946,
    alpha=1.6732632423543772848170429916717,
    name=None,
):
    return apply_op(lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)), _t(x))


def celu(x, alpha=1.0, name=None):
    return apply_op(lambda a: jax.nn.celu(a, alpha), _t(x))


def gelu(x, approximate=False, name=None):
    return apply_op(lambda a: jax.nn.gelu(a, approximate=approximate), _t(x))


def sigmoid(x, name=None):
    return apply_op(jax.nn.sigmoid, _t(x))


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return apply_op(lambda a: jnp.clip(slope * a + offset, 0.0, 1.0), _t(x))


def hardswish(x, name=None):
    return apply_op(lambda a: a * jnp.clip(a + 3.0, 0.0, 6.0) / 6.0, _t(x))


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply_op(lambda a: jnp.clip(a, min, max), _t(x))


def hardshrink(x, threshold=0.5, name=None):
    return apply_op(lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0), _t(x))


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply_op(lambda a: jax.nn.leaky_relu(a, negative_slope), _t(x))


def log_sigmoid(x, name=None):
    return apply_op(jax.nn.log_sigmoid, _t(x))


def log_softmax(x, axis=-1, dtype=None, name=None):
    from ...core import dtype as dtype_mod

    d = dtype_mod.convert_dtype(dtype)

    def f(a):
        if d is not None:
            a = a.astype(d)
        return jax.nn.log_softmax(a, axis=axis)

    return apply_op(f, _t(x))


def maxout(x, groups, axis=1, name=None):
    def f(a):
        shape = list(a.shape)
        c = shape[axis]
        shape[axis : axis + 1] = [c // groups, groups]
        return jnp.max(a.reshape(shape), axis=axis + 1)

    return apply_op(f, _t(x))


def mish(x, name=None):
    return apply_op(lambda a: a * jnp.tanh(jax.nn.softplus(a)), _t(x))


def prelu(x, weight, data_format="NCHW", name=None):
    def f(a, w):
        if w.size == 1:
            return jnp.where(a > 0, a, w.reshape(()) * a)
        ch_axis = 1 if data_format.startswith("NC") else a.ndim - 1
        shape = [1] * a.ndim
        shape[ch_axis] = w.size
        return jnp.where(a > 0, a, w.reshape(shape) * a)

    return apply_op(f, _t(x), _t(weight))


def rrelu(x, lower=0.125, upper=0.3333333333333333, training=True, name=None):
    from ...core import rng as rng_mod

    x = _t(x)
    if training:
        key = rng_mod.next_key()
        slope = jax.random.uniform(
            key, tuple(x.shape), x._value.dtype, lower, upper
        )
        return apply_op(lambda a: jnp.where(a >= 0, a, slope * a), x)
    mid = (lower + upper) / 2.0
    return apply_op(lambda a: jnp.where(a >= 0, a, mid * a), x)


def silu(x, name=None):
    return apply_op(jax.nn.silu, _t(x))


def swish(x, name=None):
    return silu(x)


def softmax(x, axis=-1, dtype=None, name=None):
    from ...core import dtype as dtype_mod

    d = dtype_mod.convert_dtype(dtype)

    def f(a):
        if d is not None:
            a = a.astype(d)
        return jax.nn.softmax(a, axis=axis)

    return apply_op(f, _t(x))


def softmax_(x, axis=-1, dtype=None, name=None):
    x._rebind(softmax(x, axis, dtype))
    return x


def softplus(x, beta=1, threshold=20, name=None):
    return apply_op(
        lambda a: jnp.where(a * beta > threshold, a, jnp.log1p(jnp.exp(beta * a)) / beta),
        _t(x),
    )


def softshrink(x, threshold=0.5, name=None):
    return apply_op(
        lambda a: jnp.where(
            a > threshold, a - threshold, jnp.where(a < -threshold, a + threshold, 0.0)
        ),
        _t(x),
    )


def softsign(x, name=None):
    return apply_op(jax.nn.soft_sign, _t(x))


def tanh(x, name=None):
    return apply_op(jnp.tanh, _t(x))


def tanh_(x, name=None):
    x._rebind(tanh(x))
    return x


def tanhshrink(x, name=None):
    return apply_op(lambda a: a - jnp.tanh(a), _t(x))


def thresholded_relu(x, threshold=1.0, name=None):
    return apply_op(lambda a: jnp.where(a > threshold, a, 0.0), _t(x))


def glu(x, axis=-1, name=None):
    def f(a):
        a1, a2 = jnp.split(a, 2, axis=axis)
        return a1 * jax.nn.sigmoid(a2)

    return apply_op(f, _t(x))


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...core import rng as rng_mod

    x = _t(x)
    key = rng_mod.next_key()
    g = jax.random.gumbel(key, tuple(x.shape), x._value.dtype)

    def f(a):
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y)
            y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis, inplace=False)
            return y_hard - jax.lax.stop_gradient(y) + y
        return y

    return apply_op(f, x)
