"""Spatial warping ops — parity with the reference's vision kernels
(operators/grid_sampler_op.*, affine_grid_op.*, temporal_shift_op.*):
grid_sample (bilinear/nearest, zeros/border padding, align_corners),
affine_grid, temporal_shift. Pure jnp gather/lerp — jittable, vmappable,
differentiable; XLA fuses the 4-corner gathers, replacing the reference's
hand-written CUDA samplers.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, apply_op

__all__ = ["grid_sample", "affine_grid", "temporal_shift"]


def _unnormalize(coord, size, align_corners):
    if align_corners:
        return (coord + 1.0) * 0.5 * (size - 1)
    return ((coord + 1.0) * size - 1.0) * 0.5


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """x: [N, C, H, W]; grid: [N, Hg, Wg, 2] in [-1, 1] (xy order).
    Returns [N, C, Hg, Wg]. Parity: grid_sampler_op.cc semantics."""
    if mode not in ("bilinear", "nearest"):
        raise ValueError(f"unsupported mode {mode!r}")
    if padding_mode not in ("zeros", "border", "reflection"):
        raise ValueError(f"unsupported padding_mode {padding_mode!r}")

    def fn(img, g):
        n, c, h, w = img.shape
        gx = _unnormalize(g[..., 0].astype(jnp.float32), w, align_corners)
        gy = _unnormalize(g[..., 1].astype(jnp.float32), h, align_corners)

        def reflect(v, size):
            # canonical reflect_coordinates (grid_sampler reference kernel):
            # align_corners=True reflects about [0, size-1]; False about
            # [-0.5, size-0.5]
            if align_corners:
                lo, span = 0.0, float(size - 1)
            else:
                lo, span = -0.5, float(size)
            if span <= 0:
                return jnp.zeros_like(v)
            u = jnp.abs(v - lo)
            extra = jnp.mod(u, span)
            flips = jnp.floor(u / span)
            even = jnp.mod(flips, 2.0) == 0
            out = jnp.where(even, extra + lo, span - extra + lo)
            return jnp.clip(out, 0, size - 1)

        if padding_mode == "reflection":
            gx = reflect(gx, w)
            gy = reflect(gy, h)

        def sample(ix, iy):
            """Gather img[n, :, iy, ix] with out-of-range handling."""
            inb = ((ix >= 0) & (ix <= w - 1) & (iy >= 0) & (iy <= h - 1))
            cx = jnp.clip(ix, 0, w - 1).astype(jnp.int32)
            cy = jnp.clip(iy, 0, h - 1).astype(jnp.int32)
            # img: [N, C, H, W]; cx/cy: [N, Hg, Wg]
            batch = jnp.arange(n)[:, None, None]
            vals = img[batch, :, cy, cx]          # [N, Hg, Wg, C]
            vals = jnp.moveaxis(vals, -1, 1)      # [N, C, Hg, Wg]
            if padding_mode == "zeros":
                vals = vals * inb[:, None, :, :].astype(vals.dtype)
            return vals

        if mode == "nearest":
            return sample(jnp.round(gx), jnp.round(gy)).astype(img.dtype)

        x0 = jnp.floor(gx)
        y0 = jnp.floor(gy)
        x1, y1 = x0 + 1, y0 + 1
        wx = gx - x0
        wy = gy - y0
        out = (sample(x0, y0) * ((1 - wx) * (1 - wy))[:, None]
               + sample(x1, y0) * (wx * (1 - wy))[:, None]
               + sample(x0, y1) * ((1 - wx) * wy)[:, None]
               + sample(x1, y1) * (wx * wy)[:, None])
        return out.astype(img.dtype)

    return apply_op(fn, x, grid, op_name="grid_sample")


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """theta: [N, 2, 3] affine matrices → sampling grid [N, H, W, 2] for
    grid_sample. Parity: affine_grid_op.cc."""
    if isinstance(out_shape, Tensor):
        out_shape = [int(v) for v in out_shape.numpy()]
    n, c, h, w = [int(v) for v in out_shape]

    def fn(th):
        if align_corners:
            ys = jnp.linspace(-1.0, 1.0, h)
            xs = jnp.linspace(-1.0, 1.0, w)
        else:
            ys = (jnp.arange(h) * 2 + 1) / h - 1.0
            xs = (jnp.arange(w) * 2 + 1) / w - 1.0
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1).reshape(-1, 3)  # [H*W, 3]
        out = jnp.einsum("nij,pj->npi", th.astype(jnp.float32), base)
        return out.reshape(th.shape[0], h, w, 2).astype(th.dtype)

    return apply_op(fn, theta, op_name="affine_grid")


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    """TSM temporal shift (temporal_shift_op.cc): x: [N*T, C, H, W]; the
    first fold of channels shifts back one timestep, the second shifts
    forward, the rest stay."""
    if data_format != "NCHW":
        raise ValueError("temporal_shift supports NCHW")

    def fn(a):
        nt, c, h, w = a.shape
        n = nt // seg_num
        v = a.reshape(n, seg_num, c, h, w)
        fold = int(c * shift_ratio)
        back = jnp.concatenate(
            [v[:, 1:, :fold], jnp.zeros_like(v[:, :1, :fold])], axis=1)
        fwd = jnp.concatenate(
            [jnp.zeros_like(v[:, :1, fold:2 * fold]), v[:, :-1, fold:2 * fold]],
            axis=1)
        rest = v[:, :, 2 * fold:]
        return jnp.concatenate([back, fwd, rest], axis=2).reshape(nt, c, h, w)

    return apply_op(fn, x, op_name="temporal_shift")
