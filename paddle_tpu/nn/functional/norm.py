"""Normalization functionals — parity with python/paddle/nn/functional/norm.py.
Replaces the reference's cuDNN batch-norm kernels (operators/batch_norm_op.cu)
with jnp reductions XLA fuses; running stats updated imperatively on the layer.
"""
from __future__ import annotations

import contextlib
import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor, apply_op, to_tensor

__all__ = ["batch_norm", "layer_norm", "instance_norm", "group_norm",
           "local_response_norm", "manual_ln_scope"]

# The manual-LN VJP is a PER-WORKLOAD knob (+2.2% on GPT-2 345M, -24% on
# BERT-base under the fleet engine — the custom_vjp blocks a fusion BERT's
# step depends on). Models that measure a win scope it over their own
# forward with `manual_ln_scope(True)` (GPTConfig.manual_layer_norm does);
# the env var remains as a global override for experiments.
_MANUAL_LN_STACK: list = []


@contextlib.contextmanager
def manual_ln_scope(enabled: bool):
    """Scope the manual LayerNorm VJP to the enclosed trace (a model's
    forward), instead of flipping the process-wide env var."""
    _MANUAL_LN_STACK.append(bool(enabled))
    try:
        yield
    finally:
        _MANUAL_LN_STACK.pop()


def _manual_ln_enabled() -> bool:
    if _MANUAL_LN_STACK:
        return _MANUAL_LN_STACK[-1]
    return os.environ.get("PADDLE_TPU_MANUAL_LN", "0") == "1"


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _bn_stats(af, axes):
    """Batch mean/var ([C]-shaped) from ONE variadic reduction pass.

    sum(x) and sum(x*x) over the same operand fuse into a single
    multi-output reduce on TPU, so stats cost one read of the activation
    instead of jnp.mean + jnp.var's two-to-three (r4 profile: 5.4 ms/step
    of convert_reduce fusions on ResNet-50 were exactly these passes).
    var = E[x^2] - mu^2, ALWAYS accumulated in f32 (in bf16 the
    uncentered form cancels catastrophically — mean 10/std 0.1 data
    rounds var to the 0-clamp — and sum(x*x) overflows fp16);
    clamped at 0 against residual cancellation."""
    af = af.astype(jnp.float32)
    n = 1.0
    for ax in axes:
        n *= af.shape[ax]
    s1 = jnp.sum(af, axis=axes)
    s2 = jnp.sum(af * af, axis=axes)
    mu = s1 / n
    var = jnp.maximum(s2 / n - mu * mu, 0.0)
    return mu, var


def _bn_fwd_impl(a, w, b, ch_axis, axes, epsilon):
    af = a.astype(jnp.float32)
    mu, var = _bn_stats(af, axes)
    rstd = jax.lax.rsqrt(var + epsilon)
    shape = [1] * a.ndim
    shape[ch_axis] = a.shape[ch_axis]
    # fold the normalize+affine into one per-channel scale/shift so the
    # output pass is a single fused multiply-add over a (no full-size f32
    # (af-mu) intermediate)
    k = w.astype(jnp.float32) * rstd
    c = b.astype(jnp.float32) - mu * k
    out = (af * k.reshape(shape) + c.reshape(shape)).astype(a.dtype)
    return out, (a, w, b, mu, rstd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _bn_manual(a, w, b, ch_axis, axes, epsilon):
    """Training-mode affine BatchNorm with a hand-written backward.

    Same rationale as ``_ln_manual``: autodiff's backward through the
    separate mean/var ops fuses poorly on TPU; the manual rule recomputes
    xhat from the saved f32 stats and produces dx/dw/db from one pass
    structure, with stats accumulated in f32. Batch stats for the
    running-stat update are NOT outputs — the caller computes them as
    separate grad-free reductions that CSE with this forward's own under
    jit (stat cotangents would otherwise ride every backward as
    unfoldable zero passes in eager mode)."""
    out, _ = _bn_fwd_impl(a, w, b, ch_axis, axes, epsilon)
    return out


def _bn_manual_fwd(a, w, b, ch_axis, axes, epsilon):
    return _bn_fwd_impl(a, w, b, ch_axis, axes, epsilon)


def _bn_manual_bwd(ch_axis, axes, epsilon, res, dy):
    # Two passes over (a, dy) total: pass 1 is the db/dw variadic reduce
    # (xhat recomputed from the saved [C] stats — no residual store); pass 2
    # the dx elementwise. The centering constants come from db/dw instead of
    # their own mean(g)/mean(g*xh) reductions: with per-channel w,
    # mean(g) = w*db/n and mean(g*xh) = w*dw/n.
    a, w, b, mu, rstd = res
    shape = [1] * a.ndim
    shape[ch_axis] = a.shape[ch_axis]
    n = 1.0
    for ax in axes:
        n *= a.shape[ax]
    af = a.astype(jnp.float32)
    xh = (af - mu.reshape(shape)) * rstd.reshape(shape)
    dyf = dy.astype(jnp.float32)
    db = jnp.sum(dyf, axis=axes)
    dw = jnp.sum(dyf * xh, axis=axes)
    k = (w.astype(jnp.float32) * rstd).reshape(shape)
    dx = (k * (dyf - (db / n).reshape(shape) - xh * (dw / n).reshape(shape))
          ).astype(a.dtype)
    return dx, dw.astype(w.dtype), db.astype(b.dtype)


_bn_manual.defvjp(_bn_manual_fwd, _bn_manual_bwd)


def batch_norm(
    x,
    running_mean,
    running_var,
    weight=None,
    bias=None,
    training=False,
    momentum=0.9,
    epsilon=1e-05,
    data_format="NCHW",
    use_global_stats=None,
    name=None,
):
    x = _t(x)
    ch_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    reduce_axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    use_batch_stats = training and not use_global_stats

    if use_batch_stats:
        manual = (weight is not None and bias is not None
                  and os.environ.get("PADDLE_TPU_MANUAL_BN", "1") == "1")
        # batch stats; update running stats imperatively (momentum
        # semantics match the reference: r = m*r + (1-m)*batch). On the
        # manual path these reductions CSE with _bn_manual's internal ones
        # under jit (identical expressions over the same operand).
        mean, var = apply_op(
            lambda a: _bn_stats(a, reduce_axes), x, multi_out=True)
        if running_mean is not None:
            # EMA in the running-stat buffer's own dtype: the f32 batch
            # stats would otherwise silently promote bf16/fp16 buffers
            # (dtype drift in state_dict + a retrace on the next step)
            running_mean._value = (
                momentum * running_mean._value
                + (1.0 - momentum)
                * mean._value.astype(running_mean._value.dtype)
            )
            running_var._value = (
                momentum * running_var._value
                + (1.0 - momentum)
                * var._value.astype(running_var._value.dtype)
            )
        if manual:
            return apply_op(
                lambda a, w, b: _bn_manual(a, w, b, ch_axis, reduce_axes,
                                           epsilon),
                x, weight, bias)
    else:
        mean, var = running_mean, running_var

    shape = [1] * x.ndim
    shape[ch_axis] = x.shape[ch_axis]

    cast_back = use_batch_stats  # train-mode stats are f32; keep the
    # output in the input's dtype (eval keeps its historical promotion
    # semantics when running stats are wider than the input)

    def f(a, m, v, *wb):
        m = m.reshape(shape)
        v = v.reshape(shape)
        out = (a - m) * jax.lax.rsqrt(v + epsilon)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out.astype(a.dtype) if cast_back else out

    args = [x, mean, var]
    if weight is not None:
        args.append(weight)
    if bias is not None:
        args.append(bias)
    return apply_op(f, *args)


def _ln_fwd_impl(a, w, b, epsilon):
    af = a.astype(jnp.float32)
    n = af.shape[-1]
    # one-pass row stats: sum(x) and sum(x·x) fuse into a single
    # multi-output reduce (one read of the activation); jnp.mean + jnp.var
    # is two sequential passes (var needs the mean first). Uncentered var
    # in f32 — same rationale and clamp as _bn_stats.
    # ASSUMPTION (documented in README "Observability"): E[x²]−E[x]²
    # cancels catastrophically when |mean| ≫ std (var ≈ difference of two
    # large near-equal f32 numbers). Safe here because LN inputs are
    # residual-stream activations with |mean|/std of order 1; feeding
    # un-normalized data with a huge DC offset through LayerNorm would
    # lose var precision (the clamp floors it at 0 rather than going
    # negative).
    s1 = jnp.sum(af, axis=-1, keepdims=True)
    s2 = jnp.sum(af * af, axis=-1, keepdims=True)
    mu = s1 / n
    var = jnp.maximum(s2 / n - mu * mu, 0.0)
    rstd = jax.lax.rsqrt(var + epsilon)
    out = ((af - mu) * rstd).astype(a.dtype) * w + b
    return out, (a, w, jnp.zeros((), b.dtype), mu, rstd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _ln_manual(a, w, b, epsilon):
    """Single-trailing-axis affine LayerNorm with a hand-written backward.

    Autodiff's LN backward emits separate mean/var transpose chains that XLA
    fuses poorly (measured 0.48 ms autodiff vs 0.34 ms manual per
    [8192,1024] bf16 LN fwd+bwd on v5e). The manual rule recomputes xhat
    from the saved f32 row stats (no xhat residual store) and emits
    dx/dw/db from one shared pass. Stats accumulate in f32 regardless of
    input dtype. custom_vjp inlines into the jaxpr, so XLA still fuses the
    LN into surrounding residual adds."""
    out, _ = _ln_fwd_impl(a, w, b, epsilon)
    return out


def _ln_manual_fwd(a, w, b, epsilon):
    return _ln_fwd_impl(a, w, b, epsilon)


def _ln_manual_bwd(epsilon, res, dy):
    a, w, b_proto, mu, rstd = res
    af = a.astype(jnp.float32)
    xh = (af - mu) * rstd
    g = dy.astype(jnp.float32) * w.astype(jnp.float32)
    c1 = jnp.mean(g, axis=-1, keepdims=True)
    c2 = jnp.mean(g * xh, axis=-1, keepdims=True)
    dx = (rstd * (g - c1 - xh * c2)).astype(a.dtype)
    dyf = dy.astype(jnp.float32)
    red = tuple(range(a.ndim - 1))
    dw = jnp.sum(dyf * xh, axis=red).astype(w.dtype)
    db = jnp.sum(dyf, axis=red).astype(b_proto.dtype)
    return dx, dw, db


_ln_manual.defvjp(_ln_manual_fwd, _ln_manual_bwd)


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05, name=None):
    x = _t(x)
    if isinstance(normalized_shape, (int, np.integer)):
        normalized_shape = (int(normalized_shape),)
    n_norm = len(tuple(normalized_shape))
    axes = tuple(range(x.ndim - n_norm, x.ndim))

    def f(a, *wb):
        if (len(axes) == 1 and weight is not None and bias is not None
                and os.environ.get("PADDLE_TPU_FUSED_LN") == "1"
                and jax.default_backend() == "tpu"):
            # opt-in Pallas fwd/bwd kernels (ops/fused.py). Measured on v5e
            # GPT-2 345M: XLA's own LN fusions fold into the surrounding
            # residual adds and win end-to-end — the kernel is kept for wide
            # rows where XLA splits the reduction.
            from paddle_tpu.ops.fused import fused_layer_norm

            return fused_layer_norm(a, wb[0], wb[1], epsilon)
        # per-workload knob — see _MANUAL_LN_STACK above
        if (len(axes) == 1 and weight is not None and bias is not None
                and _manual_ln_enabled()):
            return _ln_manual(a, wb[0], wb[1], epsilon)
        # two-pass mean/var DELIBERATELY: on the autodiff path the
        # uncentered one-pass form was measured 2% WORSE end-to-end on
        # BERT-base (d(sum x²)/dx = 2x adds an extra full elementwise pass
        # to the backward that outweighs the forward's saved read). The
        # one-pass trick only pays where the backward is hand-written
        # (_ln_manual / _bn_manual).
        mean = jnp.mean(a, axis=axes, keepdims=True)
        var = jnp.var(a, axis=axes, keepdims=True)
        out = (a - mean) * jax.lax.rsqrt(var + epsilon)
        i = 0
        if weight is not None:
            out = out * wb[i]
            i += 1
        if bias is not None:
            out = out + wb[i]
        return out

    args = [x] + [w for w in (weight, bias) if w is not None]
    return apply_op(f, *args)


def instance_norm(
    x, running_mean=None, running_var=None, weight=None, bias=None,
    use_input_stats=True, momentum=0.9, eps=1e-05, data_format="NCHW", name=None,
):
    x = _t(x)
    ch_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    spatial_axes = tuple(i for i in range(2, x.ndim)) if ch_axis == 1 else tuple(
        i for i in range(1, x.ndim - 1)
    )

    def f(a, *wb):
        mean = jnp.mean(a, axis=spatial_axes, keepdims=True)
        var = jnp.var(a, axis=spatial_axes, keepdims=True)
        out = (a - mean) * jax.lax.rsqrt(var + eps)
        shape = [1] * a.ndim
        shape[ch_axis] = a.shape[ch_axis]
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out

    args = [x] + [w for w in (weight, bias) if w is not None]
    return apply_op(f, *args)


def group_norm(x, num_groups, epsilon=1e-05, weight=None, bias=None,
               data_format="NCHW", name=None):
    x = _t(x)
    channel_last = not data_format.startswith("NC")

    def f(a, *wb):
        if channel_last:
            a_m = jnp.moveaxis(a, -1, 1)
        else:
            a_m = a
        n, c = a_m.shape[:2]
        rest = a_m.shape[2:]
        g = a_m.reshape((n, num_groups, c // num_groups) + rest)
        axes = tuple(range(2, g.ndim))
        mean = jnp.mean(g, axis=axes, keepdims=True)
        var = jnp.var(g, axis=axes, keepdims=True)
        out = ((g - mean) * jax.lax.rsqrt(var + epsilon)).reshape(a_m.shape)
        shape = [1] * a_m.ndim
        shape[1] = c
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out

    args = [x] + [w for w in (weight, bias) if w is not None]
    return apply_op(f, *args)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
    def f(a):
        channel_last = not data_format.startswith("NC")
        if channel_last:
            a = jnp.moveaxis(a, -1, 1)
        sq = a * a
        c = a.shape[1]
        half = size // 2
        pad_width = [(0, 0)] * a.ndim
        pad_width[1] = (half, size - half - 1)
        padded = jnp.pad(sq, pad_width)
        acc = jnp.zeros_like(a)
        for i in range(size):
            acc = acc + jax.lax.slice_in_dim(padded, i, i + c, axis=1)
        out = a / (k + alpha * acc) ** beta
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out

    return apply_op(f, _t(x))
