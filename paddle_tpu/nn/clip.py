"""Gradient clipping — parity with python/paddle/fluid/clip.py
(ClipGradByGlobalNorm etc. used by optimizers' grad_clip argument)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor, wrap_raw

__all__ = ["ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm"]


class ClipGradBase:
    def __call__(self, params_grads):
        return self._clip(params_grads)


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def _clip(self, params_grads):
        from ..core.selected_rows import RowSparseGrad

        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            if isinstance(g, RowSparseGrad):
                if self.min > 0.0 or self.max < 0.0:
                    # an asymmetric range that excludes 0 moves UNTOUCHED
                    # rows too (dense clip turns their 0 grad into min/max);
                    # only the dense path can express that
                    out.append((p, wrap_raw(
                        jnp.clip(g.to_dense(), self.min, self.max))))
                    continue
                # clip the merged values (duplicates combine first, like the
                # dense path clipping the summed gradient)
                m = g.merged()
                out.append((p, RowSparseGrad(
                    m.rows, jnp.clip(m.values, self.min, self.max),
                    m.num_rows, merged=True)))
                continue
            out.append((p, wrap_raw(jnp.clip(g._value, self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _clip(self, params_grads):
        from ..core.selected_rows import RowSparseGrad

        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            if isinstance(g, RowSparseGrad):
                norm = jnp.sqrt(g.sq_l2norm())
                scale = jnp.minimum(
                    self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
                out.append((p, g.scale(scale.astype(g.dtype))))
                continue
            norm = jnp.sqrt(jnp.sum(g._value.astype(jnp.float32) ** 2))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, wrap_raw((g._value * scale).astype(g._value.dtype))))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def _clip(self, params_grads):
        from ..core.selected_rows import RowSparseGrad

        sq = 0.0
        any_clip = False
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                continue
            any_clip = True
            if isinstance(g, RowSparseGrad):
                # reference merges SelectedRows before the norm
                # (gradient_clip merge_selected_rows); duplicates must be
                # combined or the norm overcounts
                sq = sq + g.sq_l2norm()
            else:
                sq = sq + jnp.sum(g._value.astype(jnp.float32) ** 2)
        if not any_clip:
            return params_grads
        global_norm = jnp.sqrt(sq)
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            if isinstance(g, RowSparseGrad):
                out.append((p, g.scale(scale.astype(g.dtype))))
                continue
            out.append((p, wrap_raw((g._value * scale).astype(g._value.dtype))))
        return out


# functional forms used by the jitted train-step compiler
def clip_grads_global_norm_raw(grads, clip_norm):
    """Pure pytree version for staged training steps."""
    import jax

    leaves = jax.tree_util.tree_leaves(grads)
    sq = sum(jnp.sum(l.astype(jnp.float32) ** 2) for l in leaves)
    gn = jnp.sqrt(sq)
    scale = clip_norm / jnp.maximum(gn, clip_norm)
    return jax.tree_util.tree_map(lambda g: (g * scale).astype(g.dtype), grads)
