"""Beam-search decoding — Decoder / BeamSearchDecoder / dynamic_decode /
gather_tree, plus batch-major functional beam_search / beam_search_decode.

Capability parity with the reference's decoding stack
(/root/reference/python/paddle/fluid/layers/rnn.py:866 BeamSearchDecoder,
:1581 dynamic_decode, :3154 beam_search, :3313 beam_search_decode, and the
gather_tree op paddle/fluid/operators/gather_tree_op.cc).

TPU-first design deltas:
- the reference's low-level ``beam_search`` op walks LoD levels of a
  shrinking [N, 1] candidate tensor; here every tensor is **batch-major
  with static shapes** — ``[batch, beam, ...]`` throughout, finished beams
  masked instead of removed (the same redesign the repo applies to all
  LoD machinery, tensor/sequence.py).
- backtracking (gather_tree) is a reverse ``lax.scan`` over backpointers,
  not a per-sequence C++ loop — jittable, batched.
- ``dynamic_decode`` drives the decoder with a python loop that early-exits
  when every beam is finished (eager path; fixed ``max_step_num`` bounds
  it under tracing where data-dependent exits can't run).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, apply_op
from ..layer_base import Layer

__all__ = ["Decoder", "BeamSearchDecoder", "dynamic_decode", "gather_tree",
           "beam_search", "beam_search_decode"]

_KINF = 1e9


def _raw(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def gather_tree(ids, parents):
    """Backtrace full beams from per-step tokens and parent indices.

    ``ids``/``parents``: [T, batch, beam] int64. Returns [T, batch, beam]
    where column (b, k) holds the full history of the k-th final beam —
    the gather_tree op (gather_tree_op.cc) as a reverse scan.
    """

    def f(ids, parents):
        T, B, K = ids.shape
        binx = jnp.arange(B)[:, None]

        def back(beam, xs):
            # beam: [B, K] — which original beam holds position k's history
            # at this step; emit its token, follow its backpointer
            step_ids, step_parents = xs
            tok = step_ids[binx, beam]
            prev = step_parents[binx, beam]
            return prev, tok

        last = jnp.broadcast_to(jnp.arange(K, dtype=jnp.int32)[None, :],
                                (B, K))
        _, toks = jax.lax.scan(back, last,
                               (ids, parents.astype(jnp.int32)),
                               reverse=True)
        return toks

    return apply_op(f, ids, parents)


class Decoder:
    """Abstract decoder interface (reference fluid/layers/rnn.py Decoder):
    ``initialize`` → ``step``* → ``finalize``."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        raise NotImplementedError

    @property
    def tracks_own_finished(self):
        return False


class BeamSearchDecoder(Decoder):
    """Beam-search decoding over any RNN cell (nn.layer.rnn.RNNCellBase or
    anything with ``cell(inputs, states) -> (outputs, new_states)``).

    Mirrors the reference decoder's contract: cell inputs/states run merged
    as [batch*beam, ...]; scores/ids run split as [batch, beam]. Finished
    beams only propose ``end_token`` at zero incremental cost (_mask_probs).
    """

    class OutputWrapper:
        def __init__(self, scores, predicted_ids, parent_ids):
            self.scores = scores
            self.predicted_ids = predicted_ids
            self.parent_ids = parent_ids

    class StateWrapper:
        def __init__(self, cell_states, log_probs, finished, lengths):
            self.cell_states = cell_states
            self.log_probs = log_probs
            self.finished = finished
            self.lengths = lengths

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # -- shape helpers (reference names) ------------------------------------
    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """[batch, ...] → [batch*beam, ...] with each row repeated."""
        return apply_op(
            lambda a: jnp.repeat(a, beam_size, axis=0), x
        )

    def _merge_batch_beams(self, x):
        return apply_op(lambda a: a.reshape((-1,) + a.shape[2:]), x)

    def _split_batch_beams(self, x):
        return apply_op(
            lambda a: a.reshape((-1, self.beam_size) + a.shape[1:]), x)

    def _expand_to_beam_size(self, x):
        return apply_op(
            lambda a: jnp.broadcast_to(
                a[:, None], (a.shape[0], self.beam_size) + a.shape[1:]), x)

    # -----------------------------------------------------------------------
    def initialize(self, initial_cell_states):
        cell_states = jax.tree_util.tree_map(
            self._expand_to_beam_size, initial_cell_states,
            is_leaf=lambda t: isinstance(t, Tensor))
        sample = jax.tree_util.tree_leaves(cell_states)[0]
        B = sample.shape[0]
        K = self.beam_size
        # only beam 0 is live at t=0, so the first top-k picks K distinct
        # tokens instead of K copies of the best one
        log_probs = np.full((B, K), -_KINF, np.float32)
        log_probs[:, 0] = 0.0
        from ...tensor.creation import to_tensor

        state = self.StateWrapper(
            cell_states,
            to_tensor(log_probs),
            to_tensor(np.zeros((B, K), bool)),
            to_tensor(np.zeros((B, K), np.int64)),
        )
        init_ids = to_tensor(
            np.full((B, K), self.start_token, np.int64))
        init_inputs = (self.embedding_fn(init_ids)
                       if self.embedding_fn is not None else init_ids)
        finished = to_tensor(np.zeros((B, K), bool))
        return init_inputs, state, finished

    def _beam_search_step(self, time, logits, next_cell_states, beam_state):
        K = self.beam_size
        end = self.end_token

        def f(logits, prev_log_probs, prev_finished, prev_lengths):
            B, _, V = logits.shape
            step_lp = jax.nn.log_softmax(logits, axis=-1)     # [B, K, V]
            noend = jnp.full((V,), -_KINF).at[end].set(0.0)
            step_lp = jnp.where(prev_finished[..., None], noend[None, None],
                                step_lp)
            log_probs = step_lp + prev_log_probs[..., None]
            scores = log_probs.reshape(B, K * V)
            topk_scores, topk_idx = jax.lax.top_k(scores, K)  # [B, K]
            beam_idx = (topk_idx // V).astype(jnp.int32)
            token_idx = (topk_idx % V).astype(jnp.int64)
            binx = jnp.arange(B)[:, None]
            fin = prev_finished[binx, beam_idx]
            lengths = prev_lengths[binx, beam_idx] + (~fin)
            finished = fin | (token_idx == end)
            return (topk_scores, token_idx, beam_idx.astype(jnp.int64),
                    finished, lengths)

        scores, token_idx, beam_idx, finished, lengths = apply_op(
            f, logits, beam_state.log_probs, beam_state.finished.detach(),
            beam_state.lengths.detach(), multi_out=True)

        def gather_beams(x):
            return apply_op(
                lambda a, bi: a[jnp.arange(a.shape[0])[:, None],
                                bi.astype(jnp.int32)],
                x, beam_idx.detach())

        next_cell_states = jax.tree_util.tree_map(
            gather_beams, next_cell_states,
            is_leaf=lambda t: isinstance(t, Tensor))
        out = self.OutputWrapper(scores, token_idx, beam_idx)
        state = self.StateWrapper(next_cell_states, scores, finished, lengths)
        return out, state

    def step(self, time, inputs, states, **kwargs):
        merged_inputs = jax.tree_util.tree_map(
            self._merge_batch_beams, inputs,
            is_leaf=lambda t: isinstance(t, Tensor))
        merged_states = jax.tree_util.tree_map(
            self._merge_batch_beams, states.cell_states,
            is_leaf=lambda t: isinstance(t, Tensor))
        cell_outputs, next_cell_states = self.cell(merged_inputs,
                                                   merged_states, **kwargs)
        cell_outputs = jax.tree_util.tree_map(
            self._split_batch_beams, cell_outputs,
            is_leaf=lambda t: isinstance(t, Tensor))
        next_cell_states = jax.tree_util.tree_map(
            self._split_batch_beams, next_cell_states,
            is_leaf=lambda t: isinstance(t, Tensor))
        if self.output_fn is not None:
            cell_outputs = self.output_fn(cell_outputs)
        out, state = self._beam_search_step(time, cell_outputs,
                                            next_cell_states, states)
        sample_ids = out.predicted_ids
        next_inputs = (self.embedding_fn(sample_ids)
                       if self.embedding_fn is not None else sample_ids)
        return out, state, next_inputs, state.finished

    def finalize(self, outputs, final_states, sequence_lengths):
        predicted_ids = gather_tree(outputs.predicted_ids, outputs.parent_ids)
        return predicted_ids, final_states

    @property
    def tracks_own_finished(self):
        return True


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """Drive ``decoder`` until every sequence finishes or ``max_step_num``.

    Returns ``(outputs, final_states)`` — for BeamSearchDecoder, outputs is
    the gather_tree'd predicted_ids [batch, beam, T] (or time-major with
    ``output_time_major=True``) — plus sequence lengths when
    ``return_length=True``. Parity: fluid/layers/rnn.py:1581.
    """
    from ...tensor.creation import to_tensor
    from ...tensor.manipulation import stack

    inputs, states, finished = decoder.initialize(inits)
    step_outputs = []
    # matches the reference's implicit bound (fluid/layers/rnn.py
    # dynamic_decode loops until finished); a custom decoder that never
    # finishes stops at this many steps rather than looping forever
    max_steps = max_step_num if max_step_num is not None else 256
    for t in range(int(max_steps)):
        out, states, inputs, step_finished = decoder.step(
            to_tensor(np.array([t], np.int64)), inputs, states, **kwargs)
        step_outputs.append(out)
        if getattr(decoder, "tracks_own_finished", False):
            finished = step_finished
        else:
            # reference semantics (rnn.py): OR the step flags into the
            # global finished — a decoder emitting per-step-only flags must
            # not be able to un-finish a sequence
            finished = apply_op(jnp.logical_or, finished, step_finished)
        if bool(np.asarray(_raw(finished)).all()):
            break

    if isinstance(decoder, BeamSearchDecoder):
        stacked = BeamSearchDecoder.OutputWrapper(
            stack([o.scores for o in step_outputs], axis=0),
            stack([o.predicted_ids for o in step_outputs], axis=0),
            stack([o.parent_ids for o in step_outputs], axis=0),
        )
        lengths = states.lengths
        predicted_ids, final_states = decoder.finalize(stacked, states,
                                                       lengths)
        if not output_time_major:
            predicted_ids = apply_op(
                lambda a: jnp.transpose(a, (1, 2, 0)), predicted_ids)
        if return_length:
            return predicted_ids, final_states, lengths
        return predicted_ids, final_states

    outs = jax.tree_util.tree_map(
        lambda *xs: stack(list(xs), axis=0 if output_time_major else 1),
        *step_outputs, is_leaf=lambda t: isinstance(t, Tensor))
    if return_length:
        return outs, states, finished
    return outs, states


# ---------------------------------------------------------------------------
# Functional one-step beam_search / beam_search_decode (batch-major forms of
# the reference's LoD ops)
# ---------------------------------------------------------------------------
def beam_search(pre_ids, pre_scores, ids, scores, beam_size, end_id,
                level=0, is_accumulated=True, name=None,
                return_parent_idx=False):
    """One beam-search step (batch-major form of beam_search_op.cc).

    ``pre_ids``/``pre_scores``: [batch, beam] int64/float32 from the previous
    step. ``scores``: [batch, beam, K] candidate scores (accumulated if
    ``is_accumulated`` else per-step probabilities), ``ids``: matching
    candidate token ids (or None → candidate index). Returns
    ``(selected_ids, selected_scores[, parent_idx])`` each [batch, beam].
    Ended beams (pre_ids == end_id) keep their score and only propose
    end_id, like the reference's handling of finished hypotheses.
    """

    def f(pre_ids, pre_scores, scores, *rest):
        cand_ids = rest[0] if rest else None
        B, K, C = scores.shape
        if not is_accumulated:
            scores = jnp.log(jnp.clip(scores, 1e-30, None)) \
                + pre_scores[..., None]
        ended = pre_ids == end_id
        # an ended beam contributes exactly one candidate: end_id at its
        # frozen score; everything else is masked out
        keep_first = jnp.arange(C)[None, None, :] == 0
        scores = jnp.where(ended[..., None],
                           jnp.where(keep_first, pre_scores[..., None],
                                     -_KINF),
                           scores)
        flat = scores.reshape(B, K * C)
        top_scores, top_idx = jax.lax.top_k(flat, K)
        parent = (top_idx // C).astype(jnp.int64)
        cand = (top_idx % C).astype(jnp.int32)
        binx = jnp.arange(B)[:, None]
        if cand_ids is not None:
            sel_ids = cand_ids[binx, parent, cand].astype(jnp.int64)
        else:
            sel_ids = cand.astype(jnp.int64)
        sel_ids = jnp.where(ended[binx, parent], end_id, sel_ids)
        outs = (sel_ids, top_scores, parent)
        return outs if return_parent_idx else outs[:2]

    args = [pre_ids, pre_scores, scores] + ([ids] if ids is not None else [])
    return apply_op(f, *args, multi_out=True)


def beam_search_decode(ids, scores, beam_size, end_id, name=None,
                       parent_ids=None):
    """Backtrace stacked per-step selections into full sequences.

    ``ids``/``scores``: [T, batch, beam] per-step selected tokens and
    accumulated scores (the stacked outputs of ``beam_search``).
    ``parent_ids``: [T, batch, beam] backpointers from
    ``beam_search(..., return_parent_idx=True)``; identity when omitted
    (beams never reordered). Returns ``(sequences [batch, beam, T],
    final_scores [batch, beam])`` — the batch-major equivalent of
    beam_search_decode_op.cc's LoD walk (the reference recovers parents
    from LoD offsets; static-shape tensors carry them explicitly).
    """
    if parent_ids is None:
        def ident(i):
            T, B, K = i.shape
            return jnp.broadcast_to(
                jnp.arange(K, dtype=jnp.int64)[None, None], (T, B, K))

        parent_ids = apply_op(ident, ids)
    seqs = apply_op(lambda t: jnp.transpose(t, (1, 2, 0)),
                    gather_tree(ids, parent_ids))
    final_scores = apply_op(lambda s: s[-1].astype(jnp.float32), scores)
    return seqs, final_scores
