"""Recurrent layers — parity with python/paddle/nn/layer/rnn.py.

TPU-first design: the time loop is a ``jax.lax.scan`` (compiles to a single
fused XLA While with MXU matmuls per step) instead of the reference's
per-timestep kernel launches / fused_lstm CUDA kernels
(operators/fused/fusion_lstm_op.cc, operators/rnn_op.h).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...core import dtype as dtype_mod
from ...core.tensor import Tensor, apply_op, to_tensor, wrap_raw
from .. import functional as F
from .. import initializer as I
from ..layer_base import Layer

__all__ = [
    "RNNCellBase", "SimpleRNNCell", "LSTMCell", "GRUCell", "RNN", "BiRNN",
    "SimpleRNN", "LSTM", "GRU",
]


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None, init_value=0.0,
                           batch_dim_idx=0):
        batch = batch_ref.shape[batch_dim_idx]
        from ...tensor import full

        shape = shape or self.state_shape
        if isinstance(shape[0], (list, tuple)):
            return tuple(
                full([batch] + list(s), init_value, dtype or "float32") for s in shape
            )
        return full([batch] + list(shape), init_value, dtype or "float32")


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter([hidden_size, input_size],
                                               attr=weight_ih_attr, default_initializer=u)
        self.weight_hh = self.create_parameter([hidden_size, hidden_size],
                                               attr=weight_hh_attr, default_initializer=u)
        self.bias_ih = self.create_parameter([hidden_size], attr=bias_ih_attr,
                                             is_bias=True, default_initializer=u)
        self.bias_hh = self.create_parameter([hidden_size], attr=bias_hh_attr,
                                             is_bias=True, default_initializer=u)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu

        def f(x, h, wi, wh, bi, bh):
            return act(x @ wi.T + bi + h @ wh.T + bh)

        h = apply_op(f, inputs, states, self.weight_ih, self.weight_hh,
                     self.bias_ih, self.bias_hh)
        return h, h


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter([4 * hidden_size, input_size],
                                               attr=weight_ih_attr, default_initializer=u)
        self.weight_hh = self.create_parameter([4 * hidden_size, hidden_size],
                                               attr=weight_hh_attr, default_initializer=u)
        self.bias_ih = self.create_parameter([4 * hidden_size], attr=bias_ih_attr,
                                             is_bias=True, default_initializer=u)
        self.bias_hh = self.create_parameter([4 * hidden_size], attr=bias_hh_attr,
                                             is_bias=True, default_initializer=u)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs, self.state_shape)
        h, c = states

        def f(x, hp, cp, wi, wh, bi, bh):
            gates = x @ wi.T + bi + hp @ wh.T + bh
            i, fg, g, o = jnp.split(gates, 4, axis=-1)
            i = jax.nn.sigmoid(i)
            fg = jax.nn.sigmoid(fg)
            g = jnp.tanh(g)
            o = jax.nn.sigmoid(o)
            cn = fg * cp + i * g
            hn = o * jnp.tanh(cn)
            return hn, cn

        hn, cn = apply_op(f, inputs, h, c, self.weight_ih, self.weight_hh,
                          self.bias_ih, self.bias_hh, multi_out=True)
        return hn, (hn, cn)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter([3 * hidden_size, input_size],
                                               attr=weight_ih_attr, default_initializer=u)
        self.weight_hh = self.create_parameter([3 * hidden_size, hidden_size],
                                               attr=weight_hh_attr, default_initializer=u)
        self.bias_ih = self.create_parameter([3 * hidden_size], attr=bias_ih_attr,
                                             is_bias=True, default_initializer=u)
        self.bias_hh = self.create_parameter([3 * hidden_size], attr=bias_hh_attr,
                                             is_bias=True, default_initializer=u)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def f(x, hp, wi, wh, bi, bh):
            gi = x @ wi.T + bi
            gh = hp @ wh.T + bh
            ir, iz, inw = jnp.split(gi, 3, axis=-1)
            hr, hz, hn = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            n = jnp.tanh(inw + r * hn)
            return (1.0 - z) * n + z * hp

        h = apply_op(f, inputs, states, self.weight_ih, self.weight_hh,
                     self.bias_ih, self.bias_hh)
        return h, h


class RNN(Layer):
    """Wraps a cell into a layer that runs over the time axis with lax.scan."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        outs = []
        # eager reference loop (per-step) keeps autograd simple and correct;
        # the jit path stages this whole loop into one XLA while via tracing.
        x = inputs if self.time_major else inputs.transpose([1, 0, 2])
        T = x.shape[0]
        states = initial_states
        step_range = range(T - 1, -1, -1) if self.is_reverse else range(T)
        for t in step_range:
            out, states = self.cell(x[t], states)
            outs.append(out)
        if self.is_reverse:
            outs = outs[::-1]
        from ...tensor.manipulation import stack

        y = stack(outs, axis=0)
        if not self.time_major:
            y = y.transpose([1, 0, 2])
        return y, states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        s_fw, s_bw = (initial_states if initial_states is not None else (None, None))
        y_fw, st_fw = self.rnn_fw(inputs, s_fw)
        y_bw, st_bw = self.rnn_bw(inputs, s_bw)
        from ...tensor.manipulation import concat

        return concat([y_fw, y_bw], axis=-1), (st_fw, st_bw)


class _RNNBase(Layer):
    """Multi-layer (bi)directional recurrent network over lax.scan.

    The full multi-layer scan runs as ONE traced computation per call —
    weights are closed over per layer, and each layer is a scan, so XLA sees
    a static nest of whiles it can pipeline.
    """

    _mode = "RNN_TANH"

    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.direction = direction
        self.time_major = time_major
        self.dropout = dropout
        self.activation = activation
        self.num_directions = 2 if direction in ("bidirect", "bidirectional") else 1
        if self._mode == "LSTM":
            g = 4
        elif self._mode == "GRU":
            g = 3
        else:
            g = 1
        self._gate_mult = g
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        for layer in range(num_layers):
            for d in range(self.num_directions):
                suffix = "_reverse" if d == 1 else ""
                in_size = input_size if layer == 0 else hidden_size * self.num_directions
                self.add_parameter(
                    f"weight_ih_l{layer}{suffix}",
                    self.create_parameter([g * hidden_size, in_size],
                                          attr=weight_ih_attr, default_initializer=u),
                )
                self.add_parameter(
                    f"weight_hh_l{layer}{suffix}",
                    self.create_parameter([g * hidden_size, hidden_size],
                                          attr=weight_hh_attr, default_initializer=u),
                )
                self.add_parameter(
                    f"bias_ih_l{layer}{suffix}",
                    self.create_parameter([g * hidden_size], attr=bias_ih_attr,
                                          is_bias=True, default_initializer=u),
                )
                self.add_parameter(
                    f"bias_hh_l{layer}{suffix}",
                    self.create_parameter([g * hidden_size], attr=bias_hh_attr,
                                          is_bias=True, default_initializer=u),
                )

    def _step(self, mode, activation):
        if mode == "LSTM":
            def step(carry, xt, wi, wh, bi, bh):
                hp, cp = carry
                gates = xt @ wi.T + bi + hp @ wh.T + bh
                i, fg, gq, o = jnp.split(gates, 4, axis=-1)
                i = jax.nn.sigmoid(i)
                fg = jax.nn.sigmoid(fg)
                gq = jnp.tanh(gq)
                o = jax.nn.sigmoid(o)
                cn = fg * cp + i * gq
                hn = o * jnp.tanh(cn)
                return (hn, cn), hn
        elif mode == "GRU":
            def step(carry, xt, wi, wh, bi, bh):
                hp = carry[0]
                gi = xt @ wi.T + bi
                gh = hp @ wh.T + bh
                ir, iz, inw = jnp.split(gi, 3, axis=-1)
                hr, hz, hn_ = jnp.split(gh, 3, axis=-1)
                r = jax.nn.sigmoid(ir + hr)
                z = jax.nn.sigmoid(iz + hz)
                n = jnp.tanh(inw + r * hn_)
                hn = (1.0 - z) * n + z * hp
                return (hn,), hn
        else:
            act = jnp.tanh if activation == "tanh" else jax.nn.relu

            def step(carry, xt, wi, wh, bi, bh):
                hp = carry[0]
                hn = act(xt @ wi.T + bi + hp @ wh.T + bh)
                return (hn,), hn

        return step

    def forward(self, inputs, initial_states=None, sequence_length=None):
        mode = self._mode
        nl, nd, hs = self.num_layers, self.num_directions, self.hidden_size
        time_major = self.time_major
        dropout = self.dropout if self.training else 0.0
        step = self._step(mode, self.activation)
        weights = []
        for layer in range(nl):
            for d in range(nd):
                sfx = "_reverse" if d == 1 else ""
                weights.extend([
                    getattr(self, f"weight_ih_l{layer}{sfx}"),
                    getattr(self, f"weight_hh_l{layer}{sfx}"),
                    getattr(self, f"bias_ih_l{layer}{sfx}"),
                    getattr(self, f"bias_hh_l{layer}{sfx}"),
                ])

        # dropout masks sampled outside the traced fn (stateful RNG)
        masks = []
        if dropout > 0.0 and nl > 1:
            from ...core import rng as rng_mod

            x_shape = inputs.shape
            batch = x_shape[1] if time_major else x_shape[0]
            for _ in range(nl - 1):
                key = rng_mod.next_key()
                masks.append(
                    jax.random.bernoulli(key, 1.0 - dropout, (batch, hs * nd)).astype(
                        np.float32
                    )
                    / (1.0 - dropout)
                )

        has_init = initial_states is not None
        init_raws = []
        if has_init:
            if mode == "LSTM":
                h0, c0 = initial_states
                init_raws = [h0, c0]
            else:
                init_raws = [initial_states]

        def run(x, *flat):
            wlist = flat[: 4 * nl * nd]
            inits = flat[4 * nl * nd:]
            if not time_major:
                x = jnp.swapaxes(x, 0, 1)
            batch = x.shape[1]
            hs_list, cs_list = [], []
            for layer in range(nl):
                outs_dirs = []
                for d in range(nd):
                    wi, wh, bi, bh = wlist[(layer * nd + d) * 4: (layer * nd + d) * 4 + 4]
                    if inits:
                        if mode == "LSTM":
                            h0_all, c0_all = inits
                            carry = (h0_all[layer * nd + d], c0_all[layer * nd + d])
                        else:
                            carry = (inits[0][layer * nd + d],)
                    else:
                        z = jnp.zeros((batch, hs), x.dtype)
                        carry = (z, z) if mode == "LSTM" else (z,)
                    seq = jnp.flip(x, 0) if d == 1 else x

                    def body(c, xt):
                        c2, y = step(c, xt, wi, wh, bi, bh)
                        return c2, y

                    carry_f, ys = jax.lax.scan(body, carry, seq)
                    if d == 1:
                        ys = jnp.flip(ys, 0)
                    outs_dirs.append(ys)
                    hs_list.append(carry_f[0])
                    if mode == "LSTM":
                        cs_list.append(carry_f[1])
                x = jnp.concatenate(outs_dirs, axis=-1) if nd == 2 else outs_dirs[0]
                if dropout > 0.0 and layer < nl - 1:
                    x = x * masks[layer][None, :, :]
            y = x if time_major else jnp.swapaxes(x, 0, 1)
            h_final = jnp.stack(hs_list, axis=0)
            if mode == "LSTM":
                c_final = jnp.stack(cs_list, axis=0)
                return y, h_final, c_final
            return y, h_final

        outs = apply_op(run, inputs, *weights, *init_raws, multi_out=True)
        if mode == "LSTM":
            y, h, c = outs
            return y, (h, c)
        y, h = outs
        return y, h


class SimpleRNN(_RNNBase):
    _mode = "RNN_TANH"


class LSTM(_RNNBase):
    _mode = "LSTM"

    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__(input_size, hidden_size, num_layers, direction, time_major,
                         dropout, "tanh", weight_ih_attr, weight_hh_attr,
                         bias_ih_attr, bias_hh_attr)


class GRU(_RNNBase):
    _mode = "GRU"

    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__(input_size, hidden_size, num_layers, direction, time_major,
                         dropout, "tanh", weight_ih_attr, weight_hh_attr,
                         bias_ih_attr, bias_hh_attr)
