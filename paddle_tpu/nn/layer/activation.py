"""Activation layers — parity with python/paddle/nn/layer/activation.py."""
from __future__ import annotations

from .. import functional as F
from .. import initializer as I
from ..layer_base import Layer

__all__ = [
    "ReLU", "ReLU6", "ELU", "SELU", "CELU", "GELU", "Sigmoid", "Hardsigmoid",
    "Hardswish", "Hardtanh", "Hardshrink", "LeakyReLU", "LogSigmoid",
    "LogSoftmax", "Maxout", "Mish", "PReLU", "RReLU", "Silu", "Swish",
    "Softmax", "Softplus", "Softshrink", "Softsign", "Tanh", "Tanhshrink",
    "ThresholdedReLU", "GLU",
]


def _simple(name, fn_name, params=()):
    def __init__(self, *args, **kwargs):
        Layer.__init__(self)
        for i, p in enumerate(params):
            val = args[i] if i < len(args) else kwargs.get(p[0], p[1])
            setattr(self, p[0], val)

    def forward(self, x):
        fn = getattr(F, fn_name)
        return fn(x, *[getattr(self, p[0]) for p in params])

    return type(name, (Layer,), {"__init__": __init__, "forward": forward})


ReLU = _simple("ReLU", "relu")
ReLU6 = _simple("ReLU6", "relu6")
ELU = _simple("ELU", "elu", [("alpha", 1.0)])
SELU = _simple(
    "SELU", "selu",
    [("scale", 1.0507009873554804934193349852946),
     ("alpha", 1.6732632423543772848170429916717)],
)
CELU = _simple("CELU", "celu", [("alpha", 1.0)])
GELU = _simple("GELU", "gelu", [("approximate", False)])
Sigmoid = _simple("Sigmoid", "sigmoid")
Hardsigmoid = _simple("Hardsigmoid", "hardsigmoid")
Hardswish = _simple("Hardswish", "hardswish")
Hardtanh = _simple("Hardtanh", "hardtanh", [("min", -1.0), ("max", 1.0)])
Hardshrink = _simple("Hardshrink", "hardshrink", [("threshold", 0.5)])
LeakyReLU = _simple("LeakyReLU", "leaky_relu", [("negative_slope", 0.01)])
LogSigmoid = _simple("LogSigmoid", "log_sigmoid")
LogSoftmax = _simple("LogSoftmax", "log_softmax", [("axis", -1)])
Maxout = _simple("Maxout", "maxout", [("groups", 2), ("axis", 1)])
Mish = _simple("Mish", "mish")
RReLU = _simple("RReLU", "rrelu", [("lower", 0.125), ("upper", 1.0 / 3.0)])
Silu = _simple("Silu", "silu")
Swish = _simple("Swish", "swish")
Softmax = _simple("Softmax", "softmax", [("axis", -1)])
Softplus = _simple("Softplus", "softplus", [("beta", 1), ("threshold", 20)])
Softshrink = _simple("Softshrink", "softshrink", [("threshold", 0.5)])
Softsign = _simple("Softsign", "softsign")
Tanh = _simple("Tanh", "tanh")
Tanhshrink = _simple("Tanhshrink", "tanhshrink")
ThresholdedReLU = _simple("ThresholdedReLU", "thresholded_relu", [("threshold", 1.0)])
GLU = _simple("GLU", "glu", [("axis", -1)])


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            [num_parameters], attr=weight_attr, default_initializer=I.Constant(init)
        )

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)
