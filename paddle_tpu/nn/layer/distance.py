"""Distance layers — parity with python/paddle/nn/layer/distance.py."""
from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor, apply_op, to_tensor
from ..layer_base import Layer

__all__ = ["PairwiseDistance"]


class PairwiseDistance(Layer):
    """p-norm distance between row vectors — parity with
    python/paddle/nn/layer/distance.py:26 (the reference lowers to a
    p_norm op over x−y+epsilon; one fused elementwise+reduce here)."""

    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p = float(p)
        self.epsilon = float(epsilon)
        self.keepdim = keepdim

    def forward(self, x, y):
        x = x if isinstance(x, Tensor) else to_tensor(x)
        y = y if isinstance(y, Tensor) else to_tensor(y)
        p, eps, keepdim = self.p, self.epsilon, self.keepdim

        def f(a, b):
            d = jnp.abs(a - b + eps)
            if p == jnp.inf:
                return jnp.max(d, axis=-1, keepdims=keepdim)
            if p == -jnp.inf:
                return jnp.min(d, axis=-1, keepdims=keepdim)
            return jnp.sum(d ** p, axis=-1, keepdims=keepdim) ** (1.0 / p)

        return apply_op(f, x, y)
