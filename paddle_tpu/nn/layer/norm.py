"""Norm layers — parity with python/paddle/nn/layer/norm.py.

SyncBatchNorm note: on TPU under pjit, batch-norm statistics are computed over
the global (sharded) batch automatically when the reduction spans the data
axis, so SyncBatchNorm degenerates to BatchNorm inside a jitted step; the
eager implementation additionally psums stats over the dp mesh axis when a
distributed context is active (replaces reference's sync_batch_norm_op.cu).
"""
from __future__ import annotations

import numpy as np

from ...core import dtype as dtype_mod
from ...core.tensor import Tensor, wrap_raw
from .. import functional as F
from .. import initializer as I
from ..layer_base import Layer

__all__ = [
    "BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D", "SyncBatchNorm",
    "LayerNorm", "GroupNorm", "InstanceNorm1D", "InstanceNorm2D",
    "InstanceNorm3D", "LocalResponseNorm", "SpectralNorm",
]


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            [num_features], attr=weight_attr, default_initializer=I.Constant(1.0)
        )
        self.bias = self.create_parameter([num_features], attr=bias_attr, is_bias=True)
        import jax.numpy as jnp

        self.register_buffer("_mean", wrap_raw(jnp.zeros([num_features], jnp.float32)))
        self.register_buffer("_variance", wrap_raw(jnp.ones([num_features], jnp.float32)))

    def forward(self, input):
        return F.batch_norm(
            input, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum, epsilon=self._epsilon,
            data_format=self._data_format, use_global_stats=self._use_global_stats,
        )

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}"


class BatchNorm(_BatchNormBase):
    """fluid-style BatchNorm (accepts act=...)."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-05,
                 param_attr=None, bias_attr=None, dtype="float32",
                 data_layout="NCHW", in_place=False, moving_mean_name=None,
                 moving_variance_name=None, do_model_average_for_mean_and_var=True,
                 use_global_stats=False, trainable_statistics=False):
        super().__init__(num_channels, momentum, epsilon, param_attr, bias_attr,
                         data_layout, use_global_stats)
        self._act = act

    def forward(self, input):
        out = super().forward(input)
        if self._act:
            out = getattr(F, self._act)(out)
        return out


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN. Inside pjit the batch axis is global already; in
    eager DP mode stats are allreduced over the data-parallel group."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            out = SyncBatchNorm(layer._num_features, layer._momentum, layer._epsilon,
                                None, None, layer._data_format)
            out.weight.set_value(layer.weight)
            out.bias.set_value(layer.bias)
            out._mean.set_value(layer._mean)
            out._variance.set_value(layer._variance)
        for name, sub in layer._sub_layers.items():
            out._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return out


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, (int, np.integer)):
            normalized_shape = [int(normalized_shape)]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                self._normalized_shape, attr=weight_attr,
                default_initializer=I.Constant(1.0),
            )
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                self._normalized_shape, attr=bias_attr, is_bias=True
            )

    def forward(self, input):
        return F.layer_norm(input, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}, epsilon={self._epsilon}"


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._num_channels = num_channels
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = (
            None if weight_attr is False else self.create_parameter(
                [num_channels], attr=weight_attr, default_initializer=I.Constant(1.0)
            )
        )
        self.bias = (
            None if bias_attr is False else self.create_parameter(
                [num_channels], attr=bias_attr, is_bias=True
            )
        )

    def forward(self, input):
        return F.group_norm(input, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_features = num_features
        self._epsilon = epsilon
        self._data_format = data_format
        if weight_attr is False:
            self.scale = None
            self.bias = None
        else:
            self.scale = self.create_parameter(
                [num_features], attr=weight_attr, default_initializer=I.Constant(1.0)
            )
            self.bias = self.create_parameter([num_features], attr=bias_attr, is_bias=True)

    def forward(self, input):
        return F.instance_norm(input, weight=self.scale, bias=self.bias,
                               eps=self._epsilon, data_format=self._data_format)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=0.0001, beta=0.75, k=1.0, data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k
        self.data_format = data_format

    def forward(self, input):
        return F.local_response_norm(input, self.size, self.alpha, self.beta,
                                     self.k, self.data_format)


class SpectralNorm(Layer):
    """Weight spectral normalization via power iteration
    (parity operators/spectral_norm_op)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12, dtype="float32"):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._eps = eps
        import jax.numpy as jnp

        h = int(weight_shape[dim])
        w = int(np.prod(weight_shape)) // h
        self.weight_u = self.create_parameter(
            [h], default_initializer=I.Normal(0.0, 1.0)
        )
        self.weight_v = self.create_parameter(
            [w], default_initializer=I.Normal(0.0, 1.0)
        )
        self.weight_u.stop_gradient = True
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        import jax.numpy as jnp
        from ...core.tensor import apply_op

        dim = self._dim
        eps = self._eps
        iters = self._power_iters
        u0 = self.weight_u._value
        v0 = self.weight_v._value

        def f(w):
            w_m = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
            u, v = u0, v0
            for _ in range(iters):
                v = w_m.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = w_m @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ w_m @ v
            return w / sigma

        out = apply_op(f, weight)
        return out
