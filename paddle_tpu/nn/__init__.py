"""paddle_tpu.nn — layers, functional, initializers.

Parity surface with python/paddle/nn/ in the reference (~21k LoC layer zoo),
implemented over jax/XLA (see SURVEY.md §2 #55-57).
"""
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .layer_base import Layer  # noqa: F401
from .param_attr import ParamAttr  # noqa: F401

from .layer.activation import *  # noqa: F401,F403
from .layer.common import *  # noqa: F401,F403
from .layer.container import *  # noqa: F401,F403
from .layer.conv import *  # noqa: F401,F403
from .layer.loss import *  # noqa: F401,F403
from .layer import loss  # noqa: F401  (paddle.nn.loss submodule parity)
from .layer.norm import *  # noqa: F401,F403
from .layer.pooling import *  # noqa: F401,F403
from .layer.decode import *  # noqa: F401,F403
from .layer.distance import *  # noqa: F401,F403
from .layer_dp import DataParallel  # noqa: F401
from .layer.rnn import *  # noqa: F401,F403
from .layer.transformer import *  # noqa: F401,F403

from . import clip  # noqa: F401
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401

from .utils import weight_norm, remove_weight_norm, spectral_norm  # noqa: F401
