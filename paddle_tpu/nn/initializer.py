"""Parameter initializers — parity with python/paddle/nn/initializer/ and
fluid/initializer.py in the reference. Initializers are pure: they produce a
jax array from (shape, dtype, key) so parameter creation is reproducible and
usable inside staged init functions.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtype_mod
from ..core import rng as rng_mod

__all__ = [
    "Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
    "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
    "Assign", "Dirac", "Orthogonal", "calculate_gain",
]


def calculate_gain(nonlinearity, param=None):
    gains = {
        "sigmoid": 1.0,
        "linear": 1.0,
        "conv1d": 1.0,
        "conv2d": 1.0,
        "conv3d": 1.0,
        "tanh": 5.0 / 3.0,
        "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param if param is not None else 0.01) ** 2)),
        "selu": 3.0 / 4.0,
    }
    return gains[nonlinearity]


def _fans(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        # paddle stores linear weights [in, out]
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, shape, dtype=None, key=None):
        dtype = dtype_mod.convert_dtype(dtype) or dtype_mod.get_default_dtype()
        if key is None:
            key = rng_mod.next_key()
        return self._generate(tuple(int(s) for s in shape), dtype, key)

    def _generate(self, shape, dtype, key):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def _generate(self, shape, dtype, key):
        return jnp.full(shape, self.value, dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def _generate(self, shape, dtype, key):
        return jax.random.normal(key, shape, dtype) * self.std + self.mean


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def _generate(self, shape, dtype, key):
        return (
            jax.random.truncated_normal(key, -2.0, 2.0, shape, dtype) * self.std
            + self.mean
        )


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def _generate(self, shape, dtype, key):
        return jax.random.uniform(key, shape, dtype, self.low, self.high)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def _generate(self, shape, dtype, key):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(key, shape, dtype, -limit, limit)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def _generate(self, shape, dtype, key):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return jax.random.normal(key, shape, dtype) * std


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="leaky_relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def _generate(self, shape, dtype, key):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(key, shape, dtype, -limit, limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="leaky_relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def _generate(self, shape, dtype, key):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        return jax.random.normal(key, shape, dtype) * std


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def _generate(self, shape, dtype, key):
        from ..core.tensor import Tensor

        v = self.value
        if isinstance(v, Tensor):
            v = v._value
        arr = jnp.asarray(np.asarray(v), dtype)
        return arr.reshape(shape)


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def _generate(self, shape, dtype, key):
        out = np.zeros(shape, dtype=np.float32)
        oc, ic = shape[0], shape[1]
        spatial = shape[2:]
        centers = tuple(s // 2 for s in spatial)
        per_group = oc // self.groups
        for g in range(self.groups):
            for i in range(min(per_group, ic)):
                out[(g * per_group + i, i) + centers] = 1.0
        return jnp.asarray(out, dtype)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def _generate(self, shape, dtype, key):
        rows = shape[0]
        cols = int(np.prod(shape[1:]))
        flat = jax.random.normal(key, (max(rows, cols), min(rows, cols)), jnp.float32)
        q, r = jnp.linalg.qr(flat)
        q = q * jnp.sign(jnp.diagonal(r))
        q = q.T if rows < cols else q
        return (self.gain * q[:rows, :cols]).reshape(shape).astype(dtype)


def _resolve(init, is_bias=False):
    """Accept Initializer | None; default Xavier for weights, zeros for bias —
    the reference's LayerHelperBase default (fluid/layer_helper_base.py)."""
    if init is None:
        return Constant(0.0) if is_bias else XavierUniform()
    return init
