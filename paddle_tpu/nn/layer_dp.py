"""DataParallel — parity with paddle.DataParallel
(fluid/dygraph/parallel.py:380) over the C++ Reducer (imperative/reducer.cc).

TPU-native: inside a jitted train step over the dp mesh axis, gradients are
globally summed by XLA (one fused reduce per step — bucketing/overlap that the
reference's Reducer hand-builds comes from the XLA latency-hiding scheduler).
The eager path allreduces each parameter gradient after backward via the
process-level collective; with one process it is the identity.
"""
from __future__ import annotations

from ..core.tensor import Tensor
from .layer_base import Layer

__all__ = ["DataParallel"]


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self.find_unused_parameters = find_unused_parameters
        self._group = group
        self._hooked = []
        self._register_grad_hooks()

    def _register_grad_hooks(self):
        from ..distributed.parallel import get_world_size

        if get_world_size() <= 1:
            return

        from ..distributed.communication import all_reduce, ReduceOp

        world = get_world_size()

        def make_hook():
            def hook(grad):
                out = all_reduce(grad, op=ReduceOp.SUM, group=self._group)
                return out.scale_(1.0 / world) if hasattr(out, "scale_") else out

            return hook

        for p in self._layers.parameters():
            if p.trainable:
                self._hooked.append(p.register_hook(make_hook()))

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    # passthrough surface
    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    set_dict = set_state_dict
    load_dict = set_state_dict

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        from ..distributed.parallel import get_world_size

        if get_world_size() <= 1:
            return
        from ..distributed.communication import all_reduce

        for p in self._layers.parameters():
            if p.grad is not None:
                all_reduce(p.grad, group=self._group)
                p.grad = p.grad / get_world_size()

    @property
    def _layers_attr(self):
        return self._layers
