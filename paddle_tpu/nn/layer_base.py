"""Layer: the imperative module system.

Parity with the reference's dygraph Layer
(/root/reference/python/paddle/fluid/dygraph/layers.py:80,264,313): named
parameters/buffers/sublayers, forward pre/post hooks, state_dict round-trip,
train/eval modes. TPU-first difference: a Layer is also a *pytree of
parameters* — ``paddle_tpu.jit`` can functionalize any Layer into
``(apply_fn, params)`` for pjit compilation without touching user code.
"""
from __future__ import annotations

import collections
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from ..core import dtype as dtype_mod
from ..core.enforce import InvalidArgumentError, enforce
from ..core.tensor import Parameter, Tensor, to_tensor
from . import initializer as I
from .param_attr import ParamAttr

__all__ = ["Layer"]


class HookRemoveHelper:
    _next_id = [0]

    def __init__(self, hooks):
        self._hooks = hooks
        HookRemoveHelper._next_id[0] += 1
        self._id = HookRemoveHelper._next_id[0]
        hooks[self._id] = None  # placeholder replaced by caller

    def remove(self):
        self._hooks.pop(self._id, None)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self.training = True
        self._dtype = dtype_mod.convert_dtype(dtype)
        self._parameters: "collections.OrderedDict[str, Parameter]" = collections.OrderedDict()
        self._sub_layers: "collections.OrderedDict[str, Layer]" = collections.OrderedDict()
        self._buffers: "collections.OrderedDict[str, Tensor]" = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._casted_by_pure_fp16 = False
        self._name_scope = name_scope or type(self).__name__.lower()

    # ------------------------------------------------------------------ params
    def create_parameter(
        self,
        shape,
        attr=None,
        dtype=None,
        is_bias=False,
        default_initializer=None,
    ) -> Optional[Parameter]:
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = dtype_mod.convert_dtype(dtype) or self._dtype
        init = attr.initializer or I._resolve(default_initializer, is_bias)
        value = init(shape, dtype)
        p = Parameter(value, trainable=attr.trainable, name=attr.name)
        p.optimize_attr["learning_rate"] = attr.learning_rate
        p.regularizer = attr.regularizer
        p.need_clip = attr.need_clip
        return p

    def create_tensor(self, name=None, persistable=None, dtype=None):
        from ..tensor import zeros

        return zeros([1], dtype or self._dtype)

    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        if parameter is None:
            self._parameters[name] = None
            return None
        enforce(
            isinstance(parameter, Parameter),
            f"add_parameter expects Parameter, got {type(parameter)}",
        )
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer"):
        self._sub_layers[name] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Optional[Tensor], persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # ------------------------------------------------------------------ hooks
    def register_forward_pre_hook(self, hook: Callable):
        helper = HookRemoveHelper(self._forward_pre_hooks)
        self._forward_pre_hooks[helper._id] = hook
        return helper

    def register_forward_post_hook(self, hook: Callable):
        helper = HookRemoveHelper(self._forward_post_hooks)
        self._forward_post_hooks[helper._id] = hook
        return helper

    # ------------------------------------------------------------------ modes
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    def apply(self, fn: Callable):
        for l in self.children():
            l.apply(fn)
        fn(self)
        return self

    def full_name(self):
        return self._name_scope

    # ------------------------------------------------------------------ walk
    def parameters(self, include_sublayers=True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_parameters(
        self, prefix="", include_sublayers=True
    ) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for name, layer, lp in self._walk(prefix, include_sublayers):
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (f"{lp}{pname}" if lp else pname), p

    def buffers(self, include_sublayers=True) -> List[Tensor]:
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(
        self, prefix="", include_sublayers=True
    ) -> Iterator[Tuple[str, Tensor]]:
        seen = set()
        for name, layer, lp in self._walk(prefix, include_sublayers):
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (f"{lp}{bname}" if lp else bname), b

    def _walk(self, prefix, include_sublayers):
        """Yields (name, layer, layer_prefix)."""
        yield "", self, prefix and prefix + "."
        if include_sublayers:
            for name, sub in self.named_sublayers(prefix=prefix):
                yield name, sub, name + "."

    def children(self) -> Iterator["Layer"]:
        for _, l in self.named_children():
            yield l

    def named_children(self) -> Iterator[Tuple[str, "Layer"]]:
        seen = set()
        for name, l in self._sub_layers.items():
            if l is not None and id(l) not in seen:
                seen.add(id(l))
                yield name, l

    def sublayers(self, include_self=False) -> List["Layer"]:
        out = [l for _, l in self.named_sublayers(include_self=include_self)]
        return out

    def named_sublayers(self, prefix="", include_self=False, layers_set=None):
        if layers_set is None:
            layers_set = set()
        if include_self and id(self) not in layers_set:
            layers_set.add(id(self))
            yield prefix, self
        for name, sub in self._sub_layers.items():
            if sub is None or id(sub) in layers_set:
                continue
            layers_set.add(id(sub))
            subprefix = f"{prefix}.{name}" if prefix else name
            yield subprefix, sub
            yield from sub.named_sublayers(prefix=subprefix, layers_set=layers_set)

    # ------------------------------------------------------------------ state
    def state_dict(
        self, destination=None, include_sublayers=True, structured_name_prefix="",
        use_hook=True,
    ) -> Dict[str, Tensor]:
        dest = collections.OrderedDict() if destination is None else destination
        for name, p in self.named_parameters(include_sublayers=include_sublayers):
            dest[structured_name_prefix + name] = p
        for name, b in self.named_buffers(include_sublayers=include_sublayers):
            short = name.rsplit(".", 1)[-1]
            if short in self._non_persistable_buffer_names:
                continue
            dest[structured_name_prefix + name] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        missing, unexpected = [], []
        own = self.state_dict()
        matched = set()
        for k, v in state_dict.items():
            if k in own:
                target = own[k]
                arr = v.numpy() if isinstance(v, Tensor) else np.asarray(v)
                if list(arr.shape) != list(target.shape):
                    raise InvalidArgumentError(
                        f"shape mismatch for {k}: checkpoint {list(arr.shape)} vs "
                        f"parameter {list(target.shape)}"
                    )
                target.set_value(arr.astype(target.dtype))
                matched.add(k)
            else:
                unexpected.append(k)
        missing = [k for k in own if k not in matched]
        return missing, unexpected

    load_dict = set_state_dict
    set_dict = set_state_dict

    # ------------------------------------------------------------------ dtype/device moves
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            self._convert_dtype(dtype_mod.convert_dtype(dtype))
        return self

    def astype(self, dtype):
        self._convert_dtype(dtype_mod.convert_dtype(dtype))
        return self

    def float(self):
        return self.astype("float32")

    def half(self):
        return self.astype("float16")

    def bfloat16(self):
        return self.astype("bfloat16")

    def _convert_dtype(self, d, only_floating=True):
        for layer in [self] + self.sublayers():
            layer._dtype = d
            for name, p in layer._parameters.items():
                if p is not None and (not only_floating or dtype_mod.is_floating_point(p.dtype)):
                    p._value = p._value.astype(d)
            for name, b in layer._buffers.items():
                if b is not None and (not only_floating or dtype_mod.is_floating_point(b.dtype)):
                    b._value = b._value.astype(d)

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    # ------------------------------------------------------------------ call
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            result = hook(self, inputs, outputs)
            if result is not None:
                outputs = result
        return outputs

    # ------------------------------------------------------------------ attr routing
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        subs = self.__dict__.get("_sub_layers")
        bufs = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            _remove_from(name, subs, bufs)
            params[name] = value
        elif isinstance(value, Layer):
            if subs is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            _remove_from(name, params, bufs)
            subs[name] = value
        elif params is not None and name in params:
            if value is None:
                params[name] = None
            elif isinstance(value, Tensor):
                params[name].set_value(value)
            else:
                raise TypeError(f"cannot assign {type(value)} to parameter {name!r}")
        elif bufs is not None and name in bufs:
            bufs[name] = value
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        # only called when normal lookup fails
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"{type(self).__name__!r} object has no attribute {name!r}")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + list(self._sub_layers) + list(self._buffers)

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, sub in self._sub_layers.items():
            mod_str = repr(sub)
            mod_str = _addindent(mod_str, 2)
            lines.append(f"({name}): {mod_str}")
        main = type(self).__name__ + "("
        if extra:
            main += extra
        if lines:
            main += "\n  " + "\n  ".join(lines) + "\n"
        return main + ")"


def _remove_from(name, *dicts):
    for d in dicts:
        if d is not None and name in d:
            del d[name]


def _addindent(s, n):
    lines = s.split("\n")
    if len(lines) == 1:
        return s
    pad = " " * n
    return lines[0] + "\n" + "\n".join(pad + l for l in lines[1:])
