"""nn.utils — weight_norm / spectral_norm wrappers, parity with
python/paddle/nn/utils/ in the reference."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Parameter, Tensor, apply_op
from .layer_base import Layer

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm"]


def _norm_except(w, dim):
    if dim is None:
        return jnp.sqrt(jnp.sum(w * w))
    axes = tuple(i for i in range(w.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(w * w, axis=axes, keepdims=True))


def weight_norm(layer: Layer, name="weight", dim=0):
    """Reparametrize layer.weight = g * v / ||v||; recomputed each forward
    via a pre-hook (parity with paddle.nn.utils.weight_norm)."""
    w = getattr(layer, name)
    arr = w._value
    norm = _norm_except(arr, dim)
    g = Parameter(norm.reshape(-1) if dim is not None else norm.reshape(()))
    v = Parameter(arr)
    layer.add_parameter(name + "_g", g)
    layer.add_parameter(name + "_v", v)
    del layer._parameters[name]

    def pre_hook(l, inputs):
        vv, gg = getattr(l, name + "_v"), getattr(l, name + "_g")

        def compute(v_raw, g_raw):
            n = _norm_except(v_raw, dim)
            gshape = n.shape if dim is not None else ()
            return v_raw / n * g_raw.reshape(gshape)

        w_t = apply_op(compute, vv, gg)
        object.__setattr__(l, name, w_t)
        return None

    handle = layer.register_forward_pre_hook(pre_hook)
    layer._weight_norm_handle = handle
    layer._weight_norm_cfg = (name, dim)
    # materialize once so .weight exists before the first call
    pre_hook(layer, ())
    return layer


def remove_weight_norm(layer: Layer, name="weight"):
    handle = getattr(layer, "_weight_norm_handle", None)
    if handle is not None:
        handle.remove()
    name_, dim = getattr(layer, "_weight_norm_cfg", (name, 0))
    v = layer._parameters.pop(name + "_v")
    g = layer._parameters.pop(name + "_g")

    arr = v._value
    n = _norm_except(arr, dim)
    gshape = n.shape if dim is not None else ()
    w = Parameter(arr / n * g._value.reshape(gshape))
    if hasattr(layer, name):
        try:
            object.__delattr__(layer, name)
        except AttributeError:
            pass
    layer.add_parameter(name, w)
    return layer


def spectral_norm(layer: Layer, name="weight", n_power_iterations=1, eps=1e-12, dim=None):
    from .layer.norm import SpectralNorm as _SN

    w = getattr(layer, name)
    if dim is None:
        dim = 0
    sn = _SN(list(w.shape), dim=dim, power_iters=n_power_iterations, eps=eps)
    layer.add_sublayer(name + "_sn", sn)
    v = Parameter(w._value)
    layer.add_parameter(name + "_orig", v)
    del layer._parameters[name]

    def pre_hook(l, inputs):
        w_t = sn(getattr(l, name + "_orig"))
        object.__setattr__(l, name, w_t)
        return None

    layer.register_forward_pre_hook(pre_hook)
    pre_hook(layer, ())
    return layer
