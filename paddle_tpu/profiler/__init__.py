"""paddle_tpu.profiler — the runtime telemetry subsystem.

Parity target: the reference's observability stack
(platform/profiler.h RecordEvent/DeviceTracer + platform/monitor.h
StatRegistry) as ONE surface with three sinks:

- ``Telemetry`` (telemetry.py): counters (on StatRegistry), gauges,
  streaming histograms/timers; ``to_jsonl`` appends flat scalar records
  in the schema ``tools/check_telemetry_schema.py`` validates.
- chrome tracing: re-exported from ``utils.profiler`` — host spans plus
  telemetry counter snapshots as instant events in one catapult JSON.
- ``hapi.callbacks.TelemetryLogger``: streams the same scalars during
  ``Model.fit`` (VisualDL-parity surface).

``tracked_jit`` (retrace.py) wraps the engines' ``jax.jit`` entry points
to count/time XLA compilations per function and warn (rate-limited) when
a function retraces more than ``PADDLE_TPU_RETRACE_WARN`` times.

Cost attribution (``xla_cost``): every fresh ``tracked_jit`` compile is
cost-analyzed (XLA FLOPs / bytes accessed / peak HBM) and combined with
the ``*step_ms`` histograms and a per-chip peak registry
(``PADDLE_TPU_PEAK_FLOPS`` / ``PADDLE_TPU_HBM_GBPS`` overrides) into
live ``gauge/mfu``, achieved HBM GB/s, and a roofline verdict.

Structured spans (``spans``): hierarchical, step-correlated scoped spans
(fit → epoch → step → h2d/compute/d2h/callback/checkpoint) behind the
chrome export, plus the always-on bounded **flight recorder** whose
event tail rides the watchdog/StepGuard crash reports.

Cross-rank aggregation (``aggregate`` + ``tools/telemetry_agg.py``):
merges the per-rank JSONL files a ``distributed.launch`` job leaves into
one cluster view with straggler detection.

Cluster attribution plane (this PR): ``collective_attrib`` walks the
compiled HLO already held by ``xla_cost``/``hlo_attrib`` into a per-axis
collective inventory (``gauge/collective/<axis>/{bytes,ms,count}
.<entry>``, the ``comm_bound:<axis>`` verdict refinement);
``cluster_trace`` fuses per-rank trace/collective/clock artifacts into
ONE timeline with per-rank tracks and names the late rank per collective
instance (LATE-RANK findings in ``telemetry_agg``, gated by
``tools/check_cluster_timeline.py``).

Goodput ledger (``goodput``): process-wide wall-clock attribution —
every job second lands in exactly one category of a closed vocabulary
(productive_step / compile / input_wait / checkpoint / rollback /
restart downtime / …), fed by the instrumentation points above,
published as ``gauge/goodput/*`` + a structured ``"goodput"`` JSONL
table, merged cross-rank and cross-restart by ``aggregate``, and gated
for conservation by ``tools/check_goodput.py``.

The legacy span API (``RecordEvent``, ``Profiler``, ``start_profiler``…)
stays in ``paddle_tpu.utils.profiler`` and is re-exported here so
``paddle.profiler.Profiler``-style code ports unchanged.
"""
from . import aggregate, bottleneck, device_profile, hlo_attrib  # noqa: F401
from . import cluster_trace, collective_attrib  # noqa: F401
from . import goodput, spans, xla_cost  # noqa: F401
from .goodput import GoodputLedger  # noqa: F401
from .bottleneck import VERDICT_IDS, VERDICT_NAMES  # noqa: F401
from .device_profile import request_capture  # noqa: F401
from .hlo_attrib import attribute_trace, hlo_registry, parse_hlo_text  # noqa: F401
from ..utils.profiler import (  # noqa: F401
    Profiler,
    RecordEvent,
    export_chrome_tracing,
    record_event,
    start_profiler,
    stop_profiler,
)
from . import ops_server, slo  # noqa: F401
from .ops_server import (  # noqa: F401
    OpsServer,
    prometheus_text,
    start_ops_server,
    stop_ops_server,
)
from .retrace import RetraceTracker, reset_trackers, tracked_jit  # noqa: F401
from .slo import SLOMonitor, SLOObjective, parse_slos  # noqa: F401
from .spans import (  # noqa: F401
    FlightRecorder,
    ReqTrace,
    Span,
    flight_recorder,
    span,
    trace_store,
)
from .telemetry import (  # noqa: F401
    Histogram,
    Telemetry,
    get_telemetry,
    sample_device_memory,
    start_device_memory_sampler,
    start_periodic_flush,
)
from .xla_cost import (  # noqa: F401
    CostRecord,
    capture as capture_compile_cost,
    chip_peaks,
    cost_registry,
    publish_mfu,
    set_steps_per_call,
)

__all__ = [
    "Telemetry", "Histogram", "get_telemetry", "sample_device_memory",
    "start_periodic_flush", "start_device_memory_sampler",
    "tracked_jit", "RetraceTracker", "reset_trackers",
    "Span", "span", "FlightRecorder", "flight_recorder",
    "ReqTrace", "trace_store",
    "OpsServer", "start_ops_server", "stop_ops_server", "prometheus_text",
    "SLOMonitor", "SLOObjective", "parse_slos",
    "CostRecord", "cost_registry", "chip_peaks", "publish_mfu",
    "set_steps_per_call", "capture_compile_cost",
    "Profiler", "RecordEvent", "record_event", "start_profiler",
    "stop_profiler", "export_chrome_tracing",
    "request_capture", "VERDICT_IDS", "VERDICT_NAMES",
    "attribute_trace", "hlo_registry", "parse_hlo_text",
    "spans", "xla_cost", "aggregate", "ops_server", "slo",
    "device_profile", "hlo_attrib", "bottleneck",
    "collective_attrib", "cluster_trace",
    "goodput", "GoodputLedger",
]
