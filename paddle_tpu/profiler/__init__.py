"""paddle_tpu.profiler — the runtime telemetry subsystem.

Parity target: the reference's observability stack
(platform/profiler.h RecordEvent/DeviceTracer + platform/monitor.h
StatRegistry) as ONE surface with three sinks:

- ``Telemetry`` (telemetry.py): counters (on StatRegistry), gauges,
  streaming histograms/timers; ``to_jsonl`` appends flat scalar records
  in the schema ``tools/check_telemetry_schema.py`` validates.
- chrome tracing: re-exported from ``utils.profiler`` — host spans plus
  telemetry counter snapshots as instant events in one catapult JSON.
- ``hapi.callbacks.TelemetryLogger``: streams the same scalars during
  ``Model.fit`` (VisualDL-parity surface).

``tracked_jit`` (retrace.py) wraps the engines' ``jax.jit`` entry points
to count/time XLA compilations per function and warn (rate-limited) when
a function retraces more than ``PADDLE_TPU_RETRACE_WARN`` times.

The legacy span API (``RecordEvent``, ``Profiler``, ``start_profiler``…)
stays in ``paddle_tpu.utils.profiler`` and is re-exported here so
``paddle.profiler.Profiler``-style code ports unchanged.
"""
from ..utils.profiler import (  # noqa: F401
    Profiler,
    RecordEvent,
    export_chrome_tracing,
    record_event,
    start_profiler,
    stop_profiler,
)
from .retrace import RetraceTracker, tracked_jit  # noqa: F401
from .telemetry import (  # noqa: F401
    Histogram,
    Telemetry,
    get_telemetry,
    sample_device_memory,
)

__all__ = [
    "Telemetry", "Histogram", "get_telemetry", "sample_device_memory",
    "tracked_jit", "RetraceTracker",
    "Profiler", "RecordEvent", "record_event", "start_profiler",
    "stop_profiler", "export_chrome_tracing",
]
