"""Goodput ledger — exhaustive wall-clock attribution of every job second.

Throughput tells you how fast the steps you ran were; **goodput** tells
you what fraction of the wall-clock you *paid for* became steps at all.
This module keeps one process-wide ledger in which every second since
process start lands in exactly ONE category of a closed vocabulary:

    startup             process start until the first productive step
    productive_step     inside a train-step call (engine/executor/guard)
    compile             trace + XLA compile of an unseen signature
    input_wait          the consumer blocked on the prefetch queue
    checkpoint_save     checkpoint write / emergency spill
    checkpoint_restore  checkpoint read / manifest-fallback walk / resume
    rollback_recovery   StepGuard quarantine + snapshot rollback + replay
    eval                inside an EvalStep call
    drain_shutdown      preemption / serving drain until exit
    restart_downtime    dead job gap between attempts (launcher-booked)
    unattributed        the honest remainder — nothing claimed it

The ledger is NOT a second layer of clocks: the instrumentation points
that already exist (tracked_jit compile timing, prefetch queue waits,
checkpoint timers, StepGuard rollback paths, step boundaries) each wrap
their existing timed region in :func:`activity`, which claims the span
for its category. Claims nest: an inner claim suspends the outer one, so
overlapping activities (a compile inside an open step, a spill inside a
drain) never double-book — each wall second has exactly one owner.

Mechanics — a tiny state machine on the *driver thread* (the first
thread to claim an activity; claims from other threads are no-ops, so a
background prefetch stage overlapping a device step books nothing):

- the base state starts at ``startup`` and flips permanently to
  ``unattributed`` at the first ``productive_step`` claim (everything a
  claim does not cover after training begins is honestly unaccounted);
- ``shutdown_begin()`` flips the base to ``drain_shutdown``;
- every transition books ``perf_counter`` elapsed to the outgoing top of
  the claim stack. ``snapshot()`` folds the pending span in and computes
  ``unattributed = wall - sum(claimed)``, so categories sum to measured
  wall by construction — the conservation contract ``check_goodput.py``
  gates on.

Cross-restart stitching: each attempt's ledger is stamped with
``PADDLE_TPU_LAUNCH_ATTEMPT`` and flushed into the rank's JSONL as a
structured ``"goodput"`` record table; the launcher books the dead gap
between attempts (heartbeat-dated death -> respawn) into its OWN ledger
as ``restart_downtime``. ``profiler.aggregate.goodput_summary`` sums a
rank across attempts and adds the launcher's downtime once, so the
category survives the process that caused it.

Everything here is host-side (two ``perf_counter`` reads and a dict add
per transition) — no device syncs, nothing traced, zero retrace impact.
Disable with ``PADDLE_TPU_GOODPUT=0``.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional

__all__ = [
    "CATEGORIES", "GoodputLedger", "ledger", "activity", "shutdown_begin",
    "publish", "jsonl_payload", "snapshot", "reset",
]

# The closed vocabulary. ``unattributed`` is computed, never claimed by
# instrumentation — claiming it would defeat its honesty.
CATEGORIES = (
    "startup",
    "productive_step",
    "compile",
    "input_wait",
    "checkpoint_save",
    "checkpoint_restore",
    "rollback_recovery",
    "eval",
    "drain_shutdown",
    "restart_downtime",
    "unattributed",
)


def _env_enabled() -> bool:
    return os.environ.get("PADDLE_TPU_GOODPUT", "1").strip().lower() not in (
        "0", "false", "off")


class _Activity:
    """Context manager for one claimed span (see ``activity``). Cheap:
    allocation + two lock/clock pairs; safe to enter per batch."""

    __slots__ = ("_led", "_cat", "_live")

    def __init__(self, led: "GoodputLedger", cat: str):
        self._led = led
        self._cat = cat
        self._live = False

    def __enter__(self):
        led = self._led
        if led._enabled:
            with led._lock:
                if led._claims_here():
                    led._book_to_top(time.perf_counter())
                    if (self._cat == "productive_step"
                            and led._stack[0] == "startup"):
                        # training has begun: from here on, unclaimed
                        # time is honestly unaccounted, not "startup"
                        led._stack[0] = "unattributed"
                    led._stack.append(self._cat)
                    self._live = True
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._live:
            led = self._led
            with led._lock:
                led._book_to_top(time.perf_counter())
                if len(led._stack) > 1:
                    led._stack.pop()
        return False


class GoodputLedger:
    """Process-wide wall-clock ledger (one per process; see module doc)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        self.started_at = time.time()
        self._mark = self._t0
        self._totals: Dict[str, float] = {c: 0.0 for c in CATEGORIES}
        self._stack = ["startup"]
        self._owner: Optional[int] = None
        self._enabled = _env_enabled()
        try:
            self.attempt = int(
                os.environ.get("PADDLE_TPU_LAUNCH_ATTEMPT", "0") or 0)
        except ValueError:
            self.attempt = 0

    # -- internals (lock held) ---------------------------------------------
    def _book_to_top(self, now: float) -> None:
        dt = now - self._mark
        if dt > 0:
            self._totals[self._stack[-1]] += dt
        self._mark = now

    def _claims_here(self) -> bool:
        # the first claiming thread becomes the driver; a background
        # stage thread overlapping the step loop must not double-book
        ident = threading.get_ident()
        if self._owner is None:
            self._owner = ident
        return self._owner == ident

    # -- claiming API -------------------------------------------------------
    def activity(self, category: str) -> _Activity:
        if category not in CATEGORIES or category == "unattributed":
            raise ValueError(f"unknown goodput category: {category!r}")
        return _Activity(self, category)

    def shutdown_begin(self) -> None:
        """Flip the base state to ``drain_shutdown`` (preemption exit,
        serving drain). Thread-agnostic — the latch may be flipped from a
        scheduler thread; open claims keep booking to themselves and the
        base change takes effect when they pop."""
        if not self._enabled:
            return
        with self._lock:
            if self._stack[0] == "drain_shutdown":
                return
            if len(self._stack) == 1:
                # the base IS the running span: close it first so the
                # pre-drain seconds stay with the old state
                self._book_to_top(time.perf_counter())
            self._stack[0] = "drain_shutdown"

    def reattribute(self, category: str, seconds: float,
                    source: Optional[str] = None) -> float:
        """Move up to ``seconds`` of already-booked wall time from
        ``source`` (default: the base state) into ``category`` — the
        launcher uses this to backdate restart downtime to the
        heartbeat-dated death, which precedes its own detection of it.
        Conservation-preserving by construction (a transfer, not an
        addition). Returns the seconds actually moved."""
        if category not in CATEGORIES or not self._enabled:
            return 0.0
        with self._lock:
            self._book_to_top(time.perf_counter())
            src = source or self._stack[0]
            take = min(max(0.0, float(seconds)), self._totals.get(src, 0.0))
            if take > 0:
                self._totals[src] -= take
                self._totals[category] += take
            return take

    # -- reading ------------------------------------------------------------
    def snapshot(self) -> dict:
        """Current totals including the pending span; ``unattributed`` is
        recomputed as the wall residual so categories sum to ``wall_s``
        exactly (the conservation contract)."""
        with self._lock:
            now = time.perf_counter()
            self._book_to_top(now)
            wall = now - self._t0
            cats = dict(self._totals)
            current = self._stack[-1]
        claimed = sum(v for c, v in cats.items() if c != "unattributed")
        cats["unattributed"] = max(0.0, wall - claimed)
        frac = min(1.0, cats["productive_step"] / wall) if wall > 0 else 0.0
        return {
            "wall_s": wall,
            "fraction": frac,
            "attempt": self.attempt,
            "current": current,
            "categories": cats,
        }


# -- module-level singleton ------------------------------------------------
# Created at import so the startup clock starts as early as the first
# paddle_tpu import; ``reset()`` swaps in a fresh ledger (bench_all resets
# telemetry per config — each config then gets its own wall denominator).
_LEDGER = GoodputLedger()


def ledger() -> GoodputLedger:
    return _LEDGER


def activity(category: str) -> _Activity:
    """Claim the enclosed span for ``category`` on the driver thread.
    Nested claims suspend the outer one (no double-booking); claims from
    non-driver threads are no-ops."""
    return _LEDGER.activity(category)


def shutdown_begin() -> None:
    _LEDGER.shutdown_begin()


def snapshot() -> dict:
    return _LEDGER.snapshot()


def reset() -> None:
    global _LEDGER
    _LEDGER = GoodputLedger()


def publish(tel=None) -> Optional[dict]:
    """Refresh ``gauge/goodput/*`` from the live ledger (called by
    ``Telemetry.to_jsonl`` and the ``/metrics`` scrape, same lazy pattern
    as the MFU/bottleneck publishers). Returns the snapshot."""
    if not _LEDGER._enabled:
        return None
    snap = _LEDGER.snapshot()
    if tel is None:
        from .telemetry import get_telemetry

        tel = get_telemetry()
    if tel.enabled:
        tel.gauge("goodput/wall_s", round(snap["wall_s"], 3))
        tel.gauge("goodput/fraction", round(snap["fraction"], 4))
        for cat, s in snap["categories"].items():
            # always publish the headline pair; others only once nonzero
            # (a closed vocabulary, not a mandatory one — a process that
            # never checkpointed should not advertise checkpoint_save=0)
            if s > 0 or cat in ("productive_step", "unattributed"):
                tel.gauge(f"goodput/{cat}_s", round(s, 3))
    return snap


def jsonl_payload() -> Optional[dict]:
    """Structured ``rec["goodput"]`` table for ``Telemetry.to_jsonl``
    (``rec["profile"]`` precedent): rounded snapshot keyed for the
    aggregator's cross-restart stitching."""
    if not _LEDGER._enabled:
        return None
    snap = _LEDGER.snapshot()
    return {
        "wall_s": round(snap["wall_s"], 3),
        "fraction": round(snap["fraction"], 4),
        "attempt": snap["attempt"],
        "current": snap["current"],
        "categories": {c: round(s, 3)
                       for c, s in snap["categories"].items()
                       if round(s, 3) > 0},
    }
