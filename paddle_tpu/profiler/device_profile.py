"""On-demand windowed device profiling with automatic attribution.

"Where did this step's time go" as a runtime service instead of a
by-hand ritual: arm a capture (env knob, ops-server ``POST
/debug/profile``, or :func:`request_capture`), and the next N step
boundaries of whatever engine is running are traced with
``jax.profiler.start_trace``, parsed, joined against the compiled HLO
already held by ``xla_cost``/``hlo_attrib``, and published as

- ``gauge/profile/{compute,collective,transfer,host_gap}_frac.<entry>``
  — the per-entry step-time decomposition (fractions of window wall,
  summing ≤ 1 per entry by construction),
- ``gauge/profile/device_total_ms`` / ``gauge/profile/wall_ms`` and
  ``counter/profile/captures``,
- a structured report (:func:`last_report`) carrying the per-op /
  per-source-line top-K tables — merged into every ``to_jsonl`` record
  as a top-level ``"profile"`` object and into the chrome export as
  device-op slices realigned with the PR 5 host spans,
- ``gauge/bottleneck/<entry>`` verdicts (via ``profiler.bottleneck``).

Step boundaries are hooked where the engines already heartbeat:
``jit.TrainStep``, ``fleet.ParallelTrainStep`` (``__call__`` and
``run_steps`` windows), ``static.Executor.run``/``run_steps``, and the
serving/decode scheduler loops. The hook is two module-global reads when
nothing is armed — zero per-step cost by construction, and capture
start/stop live entirely on the host side of the boundary, so arming a
capture can never change a program signature (zero retraces).

Env contract:

- ``PADDLE_TPU_DEVICE_PROFILE_EVERY=K`` — arm a capture automatically at
  every K-th step boundary (0/unset = off);
- ``PADDLE_TPU_DEVICE_PROFILE_STEPS=N`` — window length in trigger-entry
  steps (default 3);
- ``PADDLE_TPU_DEVICE_PROFILE_DIR`` — where raw traces land (default: a
  temp dir, deleted after parsing; set it to keep the TensorBoard
  artifact).

Exactly ONE device trace can be live per process (an XLA constraint):
overlapping capture requests — or a capture racing a
``utils.profiler.start_profiler(device_trace=True)`` window — are
refused with a warning and a counted ``profile/capture_skipped``, never
an exception mid-training.
"""
from __future__ import annotations

import logging
import os
import shutil
import tempfile
import threading
import time
from typing import Dict, Optional

from . import hlo_attrib
from .telemetry import get_telemetry

__all__ = [
    "request_capture", "step_boundary", "capture_state", "last_report",
    "configure", "reset", "publish", "jsonl_payload", "chrome_events",
    "acquire_device_trace", "release_device_trace", "device_trace_owner",
]

logger = logging.getLogger("paddle_tpu.profiler")

_DEFAULT_STEPS = 3


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


# -- device-trace ownership ---------------------------------------------------
# jax.profiler supports one live trace per process. Both producers (this
# module's windowed captures and utils.profiler's profiling windows)
# acquire through here, so a second start anywhere warns-and-noops
# instead of raising out of XLA mid-training.

_owner_lock = threading.Lock()
_trace_owner: Optional[str] = None


def acquire_device_trace(owner: str) -> bool:
    global _trace_owner
    with _owner_lock:
        if _trace_owner is not None:
            return False
        _trace_owner = str(owner)
        return True


def release_device_trace(owner: str) -> bool:
    global _trace_owner
    with _owner_lock:
        if _trace_owner != owner:
            return False
        _trace_owner = None
        return True


def device_trace_owner() -> Optional[str]:
    return _trace_owner


# -- capture state machine ----------------------------------------------------

class _Capture:
    __slots__ = ("steps_total", "logdir", "cleanup", "t_start",
                 "trigger_entry", "trigger_seen", "entry_steps", "started")

    def __init__(self, steps_total: int, logdir: str, cleanup: bool):
        self.steps_total = max(int(steps_total), 1)
        self.logdir = logdir
        self.cleanup = cleanup
        self.t_start = 0.0
        self.trigger_entry: Optional[str] = None
        self.trigger_seen = 0
        self.entry_steps: Dict[str, int] = {}
        self.started = False


_lock = threading.Lock()
_armed: Optional[_Capture] = None       # waiting for the next boundary
_active: Optional[_Capture] = None      # trace live
_hot = False                            # armed or active (hot-path gate)
_last_report: Optional[dict] = None
_last_chrome: list = []
_boundary_count = 0
_every = _env_int("PADDLE_TPU_DEVICE_PROFILE_EVERY", 0)
_window_steps = _env_int("PADDLE_TPU_DEVICE_PROFILE_STEPS", _DEFAULT_STEPS)
_top_k = 10


def configure(every: Optional[int] = None,
              steps: Optional[int] = None) -> None:
    """Override the env-derived trigger cadence / window length
    (tests, notebooks). ``reset()`` re-reads the env."""
    global _every, _window_steps, _hot
    with _lock:
        if every is not None:
            _every = max(int(every), 0)
        if steps is not None:
            _window_steps = max(int(steps), 1)
        _hot = _armed is not None or _active is not None or _every > 0


def _discard(cap: Optional[_Capture]) -> None:
    """Drop a capture's disposable logdir (the mkdtemp ones — a user- or
    env-specified dir is never touched). Every path that abandons a
    capture without finishing it must route here, or armed-then-reset
    cycles leak one temp dir each."""
    if cap is not None and cap.cleanup:
        shutil.rmtree(cap.logdir, ignore_errors=True)


def capture_state() -> str:
    """"idle" | "armed" | "capturing"."""
    with _lock:
        if _active is not None:
            return "capturing"
        if _armed is not None:
            return "armed"
        return "idle"


def last_report() -> Optional[dict]:
    return _last_report


def request_capture(steps: Optional[int] = None,
                    logdir: Optional[str] = None) -> bool:
    """Arm a windowed capture starting at the next step boundary. False
    (warning + ``counter/profile/capture_skipped``) when a capture is
    already armed/active or another component owns the device trace."""
    global _armed, _hot
    n = max(int(steps or _window_steps), 1)
    tel = get_telemetry()
    with _lock:
        if _armed is not None or _active is not None:
            tel.counter("profile/capture_skipped")
            logger.warning(
                "device_profile: capture request (steps=%d) refused — a "
                "capture is already %s; one windowed trace at a time",
                n, "running" if _active is not None else "armed")
            return False
        if device_trace_owner() is not None:
            tel.counter("profile/capture_skipped")
            logger.warning(
                "device_profile: capture request refused — %r holds the "
                "device trace (a profiler window is open)",
                device_trace_owner())
            return False
        if logdir:
            cap = _Capture(n, logdir, cleanup=False)
        else:
            env_dir = os.environ.get("PADDLE_TPU_DEVICE_PROFILE_DIR")
            if env_dir:
                cap = _Capture(n, env_dir, cleanup=False)
            else:
                cap = _Capture(n, tempfile.mkdtemp(
                    prefix="paddle_tpu_devprof_"), cleanup=True)
        _armed = cap
        _hot = True
    return True


def step_boundary(entry: str) -> None:
    """Called by every engine at its step boundary (host side, before
    dispatch). Cheap when cold: one global read."""
    global _boundary_count
    if not _hot:
        return
    with _lock:
        _boundary_count += 1
        if (_active is None and _armed is None and _every > 0
                and _boundary_count % _every == 0):
            # env-cadence trigger: arm in place (inline, lock held)
            _arm_from_env_locked()
        if _armed is not None and _active is None:
            _start_locked(entry)
            return
        cap = _active
        if cap is None:
            return
        cap.entry_steps[entry] = cap.entry_steps.get(entry, 0) + 1
        if entry == cap.trigger_entry:
            cap.trigger_seen += 1
            if cap.trigger_seen >= cap.steps_total:
                _stop_locked(cap)


def _arm_from_env_locked() -> None:
    global _armed, _hot
    if device_trace_owner() is not None:
        get_telemetry().counter("profile/capture_skipped")
        return
    env_dir = os.environ.get("PADDLE_TPU_DEVICE_PROFILE_DIR")
    if env_dir:
        _armed = _Capture(_window_steps, env_dir, cleanup=False)
    else:
        _armed = _Capture(_window_steps, tempfile.mkdtemp(
            prefix="paddle_tpu_devprof_"), cleanup=True)
    _hot = True


def _start_locked(entry: str) -> None:
    """Begin the armed capture at this boundary (lock held)."""
    global _armed, _active
    cap = _armed
    if cap is None:
        return
    if not acquire_device_trace("device_profile"):
        get_telemetry().counter("profile/capture_skipped")
        logger.warning("device_profile: cannot start capture — device "
                       "trace held by %r", device_trace_owner())
        _discard(cap)
        _armed = None
        _refresh_hot_locked()
        return
    try:
        import jax

        os.makedirs(cap.logdir, exist_ok=True)
        jax.profiler.start_trace(cap.logdir)
    except Exception as e:  # noqa: BLE001 — profiling never kills a run
        release_device_trace("device_profile")
        get_telemetry().counter("profile/capture_failed")
        logger.warning("device_profile: jax.profiler.start_trace failed "
                       "(%s) — capture dropped", e)
        _discard(cap)
        _armed = None
        _refresh_hot_locked()
        return
    cap.started = True
    cap.t_start = time.perf_counter()
    # the starting boundary is the step's BEGINNING: zero steps have
    # completed inside the window yet — each LATER boundary of the
    # trigger entry marks one completed step
    cap.trigger_entry = entry
    cap.trigger_seen = 0
    _armed = None
    _active = cap


def _stop_locked(cap: _Capture) -> None:
    """End the window at this boundary: stop the trace, attribute,
    publish (lock held — boundary calls are engine-loop serialized, and
    parsing one small windowed trace is an explicitly requested cost)."""
    global _active
    wall_ms = (time.perf_counter() - cap.t_start) * 1e3
    tel = get_telemetry()
    try:
        import jax

        jax.profiler.stop_trace()
    except Exception as e:  # noqa: BLE001
        tel.counter("profile/capture_failed")
        logger.warning("device_profile: jax.profiler.stop_trace failed "
                       "(%s)", e)
        _active = None
        release_device_trace("device_profile")
        _refresh_hot_locked()
        return
    _active = None
    release_device_trace("device_profile")
    _refresh_hot_locked()
    try:
        _finish_capture(cap, wall_ms, tel)
    except Exception as e:  # noqa: BLE001 — attribution is best-effort
        tel.counter("profile/capture_failed")
        logger.warning("device_profile: attribution failed (%s) — raw "
                       "trace %s", e,
                       cap.logdir if not cap.cleanup else "discarded")
    finally:
        if cap.cleanup:
            shutil.rmtree(cap.logdir, ignore_errors=True)


def _refresh_hot_locked() -> None:
    global _hot
    _hot = _armed is not None or _active is not None or _every > 0


def _finish_capture(cap: _Capture, wall_ms: float, tel) -> None:
    global _last_report, _last_chrome
    trace = hlo_attrib.load_trace(cap.logdir)
    if trace is None:
        tel.counter("profile/capture_failed")
        return
    # steps for windowed entries: one boundary may cover N compiled
    # steps (executor.run_steps / fleet.train_step_multi) — scale by the
    # registered steps-per-call so per-step numbers stay per-STEP
    from . import xla_cost

    steps = {e: n * xla_cost.cost_registry().steps_per_call(e)
             for e, n in cap.entry_steps.items()}
    texts = xla_cost.hlo_texts()
    report = hlo_attrib.attribute_trace(
        trace, texts, steps=steps, wall_ms=wall_ms,
        trigger_entry=cap.trigger_entry,
        default_steps=max(steps.get(cap.trigger_entry or "", 1), 1))
    if report is None:
        tel.counter("profile/capture_failed")
        return
    tel.counter("profile/captures")
    _last_report = report.to_dict(top_k=_top_k)
    _last_chrome = _chrome_from_trace(trace, cap, report)
    publish(tel)
    try:
        # join the per-op device ms against the compiled-HLO collective
        # inventory: gauge/collective/<axis>/ms.<entry> — the measured
        # half of the per-axis attribution (static bytes/count ride
        # along), and the evidence the comm_bound:<axis> verdict
        # refinement reads
        from . import collective_attrib

        collective_attrib.on_capture(report, tel)
    except Exception:  # noqa: BLE001 — attribution is best-effort
        pass
    try:
        # fold the fresh decomposition with the roofline/MFU gauges into
        # bottleneck verdicts NOW — a /metrics scrape right after the
        # window closes must already carry gauge/bottleneck/<entry>
        from . import bottleneck

        xla_cost.publish_mfu(tel)
        bottleneck.publish(tel)
    except Exception:  # noqa: BLE001
        pass
    logger.info(
        "device_profile: captured %d step(s) of %s — wall %.2f ms, "
        "device %.2f ms, host gap %.2f ms",
        cap.steps_total, cap.trigger_entry, report.wall_ms,
        report.device_total_ms, report.host_gap_ms)


def _chrome_from_trace(trace: dict, cap: _Capture,
                       report, max_events: int = 512) -> list:
    """Device-op slices for the chrome export, realigned onto the host
    perf_counter epoch the PR 5 spans use (trace timestamps live on
    XLA's own clock): the earliest device event maps to the capture's
    start boundary. Top-N by duration, bounded."""
    events = hlo_attrib.device_events(
        trace, known_names=set().union(
            *(set(a.by_op) for a in report.entries.values())) or None)
    events = sorted(events, key=lambda e: -e.get("dur", 0))[:max_events]
    if not events:
        return []
    from .spans import rank_pid

    t0 = min(e.get("ts", 0) for e in events)
    base_us = cap.t_start * 1e6
    pid = rank_pid()  # rank-scoped like every chrome export (merge-safe)
    out = []
    for e in events:
        out.append({"name": e.get("name", "?"), "ph": "X",
                    "ts": base_us + (e.get("ts", 0) - t0),
                    "dur": e.get("dur", 0), "pid": pid,
                    "tid": "device ops", "cat": "device",
                    "args": {"entry": report.dominant_entry}})
    return out


def publish(telemetry=None) -> Dict[str, dict]:
    """Refresh the profile gauges from the last report (hooked from
    ``Telemetry.to_jsonl`` like ``publish_mfu``). Returns
    ``{entry: fractions}`` for programmatic callers."""
    rep = _last_report
    if not rep:
        return {}
    tel = telemetry or get_telemetry()
    tel.gauge("profile/wall_ms", rep["wall_ms"])
    tel.gauge("profile/device_total_ms", rep["device_total_ms"])
    out: Dict[str, dict] = {}
    for entry, att in rep.get("entries", {}).items():
        fr = att.get("fractions", {})
        for key, v in fr.items():
            tel.gauge(f"profile/{key}.{entry}", v)
        out[entry] = fr
    return out


def jsonl_payload() -> Optional[dict]:
    """The structured top-K report for the JSONL record (merged as a
    top-level ``"profile"`` key by ``Telemetry.to_jsonl``)."""
    return dict(_last_report) if _last_report else None


def chrome_events(drain: bool = True) -> list:
    """Realigned device-op slices of the last capture for the chrome
    export (drained by default — each export owns its window)."""
    global _last_chrome
    out = list(_last_chrome)
    if drain:
        _last_chrome = []
    return out


def reset() -> None:
    """Forget reports and re-read the env knobs (test isolation; hooked
    from ``Telemetry.reset``). An in-flight capture is abandoned: its
    trace is stopped and discarded."""
    global _armed, _active, _last_report, _last_chrome, _boundary_count
    global _every, _window_steps
    with _lock:
        if _active is not None:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception:  # noqa: BLE001
                pass
            release_device_trace("device_profile")
            _discard(_active)
        _discard(_armed)  # an armed-but-unstarted capture owns a dir too
        _armed = None
        _active = None
        _last_report = None
        _last_chrome = []
        _boundary_count = 0
        _every = _env_int("PADDLE_TPU_DEVICE_PROFILE_EVERY", 0)
        _window_steps = _env_int("PADDLE_TPU_DEVICE_PROFILE_STEPS",
                                 _DEFAULT_STEPS)
        _refresh_hot_locked()
