"""Cluster timeline: fuse per-rank trace artifacts into ONE view and
name the late rank.

Distributed stalls are invisible from any single rank: the straggler's
own timeline looks busy, every peer's looks idle-inside-a-collective.
The reference shipped a post-hoc multi-trainer timeline tool
(fluid ``tools/timeline.py``) for exactly this reason. This module is
the axis-aware, gated version over our artifacts:

- **per-rank inputs** (all under one job ``log_dir``):
  ``trace.rank<i>.json`` chrome exports (``utils.profiler
  .export_chrome_tracing`` — rank-stamped pids since this PR),
  ``collectives.rank<i>.jsonl`` eager-collective event logs
  (``distributed.communication`` recorder, armed by
  ``PADDLE_TPU_COLLECTIVE_LOG``), and ``clock.rank<i>.json`` clock
  handshakes;
- **clock offsets**: :func:`clock_handshake` runs K barrier-echo rounds
  over the existing ``all_gather_object`` transport — each round every
  rank records when its gather COMPLETED; completion is within one poll
  quantum of the same global instant on every rank, so the median
  per-round delta to rank 0 estimates this rank's ``perf_counter``
  offset (error ≈ the handshake poll interval, reported alongside);
- **collective instances**: eager collectives execute in the same order
  on every rank (SPMD), so the recorder's per-rank sequence numbers
  identify instances. Per instance, each rank's aligned ARRIVAL time
  yields its skew vs the earliest rank — the late rank by name
  ("rank 3 late 41 ms into all-reduce #17, axis dp");
- **one merged chrome trace**: per-rank process tracks (pid = rank,
  ``process_name`` metadata), offset-aligned timestamps, per-instance
  collective slices and flow arrows binding the same instance across
  ranks.

Offline pieces are stdlib-only (``tools/telemetry_agg.py`` loads this
file standalone, like ``aggregate.py``); only :func:`clock_handshake`
touches the framework, lazily. LATE-RANK findings surface through
``aggregate.detect_late_ranks`` / ``tools/telemetry_agg.py
--fail-on-late-rank`` and the ``tools/check_cluster_timeline.py`` gate.

Offset-estimation caveats (README "Operations plane" has the operator
view): the estimate rides the rendezvous transport's poll quantum — use
a small handshake ``poll_s`` (default 5 ms) and judge skews only well
above ``offset_error_s``; clocks are assumed drift-free over the run
(re-run the handshake near the window of interest for long jobs).
"""
from __future__ import annotations

import glob
import json
import os
import re
import statistics
import time
from typing import Dict, List, Optional, Sequence

__all__ = [
    "clock_handshake", "load_clock_files", "estimate_offsets",
    "load_collective_logs", "collective_instances", "merge_chrome_traces",
    "write_merged_trace", "analyze", "trace_paths",
    "CLOCK_FILE", "COLLECTIVES_FILE", "TRACE_FILE", "DEFAULT_LATE_MS",
]

CLOCK_FILE = "clock.rank{rank}.json"
COLLECTIVES_FILE = "collectives.rank{rank}.jsonl"
TRACE_FILE = "trace.rank{rank}.json"

# arrival skew above this names a late rank (well above the handshake
# poll quantum + scheduling jitter of the CPU gate topology; real
# cross-host runs may tighten it via --late-ms / analyze(threshold_ms=))
DEFAULT_LATE_MS = 100.0

_RANK_RE = re.compile(r"rank(\d+)")


def _rank_of(path: str, fallback: int) -> int:
    m = _RANK_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else fallback


# -- in-run: the barrier-echo clock handshake ---------------------------------

def clock_handshake(out_dir: str, rounds: int = 8,
                    rendezvous_dir: Optional[str] = None,
                    poll_s: float = 0.005, timeout_s: float = 60.0,
                    key_prefix: str = "clocksync") -> dict:
    """Run K barrier-echo rounds over ``all_gather_object`` and write
    this rank's ``clock.rank<r>.json`` under ``out_dir``. Every rank of
    the job must call it (it IS a collective); call it near the window
    being analyzed — the offline merge assumes drift-free clocks between
    handshake and events. Returns this rank's record."""
    from ..distributed.communication import all_gather_object, \
        launch_world_rank

    world, rank = launch_world_rank()
    rows = []
    for k in range(int(rounds)):
        t_send = time.perf_counter()
        all_gather_object({"rank": rank, "t_send": t_send},
                          key=f"{key_prefix}.{k}",
                          rendezvous_dir=rendezvous_dir,
                          timeout_s=timeout_s, poll_s=poll_s,
                          cleanup_prev=True)
        # the gather completes within one poll quantum of the same
        # global instant on every rank — t_done is the echo the offline
        # offset estimate is built from
        rows.append({"t_send": t_send, "t_done": time.perf_counter()})
    rec = {"rank": rank, "world": world, "rounds": rows,
           "poll_s": float(poll_s), "pid": os.getpid()}
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, CLOCK_FILE.format(rank=rank))
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(rec, f)
    os.replace(tmp, path)
    return rec


# -- offline: loading ---------------------------------------------------------

def load_clock_files(log_dir: str) -> Dict[int, dict]:
    out: Dict[int, dict] = {}
    for i, path in enumerate(sorted(glob.glob(
            os.path.join(log_dir, "clock.rank*.json")))):
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        out[_rank_of(path, i)] = rec
    return out


def estimate_offsets(clock: Dict[int, dict]
                     ) -> Dict[int, Dict[str, float]]:
    """``{rank: {offset_s, error_s}}`` — rank r's ``perf_counter``
    minus rank 0's at the same instant (subtract ``offset_s`` from
    rank r's local timestamps to land on rank 0's clock). Median over
    rounds; ``error_s`` is the half-spread of the per-round deltas
    (bounded by the handshake poll quantum plus scheduling jitter)."""
    if 0 not in clock:
        return {r: {"offset_s": 0.0, "error_s": float("inf")}
                for r in clock}
    base = [row["t_done"] for row in clock[0].get("rounds", [])]
    out: Dict[int, Dict[str, float]] = {}
    for rank, rec in clock.items():
        rows = rec.get("rounds", [])
        deltas = [row["t_done"] - b
                  for row, b in zip(rows, base)
                  if isinstance(row.get("t_done"), (int, float))]
        if not deltas:
            out[rank] = {"offset_s": 0.0, "error_s": float("inf")}
            continue
        out[rank] = {
            "offset_s": float(statistics.median(deltas)),
            "error_s": float((max(deltas) - min(deltas)) / 2.0),
        }
    return out


def load_collective_logs(log_dir: str) -> Dict[int, List[dict]]:
    """``{rank: [event]}`` from the recorder's per-rank JSONL (events
    carry seq/name/axis/t_start/dur_s/nbytes). Torn tail lines (a
    killed rank mid-write) are skipped, not fatal."""
    out: Dict[int, List[dict]] = {}
    for i, path in enumerate(sorted(glob.glob(
            os.path.join(log_dir, "collectives.rank*.jsonl")))):
        events = []
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        ev = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(ev, dict) and "seq" in ev:
                        events.append(ev)
        except OSError:
            continue
        out[_rank_of(path, i)] = events
    return out


# -- offline: instance fusion + skew ------------------------------------------

def collective_instances(rank_events: Dict[int, List[dict]],
                         offsets: Optional[Dict[int, dict]] = None
                         ) -> List[dict]:
    """Fuse per-rank recorder events into per-INSTANCE rows. Eager
    collectives run in program order on every rank, so equal sequence
    numbers are the same instance; an instance only forms when every
    reporting rank logged that seq (a missing rank is a dead-rank
    problem, not a skew). Arrival/end times are offset-aligned onto
    rank 0's clock; ``skew_ms[rank]`` is the rank's arrival lag behind
    the earliest rank."""
    offsets = offsets or {}
    ranks = sorted(rank_events)
    if not ranks:
        return []
    by_seq: Dict[int, Dict[int, dict]] = {}
    for rank, events in rank_events.items():
        for ev in events:
            by_seq.setdefault(int(ev["seq"]), {})[rank] = ev
    out: List[dict] = []
    for seq in sorted(by_seq):
        per_rank = by_seq[seq]
        if set(per_rank) != set(ranks):
            continue
        arrivals, ends, durs = {}, {}, {}
        for rank, ev in per_rank.items():
            off = float(offsets.get(rank, {}).get("offset_s", 0.0))
            t0 = float(ev.get("t_start", 0.0)) - off
            dur = float(ev.get("dur_s", 0.0))
            arrivals[rank] = t0
            ends[rank] = t0 + dur
            durs[rank] = dur
        first = min(arrivals.values())
        names = {ev.get("name", "?") for ev in per_rank.values()}
        name = per_rank[ranks[0]].get("name", "?") \
            if len(names) == 1 else "mixed:" + "/".join(sorted(names))
        out.append({
            "seq": seq,
            "name": name,
            "axis": per_rank[ranks[0]].get("axis", "world"),
            "arrivals": arrivals,
            "ends": ends,
            "durs": durs,
            "skew_ms": {r: (arrivals[r] - first) * 1e3 for r in arrivals},
            "end_spread_ms": (max(ends.values()) - min(ends.values())) * 1e3,
            # the job's FIRST common collective is its startup
            # synchronization point: its arrival skew measures import/
            # compile-time differences, not a straggler — flagged so
            # detect_late_ranks can skip it (every later instance starts
            # from the aligned exit of the previous one)
            "startup": False,
        })
    if out:
        out[0]["startup"] = True
    return out


# -- offline: the merged chrome trace -----------------------------------------

def trace_paths(log_dir: str) -> Dict[int, str]:
    return {_rank_of(p, i): p
            for i, p in enumerate(sorted(glob.glob(
                os.path.join(log_dir, "trace.rank*.json"))))}


def merge_chrome_traces(traces: Dict[int, str],
                        offsets: Optional[Dict[int, dict]] = None,
                        instances: Optional[Sequence[dict]] = None) -> dict:
    """One chrome trace from per-rank exports: every rank becomes its
    own process track (pid = rank + ``process_name`` metadata —
    pre-stamped pids are overridden so hand-merged mixed-vintage
    artifacts cannot collide), timestamps are shifted onto rank 0's
    clock, and each collective instance contributes per-rank slices on
    a ``collectives`` lane plus flow arrows binding the instance across
    ranks (the arrow points from the earliest arrival to each later
    one — the visual form of the skew table). Events are sorted by
    timestamp, so the merged timeline is monotonic by construction."""
    offsets = offsets or {}
    meta_events: List[dict] = []
    events: List[dict] = []
    for rank, path in sorted(traces.items()):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        off_us = float(offsets.get(rank, {}).get("offset_s", 0.0)) * 1e6
        meta_events.append({"name": "process_name", "ph": "M", "pid": rank,
                            "args": {"name": f"rank {rank}"}})
        meta_events.append({"name": "process_sort_index", "ph": "M",
                            "pid": rank, "args": {"sort_index": rank}})
        for ev in doc.get("traceEvents", []):
            ev = dict(ev)
            if ev.get("ph") == "M":
                if ev.get("name") in ("process_name", "process_sort_index"):
                    continue  # re-stamped above on the merged pid
                ev["pid"] = rank
                meta_events.append(ev)
                continue
            if isinstance(ev.get("ts"), (int, float)):
                ev["ts"] = float(ev["ts"]) - off_us
            ev["pid"] = rank
            events.append(ev)
    for inst in instances or []:
        first_rank = min(inst["arrivals"], key=inst["arrivals"].get)
        label = f'{inst["name"]} #{inst["seq"]}'
        for rank, t0 in inst["arrivals"].items():
            events.append({
                "name": label, "ph": "X", "ts": t0 * 1e6,
                "dur": max(inst["durs"].get(rank, 0.0), 0.0) * 1e6,
                "pid": rank, "tid": "collectives", "cat": "collective",
                "args": {"seq": inst["seq"], "axis": inst["axis"],
                         "skew_ms": round(inst["skew_ms"][rank], 3)}})
            flow = {"name": label, "cat": "collective_flow",
                    "id": int(inst["seq"]), "pid": rank,
                    "tid": "collectives", "ts": t0 * 1e6}
            if rank == first_rank:
                events.append({**flow, "ph": "s"})
            else:
                events.append({**flow, "ph": "f", "bp": "e"})
    events.sort(key=lambda e: (float(e.get("ts", 0.0)),
                               str(e.get("ph", ""))))
    return {"traceEvents": meta_events + events,
            "displayTimeUnit": "ms",
            "metadata": {
                "clock_offsets_s": {str(r): o.get("offset_s", 0.0)
                                    for r, o in (offsets or {}).items()},
                "ranks": sorted(traces),
            }}


def write_merged_trace(path: str, merged: dict) -> str:
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(merged, f)
    os.replace(tmp, path)
    return path


# -- offline: one-call analysis ----------------------------------------------

def analyze(log_dir: str, threshold_ms: float = DEFAULT_LATE_MS,
            merged_path: Optional[str] = None) -> dict:
    """The whole pipeline over one job's ``log_dir``: offsets from the
    clock handshakes (identity + infinite error when absent — skews are
    then raw and flagged ``offsets_estimated: false``), collective
    instances with per-rank skews, LATE-RANK findings past
    ``threshold_ms`` (one finding per late rank, naming its worst
    instance and counting the rest), and — when ``merged_path`` is set —
    the merged chrome trace written there."""
    clock = load_clock_files(log_dir)
    offsets = estimate_offsets(clock) if clock else {}
    rank_events = load_collective_logs(log_dir)
    instances = collective_instances(rank_events, offsets)
    # blame needs ALIGNED clocks: every rank with events must have a
    # finite-error offset estimate, or the "skews" are differences of
    # unrelated perf_counter epochs — fabricated lateness. Skipping
    # (with the reason) beats gating CI on garbage.
    skip_reason = None
    if not clock:
        skip_reason = ("no clock.rank*.json handshake artifacts — run "
                       "cluster_trace.clock_handshake on every rank")
    else:
        unaligned = [r for r in rank_events
                     if not (offsets.get(r, {}).get("error_s",
                                                    float("inf"))
                             < float("inf"))]
        if unaligned:
            skip_reason = (f"rank(s) {unaligned} have no finite clock-"
                           f"offset estimate (missing/torn handshake "
                           f"file, or rank 0's is gone)")
    findings = [] if skip_reason else detect_late_ranks(instances,
                                                        threshold_ms)
    result = {
        "log_dir": log_dir,
        "ranks": sorted(rank_events),
        "offsets_estimated": skip_reason is None,
        "offsets": {str(r): o for r, o in offsets.items()},
        "n_instances": len(instances),
        "instances": instances,
        "threshold_ms": float(threshold_ms),
        "late_ranks": findings,
    }
    if skip_reason:
        result["late_rank_analysis_skipped"] = skip_reason
    if merged_path:
        merged = merge_chrome_traces(trace_paths(log_dir), offsets,
                                     instances)
        result["merged_trace"] = write_merged_trace(merged_path, merged)
        result["merged_events"] = len(merged["traceEvents"])
    return result


def detect_late_ranks(instances: Sequence[dict],
                      threshold_ms: float = DEFAULT_LATE_MS) -> List[dict]:
    """One finding per rank whose arrival skew exceeded ``threshold_ms``
    on any instance: the worst instance named (seq, collective name,
    axis, skew) plus the count of late instances. Sorted worst-first.
    (``profiler.aggregate.detect_late_ranks`` delegates here — this is
    the one implementation.)"""
    worst: Dict[int, dict] = {}
    counts: Dict[int, int] = {}
    for inst in instances:
        if inst.get("startup"):
            continue  # startup sync absorbs import/compile-time skew
        for rank, skew in inst["skew_ms"].items():
            if skew <= float(threshold_ms):
                continue
            counts[rank] = counts.get(rank, 0) + 1
            cur = worst.get(rank)
            if cur is None or skew > cur["skew_ms"]:
                worst[rank] = {"seq": inst["seq"], "name": inst["name"],
                               "axis": inst["axis"],
                               "skew_ms": float(skew)}
    findings = [{"rank": rank, "late_instances": counts[rank],
                 "threshold_ms": float(threshold_ms), "worst": w}
                for rank, w in worst.items()]
    findings.sort(key=lambda f: -f["worst"]["skew_ms"])
    return findings
