"""Retrace/compile tracking for jitted entry points.

Silent XLA retraces are the classic JAX production regression: a feed
whose shape drifts batch-to-batch recompiles the step program every
iteration and throughput falls off a cliff with no error anywhere.
``tracked_jit`` wraps ``jax.jit`` so every compilation is *counted*
(``counter compile/<name>``), *timed* (``hist compile_ms/<name>`` — the
wall time of the triggering call, which is dominated by trace+compile),
and *warned about* through a rate-limited logger once a function has
compiled more than ``PADDLE_TPU_RETRACE_WARN`` times (default 3; ``0``
disables the warning).

Compilations are detected by the abstract signature of the call — the
(shape, dtype, weak_type) of every array leaf, the type of Python-scalar
leaves, and the pytree structure — the dominant drivers of jax.jit's
tracing cache. This is deliberately independent of private jax cache
APIs so counts are deterministic and testable; exotic cache keys the
signature cannot see (e.g. sharding-driven recompiles under some
configs) may undercount, never overcount.
"""
from __future__ import annotations

import logging
import os
import time
import weakref
from typing import Optional

import jax

from .telemetry import get_telemetry

__all__ = ["tracked_jit", "RetraceTracker", "retrace_warn_threshold",
           "reset_trackers"]

logger = logging.getLogger("paddle_tpu.profiler")

_WARN_EVERY_S = 30.0  # at most one retrace warning per function per 30 s

# every live tracker, so Telemetry.reset() can clear per-function compile
# state: without this, back-to-back tests/benches in one process inherit
# retrace counts (compile/<name> counters reset but tracker.compiles did
# not, so the next retrace-warning threshold fired early and gates read
# stale per-function totals)
_trackers: "weakref.WeakSet[RetraceTracker]" = weakref.WeakSet()


def reset_trackers() -> None:
    """Zero every tracker's compile count and forget seen signatures.
    Hooked from ``Telemetry.reset()``. A signature seen before the reset
    counts as a fresh compile after it — jax's own cache may satisfy it
    instantly, but the accounting starts from zero, which is what test
    isolation needs."""
    for t in list(_trackers):
        t.reset()


def retrace_warn_threshold() -> int:
    try:
        return int(os.environ.get("PADDLE_TPU_RETRACE_WARN", "3"))
    except ValueError:
        return 3


def _leaf_signature(x):
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        # weak_type participates in jit's cache key: a weak f32 scalar and
        # a strong one of the same shape/dtype trace separately
        return (tuple(x.shape), str(x.dtype),
                bool(getattr(x, "weak_type", False)))
    if isinstance(x, (bool, int, float, complex)):
        # jax traces Python scalars as weak-typed 0-d DYNAMIC values: a
        # new VALUE does not retrace, only a new type does — keying on
        # the value would report a false compile every step for e.g. a
        # host-side lr float
        return ("pyscalar", type(x).__name__)
    return (type(x).__name__, repr(x))


class RetraceTracker:
    """Per-function compile bookkeeping shared by every tracked_jit
    wrapper with the same ``name`` (cross-instance counts aggregate in
    telemetry; signatures are tracked per tracker)."""

    def __init__(self, name: str):
        self.name = name
        self._signatures = set()
        self.compiles = 0
        self._last_warn = 0.0
        _trackers.add(self)

    def reset(self) -> None:
        self._signatures.clear()
        self.compiles = 0
        self._last_warn = 0.0

    def signature_of(self, args, kwargs):
        """Hash digest of the call's abstract signature. Only the digest
        is kept: storing the full per-call signature tuple (thousands of
        leaves for a large model's params/opt-state) would leak one big
        tuple per retrace — exactly in the drifting-shape pathology this
        tracker exists to catch."""
        leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
        return hash((treedef, tuple(_leaf_signature(l) for l in leaves)))

    def seen(self, sig) -> bool:
        return sig in self._signatures

    def commit(self, sig) -> None:
        """Register a signature whose compile COMPLETED. Called after the
        jitted call returns — a call that raises mid-compile (OOM, TPU
        compile-service rejection) must not mark its signature compiled,
        or the retry would count as a cache hit and its compile time
        would pollute the dispatch histograms."""
        self._signatures.add(sig)
        self.compiles += 1
        tel = get_telemetry()
        tel.counter(f"compile/{self.name}")
        threshold = retrace_warn_threshold()
        if threshold and self.compiles > threshold:
            now = time.monotonic()
            if now - self._last_warn >= _WARN_EVERY_S:
                self._last_warn = now
                logger.warning(
                    "jitted function %r compiled %d times (threshold %d) — "
                    "an input shape/dtype is drifting call-to-call and every "
                    "drift pays a full XLA retrace+compile; pad or bucket "
                    "the offending input [tpu-lint R3: tools/tpu_lint.py "
                    "flags this hazard statically] (warning rate-limited "
                    "to one per %.0f s)", self.name, self.compiles, threshold,
                    _WARN_EVERY_S)


def tracked_jit(fn=None, *, name: Optional[str] = None,
                sig_argnums: Optional[tuple] = None, **jit_kwargs):
    """``jax.jit`` with compile telemetry. Drop-in: accepts every jit
    kwarg (donate_argnums, out_shardings, static_argnums, ...) and works
    bare or as a decorator factory::

        step = tracked_jit(step_fn, name="fleet.train_step",
                           donate_argnums=(0, 2))

    ``sig_argnums`` limits signature hashing to those positional args
    (an index tuple, or a ``slice`` for "everything from position k on")
    — the engines pass only the drift-capable inputs (batch, lr), since
    flattening a large model's params/opt-state pytree every call would
    put O(n_leaves) host work on the dispatch hot path. Signatures of
    the excluded args are assumed stable after construction (true for
    engine-owned state); a drift there undercounts, never overcounts.

    The wrapper exposes ``.tracker`` (compile count / signatures) and
    ``.jitted`` (the underlying jax.jit object, for ``.lower`` etc.).
    """
    if fn is None:
        return lambda f: tracked_jit(f, name=name, sig_argnums=sig_argnums,
                                     **jit_kwargs)

    label = name or getattr(fn, "__name__", "jit_fn")
    jitted = jax.jit(fn, **jit_kwargs)
    tracker = RetraceTracker(label)
    tel = get_telemetry()

    def wrapper(*args, **kwargs):
        if not tel.enabled:  # telemetry off ⇒ zero hot-path overhead
            return jitted(*args, **kwargs)
        if sig_argnums is None:
            sig_args = args
        elif isinstance(sig_argnums, slice):
            sig_args = args[sig_argnums]
        else:
            sig_args = tuple(args[i] for i in sig_argnums if i < len(args))
        sig = tracker.signature_of(sig_args, kwargs)
        if tracker.seen(sig):
            return jitted(*args, **kwargs)
        t0 = time.perf_counter()
        # goodput: the same region compile_ms times — an unseen
        # signature's triggering call is (re)trace + XLA compile, badput
        # the wall-clock ledger must own (nested under the step's claim)
        from . import goodput

        with goodput.activity("compile"):
            out = jitted(*args, **kwargs)  # raises ⇒ signature NOT committed
        tracker.commit(sig)
        # the triggering call's wall time ≈ trace+compile (+1 run):
        # the honest host-visible cost of the retrace
        tel.observe(f"compile_ms/{label}",
                    (time.perf_counter() - t0) * 1e3)
        # attribution: cost-analyze the executable this compile produced
        # (flops/HBM -> MFU). After the call on purpose: lower() reads
        # only avals, so donated (deleted) buffers are safe, and a failed
        # compile never reaches here.
        from . import xla_cost

        xla_cost.capture(label, jitted, args, kwargs)
        return out

    wrapper.__name__ = f"tracked_{label}"
    wrapper.tracker = tracker
    wrapper.jitted = jitted
    return wrapper
