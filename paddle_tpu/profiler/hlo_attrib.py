"""HLO ↔ device-trace attribution: per-op / per-source-line / per-category
step-time decomposition.

The promoted, tested library form of ``tools/attribute_profile.py`` (the
one-off script the r4/r5 perf rounds ran by hand). It answers *where a
step's device time went* by joining two artifacts the framework already
produces:

- the **compiled HLO text** of every ``tracked_jit`` entry — op names,
  ``metadata={op_name=... source_file=... source_line=...}`` — captured
  at compile time into the :class:`HloRegistry` by ``xla_cost.capture``
  (full mode stores the optimized text the compile already produced; the
  default mode stores the in-hand ``Lowered`` and compiles to text only
  when a profile actually asks — never a second lowering);
- a **jax.profiler trace** (``.trace.json.gz``) covering a window of
  steps — per-op device durations in the "XLA Ops" lanes on TPU, or the
  thunk-executor per-op events the CPU runtime emits (names match the
  optimized HLO either way).

``attribute_trace`` joins them into an :class:`AttributionReport`:
per-op and per-source-line tables, per-category totals (compute /
collective / h2d-d2h transfer), the host gap (wall time the device sat
idle inside the window), and per-entry fractions whose sum is ≤ 1 by
construction. ``device_profile`` drives it live; the CLI wrapper keeps
the old script's interface for post-hoc use.

Failure contract: parsing is **best-effort** — a malformed / empty /
truncated trace degrades to a warning and ``None``, never an exception
mid-training (profiling must not kill the run it is explaining).
"""
from __future__ import annotations

import dataclasses
import glob
import gzip
import json
import logging
import os
import re
import threading
from typing import Dict, List, Optional, Tuple

from ..analysis.hlo import parsing as _hloparse
from .telemetry import get_telemetry

__all__ = [
    "HloOp", "parse_hlo_text", "categorize_opcode",
    "AttributionReport", "EntryAttribution", "attribute_trace",
    "load_trace", "newest_trace_path", "device_events",
    "HloRegistry", "hlo_registry", "CATEGORIES",
]

logger = logging.getLogger("paddle_tpu.profiler")

# the closed category vocabulary of the device-side decomposition; the
# host gap (wall - device busy) is the fourth, computed, category
CATEGORIES = ("compute", "collective", "transfer")

_COLLECTIVE_OPCODES = {
    "all-reduce", "all-gather", "all-to-all", "reduce-scatter",
    "collective-permute", "collective-broadcast",
    "all-reduce-start", "all-reduce-done",
    "all-gather-start", "all-gather-done",
    "collective-permute-start", "collective-permute-done",
    "send", "send-done", "recv", "recv-done",
}
_TRANSFER_OPCODES = {
    "copy-start", "copy-done", "infeed", "outfeed",
}


def categorize_opcode(opcode: str, name: str = "") -> str:
    """Map an HLO opcode (or, for unattributed trace events, a name stem)
    onto the closed category vocabulary."""
    op = (opcode or "").lower()
    if op in _COLLECTIVE_OPCODES:
        return "collective"
    if op in _TRANSFER_OPCODES:
        return "transfer"
    stem = re.sub(r"[.\d]+$", "", (name or "").lower())
    if stem in _COLLECTIVE_OPCODES or any(
            stem.startswith(c + "-fusion") for c in ("all-reduce",
                                                     "all-gather")):
        return "collective"
    if stem in _TRANSFER_OPCODES:
        return "transfer"
    return "compute"


@dataclasses.dataclass
class HloOp:
    """One HLO instruction's identity: where it came from in the model
    source and what it is."""

    name: str
    opcode: str = "?"
    src: str = "?"            # "file.py:123" (basename)
    op_name: str = "?"        # XLA op_name path (jit(...)/.../dot_general)

    @property
    def category(self) -> str:
        return categorize_opcode(self.opcode, self.name)


# the low-level text primitives live in analysis.hlo.parsing (shared
# with the standalone hlo-lint, which must not import the framework —
# so the dependency points this way); historic names kept
_NAME_RE = _hloparse.NAME_RE
_opcode_of = _hloparse.opcode_of


def parse_hlo_text(text: str) -> Dict[str, HloOp]:
    """``{instruction_name: HloOp}`` from optimized HLO text. Tolerant:
    lines without metadata still register (opcode + name only), so trace
    events can at least be categorized and counted."""
    ops: Dict[str, HloOp] = {}
    for name, body, _lineno in _hloparse.iter_instruction_lines(text):
        instr = _hloparse.HloInstr(name=name, opcode=_opcode_of(body),
                                   type_text="", body=body, line=_lineno,
                                   computation="")
        src = instr.source_src()
        ops[name] = HloOp(name=name, opcode=instr.opcode, src=src,
                          op_name=instr.op_name())
    return ops


# -- trace loading ------------------------------------------------------------

def newest_trace_path(logdir: str) -> Optional[str]:
    paths = sorted(glob.glob(
        os.path.join(logdir, "plugins", "profile", "*", "*.trace.json.gz")))
    return paths[-1] if paths else None


def load_trace(path_or_logdir: str) -> Optional[dict]:
    """The parsed trace JSON, or None (with a warning) on any failure —
    missing file, truncated gzip, malformed JSON."""
    path = path_or_logdir
    if os.path.isdir(path_or_logdir):
        path = newest_trace_path(path_or_logdir)
        if path is None:
            logger.warning("hlo_attrib: no .trace.json.gz under %s — "
                           "profiler produced no trace", path_or_logdir)
            return None
    try:
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            trace = json.load(f)
        if not isinstance(trace, dict) or "traceEvents" not in trace:
            raise ValueError("no traceEvents key")
        return trace
    except Exception as e:  # noqa: BLE001 — degrade, never kill the run
        logger.warning("hlo_attrib: unreadable trace %s (%s) — skipping "
                       "attribution for this capture", path, e)
        return None


def device_events(trace: dict,
                  known_names: Optional[set] = None) -> List[dict]:
    """The per-op device events of a trace: every complete ("X") event in
    an "XLA Ops" lane of a device process (the TPU layout). When the
    trace has NO such lanes (XLA:CPU emits per-op thunk events on
    runtime threads instead), fall back to events whose name matches a
    known HLO instruction name — lane membership wins when lanes exist,
    so a host-side event that happens to shadow an HLO name can never
    pollute a real device timeline."""
    events = trace.get("traceEvents") or []
    procs: Dict[int, str] = {}
    op_lanes = set()
    for e in events:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            procs[e["pid"]] = str(e.get("args", {}).get("name", ""))
        elif (e.get("name") == "thread_name"
              and "XLA Ops" in str(e.get("args", {}).get("name", ""))):
            op_lanes.add((e["pid"], e.get("tid")))
    device_pids = {p for p, n in procs.items()
                   if "TPU" in n or "xla" in n.lower()
                   or "/device" in n.lower()}
    lanes = {(p, t) for (p, t) in op_lanes if p in device_pids}
    out = []
    for e in events:
        if e.get("ph") != "X":
            continue
        if lanes:
            if (e.get("pid"), e.get("tid")) in lanes:
                out.append(e)
        elif known_names and e.get("name") in known_names:
            out.append(e)
    return out


# -- the report ---------------------------------------------------------------

@dataclasses.dataclass
class EntryAttribution:
    """One entry's slice of the window: device ms by category plus the
    per-op and per-source-line tables."""

    entry: str
    steps: int = 1
    device_ms: float = 0.0
    category_ms: Dict[str, float] = dataclasses.field(
        default_factory=lambda: {c: 0.0 for c in CATEGORIES})
    by_op: Dict[str, float] = dataclasses.field(default_factory=dict)
    by_line: Dict[str, float] = dataclasses.field(default_factory=dict)
    op_meta: Dict[str, Tuple[str, str, str]] = dataclasses.field(
        default_factory=dict)  # op -> (src, op_name, category)

    def add(self, op: str, src: str, op_name: str, category: str,
            ms: float) -> None:
        self.device_ms += ms
        self.category_ms[category] = self.category_ms.get(category, 0.0) + ms
        self.by_op[op] = self.by_op.get(op, 0.0) + ms
        self.by_line[src] = self.by_line.get(src, 0.0) + ms
        self.op_meta.setdefault(op, (src, op_name, category))

    def top_ops(self, k: int = 10) -> List[dict]:
        rows = sorted(self.by_op.items(), key=lambda kv: -kv[1])[:k]
        denom = max(self.device_ms, 1e-12)
        return [{"op": op, "entry": self.entry,
                 "src": self.op_meta.get(op, ("?",))[0],
                 "op_name": self.op_meta.get(op, ("?", "?"))[1],
                 "category": self.op_meta.get(op, ("?", "?", "compute"))[2],
                 "ms": round(ms, 6),
                 "ms_per_step": round(ms / max(self.steps, 1), 6),
                 "frac": min(round(ms / denom, 6), 1.0)}
                for op, ms in rows]

    def top_lines(self, k: int = 10) -> List[dict]:
        rows = sorted(self.by_line.items(), key=lambda kv: -kv[1])[:k]
        denom = max(self.device_ms, 1e-12)
        return [{"src": src, "entry": self.entry, "ms": round(ms, 6),
                 "ms_per_step": round(ms / max(self.steps, 1), 6),
                 "frac": min(round(ms / denom, 6), 1.0)}
                for src, ms in rows]


@dataclasses.dataclass
class AttributionReport:
    """The whole window's decomposition. ``fractions(entry)`` are of the
    window WALL time, normalized so their sum (with the dominant entry's
    host gap) can never exceed 1 — the schema-gate contract."""

    wall_ms: float
    device_total_ms: float
    entries: Dict[str, EntryAttribution]
    unattributed_ms: float = 0.0
    steps: Dict[str, int] = dataclasses.field(default_factory=dict)
    trigger_entry: Optional[str] = None

    @property
    def dominant_entry(self) -> Optional[str]:
        if not self.entries:
            return None
        return max(self.entries.values(), key=lambda a: a.device_ms).entry

    @property
    def host_gap_ms(self) -> float:
        if self.wall_ms <= 0:
            return 0.0
        return max(self.wall_ms - self.device_total_ms, 0.0)

    def _scale(self) -> float:
        """Device-time → wall-fraction normalizer. When device lanes
        overlap (parallel thunks on CPU, concurrent streams) the summed
        device time can exceed the wall — fractions are scaled down so
        the per-entry sums stay ≤ 1."""
        if self.wall_ms <= 0 or self.device_total_ms <= self.wall_ms:
            return 1.0
        return self.wall_ms / self.device_total_ms

    def fractions(self, entry: str) -> Dict[str, float]:
        """{compute,collective,transfer}_frac (of wall) for ``entry``,
        plus host_gap_frac on the dominant entry only (the gap belongs
        to the window, not to every program in it)."""
        att = self.entries.get(entry)
        if att is None or self.wall_ms <= 0:
            return {}
        s = self._scale() / self.wall_ms
        out = {f"{c}_frac": min(max(att.category_ms.get(c, 0.0) * s, 0.0),
                                1.0)
               for c in CATEGORIES}
        if entry == self.dominant_entry:
            gap = self.host_gap_ms / self.wall_ms
            # never let rounding push the cross-field sum past 1
            gap = min(gap, max(1.0 - sum(out.values()), 0.0))
            out["host_gap_frac"] = gap
        return out

    def reconciliation_error(self) -> float:
        """|sum(category totals) - device_total| / device_total — the
        tested invariant (categories partition the device events, so
        this is ~0 up to float rounding)."""
        # unattributed events are already folded into the dominant
        # entry's categories — the entry sums alone partition the total
        cat = sum(sum(a.category_ms.values()) for a in self.entries.values())
        if self.device_total_ms <= 0:
            return 0.0
        return abs(cat - self.device_total_ms) / self.device_total_ms

    def top_ops(self, k: int = 10) -> List[dict]:
        rows: List[dict] = []
        for att in self.entries.values():
            rows.extend(att.top_ops(k))
        rows.sort(key=lambda r: -r["ms"])
        return rows[:k]

    def to_dict(self, top_k: int = 10) -> dict:
        return {
            "wall_ms": round(self.wall_ms, 6),
            "device_total_ms": round(self.device_total_ms, 6),
            "host_gap_ms": round(self.host_gap_ms, 6),
            "unattributed_ms": round(self.unattributed_ms, 6),
            "trigger_entry": self.trigger_entry,
            "dominant_entry": self.dominant_entry,
            "steps": dict(self.steps),
            "entries": {
                e: {"steps": a.steps,
                    "device_ms": round(a.device_ms, 6),
                    "device_ms_per_step": round(
                        a.device_ms / max(a.steps, 1), 6),
                    "category_ms": {c: round(v, 6)
                                    for c, v in a.category_ms.items()},
                    "fractions": self.fractions(e)}
                for e, a in self.entries.items()},
            "top_ops": self.top_ops(top_k),
            "top_lines": sorted(
                (r for a in self.entries.values()
                 for r in a.top_lines(top_k)),
                key=lambda r: -r["ms"])[:top_k],
        }


def attribute_trace(trace: dict, hlo_by_entry: Dict[str, str],
                    steps: Optional[Dict[str, int]] = None,
                    wall_ms: float = 0.0,
                    trigger_entry: Optional[str] = None,
                    default_steps: int = 1) -> Optional[AttributionReport]:
    """Join one trace with per-entry HLO texts.

    ``steps`` maps entry → step-boundary count inside the window (the
    per-step divisor); entries present in the HLO map but absent from
    ``steps`` divide by ``default_steps``. Events whose name matches no
    entry's HLO land in the dominant entry as ``<unattributed:stem>``
    rows (TPU lanes carry runtime ops the HLO never names). Returns
    ``None`` (warning logged) when the trace yields no device events —
    an empty window is a capture problem, not a 0-of-everything report.
    """
    if trace is None:
        return None
    steps = dict(steps or {})
    metas = {entry: parse_hlo_text(text)
             for entry, text in hlo_by_entry.items() if text}
    name_index: Dict[str, List[str]] = {}
    for entry, meta in metas.items():
        for name in meta:
            name_index.setdefault(name, []).append(entry)
    known = set(name_index)
    events = device_events(trace, known_names=known)
    if not events:
        logger.warning(
            "hlo_attrib: trace carries no attributable device events "
            "(no 'XLA Ops' lanes and no event matching a registered "
            "entry's HLO instruction names)")
        return None
    # dominance by matched device time decides ambiguous names later, so
    # first pass: unambiguous totals per entry
    entry_time: Dict[str, float] = {}
    for e in events:
        owners = name_index.get(e.get("name", ""))
        if owners and len(owners) == 1:
            entry_time[owners[0]] = (entry_time.get(owners[0], 0.0)
                                     + e.get("dur", 0) / 1e3)
    dominant = (max(entry_time, key=entry_time.get) if entry_time
                else (trigger_entry or (sorted(metas)[0] if metas else None)))
    report = AttributionReport(wall_ms=float(wall_ms), device_total_ms=0.0,
                               entries={}, steps=steps,
                               trigger_entry=trigger_entry)

    def _att(entry: str) -> EntryAttribution:
        a = report.entries.get(entry)
        if a is None:
            a = report.entries[entry] = EntryAttribution(
                entry=entry, steps=max(int(steps.get(entry,
                                                     default_steps)), 1))
        return a

    for e in events:
        name = e.get("name", "")
        dur_ms = e.get("dur", 0) / 1e3
        report.device_total_ms += dur_ms
        owners = name_index.get(name)
        if owners:
            entry = owners[0] if len(owners) == 1 else (
                dominant if dominant in owners else owners[0])
            op = metas[entry][name]
            _att(entry).add(name, op.src, op.op_name, op.category, dur_ms)
        elif dominant is not None:
            stem = re.sub(r"[.\d]+$", "", name)
            cat = categorize_opcode("", name)
            _att(dominant).add(f"<unattributed:{stem}>", "?", "?", cat,
                               dur_ms)
            report.unattributed_ms += dur_ms
    return report


# -- the compile-time HLO registry --------------------------------------------

class HloRegistry:
    """Latest compiled-HLO artifact per tracked_jit entry, fed by
    ``xla_cost.capture`` — the "already held, no second lowering"
    contract. The NEWEST compile of an entry always wins (a retrace
    replaces the program, and attributing a trace against a dead
    program's names would be wrong even when the old artifact was the
    nicer optimized text). Bounded: one insertion-ordered store, so
    eviction really is least-recently-compiled, never the entry a
    capture is about to join against."""

    def __init__(self, max_entries: int = 32):
        self._lock = threading.Lock()
        # entry -> ("text", str) | ("lowered", Lowered); insertion order
        # == compile recency (puts re-insert at the end)
        self._store: Dict[str, tuple] = {}
        self._max = int(max_entries)
        self._compile_warned = False

    def _put(self, entry: str, kind: str, value) -> None:
        self._store.pop(entry, None)
        self._store[entry] = (kind, value)
        while len(self._store) > self._max:
            self._store.pop(next(iter(self._store)))

    def put_text(self, entry: str, text: str) -> None:
        with self._lock:
            self._put(entry, "text", text)

    def put_lowered(self, entry: str, lowered) -> None:
        with self._lock:
            self._put(entry, "lowered", lowered)

    def entries(self) -> List[str]:
        with self._lock:
            return sorted(self._store)

    def text_for(self, entry: str) -> Optional[str]:
        """The optimized HLO text for ``entry``; compiles the stored
        Lowered on demand (counted — it is the one place attribution
        pays a compile, and only because the default cost-analysis mode
        skipped the full one)."""
        with self._lock:
            kind, value = self._store.get(entry, (None, None))
        text = value if kind == "text" else None
        lowered = value if kind == "lowered" else None
        if text is not None:
            return text
        if lowered is None:
            return None
        try:
            text = lowered.compile().as_text()
        except Exception as e:  # noqa: BLE001
            if not self._compile_warned:
                self._compile_warned = True
                logger.warning("hlo_attrib: compiling stored lowering for "
                               "%r failed (%s) — attribution will miss "
                               "this entry", entry, e)
            return None
        get_telemetry().counter("profile/hlo_compiles")
        self.put_text(entry, text)
        return text

    def texts(self, entries: Optional[List[str]] = None
              ) -> Dict[str, str]:
        out = {}
        for e in (entries if entries is not None else self.entries()):
            t = self.text_for(e)
            if t:
                out[e] = t
        return out

    def reset(self) -> None:
        with self._lock:
            self._store.clear()
            self._compile_warned = False


_registry = HloRegistry()


def hlo_registry() -> HloRegistry:
    return _registry
