"""Per-rank HTTP ops plane — live /metrics, health, and debug endpoints.

The telemetry subsystem (PR 1) and the attribution layer (PR 5) are
post-hoc: counters flush to JSONL at exit, spans export to chrome traces
after the run. The north-star workload is a serving fleet under live
traffic, where the operator's questions — "is this replica healthy?",
"what is it doing right now?", "why is this one request slow?" — must be
answerable WHILE the process runs. This is the reference framework's
operability generation (VisualDL scalar streaming + fleet metric
collection) rebuilt over our richer signal:

- ``GET /metrics`` — Prometheus text exposition (one scrape target per
  rank) built live from the ``Telemetry`` registry: counters as
  ``paddle_tpu_<name>_total``, gauges as ``paddle_tpu_<name>``,
  histograms as summaries (p50/p95/p99 quantile labels + ``_count`` /
  ``_sum``). Every sample carries a ``rank`` label; the repo's
  structured suffixes (``.b<N>`` batch buckets, ``.c<N>`` prefill
  chunks, ``.d<i>`` devices, ``.rank<i>``) become an ``entry`` label so
  one family aggregates across buckets instead of exploding the
  namespace.
- ``GET /healthz`` — is this process trustworthy? Wired to REAL runtime
  state: watchdog heartbeat freshness (``resilience.watchdog``
  last-beat age), the serving drain latch, golden-step selftest
  failures and unrepaired silent corruption
  (``resilience/selftest_failures``, ``sdc_detected`` vs
  ``sdc_repaired``), and active SLO burn alerts. 503 + per-source JSON
  on any failure, so a load balancer ejects a draining or suspect
  replica before users feel it.
- ``GET /readyz`` — should this process receive NEW traffic? Healthz
  plus admission-queue saturation (a full queue sheds; routing new
  work there just manufactures rejects).
- ``GET /debug/requests`` — the serving ledger's in-flight requests
  (age, phase, deadline remaining, tokens generated) plus recently
  completed sampled request traces.
- ``GET /debug/spans`` — the always-on flight recorder's event tail
  (``?n=`` limits), i.e. "what was this process doing just now".
- ``GET /debug/telemetry`` — the raw flat scalar view (the JSONL
  payload), for humans with curl and no Prometheus.

Env contract: ``PADDLE_TPU_OPS_PORT`` arms the server
(``distributed.launch`` auto-offsets it per rank, so rank *i* serves on
``base + i``); port 0 binds an ephemeral port (tests/gates read
``server.port``). The server is a stdlib ``ThreadingHTTPServer`` on a
daemon thread: zero cost on the step/decode hot path beyond the request
handling itself, and it can never hold a dying process open.
"""
from __future__ import annotations

import json
import os
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional
from urllib.parse import parse_qs, urlparse

from .telemetry import Telemetry, env_float, get_telemetry

__all__ = [
    "OpsServer", "start_ops_server", "stop_ops_server", "current_ops_server",
    "maybe_start_from_env", "prometheus_text", "parse_prometheus_text",
    "register_health_source", "unregister_health_source", "health_report",
    "set_serving_engine", "current_serving_engine", "rank",
]


def rank() -> int:
    """This process's global trainer rank (the ``rank`` label on every
    exposed sample), from the launcher's env contract; 0 standalone."""
    for var in ("PADDLE_TRAINER_ID", "PROCESS_ID"):
        raw = os.environ.get(var)
        if raw:
            try:
                return int(raw)
            except ValueError:
                pass
    return 0


# -- Prometheus text exposition ----------------------------------------------

_NAME_SANITIZE = re.compile(r"[^a-zA-Z0-9_]")
# the repo's structured metric suffixes: batch buckets (.b4), prefill
# chunks (.c32), local devices (.d0), ranks (.rank1) — label material,
# not name material
_ENTRY_SUFFIX = re.compile(r"^(.*)\.((?:b|c|d)\d+|rank\d+)$")


def _split_entry(name: str):
    m = _ENTRY_SUFFIX.match(name)
    return (m.group(1), m.group(2)) if m else (name, None)


def _metric_name(name: str, suffix: str = "") -> str:
    return "paddle_tpu_" + _NAME_SANITIZE.sub("_", name) + suffix


def _labels(rank_no: int, entry: Optional[str] = None,
            quantile: Optional[str] = None) -> str:
    parts = [f'rank="{rank_no}"']
    if entry is not None:
        parts.append(f'entry="{entry}"')
    if quantile is not None:
        parts.append(f'quantile="{quantile}"')
    return "{" + ",".join(parts) + "}"


def prometheus_text(telemetry: Optional[Telemetry] = None,
                    rank_no: Optional[int] = None) -> str:
    """The live registry as Prometheus text exposition format 0.0.4.
    Pure function of one ``Telemetry.snapshot()`` — scrapes see a
    consistent cut, and tests validate without HTTP."""
    tel = telemetry or get_telemetry()
    r = rank() if rank_no is None else int(rank_no)
    snap = tel.snapshot()
    lines: List[str] = []
    typed: set = set()

    def emit_type(metric: str, kind: str) -> None:
        if metric not in typed:
            typed.add(metric)
            lines.append(f"# TYPE {metric} {kind}")

    for name in sorted(snap["counters"]):
        base, entry = _split_entry(name)
        metric = _metric_name(base, "_total")
        emit_type(metric, "counter")
        lines.append(f"{metric}{_labels(r, entry)} "
                     f"{int(snap['counters'][name])}")
    for name in sorted(snap["gauges"]):
        base, entry = _split_entry(name)
        metric = _metric_name(base)
        emit_type(metric, "gauge")
        lines.append(f"{metric}{_labels(r, entry)} "
                     f"{float(snap['gauges'][name]):.10g}")
    for name in sorted(snap["histograms"]):
        s = snap["histograms"][name]
        base, entry = _split_entry(name)
        metric = _metric_name(base)
        emit_type(metric, "summary")
        for q, field in (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99")):
            if field in s and s[field] is not None:
                lines.append(f"{metric}{_labels(r, entry, q)} "
                             f"{float(s[field]):.10g}")
        lines.append(f"{metric}_sum{_labels(r, entry)} "
                     f"{float(s.get('sum', 0.0)):.10g}")
        lines.append(f"{metric}_count{_labels(r, entry)} "
                     f"{int(s.get('count', 0))}")
    return "\n".join(lines) + "\n"


_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^{}]*\})?\s+(\S+)$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_prometheus_text(text: str) -> Dict[str, List[dict]]:
    """Strict-enough parser of the exposition this module emits:
    ``{metric_name: [{labels: {...}, value: float}, ...]}``. Raises
    ``ValueError`` on any malformed line — the ops gate uses it to
    assert the exposition actually parses, not merely that bytes came
    back."""
    import math

    out: Dict[str, List[dict]] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            if not re.match(r"^# (TYPE|HELP) [a-zA-Z_:][a-zA-Z0-9_:]* ",
                            line):
                raise ValueError(f"line {lineno}: malformed comment: "
                                 f"{line!r}")
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"line {lineno}: malformed sample: {line!r}")
        name, labelstr, value = m.groups()
        try:
            v = float(value)
        except ValueError:
            raise ValueError(f"line {lineno}: non-numeric value {value!r}")
        if math.isnan(v):
            raise ValueError(f"line {lineno}: NaN sample for {name}")
        labels = dict(_LABEL_RE.findall(labelstr or ""))
        out.setdefault(name, []).append({"labels": labels, "value": v})
    return out


# -- health sources -----------------------------------------------------------
# A health source is a callable -> {"ok": bool, "ready": bool, "detail":
# str}. "ok" feeds /healthz (is this process trustworthy), "ready" feeds
# /readyz (should it receive NEW traffic) — a saturated admission queue
# is not-ready but perfectly healthy. Built-ins below; subsystems may
# register more.

_health_lock = threading.Lock()
_health_sources: Dict[str, Callable[[], dict]] = {}
_serving_engine = None  # the live ServingEngine this rank runs, if any


def register_health_source(name: str, fn: Callable[[], dict]) -> None:
    with _health_lock:
        _health_sources[str(name)] = fn


def unregister_health_source(name: str) -> None:
    with _health_lock:
        _health_sources.pop(str(name), None)


def set_serving_engine(engine) -> None:
    """Called by ``ServingEngine.start()`` so the ops plane can see the
    drain latch, queue saturation, and the in-flight ledger. Pass None
    to detach (tests)."""
    global _serving_engine
    _serving_engine = engine


def current_serving_engine():
    return _serving_engine


def _watchdog_health() -> dict:
    from ..resilience import watchdog

    age = watchdog.last_beat_age_s()
    wd = watchdog.current_watchdog()
    # staleness: explicit env override, else the armed watchdog's own
    # deadline (the process already declared what "too long" means),
    # else 60 s once any beat has been seen
    stale_s = env_float("PADDLE_TPU_OPS_STALE_HEARTBEAT_S",
                         wd.deadline_s if wd is not None else 60.0)
    if age is None:
        return {"ok": True, "ready": True,
                "detail": "no heartbeat emitted yet (no step/serve loop)"}
    ok = stale_s <= 0 or age <= stale_s
    return {"ok": ok, "ready": ok,
            "detail": f"last heartbeat {age:.1f}s ago"
                      + ("" if ok else f" (stale > {stale_s:.1f}s)")}


def _integrity_health() -> dict:
    tel = get_telemetry()
    selftest_fail = tel.counter_value("resilience/selftest_failures")
    detected = tel.counter_value("resilience/sdc_detected")
    repaired = tel.counter_value("resilience/sdc_repaired")
    if selftest_fail > 0:
        return {"ok": False, "ready": False,
                "detail": f"golden-step selftest failed {selftest_fail}x "
                          f"— this chip computes wrong numbers"}
    if detected > repaired:
        return {"ok": False, "ready": False,
                "detail": f"unrepaired silent corruption: detected "
                          f"{detected}, repaired {repaired}"}
    return {"ok": True, "ready": True,
            "detail": f"selftest clean, sdc {detected}/{repaired} "
                      f"detected/repaired"}


def _serving_health() -> dict:
    eng = _serving_engine
    if eng is None:
        return {"ok": True, "ready": True, "detail": "no serving engine"}
    if eng.draining:
        return {"ok": False, "ready": False,
                "detail": f"draining ({eng.drain_reason}) — replica is "
                          f"going away, eject it"}
    depth = len(eng._queue)
    cap = eng.config.capacity
    sat = depth / cap if cap else 0.0
    threshold = env_float("PADDLE_TPU_OPS_QUEUE_SAT", 0.95)
    if sat >= threshold:
        return {"ok": True, "ready": False,
                "detail": f"admission queue saturated: {depth}/{cap} — "
                          f"healthy but shedding, route new work away"}
    return {"ok": True, "ready": True, "detail": f"queue {depth}/{cap}"}


def _slo_health() -> dict:
    from .slo import get_slo_monitor

    mon = get_slo_monitor()
    if mon is None:
        return {"ok": True, "ready": True, "detail": "no SLO monitor"}
    alerts = mon.active_alerts()
    if alerts:
        return {"ok": False, "ready": False,
                "detail": "SLO budget burning: " + ", ".join(alerts)}
    return {"ok": True, "ready": True,
            "detail": f"{len(mon.objectives)} objective(s), no alert"}


_BUILTIN_SOURCES = (("watchdog", _watchdog_health),
                    ("integrity", _integrity_health),
                    ("serving", _serving_health),
                    ("slo", _slo_health))


def health_report() -> dict:
    """Evaluate every source. ``{"ok", "ready", "sources": {...}}`` — a
    source that RAISES reports unhealthy (an ops plane that says "fine"
    because its checker crashed is worse than none)."""
    sources: Dict[str, dict] = {}
    with _health_lock:
        extra = list(_health_sources.items())
    for name, fn in list(_BUILTIN_SOURCES) + extra:
        try:
            res = dict(fn())
            res.setdefault("ok", False)
            res.setdefault("ready", bool(res["ok"]))
        except Exception as e:  # noqa: BLE001 — any checker crash
            res = {"ok": False, "ready": False,
                   "detail": f"health source crashed: {e!r}"}
        sources[name] = res
    return {"ok": all(s["ok"] for s in sources.values()),
            "ready": all(s["ready"] for s in sources.values()),
            "rank": rank(),
            "sources": sources}


def _debug_requests(limit: int = 256) -> dict:
    eng = _serving_engine
    from .spans import trace_store

    inflight: List[dict] = []
    if eng is not None:
        try:
            inflight = eng.debug_requests(limit=limit)
        except Exception:
            inflight = []
    completed = [t.to_dict() for t in trace_store().snapshot(limit)]
    return {"rank": rank(), "in_flight": inflight,
            "completed_traces": completed}


# -- the HTTP server ----------------------------------------------------------


class _Handler(BaseHTTPRequestHandler):
    server_version = "paddle-tpu-ops/1"

    def log_message(self, fmt, *args):  # noqa: D102 — silence stderr
        pass

    def _send(self, code: int, body: str, ctype: str) -> None:
        data = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        try:
            self.wfile.write(data)
        except (BrokenPipeError, ConnectionResetError):
            pass  # scraper went away mid-write; its problem

    def _send_json(self, code: int, obj) -> None:
        self._send(code, json.dumps(obj, indent=1, sort_keys=True,
                                    default=str),
                   "application/json; charset=utf-8")

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        url = urlparse(self.path)
        q = parse_qs(url.query)
        tel = self.server.telemetry  # type: ignore[attr-defined]
        try:
            if url.path == "/metrics":
                tel.counter("ops/scrapes")
                try:
                    # refresh the derived attribution gauges (MFU,
                    # bottleneck verdicts, goodput wall-clock ledger) so
                    # a live scrape sees current values, not the last
                    # to_jsonl's — cheap dict math over existing
                    # snapshots
                    from . import bottleneck, goodput, xla_cost

                    xla_cost.publish_mfu(tel)
                    bottleneck.publish(tel)
                    goodput.publish(tel)
                except Exception:
                    pass
                self._send(200, prometheus_text(tel),
                           "text/plain; version=0.0.4; charset=utf-8")
            elif url.path == "/healthz":
                rep = health_report()
                self._send_json(200 if rep["ok"] else 503, rep)
            elif url.path == "/readyz":
                rep = health_report()
                self._send_json(200 if rep["ready"] else 503, rep)
            elif url.path == "/debug/requests":
                limit = int(q.get("n", ["256"])[0])
                self._send_json(200, _debug_requests(limit))
            elif url.path == "/debug/spans":
                from .spans import flight_recorder

                n = q.get("n", [None])[0]
                self._send_json(200, {
                    "rank": rank(),
                    "events": flight_recorder().dump(
                        int(n) if n else None)})
            elif url.path == "/debug/telemetry":
                self._send_json(200, tel.scalars())
            elif url.path == "/debug/profile":
                from . import device_profile

                self._send_json(200, {
                    "rank": rank(),
                    "state": device_profile.capture_state(),
                    "report": device_profile.last_report()})
            elif url.path == "/debug/collectives":
                # the per-axis collective picture of THIS rank: the
                # static compiled-HLO inventory + latest-capture measured
                # ms (collective_attrib), and the eager recorder's tail
                # (?n= limits). On-demand like /debug/profile — the
                # inventory may compile a stored lowering once (counted
                # profile/hlo_compiles) in the default cost mode.
                from . import collective_attrib

                payload = {
                    "rank": rank(),
                    "axes": collective_attrib.registered_axes(),
                    "inventory": collective_attrib.inventory_dict(),
                    "summary": collective_attrib.summary(),
                }
                try:
                    from ..distributed import communication

                    n = int(q.get("n", ["64"])[0])
                    payload["eager_tail"] = \
                        communication.collective_events(n)
                except Exception:  # noqa: BLE001 — recorder optional
                    payload["eager_tail"] = []
                self._send_json(200, payload)
            elif url.path == "/debug/goodput":
                # this rank's live wall-clock attribution: the full
                # category breakdown (zeros included — the closed
                # vocabulary is the contract), current ledger state and
                # the conservation identity an operator can check by eye
                from . import goodput

                snap = goodput.snapshot()
                self._send_json(200, {
                    "rank": rank(),
                    "wall_s": round(snap["wall_s"], 3),
                    "fraction": round(snap["fraction"], 4),
                    "attempt": snap["attempt"],
                    "current": snap["current"],
                    "categories": {c: round(s, 3) for c, s in
                                   snap["categories"].items()}})
            else:
                self._send_json(404, {"error": f"no route {url.path}",
                                      "routes": ["/metrics", "/healthz",
                                                 "/readyz",
                                                 "/debug/requests",
                                                 "/debug/spans",
                                                 "/debug/telemetry",
                                                 "/debug/profile",
                                                 "/debug/collectives",
                                                 "/debug/goodput"]})
        except Exception as e:  # noqa: BLE001 — handler must not die
            try:
                self._send_json(500, {"error": repr(e)})
            except Exception:
                pass

    def do_POST(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        url = urlparse(self.path)
        q = parse_qs(url.query)
        try:
            if url.path == "/debug/profile":
                # arm an on-demand windowed device capture: the next N
                # step boundaries of whatever engine is running get
                # traced + attributed (profiler.device_profile). The
                # response says armed-or-refused; the report lands on
                # GET /debug/profile (and in telemetry) once the window
                # closes.
                from . import device_profile

                try:
                    steps = int(q.get("steps", ["0"])[0]) or None
                except ValueError:
                    self._send_json(400, {"error": "steps must be an int"})
                    return
                armed = device_profile.request_capture(steps=steps)
                self._send_json(200 if armed else 409, {
                    "rank": rank(), "armed": armed,
                    "state": device_profile.capture_state(),
                    "detail": ("capture armed — report appears on GET "
                               "/debug/profile after the window closes"
                               if armed else
                               "refused: a capture or profiler window is "
                               "already live (profile/capture_skipped "
                               "counted)")})
            else:
                self._send_json(404, {"error": f"no POST route {url.path}",
                                      "routes": ["/debug/profile"]})
        except Exception as e:  # noqa: BLE001 — handler must not die
            try:
                self._send_json(500, {"error": repr(e)})
            except Exception:
                pass


class OpsServer:
    """The env-gated in-process ops plane: a ``ThreadingHTTPServer`` on a
    daemon thread. ``port=0`` binds ephemerally; read ``.port`` after
    ``start()``."""

    def __init__(self, port: int, host: str = "0.0.0.0",
                 telemetry: Optional[Telemetry] = None):
        self._requested_port = int(port)
        self.host = host
        self._tel = telemetry or get_telemetry()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> Optional[int]:
        return (self._httpd.server_address[1]
                if self._httpd is not None else None)

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> "OpsServer":
        if self.running:
            return self
        httpd = ThreadingHTTPServer((self.host, self._requested_port),
                                    _Handler)
        httpd.daemon_threads = True
        httpd.telemetry = self._tel  # type: ignore[attr-defined]
        self._httpd = httpd
        self._thread = threading.Thread(
            target=httpd.serve_forever, name="OpsServer", daemon=True,
            kwargs={"poll_interval": 0.25})
        self._thread.start()
        self._tel.gauge("ops/port", self.port)
        return self

    def stop(self, timeout: float = 2.0) -> None:
        httpd, thread = self._httpd, self._thread
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if thread is not None and thread.is_alive():
            thread.join(timeout)
        self._httpd = None
        self._thread = None


_server: Optional[OpsServer] = None
_server_lock = threading.Lock()


def start_ops_server(port: int, host: str = "0.0.0.0",
                     telemetry: Optional[Telemetry] = None) -> OpsServer:
    """Start (or return) the process-wide ops server."""
    global _server
    with _server_lock:
        if _server is not None and _server.running:
            return _server
        _server = OpsServer(port, host=host, telemetry=telemetry).start()
        return _server


def stop_ops_server() -> None:
    global _server
    with _server_lock:
        if _server is not None:
            _server.stop()
            _server = None


def current_ops_server() -> Optional[OpsServer]:
    return _server


def maybe_start_from_env(telemetry: Optional[Telemetry] = None
                         ) -> Optional[OpsServer]:
    """PADDLE_TPU_OPS_PORT set → start the server on it (the launcher
    already offset it per rank). Unset/empty/malformed → None. Also arms
    the env-gated SLO monitor (PADDLE_TPU_SLO) so a scrape-only process
    still evaluates its objectives. Never raises: a busy port logs a
    gauge and moves on — observability must not kill the workload."""
    raw = os.environ.get("PADDLE_TPU_OPS_PORT", "")
    if not raw.strip():
        return None
    try:
        port = int(raw)
    except ValueError:
        return None
    if port < 0:
        return None
    try:
        from . import slo

        slo.maybe_start_from_env(telemetry=telemetry)
    except Exception:
        pass
    try:
        return start_ops_server(port, telemetry=telemetry)
    except OSError:
        # port taken (e.g. two unranked processes with one base port):
        # record the failure where a scrape of a sibling can see it
        (telemetry or get_telemetry()).counter("ops/bind_failures")
        return None
