"""Cross-rank telemetry aggregation — one cluster view from per-rank JSONL.

``distributed.launch`` gives every worker its own telemetry sink
(``PADDLE_TPU_TELEMETRY_JSONL`` pointing at
``<log_dir>/telemetry.rank<i>.jsonl``; the process flushes a final
record at exit), so an N-rank job leaves N scalar logs. This module
merges them:

- **per-scalar cluster view** — for every scalar name, the min / median
  / max across ranks of each rank's *final* value (counters are
  monotonic, so the last record holds the total; gauges/histograms want
  the most recent state anyway);
- **straggler detection** — a data-parallel job runs at the speed of its
  slowest rank. A rank whose step-latency p50 (any ``hist/*step_ms/p50``
  scalar) exceeds the cluster median by ``threshold``× is flagged with
  the metric, its value, and the median it broke from;
- **dead-rank detection** — with ``expected_ranks``, a rank whose
  telemetry log is missing (it died before the atexit flush) or holds
  no parsable record (truncated mid-write) becomes an explicit finding
  instead of silently shrinking every cluster median — an N-1-rank
  aggregate that LOOKS healthy is the most dangerous report this tool
  could produce;
- **late-rank detection** — per-collective-instance arrival skew from
  the fused cluster timeline (``profiler.cluster_trace`` — clock-offset-
  aligned eager-collective logs): a rank arriving more than the
  threshold late into a collective becomes a LATE-RANK finding naming
  the instance ("rank 3 late 41 ms into all-reduce #17, axis dp") —
  the *why* behind a straggler median, which only says *that* a rank is
  slow. Straggler findings additionally cite per-axis collective
  evidence (``gauge/collective/<axis>/ms.*``) when the flagged rank's
  record carries it.

Pure host-side file munching — no jax import — so the CLI wrapper
(``tools/telemetry_agg.py``) stays fast enough for a watch loop.
"""
from __future__ import annotations

import json
import math
import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "read_jsonl", "rank_of_path", "final_scalars", "load_rank_scalars",
    "cluster_view", "detect_stragglers", "detect_dead_ranks",
    "detect_suspect_chips", "detect_slo_burns", "collect_bottlenecks",
    "detect_late_ranks", "dominant_collective_axis",
    "goodput_tables", "launch_restart_downtime", "goodput_summary",
    "aggregate", "STEP_HIST_PATTERN", "SDC_REPAIR_PATTERN",
    "ALERT_PATTERN", "BOTTLENECK_PATTERN", "BOTTLENECK_NAMES",
    "COLLECTIVE_PATTERN", "GOODPUT_CATEGORIES",
]

# any per-rank step-latency p50 qualifies for straggler comparison
# (engine/, executor/, jit/, hapi/ producers all end in step_ms)
STEP_HIST_PATTERN = re.compile(r"^hist/.*step_ms/p50$")

# per-repaired-rank silent-corruption repair counter
# (resilience.integrity bumps it on EVERY rank, naming the repaired one,
# so any surviving rank's log carries the evidence)
SDC_REPAIR_PATTERN = re.compile(
    r"^counter/resilience/sdc_repaired\.rank(\d+)$")

# SLO burn-rate alert episodes (profiler.slo bumps counter/alert/<name>
# on every rising edge of a multi-window burn alert)
ALERT_PATTERN = re.compile(r"^counter/alert/(.+)$")

# automated bottleneck verdicts (profiler.bottleneck publishes the id of
# a CLOSED vocabulary per compiled entry; keep the map in sync)
BOTTLENECK_PATTERN = re.compile(r"^gauge/bottleneck/(.+)$")
BOTTLENECK_NAMES = {0: "compute_bound", 1: "memory_bound", 2: "comm_bound",
                    3: "input_bound", 4: "host_bound"}

# per-axis collective attribution gauges (profiler.collective_attrib):
# gauge/collective/<axis>/<field>.<entry>
COLLECTIVE_PATTERN = re.compile(
    r"^gauge/collective/([^/]+)/(bytes|ms|count)\.(.+)$")

# the goodput ledger's closed category vocabulary — a LITERAL mirror of
# profiler.goodput.CATEGORIES (this module is loaded standalone by
# tools/telemetry_agg.py via spec_from_file_location, so it cannot
# import the sibling; tests assert the two stay identical)
GOODPUT_CATEGORIES = (
    "startup", "productive_step", "compile", "input_wait",
    "checkpoint_save", "checkpoint_restore", "rollback_recovery",
    "eval", "drain_shutdown", "restart_downtime", "unattributed",
)

_RANK_RE = re.compile(r"rank[._-]?(\d+)")


def read_jsonl(path: str) -> List[dict]:
    """Parse one telemetry JSONL log, skipping blank/corrupt lines (a
    crash mid-write must not take the whole aggregation down)."""
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict) and isinstance(rec.get("scalars"), dict):
                records.append(rec)
    return records


def rank_of_path(path: str, fallback: int) -> int:
    """Rank from a ``...rank<i>...`` filename, else the caller's index."""
    m = _RANK_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else fallback


def final_scalars(records: Sequence[dict],
                  tag: Optional[str] = None) -> Dict[str, float]:
    """Fold a rank's records into its final per-scalar state (later
    records override earlier ones name-by-name)."""
    out: Dict[str, float] = {}
    for rec in records:
        if tag is not None and rec.get("tag") != tag:
            continue
        for name, value in rec["scalars"].items():
            if isinstance(value, bool) or not isinstance(value, (int, float)):
                continue
            if math.isfinite(float(value)):
                out[name] = float(value)
    return out


def load_rank_scalars(paths: Sequence[str],
                      tag: Optional[str] = None) -> Dict[int, Dict[str, float]]:
    """{rank: final_scalars} over the given per-rank files."""
    out: Dict[int, Dict[str, float]] = {}
    for i, path in enumerate(sorted(paths)):
        rank = rank_of_path(path, i)
        try:
            records = read_jsonl(path)
        except OSError:
            continue  # a missing/unreadable rank drops out of the view
        scalars = final_scalars(records, tag=tag)
        if scalars:
            out[rank] = scalars
    return out


def _median(values: Sequence[float]) -> float:
    vs = sorted(values)
    n = len(vs)
    mid = n // 2
    return vs[mid] if n % 2 else (vs[mid - 1] + vs[mid]) / 2.0


def cluster_view(rank_scalars: Dict[int, Dict[str, float]]) -> Dict[str, dict]:
    """{scalar_name: {min, median, max, ranks: {rank: value}}} over every
    scalar any rank reported (ranks missing a scalar just don't vote)."""
    names = set()
    for scalars in rank_scalars.values():
        names.update(scalars)
    view: Dict[str, dict] = {}
    for name in sorted(names):
        per_rank = {r: s[name] for r, s in rank_scalars.items() if name in s}
        values = list(per_rank.values())
        view[name] = {"min": min(values), "median": _median(values),
                      "max": max(values), "ranks": per_rank}
    return view


def detect_stragglers(rank_scalars: Dict[int, Dict[str, float]],
                      threshold: float = 1.25) -> List[dict]:
    """Flag ranks whose step-latency p50 exceeds the cluster median by
    ``threshold``×. Needs >= 2 ranks reporting the same metric (one rank
    has no cluster to straggle behind). Returns one finding per
    (rank, metric), sorted worst-first."""
    findings: List[dict] = []
    metrics = set()
    for scalars in rank_scalars.values():
        metrics.update(n for n in scalars if STEP_HIST_PATTERN.match(n))
    for metric in sorted(metrics):
        per_rank: List[Tuple[int, float]] = [
            (r, s[metric]) for r, s in sorted(rank_scalars.items())
            if metric in s]
        if len(per_rank) < 2:
            continue
        med = _median([v for _, v in per_rank])
        if med <= 0:
            continue
        for rank, value in per_rank:
            if value > threshold * med:
                finding = {
                    "rank": rank, "metric": metric, "value": value,
                    "cluster_median": med, "ratio": value / med,
                }
                # cite per-axis collective evidence when the flagged
                # rank's record carries it: "rank 3 is 1.4x the median"
                # becomes actionable when the same record says its dp
                # all-reduces ate N ms of the last captured window
                evidence = dominant_collective_axis(
                    rank_scalars.get(rank, {}), with_entry=True)
                if evidence is not None:
                    finding["collective_axis"] = evidence[0]
                    finding["collective_ms"] = evidence[1]
                    finding["collective_entry"] = evidence[2]
                findings.append(finding)
    findings.sort(key=lambda f: -f["ratio"])
    return findings


def dominant_collective_axis(scalars: Dict[str, float],
                             entry: Optional[str] = None,
                             with_entry: bool = False):
    """``(axis, ms)`` — or ``(axis, ms, entry)`` with ``with_entry`` —
    of the biggest measured per-axis collective gauge in one rank's
    scalars (optionally restricted to one entry; the cumulative
    ``eager`` entry is skipped when any captured entry exists), or
    None. Shared by straggler evidence and the ``comm_bound:<axis>``
    verdict refinement."""
    rows = []
    for name, v in scalars.items():
        m = COLLECTIVE_PATTERN.match(name)
        if not m or m.group(2) != "ms":
            continue
        axis, _, ent = m.group(1), m.group(2), m.group(3)
        if entry is not None and ent != entry:
            continue
        rows.append((axis, ent, float(v)))
    if not rows:
        return None
    captured = [r for r in rows if r[1] != "eager"]
    pick = max(captured or rows, key=lambda r: r[2])
    return (pick[0], pick[2], pick[1]) if with_entry else (pick[0], pick[2])


def detect_suspect_chips(rank_scalars: Dict[int, Dict[str, float]],
                         max_repairs: float = 1) -> List[dict]:
    """Flag ranks whose silent-corruption repair count exceeds
    ``max_repairs`` — one repair is a cosmic ray, repeated repairs of
    the SAME rank are a marginal chip that will keep poisoning the
    replica set until the hardware is replaced. The per-rank counters
    (``counter/resilience/sdc_repaired.rank<i>``) are folded by max
    across every reporting rank's log (all ranks record each repair
    event, naming the repaired rank), so one surviving log is enough
    evidence. Sorted worst-first."""
    repairs: Dict[int, float] = {}
    for scalars in rank_scalars.values():
        for name, value in scalars.items():
            m = SDC_REPAIR_PATTERN.match(name)
            if m:
                j = int(m.group(1))
                repairs[j] = max(repairs.get(j, 0.0), float(value))
    findings = [{"rank": j, "repairs": v, "max_repairs": float(max_repairs)}
                for j, v in sorted(repairs.items()) if v > float(max_repairs)]
    findings.sort(key=lambda f: -f["repairs"])
    return findings


def detect_slo_burns(rank_scalars: Dict[int, Dict[str, float]]) -> List[dict]:
    """One finding per (rank, objective) whose log carries a fired SLO
    burn-rate alert (``counter/alert/<objective>`` > 0). An alert is an
    SLO budget actually burning while the replica served traffic — a
    run that looks "green" on throughput medians but carries alerts
    shipped a user-visible degradation. The rank's final burn gauges
    ride along when present. Sorted most-episodes-first."""
    findings: List[dict] = []
    for rank, scalars in sorted(rank_scalars.items()):
        for name, value in sorted(scalars.items()):
            m = ALERT_PATTERN.match(name)
            if not m or float(value) <= 0:
                continue
            obj = m.group(1)
            findings.append({
                "rank": rank, "objective": obj,
                "episodes": float(value),
                "burn_fast": scalars.get(f"gauge/slo/{obj}/burn_fast"),
                "burn_slow": scalars.get(f"gauge/slo/{obj}/burn_slow"),
            })
    findings.sort(key=lambda f: -f["episodes"])
    return findings


def collect_bottlenecks(rank_scalars: Dict[int, Dict[str, float]]
                        ) -> List[dict]:
    """Every rank's published bottleneck verdicts, named: one row per
    (entry, rank) — ``{"entry", "rank", "verdict"}``. Purely a surface
    (verdicts are diagnoses, not failures): the operator reading the
    cluster report sees WHY each entry spends its step time next to how
    long the step takes."""
    findings: List[dict] = []
    for rank, scalars in sorted(rank_scalars.items()):
        for name, v in scalars.items():
            m = BOTTLENECK_PATTERN.match(name)
            if not m:
                continue
            entry = m.group(1)
            verdict = BOTTLENECK_NAMES.get(int(v), f"unknown({v:g})")
            if verdict == "comm_bound":
                # refine from the same record's per-axis collective
                # gauges — the vocabulary extension the schema gate
                # documents (comm_bound:<axis>)
                evidence = dominant_collective_axis(scalars, entry=entry)
                if evidence is not None:
                    verdict = f"comm_bound:{evidence[0]}"
            findings.append({"entry": entry, "rank": rank,
                             "verdict": verdict})
    findings.sort(key=lambda f: (f["entry"], f["rank"]))
    return findings


def detect_late_ranks(instances, threshold_ms: float = 100.0) -> List[dict]:
    """LATE-RANK findings from fused collective instances (one per late
    rank, naming its worst instance) — the skew math lives in
    ``profiler.cluster_trace`` (stdlib-only, loadable standalone the
    same way this module is); this is the findings surface the
    telemetry_agg CLI and the gates consume."""
    try:
        from . import cluster_trace  # normal package context
    except ImportError:
        # standalone path-load (tools/telemetry_agg.py loads this file
        # via spec_from_file_location, so relative imports don't exist)
        import importlib.util

        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "cluster_trace.py")
        spec = importlib.util.spec_from_file_location(
            "_ptpu_cluster_trace", path)
        cluster_trace = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(cluster_trace)
    return cluster_trace.detect_late_ranks(instances, threshold_ms)


def detect_dead_ranks(paths: Sequence[str],
                      rank_scalars: Dict[int, Dict[str, float]],
                      expected_ranks: int) -> List[dict]:
    """One finding per expected rank that contributed NO scalars —
    distinguishing a missing log (the rank died before its atexit flush
    ever ran) from a present-but-unparsable one (truncated mid-write by
    a SIGKILL). Sorted by rank."""
    rank_paths: Dict[int, str] = {}
    for i, path in enumerate(sorted(paths)):
        rank_paths.setdefault(rank_of_path(path, i), path)
    findings: List[dict] = []
    for rank in range(int(expected_ranks)):
        if rank in rank_scalars:
            continue
        path = rank_paths.get(rank)
        if path is None:
            findings.append({
                "rank": rank, "reason": "missing telemetry log "
                "(rank died before its atexit flush)"})
        else:
            findings.append({
                "rank": rank, "path": path,
                "reason": "no parsable telemetry record "
                "(log truncated/empty — rank died mid-write)"})
    return findings


def goodput_tables(records: Sequence[dict]) -> Dict[int, dict]:
    """One rank's per-attempt goodput tables: the LAST structured
    ``rec["goodput"]`` table per launch attempt wins (each table is
    cumulative within its attempt, so the last one is the attempt's
    total). Launcher records (``tag == "launch"``) are skipped — the
    launcher's own ledger spans the whole job and would double-count
    every rank second it supervised."""
    out: Dict[int, dict] = {}
    for rec in records:
        if rec.get("tag") == "launch":
            continue
        g = rec.get("goodput")
        if isinstance(g, dict) and isinstance(g.get("categories"), dict):
            try:
                attempt = int(g.get("attempt", 0) or 0)
            except (TypeError, ValueError):
                attempt = 0
            out[attempt] = g
    return out


def launch_restart_downtime(rank_records: Dict[int, List[dict]]) -> float:
    """Job-level restart downtime from the launcher's flushed record
    (``tag == "launch"``): the dead gap between attempts lives in the
    LAUNCHER's ledger, because no worker process exists to book it."""
    best = 0.0
    for records in rank_records.values():
        for rec in records:
            if rec.get("tag") != "launch":
                continue
            g = rec.get("goodput") or {}
            v = (g.get("categories") or {}).get("restart_downtime")
            if v is None:
                v = rec.get("scalars", {}).get(
                    "gauge/goodput/restart_downtime_s")
            if isinstance(v, (int, float)) and math.isfinite(float(v)):
                best = max(best, float(v))
    return best


def goodput_summary(rank_records: Dict[int, List[dict]]) -> Optional[dict]:
    """Cross-rank, cross-restart goodput merge.

    Per rank: attempts SUM (each attempt's last table is its total —
    that is the cross-restart stitching). Job view: categories and wall
    are the MEAN across ranks (N ranks run concurrently; one wall second
    is one job second, not N), then the launcher's ``restart_downtime``
    is added ONCE to both the wall and its category. Returns None when
    no record carries a goodput table."""
    per_rank: Dict[int, dict] = {}
    for rank, records in sorted(rank_records.items()):
        tables = goodput_tables(records)
        if not tables:
            continue
        cats = {c: 0.0 for c in GOODPUT_CATEGORIES}
        wall = 0.0
        for _attempt, g in sorted(tables.items()):
            wall += float(g.get("wall_s", 0.0) or 0.0)
            for c, v in (g.get("categories") or {}).items():
                if c in cats and isinstance(v, (int, float)):
                    cats[c] += float(v)
        per_rank[rank] = {
            "wall_s": wall,
            "attempts": len(tables),
            "fraction": (cats["productive_step"] / wall) if wall > 0 else 0.0,
            "categories": cats,
            "conservation_err": (abs(wall - sum(cats.values())) / wall
                                 if wall > 0 else 0.0),
        }
    if not per_rank:
        return None
    downtime = launch_restart_downtime(rank_records)
    n = len(per_rank)
    job_cats = {c: sum(r["categories"][c] for r in per_rank.values()) / n
                for c in GOODPUT_CATEGORIES}
    job_wall = sum(r["wall_s"] for r in per_rank.values()) / n + downtime
    job_cats["restart_downtime"] += downtime
    worst = min(per_rank, key=lambda r: per_rank[r]["fraction"])
    return {
        "per_rank": per_rank,
        "job": {
            "wall_s": job_wall,
            "fraction": (job_cats["productive_step"] / job_wall
                         if job_wall > 0 else 0.0),
            "categories": job_cats,
            "restart_downtime_s": downtime,
        },
        "worst_rank": {"rank": worst,
                       "fraction": per_rank[worst]["fraction"]},
        "conservation_err": max(r["conservation_err"]
                                for r in per_rank.values()),
    }


def aggregate(paths: Sequence[str], threshold: float = 1.25,
              tag: Optional[str] = None,
              expected_ranks: Optional[int] = None,
              suspect_repairs: float = 1) -> dict:
    """One-call cluster report over per-rank JSONL paths. Each file is
    parsed exactly once; with a ``tag`` filter the records are folded
    twice — tag-filtered for the view, unfiltered for liveness — rather
    than re-read."""
    rank_records: Dict[int, List[dict]] = {}
    launch_records: List[dict] = []
    for i, path in enumerate(sorted(paths)):
        try:
            records = read_jsonl(path)
        except OSError:
            continue  # a missing/unreadable rank drops out of the view
        # the launcher's own flushed records (the shared base file, no
        # rank token in its name) ride along when the whole log dir is
        # globbed — partition them out so rank_of_path's index fallback
        # cannot collide them onto (and silently replace) a real rank
        launch = [r for r in records if r.get("tag") == "launch"]
        workers = [r for r in records if r.get("tag") != "launch"]
        launch_records.extend(launch)
        if workers or not launch:
            rank_records[rank_of_path(path, i)] = workers

    def _fold(fold_tag: Optional[str]) -> Dict[int, Dict[str, float]]:
        out: Dict[int, Dict[str, float]] = {}
        for rank, records in rank_records.items():
            scalars = final_scalars(records, tag=fold_tag)
            if scalars:
                out[rank] = scalars
        return out

    rank_scalars = _fold(tag)
    goodput_view = dict(rank_records)
    if launch_records:
        # the launcher's records re-enter under a key no rank uses, so
        # its restart_downtime is found without shadowing a real rank
        goodput_view[-1] = launch_records
    result = {
        "ranks": sorted(rank_scalars),
        "n_ranks": len(rank_scalars),
        "view": cluster_view(rank_scalars),
        "stragglers": detect_stragglers(rank_scalars, threshold=threshold),
        "threshold": threshold,
        "suspect_chips": detect_suspect_chips(rank_scalars,
                                              max_repairs=suspect_repairs),
        "suspect_repairs": float(suspect_repairs),
        "slo_burns": detect_slo_burns(rank_scalars),
        "bottlenecks": collect_bottlenecks(rank_scalars),
        "goodput": goodput_summary(goodput_view),
    }
    if expected_ranks is not None:
        # liveness is judged on UNFILTERED records: a healthy rank whose
        # records all carry a different tag must not be reported dead
        alive = rank_scalars if tag is None else _fold(None)
        result["expected_ranks"] = int(expected_ranks)
        result["dead_ranks"] = detect_dead_ranks(paths, alive,
                                                 expected_ranks)
    return result
