"""Telemetry core — counters, gauges, and streaming histograms/timers.

The runtime-observability layer the reference builds from
platform/monitor.h (StatRegistry int64 stats) + platform/profiler.h
(RecordEvent spans feeding a tuning loop). Here one ``Telemetry`` object
unifies three primitives:

- **counters** — monotonically accumulated int64s, layered directly on the
  existing ``core.monitor.StatRegistry`` so ``stat_add``/``all_stats`` and
  telemetry snapshots always agree;
- **gauges** — last-value scalars (loss, tokens/s, live device bytes).
  A gauge accepts anything float-convertible and coerces at *snapshot*
  time, so hot paths may store a not-yet-ready ``jax.Array`` without
  forcing a device sync;
- **histograms** — streaming distributions (step latency, compile time):
  running count/sum/min/max, an EMA, and p50/p95/p99 over a bounded
  sliding window (exact percentiles over unbounded streams would hold
  every sample; a window is what production step-latency dashboards use).

One JSONL sink (``to_jsonl``) emits flat scalar records — the schema
``tools/check_telemetry_schema.py`` validates:

    {"ts": <float unix seconds>, "step": <int|null>, "tag": <str>,
     "scalars": {<str>: <finite number>}}

Scalar names are namespaced: ``counter/<name>``, ``gauge/<name>``, and
``hist/<name>/{count,sum,min,max,mean,ema,p50,p95,p99}``.

Counter families by producer: ``engine/*`` ``executor/*`` ``reader/*``
``prefetch/*`` ``compile/*`` ``checkpoint/*`` ``device/*`` and the
recovery runtime's ``resilience/{nonfinite_steps,rollbacks,
quarantined_batches,worker_respawns,restarts,watchdog_dumps,io_retries,
spills,resumes,preempt_exits}`` (README "Fault tolerance";
``tools/check_telemetry_schema.py --require-prefix counter/resilience/``
asserts a run left a recovery trace).
"""
from __future__ import annotations

import json
import math
import os
import threading
import time
from collections import deque
from typing import Dict, Optional

import numpy as np

from ..core import monitor

__all__ = ["Histogram", "Telemetry", "get_telemetry", "sample_device_memory",
           "start_periodic_flush", "stop_periodic_flush",
           "start_device_memory_sampler", "stop_device_memory_sampler"]

_HIST_WINDOW = 1024  # sliding-window size backing the percentile estimates


class Histogram:
    """Streaming scalar distribution: running aggregates + EMA + windowed
    percentiles. Thread-safe; ``observe`` is O(1)."""

    def __init__(self, window: int = _HIST_WINDOW, ema_alpha: float = 0.1):
        self._lock = threading.Lock()
        self._window: deque = deque(maxlen=window)
        self._alpha = float(ema_alpha)
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self.ema = None

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self.count += 1
            self.sum += v
            self.min = v if v < self.min else self.min
            self.max = v if v > self.max else self.max
            self.ema = v if self.ema is None else (
                self._alpha * v + (1.0 - self._alpha) * self.ema)
            self._window.append(v)

    def percentile(self, q) -> float:
        """Linear-interpolated percentile(s) over the sliding window."""
        with self._lock:
            if not self._window:
                return float("nan")
            return float(np.percentile(np.asarray(self._window), q))

    def recent_above(self, bound: float, n: int) -> tuple:
        """``(above, considered)`` over the most recent ``min(n, window)``
        samples — the SLO monitor's bad-event estimator (fraction of new
        observations past an objective's latency bound). O(n), off the
        hot path (called at the monitor tick, never per observe)."""
        with self._lock:
            win = list(self._window)[-int(n):] if n > 0 else []
        return sum(1 for v in win if v > bound), len(win)

    def summary(self) -> Dict[str, float]:
        with self._lock:
            if self.count == 0:
                # count/sum must survive even the empty snapshot: the
                # Prometheus exposition and burn-rate math difference
                # consecutive snapshots, and a missing field reads as
                # "metric disappeared", not zero
                return {"count": 0, "sum": 0.0}
            # copy aggregates under the same lock as the window: an
            # in-flight observe() on another thread must not tear
            # count/sum apart (mean would be wrong in the export)
            count, total, lo, hi, ema = (self.count, self.sum, self.min,
                                         self.max, self.ema)
            win = np.asarray(self._window)
        p50, p95, p99 = np.percentile(win, [50, 95, 99])
        return {
            "count": count, "sum": total, "min": lo, "max": hi,
            "mean": total / count, "ema": ema,
            "p50": float(p50), "p95": float(p95), "p99": float(p99),
        }


class _Timer:
    """Context manager feeding a histogram in milliseconds."""

    def __init__(self, telemetry: "Telemetry", name: str):
        self._tel = telemetry
        self._name = name
        self.elapsed_ms = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, *exc):
        self.elapsed_ms = (time.perf_counter() - self._t0) * 1e3
        # a failed operation's partial time is not a sample of the
        # operation's duration — recording it would desync paired
        # metrics (e.g. checkpoint/write_ms count vs writes counter)
        if exc_type is None:
            self._tel.observe(self._name, self.elapsed_ms)
        return False


def _coerce_scalar(v) -> Optional[float]:
    """Best-effort float of a gauge value (may be a deferred jax.Array)."""
    try:
        f = float(np.asarray(v).ravel()[0])
    except Exception:
        return None
    return f if math.isfinite(f) else None


class Telemetry:
    """Process-wide metric hub. All mutators are cheap and thread-safe;
    disabling via ``PADDLE_TPU_TELEMETRY=0`` turns them into no-ops."""

    def __init__(self):
        self._lock = threading.Lock()
        self._gauges: Dict[str, object] = {}
        self._hists: Dict[str, Histogram] = {}
        self._counter_names: set = set()
        self.enabled = os.environ.get("PADDLE_TPU_TELEMETRY", "1") not in (
            "0", "false", "off")

    # -- primitives ------------------------------------------------------
    def counter(self, name: str, value: int = 1) -> None:
        if not self.enabled:
            return
        self._counter_names.add(name)
        monitor.stat_add(name, int(value))

    def counter_value(self, name: str) -> int:
        return monitor.stat_get(name)

    def gauge(self, name: str, value) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._gauges[name] = value

    def remove_gauges(self, match) -> int:
        """Drop every gauge whose name satisfies ``match(name)`` and
        return how many were dropped. For WINDOWED gauges (a device-
        profile capture's per-entry decomposition): a new window must
        retract the old window's values for entries it did not observe,
        or stale numbers outlive the capture that produced them and
        poison cross-field contracts."""
        with self._lock:
            stale = [n for n in self._gauges if match(n)]
            for n in stale:
                del self._gauges[n]
        return len(stale)

    def observe(self, name: str, value) -> None:
        if not self.enabled:
            return
        self.histogram(name).observe(value)

    def histogram(self, name: str) -> Histogram:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram()
            return h

    def hist_summary(self, name: str) -> Optional[Dict[str, float]]:
        """Summary of an existing histogram, or None — never creates
        one (readers like the MFU publisher must not seed empty hists
        into every snapshot)."""
        with self._lock:
            h = self._hists.get(name)
        return h.summary() if h is not None else None

    def timer(self, name: str) -> _Timer:
        return _Timer(self, name)

    def observe_interval(self, name: str, dt_ms: float) -> bool:
        """Record an inter-call interval as a steady-state step time,
        REJECTING pauses: an interval wildly above the running EMA is
        host work between steps (eval, checkpoint, data stall), not a
        step — recording it would make p99/max measure checkpoint
        cadence. One shared filter so the engine and executor step_ms
        metrics cannot drift apart. Returns True when recorded."""
        ema = self.histogram(name).ema
        if ema is not None and dt_ms >= 50 * ema + 1e3:
            return False
        self.observe(name, dt_ms)
        return True

    # -- snapshots -------------------------------------------------------
    def snapshot(self) -> dict:
        """Structured view: {'counters': .., 'gauges': .., 'histograms': ..}.
        Counters come from the shared StatRegistry, so stats bumped via
        ``monitor.stat_add`` directly appear too."""
        counters = {k: v for k, v in monitor.all_stats().items()}
        with self._lock:
            gauges = dict(self._gauges)
            hists = dict(self._hists)
        return {
            "counters": counters,
            "gauges": {k: g for k, g in (
                (k, _coerce_scalar(v)) for k, v in gauges.items())
                if g is not None},
            "histograms": {k: h.summary() for k, h in hists.items()},
        }

    def counter_scalars(self) -> Dict[str, int]:
        """Flat counters-only view (``counter/<name>``). This is the
        cheap snapshot the per-step chrome instant events use: it never
        coerces gauges (which may hold not-yet-ready device arrays — a
        ``float()`` there would block the async pipeline mid-profile)
        and never computes histogram percentiles."""
        return {f"counter/{k}": int(v)
                for k, v in monitor.all_stats().items()}

    def scalars(self) -> Dict[str, float]:
        """Flat ``{namespaced_name: number}`` view — the JSONL payload."""
        snap = self.snapshot()
        out: Dict[str, float] = {}
        for k, v in snap["counters"].items():
            out[f"counter/{k}"] = int(v)
        for k, v in snap["gauges"].items():
            out[f"gauge/{k}"] = v
        for k, s in snap["histograms"].items():
            for field, v in s.items():
                if v is not None and math.isfinite(float(v)):
                    out[f"hist/{k}/{field}"] = float(v)
        return out

    def to_jsonl(self, path: str, step: Optional[int] = None,
                 tag: str = "telemetry", extra: Optional[dict] = None,
                 append: bool = True) -> str:
        """Append one flat scalar record (the documented schema) to
        ``path``. ``extra`` scalars merge on top of the snapshot."""
        try:
            # refresh gauge/mfu + per-entry attribution gauges from the
            # latest cost records and step histograms, so every exported
            # record carries a current MFU (lazy import: xla_cost imports
            # this module)
            from . import xla_cost

            xla_cost.publish_mfu(self)
        except Exception:
            pass  # attribution must never block a telemetry export
        profile_payload = None
        try:
            # refresh the device-profile decomposition gauges and the
            # bottleneck verdicts the same way, and pick up the last
            # capture's structured top-K table for the record
            from . import bottleneck, device_profile

            device_profile.publish(self)
            bottleneck.publish(self)
            profile_payload = device_profile.jsonl_payload()
        except Exception:
            pass
        goodput_payload = None
        try:
            # refresh the wall-clock ledger gauges (gauge/goodput/*) and
            # pick up the structured attribution table — every exported
            # record then carries a current, conserving goodput snapshot
            from . import goodput

            goodput.publish(self)
            goodput_payload = goodput.jsonl_payload()
        except Exception:
            pass
        scalars = self.scalars()
        for k, v in (extra or {}).items():
            f = _coerce_scalar(v)
            if f is not None:
                scalars[str(k)] = f
        rec = {"ts": time.time(),
               "step": int(step) if step is not None else None,
               "tag": str(tag), "scalars": scalars}
        if profile_payload:
            # the per-op/per-line top-K tables ride as a STRUCTURED
            # top-level key (they are tables, not scalars); the schema
            # gate validates their shape when present
            rec["profile"] = profile_payload
        if goodput_payload:
            # per-attempt wall-clock attribution rides the same way; the
            # aggregator stitches these tables across restarts (last
            # table per attempt wins, attempts sum)
            rec["goodput"] = goodput_payload
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "a" if append else "w") as f:
            f.write(json.dumps(rec) + "\n")
        return path

    def reset(self) -> None:
        """Drop gauges/histograms and zero the counters this object
        created (other StatRegistry stats are left alone). Also resets
        the sibling per-function compile state: the ``tracked_jit``
        retrace trackers and the XLA cost registry — without that,
        back-to-back tests/benches inherit retrace counts and stale
        attribution (lazy imports: both modules import this one)."""
        with self._lock:
            self._gauges.clear()
            self._hists.clear()
            names = list(self._counter_names)
        for n in names:
            monitor.stat_reset(n)
        try:
            from .retrace import reset_trackers

            reset_trackers()
        except Exception:
            pass
        try:
            from .xla_cost import reset as _xla_reset

            _xla_reset()
        except Exception:
            pass
        try:
            # forget the last device-profile report (and abandon any
            # in-flight capture): a record written after reset must not
            # inherit the previous config's decomposition table
            from .device_profile import reset as _devprof_reset

            _devprof_reset()
        except Exception:
            pass
        try:
            # restart the goodput wall clock: per-config bench records
            # (and back-to-back tests) each get their own denominator
            from .goodput import reset as _goodput_reset

            _goodput_reset()
        except Exception:
            pass


_telemetry: Optional[Telemetry] = None
_telemetry_lock = threading.Lock()


def _flush_on_exit() -> None:
    """Final telemetry record to the env-configured sink at interpreter
    exit. This is how ``distributed.launch`` workers leave their
    per-rank JSONL (the launcher exports PADDLE_TPU_TELEMETRY_JSONL as
    ``<log_dir>/telemetry.rank<i>.jsonl`` per rank) without every
    training script remembering a to_jsonl call; ``tools/telemetry_agg``
    merges the files afterwards. ``os._exit`` paths (watchdog) skip
    atexit — the watchdog writes its record explicitly first."""
    sink = os.environ.get("PADDLE_TPU_TELEMETRY_JSONL")
    tel = _telemetry
    if not sink or tel is None or not tel.enabled:
        return
    try:
        tel.to_jsonl(sink, tag="exit")
    except Exception:
        pass  # interpreter teardown: never raise


def get_telemetry() -> Telemetry:
    global _telemetry
    if _telemetry is None:
        with _telemetry_lock:
            if _telemetry is None:
                import atexit

                _telemetry = Telemetry()
                atexit.register(_flush_on_exit)
                _autostart_background(_telemetry)
    return _telemetry


def _autostart_background(tel: Telemetry) -> None:
    """Arm the env-gated background observability services exactly once,
    when the process-wide Telemetry comes up: the periodic JSONL flush
    (PADDLE_TPU_TELEMETRY_FLUSH_EVERY_S), the device-memory sampler
    (PADDLE_TPU_DEVICE_MEM_SAMPLE_EVERY_S), and the per-rank ops HTTP
    server (PADDLE_TPU_OPS_PORT). All no-ops when their env is unset;
    none may ever take the process down."""
    if not tel.enabled:
        return
    try:
        start_periodic_flush(telemetry=tel)
    except Exception:
        pass
    try:
        start_device_memory_sampler(telemetry=tel)
    except Exception:
        pass
    try:
        # armed here, NOT inside the ops server: objectives evaluate and
        # alert into the JSONL/agg funnel even on processes that never
        # export an HTTP port
        from . import slo

        slo.maybe_start_from_env(telemetry=tel)
    except Exception:
        pass
    try:
        from . import ops_server

        ops_server.maybe_start_from_env(telemetry=tel)
    except Exception:
        pass


# -- periodic JSONL flush -----------------------------------------------------
# The atexit flush (_flush_on_exit) only covers orderly interpreter
# teardown: a SIGKILLed / OOMed rank loses its ENTIRE telemetry record,
# silently shrinking telemetry_agg's cluster medians (the dead-rank
# detector then reports it, but the signal it did emit while alive is
# gone). The periodic flusher appends an interval record so the JSONL
# always holds a recent snapshot no matter how the process dies.

def env_float(name: str, default: float = 0.0) -> float:
    """Env var as float, ``default`` on unset/malformed — the shared
    knob parser of the ops plane (slo.py / ops_server.py import it):
    observability config must never crash the workload it watches."""
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


class _IntervalService:
    """Lifecycle of one background daemon loop (flusher, mem sampler).

    Each started thread owns its OWN stop event: a stop whose join times
    out (e.g. the body blocked on a stalled filesystem) can never be
    "revived" by a later start clearing a shared event — the old thread
    still sees its permanently-set event and exits at its next wait,
    while the new thread runs off a fresh one. Start/stop are serialized
    by a lock, so two racing starts cannot both spawn writers."""

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop: Optional[threading.Event] = None

    def start(self, interval_s: float, body) -> threading.Thread:
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self._thread
            stop = threading.Event()

            def _loop():
                while not stop.wait(interval_s):
                    try:
                        body()
                    except Exception:
                        pass  # one failed tick must never kill the loop

            self._stop = stop
            self._thread = threading.Thread(target=_loop, name=self.name,
                                            daemon=True)
            self._thread.start()
            return self._thread

    def stop(self, timeout: float = 2.0) -> None:
        with self._lock:
            stop, thread = self._stop, self._thread
            self._stop = self._thread = None
        if stop is not None:
            stop.set()
        if thread is not None and thread.is_alive():
            thread.join(timeout)


_flusher = _IntervalService("TelemetryFlush")
_memsampler = _IntervalService("DeviceMemSampler")


def start_periodic_flush(interval_s: Optional[float] = None,
                         path: Optional[str] = None,
                         telemetry: Optional[Telemetry] = None,
                         tag: str = "periodic") -> Optional[threading.Thread]:
    """Append a telemetry record to ``path`` every ``interval_s`` on a
    daemon thread. Defaults come from PADDLE_TPU_TELEMETRY_FLUSH_EVERY_S
    and PADDLE_TPU_TELEMETRY_JSONL; returns None (no thread) when either
    resolves unset/<= 0. Idempotent: a live flusher is returned as-is."""
    if interval_s is None:
        interval_s = env_float("PADDLE_TPU_TELEMETRY_FLUSH_EVERY_S")
    path = path or os.environ.get("PADDLE_TPU_TELEMETRY_JSONL")
    if interval_s <= 0 or not path:
        return None
    tel = telemetry or get_telemetry()
    return _flusher.start(interval_s,
                          lambda: tel.to_jsonl(path, tag=tag))


def stop_periodic_flush(timeout: float = 2.0) -> None:
    _flusher.stop(timeout)


# The device-memory sampler: /metrics can only show live HBM
# in-use/peak if SOMETHING samples the allocator — callers historically
# had to call sample_device_memory by hand at step boundaries. The
# env-gated sampler keeps the device/* gauges fresh for scrapes with
# zero call-site changes.


def start_device_memory_sampler(interval_s: Optional[float] = None,
                                telemetry: Optional[Telemetry] = None,
                                ) -> Optional[threading.Thread]:
    """Run ``sample_device_memory`` every ``interval_s`` on a daemon
    thread (default: PADDLE_TPU_DEVICE_MEM_SAMPLE_EVERY_S; unset/<= 0 →
    no thread). Idempotent while a sampler is alive."""
    if interval_s is None:
        interval_s = env_float("PADDLE_TPU_DEVICE_MEM_SAMPLE_EVERY_S")
    if interval_s <= 0:
        return None
    tel = telemetry or get_telemetry()
    return _memsampler.start(interval_s,
                             lambda: sample_device_memory(tel))


def stop_device_memory_sampler(timeout: float = 2.0) -> None:
    _memsampler.stop(timeout)


if os.environ.get("PADDLE_TPU_TELEMETRY_JSONL"):
    # a sink is configured (e.g. this is a distributed.launch rank):
    # instantiate now so the atexit flush is registered even if the
    # process never touches telemetry before exiting — otherwise a rank
    # that crashes during setup leaves no JSONL and silently drops out
    # of the telemetry_agg cluster view
    get_telemetry()


def sample_device_memory(telemetry: Optional[Telemetry] = None) -> dict:
    """Device-memory gauges (the reference's STAT_gpu0_mem_size twin):
    ``device/live_bytes`` sums ``jax.live_arrays()``; when the backend
    reports allocator stats (TPU does), per-device gauges
    ``device/bytes_in_use.d<i>``/``device/peak_bytes_in_use.d<i>`` are
    emitted for EVERY addressable device and the legacy unsuffixed names
    carry the summed total — reading only device 0 under-reported every
    multi-chip process by a factor of the local device count."""
    import jax

    tel = telemetry or get_telemetry()
    out = {}
    try:
        out["device/live_bytes"] = float(
            sum(getattr(a, "nbytes", 0) for a in jax.live_arrays()))
    except Exception:
        pass
    totals = {"bytes_in_use": 0.0, "peak_bytes_in_use": 0.0}
    seen = {k: False for k in totals}
    try:
        for i, dev in enumerate(jax.local_devices()):
            try:
                stats = dev.memory_stats() or {}
            except Exception:
                continue  # CPU backends may not implement memory_stats
            for src in totals:
                if src in stats:
                    v = float(stats[src])
                    out[f"device/{src}.d{i}"] = v
                    totals[src] += v
                    seen[src] = True
    except Exception:
        pass
    for src, any_seen in seen.items():
        if any_seen:
            out[f"device/{src}"] = totals[src]
    for k, v in out.items():
        tel.gauge(k, v)
    return out
