"""Structured hierarchical spans + the crash flight recorder.

The reference's ``platform/profiler.h`` ``RecordEvent`` feeds two
consumers: a live timeline (device_tracer) and the post-mortem the
tuning loop reads. PR 1 reproduced only the flat half — an unstructured
``_host_spans`` list in ``utils/profiler.py`` that grew without bound
and carried no hierarchy. This module is the structured replacement:

- **Spans** — scoped, nested, step-correlated. A span records its
  parent (the innermost open span on the same thread), a process-unique
  ``span_id``, and the training ``step`` it belongs to (inherited from
  the nearest enclosing span that set one), so a timeline event can
  always be traced back to *which step of which epoch of which fit call*
  produced it. The canonical hierarchy the engines emit is
  ``fit → epoch → step → {h2d, compute, d2h, callback, checkpoint}``.
- **Window store** — completed spans recorded inside a profiling window
  (``utils.profiler.start_profiler``), exported as properly-nested
  chrome trace events. Bounded (``PADDLE_TPU_SPAN_WINDOW`` spans, FIFO)
  and drained by each export, so a long profiling session can no longer
  leak host memory (the PR 1 ``_host_spans`` bug).
- **Flight recorder** — an always-on bounded ring of span enter/exit
  events (``PADDLE_TPU_FLIGHT_EVENTS``, default 512). Recording is two
  deque appends per span — cheap enough to leave on in production — and
  the last-N-events tail is attached to the resilience watchdog dump
  and the StepGuard give-up report, so a hang or a poisoned run comes
  with the event history explaining what the process was *doing*, not
  just where its threads were parked.

Span enter/exit must stay OUTSIDE compiled regions (host code only):
under a jit trace a span would measure trace time once and then vanish
from the compiled program — the same class of mistake tpu-lint R8 flags
for Telemetry calls under trace.
"""
from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque
from typing import List, Optional

__all__ = [
    "Span", "span", "current_span", "FlightRecorder", "flight_recorder",
    "SpanStore", "window_store", "open_window", "close_window",
    "window_active", "chrome_events", "drain_window",
    "ReqTrace", "TraceStore", "trace_store", "trace_sample_rate",
    "should_trace", "trace_chrome_events",
    "rank_pid", "rank_process_metadata",
]


def rank_pid() -> int:
    """The ``pid`` every chrome export of this process stamps its events
    with: the global trainer RANK under a multi-process launch, else the
    OS pid. Per-rank exports used to all emit ``os.getpid()`` with no
    rank identity, so naively concatenated traces overlaid ranks on one
    track (and pids can genuinely collide across hosts); a rank-scoped
    pid makes every per-rank artifact merge-safe by construction
    (``profiler.cluster_trace`` and anyone hand-merging)."""
    try:
        world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1") or 1)
    except ValueError:
        world = 1
    if world > 1:
        for var in ("PADDLE_TRAINER_ID", "PROCESS_ID"):
            raw = os.environ.get(var)
            if raw:
                try:
                    return int(raw)
                except ValueError:
                    pass
    return os.getpid()


def rank_process_metadata(pid: Optional[int] = None) -> List[dict]:
    """The chrome metadata events naming this process's track: a
    ``process_name`` of ``rank <r>`` (or ``pid <p>`` standalone) plus a
    ``process_sort_index`` so merged traces list ranks in order."""
    p = rank_pid() if pid is None else int(pid)
    label = f"rank {p}" if p != os.getpid() else f"pid {p}"
    return [
        {"name": "process_name", "ph": "M", "pid": p,
         "args": {"name": label}},
        {"name": "process_sort_index", "ph": "M", "pid": p,
         "args": {"sort_index": p}},
    ]

_ids = itertools.count(1)  # process-unique span ids (GIL-atomic next())
_tls = threading.local()   # per-thread stack of open spans


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def current_span() -> Optional["Span"]:
    """Innermost open span on this thread (None outside any span)."""
    st = _stack()
    return st[-1] if st else None


def in_category(cat: str) -> bool:
    """True when any open span on this thread has category ``cat`` —
    engines use this to avoid double-opening a "step" span when a
    higher-level loop (hapi fit) already holds one."""
    return any(s.cat == cat for s in _stack())


class SpanStore:
    """Bounded FIFO of completed-span records for the profiling window.

    Each record is ``(name, cat, ts_us, dur_us, tid, span_id, parent_id,
    step)``. Bounded: when the window overflows, the OLDEST spans fall
    out — an export of a too-long window shows the most recent activity,
    and memory stays flat either way."""

    def __init__(self, capacity: Optional[int] = None):
        cap = capacity or _env_int("PADDLE_TPU_SPAN_WINDOW", 65536)
        self._lock = threading.Lock()
        self._spans: deque = deque(maxlen=cap)
        self.dropped = 0

    def __len__(self) -> int:
        return len(self._spans)

    def add(self, rec) -> None:
        with self._lock:
            if len(self._spans) == self._spans.maxlen:
                self.dropped += 1
            self._spans.append(rec)

    def drain(self) -> List[tuple]:
        """Return all records and clear — each export owns its window."""
        with self._lock:
            out = list(self._spans)
            self._spans.clear()
            self.dropped = 0
        return out

    def snapshot(self) -> List[tuple]:
        with self._lock:
            return list(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self.dropped = 0


class FlightRecorder:
    """Always-on bounded ring of span ENTER/EXIT events.

    Events are ``(phase, name, cat, ts_us, dur_us, tid, span_id,
    parent_id, step)`` with phase ``"B"``/``"E"``. Keeping both phases
    (not just completed spans) is the point: at crash time the tail
    shows which spans were OPEN — ``step#842 B, h2d B, h2d E, compute
    B`` and nothing after means the hang is inside the compiled step,
    not the input pipeline."""

    def __init__(self, capacity: Optional[int] = None):
        cap = capacity or _env_int("PADDLE_TPU_FLIGHT_EVENTS", 512)
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=cap)

    @property
    def capacity(self) -> int:
        return self._ring.maxlen

    def __len__(self) -> int:
        return len(self._ring)

    def record(self, phase, name, cat, ts_us, dur_us, tid, span_id,
               parent_id, step) -> None:
        with self._lock:
            self._ring.append((phase, name, cat, ts_us, dur_us, tid,
                               span_id, parent_id, step))

    def tail(self, n: Optional[int] = None) -> List[tuple]:
        with self._lock:
            events = list(self._ring)
        return events if n is None else events[-n:]

    def dump(self, n: Optional[int] = None) -> List[dict]:
        keys = ("phase", "name", "cat", "ts_us", "dur_us", "tid",
                "span_id", "parent_id", "step")
        return [dict(zip(keys, ev)) for ev in self.tail(n)]

    def format_tail(self, n: Optional[int] = None) -> str:
        """Human-readable tail for crash reports, newest last."""
        events = self.tail(n)
        if not events:
            return "(flight recorder empty)"
        t_end = events[-1][3]
        lines = []
        for phase, name, cat, ts, dur, tid, sid, pid, step in events:
            dt = (ts - t_end) / 1e6
            stepinfo = f" step={step}" if step is not None else ""
            durinfo = f" {dur / 1e3:.3f}ms" if phase == "E" else ""
            lines.append(f"[{dt:+9.3f}s] {phase} {name} ({cat})"
                         f"{stepinfo} span={sid}"
                         + (f" parent={pid}" if pid else "") + durinfo)
        return "\n".join(lines)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


_window = SpanStore()
_flight = FlightRecorder()
_window_active = False


def window_store() -> SpanStore:
    return _window


def flight_recorder() -> FlightRecorder:
    return _flight


def window_active() -> bool:
    return _window_active


def open_window(clear: bool = True) -> None:
    """Start recording completed spans into the window store. With
    ``clear`` (the default for a FRESH window) previous leftovers are
    dropped; re-opening while a window is live must pass ``clear=False``
    so the outer window's spans survive."""
    global _window_active
    if clear:
        _window.clear()
    _window_active = True


def close_window() -> None:
    """Stop window recording. Does NOT drain: the spans stay available
    for an export after the window closed (exports drain)."""
    global _window_active
    _window_active = False


def drain_window() -> List[tuple]:
    return _window.drain()


class Span:
    """Scoped span. Context manager; re-entrant use is a fresh span.

    ``step`` is inherited from the nearest enclosing span that set one,
    so instrumented leaf operations (h2d, compute, checkpoint) are
    step-correlated without every call site threading the step through.
    """

    __slots__ = ("name", "cat", "step", "span_id", "parent_id", "tid",
                 "ts_us", "dur_us", "_t0")

    def __init__(self, name: str, cat: str = "host",
                 step: Optional[int] = None):
        self.name = name
        self.cat = cat
        self.step = step
        self.span_id = None
        self.parent_id = None
        self.tid = None
        self.ts_us = None
        self.dur_us = None

    def __enter__(self) -> "Span":
        st = _stack()
        parent = st[-1] if st else None
        self.span_id = next(_ids)
        self.parent_id = parent.span_id if parent is not None else 0
        if self.step is None and parent is not None:
            self.step = parent.step
        self.tid = threading.get_ident()
        st.append(self)
        self._t0 = time.perf_counter()
        self.ts_us = self._t0 * 1e6
        _flight.record("B", self.name, self.cat, self.ts_us, 0.0, self.tid,
                       self.span_id, self.parent_id, self.step)
        return self

    def __exit__(self, *exc) -> bool:
        t1 = time.perf_counter()
        self.dur_us = (t1 - self._t0) * 1e6
        st = _stack()
        # tolerate a torn stack (an enclosing span leaked by an exception
        # path that bypassed __exit__): unwind to self so one bad scope
        # cannot corrupt parentage for the rest of the process
        while st and st[-1] is not self:
            st.pop()
        if st:
            st.pop()
        _flight.record("E", self.name, self.cat, t1 * 1e6, self.dur_us,
                       self.tid, self.span_id, self.parent_id, self.step)
        if _window_active:
            _window.add((self.name, self.cat, self.ts_us, self.dur_us,
                         self.tid, self.span_id, self.parent_id, self.step))
        return False


def span(name: str, cat: str = "host", step: Optional[int] = None) -> Span:
    """``with span("h2d", cat="h2d"): ...`` — the one-liner call sites use."""
    return Span(name, cat=cat, step=step)


def mark(name: str, cat: str = "host", step: Optional[int] = None) -> None:
    """Zero-duration marker span (``Profiler.step()`` boundaries)."""
    with Span(name, cat=cat, step=step):
        pass


# -- request-scoped tracing ---------------------------------------------------
# A sampled serving request carries ONE trace across its whole lifecycle
# (submit → admit → queue → prefill chunks → decode steps → terminal), so
# "p99 is slow" decomposes into queue wait vs prefill interleave vs decode
# stalls for a real request instead of being argued from aggregate
# histograms. Sampling is deterministic on the request id
# (PADDLE_TPU_TRACE_SAMPLE: a fraction; 1 traces everything, 0.01 traces
# every 100th id) so a replayed load plan samples the same requests.


class ReqTrace:
    """The timeline of one sampled request. Events are appended by the
    submit path, the admission funnel, and the scheduler thread; each is
    ``(name, t0_seconds_perf_counter, dur_seconds)``. Appends are plain
    list appends (GIL-atomic) — the trace is written by at most one
    thread per lifecycle stage and only read after the terminal
    transition publishes it to the store."""

    __slots__ = ("trace_id", "req_id", "events")

    def __init__(self, req_id: int, trace_id: Optional[str] = None):
        self.req_id = int(req_id)
        self.trace_id = trace_id or f"{os.getpid()}-{req_id}"
        self.events: list = []

    def event(self, name: str, dur_s: float = 0.0) -> None:
        """Record an event that ENDED now and lasted ``dur_s`` (0 for an
        instant mark) — call sites measure a duration then stamp it."""
        now = time.perf_counter()
        self.events.append((str(name), now - float(dur_s), float(dur_s)))

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "req_id": self.req_id,
            "events": [{"name": n, "ts_us": t0 * 1e6, "dur_us": d * 1e6}
                       for n, t0, d in self.events],
        }

    def chrome_events(self, pid: Optional[int] = None) -> List[dict]:
        """One self-contained catapult timeline: every event is a complete
        ("X") slice on a per-request track, all carrying the trace id."""
        pid = pid if pid is not None else os.getpid()
        return [{"name": n, "ph": "X", "ts": t0 * 1e6, "dur": d * 1e6,
                 "pid": pid, "tid": f"req {self.trace_id}", "cat": "request",
                 "args": {"trace_id": self.trace_id, "req_id": self.req_id}}
                for n, t0, d in self.events]


class TraceStore:
    """Bounded FIFO of COMPLETED request traces (terminal transition
    publishes them). Snapshots feed ``/debug/requests``; chrome exports
    drain (each export owns its window, like the span store)."""

    def __init__(self, capacity: Optional[int] = None):
        cap = capacity or _env_int("PADDLE_TPU_TRACE_STORE", 256)
        self._lock = threading.Lock()
        self._traces: deque = deque(maxlen=cap)

    def __len__(self) -> int:
        return len(self._traces)

    def add(self, trace: ReqTrace) -> None:
        with self._lock:
            self._traces.append(trace)

    def snapshot(self, n: Optional[int] = None) -> List[ReqTrace]:
        with self._lock:
            out = list(self._traces)
        if n is None:
            return out
        # n <= 0 means "none": out[-0:] would slice the WHOLE store,
        # answering a request for the minimum with the maximum payload
        return out[-n:] if n > 0 else []

    def drain(self) -> List[ReqTrace]:
        with self._lock:
            out = list(self._traces)
            self._traces.clear()
        return out

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()


_traces = TraceStore()


def trace_store() -> TraceStore:
    return _traces


def trace_sample_rate() -> float:
    """PADDLE_TPU_TRACE_SAMPLE as a fraction in [0, 1] (0 = tracing off,
    the default; malformed values read as 0 — observability must never
    take the serving path down)."""
    raw = os.environ.get("PADDLE_TPU_TRACE_SAMPLE", "")
    if not raw:
        return 0.0
    try:
        rate = float(raw)
    except ValueError:
        return 0.0
    return min(max(rate, 0.0), 1.0)


def should_trace(req_id: int, rate: Optional[float] = None) -> bool:
    """Deterministic id-keyed sampling: rate 1 → every request, rate r →
    every round(1/r)-th id. Id-keyed (not random) so a replayed load plan
    samples the same requests and gates can assert on a specific one."""
    r = trace_sample_rate() if rate is None else rate
    if r <= 0.0:
        return False
    if r >= 1.0:
        return True
    return int(req_id) % max(1, int(round(1.0 / r))) == 0


def trace_chrome_events(pid: Optional[int] = None,
                        drain: bool = True) -> List[dict]:
    """Catapult events of every stored request trace (chrome-export hook)."""
    traces = _traces.drain() if drain else _traces.snapshot()
    events: List[dict] = []
    for t in traces:
        events.extend(t.chrome_events(pid=pid))
    return events


def chrome_events(records=None, pid: Optional[int] = None) -> List[dict]:
    """Convert window span records to chrome://tracing complete events.

    Nesting falls out of ts/dur scoping per tid; ``args`` carries the
    structured identity (span_id/parent_id/step) so downstream tools can
    rebuild the tree without re-deriving containment."""
    if records is None:
        records = drain_window()
    pid = pid if pid is not None else os.getpid()
    events = []
    for name, cat, ts, dur, tid, sid, par, step in records:
        args = {"span_id": sid, "parent_id": par}
        if step is not None:
            args["step"] = step
        events.append({"name": name, "ph": "X", "ts": ts, "dur": dur,
                       "pid": pid, "tid": tid, "cat": cat, "args": args})
    return events
