"""XLA cost attribution: per-executable FLOPs/HBM accounting, MFU, roofline.

The benchmarks report samples/s with no denominator: nothing in the repo
can say how fast the hardware *allows*. This module closes that gap by
capturing XLA's own cost model for every compiled executable — hooked
where compiles already funnel (``tracked_jit`` wraps every jitted entry:
``jit.TrainStep/EvalStep``, ``fleet.ParallelTrainStep``,
``static.Executor._compile``/``_compile_multi``) — and combining it with
the measured ``*step_ms`` histograms and a per-chip peak registry into:

- ``gauge/compile/flops``, ``gauge/compile/bytes_accessed``,
  ``gauge/compile/peak_hbm_bytes`` — the most recently compiled
  executable, plus per-entry ``gauge/compile/<entry>/...`` twins;
- ``gauge/mfu`` (+ per-entry ``gauge/mfu/<entry>``) — model FLOPs
  utilization, % of the chip's peak;
- ``gauge/hbm_gbps/<entry>`` — achieved HBM bytes/s;
- ``gauge/roofline/<entry>`` — 1 when the program's arithmetic intensity
  (flops / bytes accessed) exceeds the machine balance point
  (peak flops / HBM bandwidth), i.e. compute-bound; 0 = memory-bound.

Capture modes (``PADDLE_TPU_COST_ANALYSIS``):

- ``1`` (default) — ``jitted.lower(...).cost_analysis()``: HLO-level
  flops/bytes with NO second XLA compile (~10 ms host work per fresh
  compile); peak HBM is *estimated* as argument+output bytes from the
  call's own leaves (no temp term — a lower bound, flagged
  ``estimated``).
- ``full`` — ``lowered.compile()`` → optimized ``cost_analysis()`` +
  ``memory_analysis()``: exact peak HBM (argument+output+temp−alias) at
  the price of a second XLA compile per fresh signature. ``bench_all.py``
  runs in this mode (the persistent compilation cache absorbs the cost
  on rigs that configure it).
- ``0`` — off.

Per-chip peaks come from a device-kind registry with env overrides:
``PADDLE_TPU_PEAK_FLOPS`` (absolute FLOP/s) and ``PADDLE_TPU_HBM_GBPS``
(GB/s). Defaults are bf16 systolic peaks; running fp32 matmuls halves
real attainable — override when that matters.

Steps-per-call: a windowed executable (``executor.run_steps``,
``fleet.train_step_multi``) runs N train steps per invocation while the
step histograms record per-step time, so the engines register their
window length via ``set_steps_per_call`` and MFU divides the program's
flops by it.
"""
from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time
from typing import Dict, List, Optional

from .telemetry import Telemetry, get_telemetry

__all__ = [
    "CostRecord", "CostRegistry", "cost_registry", "capture",
    "record_compile", "set_steps_per_call", "chip_peaks", "publish_mfu",
    "roofline_verdict", "reset", "cost_analysis_mode",
    "hbm_capacity_bytes",
]

logger = logging.getLogger("paddle_tpu.profiler")

# device_kind substring (lowercased) -> (peak FLOP/s bf16, HBM bytes/s).
# Ordered: first match wins, so the more specific kinds come first.
_CHIP_PEAKS = (
    ("v5 lite", (197e12, 819e9)),   # v5e
    ("v5litepod", (197e12, 819e9)),
    ("v5e", (197e12, 819e9)),
    ("v5p", (459e12, 2765e9)),
    ("v6 lite", (918e12, 1640e9)),  # Trillium
    ("v6e", (918e12, 1640e9)),
    ("v4", (275e12, 1228e9)),
    ("v3", (123e12, 900e9)),
    ("v2", (45e12, 700e9)),
    # CPU simulation rigs: a nominal per-process peak so MFU math stays
    # exercised end-to-end off-TPU (absolute value is not meaningful —
    # override with PADDLE_TPU_PEAK_FLOPS for a calibrated host).
    ("cpu", (5e11, 50e9)),
)
_FALLBACK_PEAKS = (1e12, 100e9)

# device_kind substring (lowercased) -> HBM capacity in bytes. Same
# first-match-wins ordering as _CHIP_PEAKS. The CPU entry is a nominal
# host budget so remat='auto' resolves to "fits, no remat" on test rigs
# unless a test pins PADDLE_TPU_DEVICE_HBM_BYTES down to force the
# escalation ladder.
_CHIP_HBM = (
    ("v5 lite", 16e9), ("v5litepod", 16e9), ("v5e", 16e9),
    ("v5p", 95e9),
    ("v6 lite", 32e9), ("v6e", 32e9),
    ("v4", 32e9), ("v3", 32e9), ("v2", 16e9),
    ("cpu", 64e9),
)
_FALLBACK_HBM = 32e9

_peaks_cache = None
_peaks_lock = threading.Lock()


def hbm_capacity_bytes() -> float:
    """Per-device HBM capacity in bytes — the budget ``ops.remat_policy``
    sizes checkpoint policies against. ``PADDLE_TPU_DEVICE_HBM_BYTES``
    overrides; else the device's own ``memory_stats()['bytes_limit']``
    when the backend reports one; else the device-kind registry."""
    try:
        ov = float(os.environ.get("PADDLE_TPU_DEVICE_HBM_BYTES") or 0)
        if ov > 0:
            return ov
    except ValueError:
        pass
    kind = "unknown"
    try:
        import jax

        dev = jax.local_devices()[0]
        kind = str(dev.device_kind).lower()
        stats = dev.memory_stats()
        limit = (stats or {}).get("bytes_limit", 0)
        if limit and limit > 0:
            return float(limit)
    except Exception:
        pass
    for sub, cap in _CHIP_HBM:
        if sub in kind:
            return cap
    return _FALLBACK_HBM


def cost_analysis_mode() -> str:
    """"off" | "on" | "full" (see module docstring)."""
    v = os.environ.get("PADDLE_TPU_COST_ANALYSIS", "1").strip().lower()
    if v in ("0", "false", "off", "no"):
        return "off"
    return "full" if v == "full" else "on"


def chip_peaks() -> Dict[str, float]:
    """{"flops": peak FLOP/s, "bytes_per_s": HBM bytes/s, "kind": str}.

    Env overrides beat the registry; the registry matches the first
    device's ``device_kind`` substring. Cached after first resolution
    (env is re-read only via ``reset()``)."""
    global _peaks_cache
    if _peaks_cache is not None:
        return _peaks_cache
    with _peaks_lock:
        if _peaks_cache is not None:
            return _peaks_cache
        kind = "unknown"
        try:
            import jax

            kind = str(jax.devices()[0].device_kind).lower()
        except Exception:
            pass
        flops, bps = _FALLBACK_PEAKS
        for sub, (f, b) in _CHIP_PEAKS:
            if sub in kind:
                flops, bps = f, b
                break
        # non-positive overrides are rejected (kept at the registry
        # default): a zero would turn every MFU division into a crash,
        # and "0 to disable" belongs to PADDLE_TPU_COST_ANALYSIS
        try:
            ov = float(os.environ.get("PADDLE_TPU_PEAK_FLOPS") or 0)
            if ov > 0:
                flops = ov
        except ValueError:
            pass
        try:
            ov = float(os.environ.get("PADDLE_TPU_HBM_GBPS") or 0)
            if ov > 0:
                bps = ov * 1e9
        except ValueError:
            pass
        _peaks_cache = {"flops": flops, "bytes_per_s": bps, "kind": kind}
    return _peaks_cache


@dataclasses.dataclass
class CostRecord:
    """One compiled executable's cost profile."""

    entry: str                  # tracked_jit entry name (compile/<entry>)
    bucket: str                 # shape-bucket key (abstract signature)
    flops: float = 0.0
    bytes_accessed: float = 0.0
    peak_hbm_bytes: float = 0.0
    argument_bytes: float = 0.0
    output_bytes: float = 0.0
    temp_bytes: float = 0.0
    alias_bytes: float = 0.0
    estimated: bool = True      # True: peak_hbm has no temp term (no compile)
    ts: float = 0.0

    def intensity(self) -> Optional[float]:
        """Arithmetic intensity, FLOP per HBM byte."""
        if self.bytes_accessed > 0:
            return self.flops / self.bytes_accessed
        return None


class CostRegistry:
    """Per-entry, per-shape-bucket cost records.

    ``latest`` keeps the most recent record per entry (the live program
    — what MFU is computed against); ``entries()`` exposes every bucket
    for offline attribution."""

    def __init__(self):
        self._lock = threading.Lock()
        self._buckets: Dict[str, Dict[str, CostRecord]] = {}
        self._latest: Dict[str, CostRecord] = {}
        self._steps_per_call: Dict[str, int] = {}
        self._last_entry: Optional[str] = None

    def add(self, rec: CostRecord) -> None:
        with self._lock:
            self._buckets.setdefault(rec.entry, {})[rec.bucket] = rec
            self._latest[rec.entry] = rec
            self._last_entry = rec.entry

    def latest(self) -> Dict[str, CostRecord]:
        with self._lock:
            return dict(self._latest)

    def entries(self) -> Dict[str, Dict[str, CostRecord]]:
        with self._lock:
            return {k: dict(v) for k, v in self._buckets.items()}

    def last_entry(self) -> Optional[str]:
        return self._last_entry

    def set_steps_per_call(self, entry: str, n: int) -> None:
        with self._lock:
            self._steps_per_call[entry] = max(int(n), 1)

    def steps_per_call(self, entry: str) -> int:
        with self._lock:
            return self._steps_per_call.get(entry, 1)

    def reset(self) -> None:
        with self._lock:
            self._buckets.clear()
            self._latest.clear()
            self._steps_per_call.clear()
            self._last_entry = None


_registry = CostRegistry()
_mfu_overflow_warned: set = set()  # entries already warned about >100% MFU


def cost_registry() -> CostRegistry:
    return _registry


def set_steps_per_call(entry: str, n: int) -> None:
    """Engines running N train steps per invocation (scan windows)
    register N so MFU divides the program's flops accordingly."""
    _registry.set_steps_per_call(entry, n)


def reset() -> None:
    """Drop all records and the cached chip peaks (tests re-read env).
    The compiled-HLO registry feeding device-profile attribution resets
    with the cost records — both describe the same compiles."""
    global _peaks_cache
    _registry.reset()
    _mfu_overflow_warned.clear()
    _lint_warned.clear()
    with _peaks_lock:
        _peaks_cache = None
    try:
        from . import hlo_attrib

        hlo_attrib.hlo_registry().reset()
    except Exception:
        pass
    try:
        # the per-axis collective attribution layer caches parses of (and
        # registers the mesh for) the same compiles — same lifetime
        from . import collective_attrib

        collective_attrib.reset()
    except Exception:
        pass


# -- capture ---------------------------------------------------------------

def _leaf_bytes(tree) -> float:
    import jax
    import numpy as np

    total = 0.0
    for leaf in jax.tree_util.tree_leaves(tree):
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is None and hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            # ShapeDtypeStruct (eval_shape output) carries no nbytes
            try:
                nbytes = int(np.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize
            except Exception:
                nbytes = None
        if nbytes is not None:
            total += float(nbytes)
    return total


def _bucket_key(args, kwargs) -> str:
    """Readable shape-bucket key from the call's array leaves, bounded
    length (a large pytree collapses to a prefix + leaf count)."""
    import jax

    leaves, _ = jax.tree_util.tree_flatten((args, kwargs))
    parts = []
    n_arrays = 0
    for leaf in leaves:
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            n_arrays += 1
            if len(parts) < 6:
                shape = ",".join(str(d) for d in leaf.shape)
                parts.append(f"{leaf.dtype}[{shape}]")
    key = " ".join(parts) or "scalar"
    if n_arrays > 6:
        key += f" +{n_arrays - 6} more"
    return key


def _normalize_cost(ca) -> dict:
    """``cost_analysis`` returns a dict (Lowered) or a per-device list of
    dicts (Compiled); either way the per-device view is what MFU wants
    (per-chip flops against per-chip peak)."""
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca or {})


def record_compile(entry: str, flops: float, bytes_accessed: float = 0.0,
                   argument_bytes: float = 0.0, output_bytes: float = 0.0,
                   temp_bytes: float = 0.0, alias_bytes: float = 0.0,
                   bucket: str = "default", estimated: bool = True,
                   telemetry: Optional[Telemetry] = None) -> CostRecord:
    """Register one executable's cost profile and publish the
    ``compile/*`` gauges. Public seam: ``capture`` feeds it from live
    jits; tests and offline tools feed it numbers directly."""
    peak_hbm = max(argument_bytes + output_bytes + temp_bytes
                   - alias_bytes, 0.0)
    rec = CostRecord(entry=entry, bucket=bucket, flops=float(flops),
                     bytes_accessed=float(bytes_accessed),
                     peak_hbm_bytes=peak_hbm,
                     argument_bytes=float(argument_bytes),
                     output_bytes=float(output_bytes),
                     temp_bytes=float(temp_bytes),
                     alias_bytes=float(alias_bytes),
                     estimated=estimated, ts=time.time())
    _registry.add(rec)
    tel = telemetry or get_telemetry()
    for suffix, value in (("flops", rec.flops),
                          ("bytes_accessed", rec.bytes_accessed),
                          ("peak_hbm_bytes", rec.peak_hbm_bytes)):
        tel.gauge(f"compile/{suffix}", value)
        tel.gauge(f"compile/{entry}/{suffix}", value)
    return rec


def capture(entry: str, jitted, args, kwargs) -> Optional[CostRecord]:
    """Cost-analyze the executable a fresh ``tracked_jit`` compile just
    produced. Best-effort by contract: attribution must never break a
    training step, so every failure degrades to a debug log. Called
    AFTER the triggering call returned — ``lower`` only reads avals, so
    donated (already-deleted) argument buffers are safe."""
    if cost_analysis_mode() == "off":
        return None
    try:
        lowered = jitted.lower(*args, **kwargs)
        bucket = _bucket_key(args, kwargs)
        if cost_analysis_mode() == "full":
            compiled = lowered.compile()
            ca = _normalize_cost(compiled.cost_analysis())
            mem = compiled.memory_analysis()
            _stash_hlo(entry, compiled=compiled)
            return record_compile(
                entry, flops=ca.get("flops", 0.0),
                bytes_accessed=ca.get("bytes accessed", 0.0),
                argument_bytes=getattr(mem, "argument_size_in_bytes", 0),
                output_bytes=getattr(mem, "output_size_in_bytes", 0),
                temp_bytes=getattr(mem, "temp_size_in_bytes", 0),
                alias_bytes=getattr(mem, "alias_size_in_bytes", 0),
                bucket=bucket, estimated=False)
        ca = _normalize_cost(lowered.cost_analysis())
        out_bytes = 0.0
        try:
            # out_info carries the output avals of the lowering we already
            # have; eval_shape would re-trace the whole step function
            out_bytes = _leaf_bytes(lowered.out_info)
        except Exception:
            try:
                out_bytes = _leaf_bytes(jitted.eval_shape(*args, **kwargs))
            except Exception:
                pass
        _stash_hlo(entry, lowered=lowered)
        return record_compile(
            entry, flops=ca.get("flops", 0.0),
            bytes_accessed=ca.get("bytes accessed", 0.0),
            argument_bytes=_leaf_bytes((args, kwargs)),
            output_bytes=out_bytes, bucket=bucket, estimated=True)
    except Exception as e:
        logger.debug("xla_cost: cost analysis failed for %s: %s", entry, e)
        return None


def _stash_hlo(entry: str, compiled=None, lowered=None) -> None:
    """Feed the device-profile attribution layer the compiled HLO this
    capture already holds: optimized text in full mode (no extra work —
    the compile happened above), the in-hand Lowered otherwise
    (hlo_attrib compiles it to text only if a profile is ever taken).
    Best-effort like everything else in this module."""
    try:
        from . import hlo_attrib

        if compiled is not None:
            hlo_attrib.hlo_registry().put_text(entry, compiled.as_text())
        elif lowered is not None:
            hlo_attrib.hlo_registry().put_lowered(entry, lowered)
    except Exception as e:  # noqa: BLE001
        logger.debug("xla_cost: HLO stash failed for %s: %s", entry, e)
    _maybe_lint(entry)


# -- the optimized-HLO-text access path + opt-in compile-time lint ---------

def hlo_text_for(entry: str) -> Optional[str]:
    """THE access path to an entry's optimized HLO text — full mode
    returns the text the compile already produced; the default mode
    compiles the stored Lowered on demand (counted ``profile/
    hlo_compiles`` — the one place attribution pays a compile). Both
    ``hlo_attrib`` consumers and the hlo-lint hook/CLI go through here:
    there is exactly one asymmetry and this is where it lives."""
    from . import hlo_attrib

    return hlo_attrib.hlo_registry().text_for(entry)


def hlo_texts(entries: Optional[List[str]] = None) -> Dict[str, str]:
    """``{entry: optimized HLO text}`` over the registry (or the given
    entries) via :func:`hlo_text_for`'s contract."""
    from . import hlo_attrib

    return hlo_attrib.hlo_registry().texts(entries)


def hlo_lint_enabled() -> bool:
    """Opt-in: ``PADDLE_TPU_HLO_LINT=1`` lints every fresh compile."""
    v = os.environ.get("PADDLE_TPU_HLO_LINT", "").strip().lower()
    return v in ("1", "true", "on", "yes")


# (entry, rule) pairs already warned about — the log gets ONE line per
# program/rule, the counters keep counting every finding
_lint_warned: set = set()


def _maybe_lint(entry: str) -> None:
    """The compile-time hook: when ``PADDLE_TPU_HLO_LINT`` is set, run
    the H-rules over the program this capture just stashed, publish
    ``counter/hlolint/findings.<rule>`` per finding, and warn once per
    (entry, rule). Best-effort like every attribution hook — lint must
    never break the compile it is judging."""
    if not hlo_lint_enabled():
        return
    try:
        from ..analysis.hlo import AnalysisContext, analyze_hlo_text
        from . import collective_attrib

        text = hlo_text_for(entry)
        if not text:
            return
        bf16 = False
        try:
            from ..amp.auto_cast import amp_state

            state = amp_state()
            bf16 = bool(getattr(state, "enabled", False)) and \
                "bf16" in str(getattr(state, "dtype", "")).replace(
                    "bfloat16", "bf16")
        except Exception:  # noqa: BLE001
            pass
        ctx = AnalysisContext(entry=entry,
                              mesh_axes=collective_attrib.registered_axes(),
                              bf16_policy=bf16)
        tel = get_telemetry()
        for f in analyze_hlo_text(text, ctx):
            tel.counter(f"hlolint/findings.{f.rule}")
            if (entry, f.rule) not in _lint_warned:
                _lint_warned.add((entry, f.rule))
                logger.warning(
                    "hlo-lint: %s (%s) in compiled entry %r at HLO line "
                    "%d [%s]: %s", f.rule, f.severity, entry, f.line,
                    f.context, f.message)
    except Exception as e:  # noqa: BLE001
        logger.debug("xla_cost: hlo lint failed for %s: %s", entry, e)


# -- MFU / roofline --------------------------------------------------------

# entry -> the step-latency histogram that entry's OWN engine records
# (divided per-step by the producer for windowed entries). Exact names
# only: a prefix rule would hand e.g. fleet.pipeline_step (whose engine
# records no step_ms) the data-parallel engine's latency and publish a
# meaningless MFU. Entries without a producer-owned histogram get none.
_STEP_HISTS = {
    "jit.train_step": "jit/step_ms",
    "executor.train_step": "executor/step_ms",
    "executor.run_steps": "executor/step_ms",
    "fleet.train_step": "engine/step_ms",
    "fleet.train_step_multi": "engine/step_ms",
}


def step_hist_for(entry: str) -> Optional[str]:
    # serving buckets: each "serve.step.b<N>" entry owns the
    # "serve/batch_ms.b<N>" histogram its scheduler records — per-bucket
    # MFU denominators, same producer-owned-exact-name principle as the
    # engine table above (the suffix IS the producer's suffix)
    if entry.startswith("serve.step"):
        return "serve/batch_ms" + entry[len("serve.step"):]
    # token-level serving (inference.serving.decode): every compiled
    # decode/prefill/verify entry owns the wall-time histogram the
    # decode scheduler records under the same bucket suffix, so
    # decode-STEP MFU is attributed per executable (the decode bench's
    # headline column)
    # draft_prefill must match before draft (shared prefix)
    for stem, hist in (("serve.decode", "serve/decode_ms"),
                       ("serve.prefill", "serve/prefill_ms"),
                       ("serve.verify", "serve/verify_ms"),
                       ("serve.draft_prefill", "serve/draft_prefill_ms"),
                       ("serve.draft", "serve/draft_ms")):
        if entry.startswith(stem):
            return hist + entry[len(stem):]
    return _STEP_HISTS.get(entry)


def roofline_verdict(rec: CostRecord) -> Optional[str]:
    """"compute-bound" | "memory-bound" | None (no byte count)."""
    intensity = rec.intensity()
    if intensity is None:
        return None
    peaks = chip_peaks()
    if peaks["bytes_per_s"] <= 0 or peaks["flops"] <= 0:
        return None  # degenerate peaks: no balance point to compare to
    balance = peaks["flops"] / peaks["bytes_per_s"]
    return "compute-bound" if intensity >= balance else "memory-bound"


def publish_mfu(telemetry: Optional[Telemetry] = None) -> Dict[str, dict]:
    """Combine the cost records with the live ``*step_ms`` histograms
    into ``gauge/mfu`` (+ per-entry twins), achieved HBM GB/s, and the
    roofline verdict. Returns ``{entry: {mfu_pct, hbm_gbps, verdict,
    flops_per_step, step_ms_p50}}`` for programmatic callers
    (``bench_all.py`` columns). Cheap and side-effect-free beyond gauge
    stores — ``Telemetry.to_jsonl`` calls it so every exported record
    carries a fresh MFU."""
    tel = telemetry or get_telemetry()
    peaks = chip_peaks()
    if peaks["flops"] <= 0:
        return {}  # no peak to normalize against — publish nothing
    out: Dict[str, dict] = {}
    headline_entry = _registry.last_entry()
    for entry, rec in _registry.latest().items():
        hist = step_hist_for(entry)
        if hist is None:
            continue
        summary = tel.hist_summary(hist)
        if not summary or not summary.get("count"):
            continue
        p50_ms = summary.get("p50")
        if not p50_ms or p50_ms <= 0:
            continue
        spc = _registry.steps_per_call(entry)
        flops_step = rec.flops / spc
        bytes_step = rec.bytes_accessed / spc
        step_s = p50_ms / 1e3
        mfu = 100.0 * flops_step / step_s / peaks["flops"]
        if mfu > 100.0:
            # >100% of peak means the flops, the step histogram, and the
            # peak registry disagree about units (a TFLOP/s value in
            # PADDLE_TPU_PEAK_FLOPS, a missing set_steps_per_call) — OR a
            # nominal fallback peak on a strong CPU host. Clamping keeps
            # the schema contract, but silently reporting exactly 100
            # would mask the defect: the raw value is preserved in
            # gauge/mfu_raw/<entry> (outside the [0,100]-checked
            # namespace) and warned about once per entry.
            tel.gauge(f"mfu_raw/{entry}", mfu)
            if entry not in _mfu_overflow_warned:
                _mfu_overflow_warned.add(entry)
                logger.warning(
                    "xla_cost: MFU for %r computed %.0f%% of peak — flops, "
                    "step_ms, and the peak-FLOPs registry disagree about "
                    "units (check PADDLE_TPU_PEAK_FLOPS is absolute FLOP/s "
                    "and windowed entries registered steps_per_call); "
                    "publishing clamped 100, raw in gauge/mfu_raw/%s",
                    entry, mfu, entry)
        mfu = min(max(mfu, 0.0), 100.0)  # schema: gauge/mfu* ∈ [0, 100]
        bps = bytes_step / step_s
        verdict = roofline_verdict(rec)
        tel.gauge(f"mfu/{entry}", mfu)
        tel.gauge(f"hbm_gbps/{entry}", bps / 1e9)
        if verdict is not None:
            tel.gauge(f"roofline/{entry}",
                      1.0 if verdict == "compute-bound" else 0.0)
        out[entry] = {"mfu_pct": mfu, "hbm_gbps": bps / 1e9,
                      "verdict": verdict, "flops_per_step": flops_step,
                      "step_ms_p50": p50_ms,
                      "peak_hbm_bytes": rec.peak_hbm_bytes}
    if out:
        # headline = the most recently compiled entry when it has a step
        # hist, else a deterministic fallback among those that do
        pick = headline_entry if headline_entry in out else sorted(out)[0]
        tel.gauge("mfu", out[pick]["mfu_pct"])
    return out


def headline(telemetry: Optional[Telemetry] = None) -> Optional[dict]:
    """The most recently compiled entry's attribution row, or None."""
    entry = _registry.last_entry()
    if entry is None:
        return None
    rec = _registry.latest().get(entry)
    if rec is None:
        return None
    row = {"entry": entry, "flops": rec.flops,
           "bytes_accessed": rec.bytes_accessed,
           "peak_hbm_bytes": rec.peak_hbm_bytes,
           "estimated": rec.estimated,
           "verdict": roofline_verdict(rec)}
    mfu = publish_mfu(telemetry).get(entry)
    if mfu:
        row.update({"mfu_pct": mfu["mfu_pct"], "hbm_gbps": mfu["hbm_gbps"]})
    return row
