"""Per-axis collective attribution: which mesh axis eats the bytes.

``device_profile`` (PR 12) decomposes a step into compute / collective /
transfer — but "collective" is one undifferentiated bucket, and the
ROADMAP-3 layout planner needs *per-axis* collective bytes and measured
latencies to price dp×tp×pp×sp candidates. This module closes that gap
by walking the compiled HLO the ``HloRegistry`` already holds (no second
lowering — ``xla_cost.capture`` stashed it at compile time):

- **inventory** every collective instruction per entry (all-reduce /
  all-gather / reduce-scatter / all-to-all / collective-permute and
  their async start/done halves), with output-payload bytes parsed from
  the instruction's result type;
- **map** each instance's ``replica_groups`` (literal ``{{0,1},{2,3}}``
  or iota ``[G,S]<=[dims]T(perm)`` form) — or a permute's
  ``source_target_pairs`` — back onto the registered mesh axes:
  a group set that varies exactly along one axis is that axis's
  collective ("dp"), a flattened multi-axis group is the joined label
  ("dp+tp"), anything else degrades to "unmapped" (never a guess);
- **publish** ``gauge/collective/<axis>/{bytes,count}.<entry>``
  statically (per step — windowed entries divide by their registered
  steps-per-call), and — when a ``device_profile`` capture ran —
  **join** the capture's per-op device milliseconds against the
  inventory into ``gauge/collective/<axis>/ms.<entry>`` (window-total
  ms, so the schema gate can hold the per-entry sum ≤ the captured
  ``gauge/profile/device_total_ms``).

The axis tables also refine the PR 12 bottleneck verdict: a
``comm_bound`` entry whose dominant collective axis is known reports
``comm_bound:<axis>`` (the numeric ``gauge/bottleneck/<entry>`` id
stays in the closed vocabulary; the axis rides the string verdict and
the evidence).

Mesh registration: ``fleet.ParallelTrainStep`` and
``mesh_utils.init_mesh/set_mesh`` call :func:`register_mesh`; partition
ids are assumed row-major over the mesh's device array (jax's own
``mesh.devices`` order), which is how GSPMD numbers them. A laneless /
capture-less run still yields the full static bytes inventory — only
the measured ``ms`` gauges need a capture.
"""
from __future__ import annotations

import dataclasses
import logging
import threading
from typing import Dict, List, Optional, Tuple

from ..analysis.hlo import axes as _hloaxes
from ..analysis.hlo import parsing as _hloparse
from .telemetry import Telemetry, get_telemetry

__all__ = [
    "CollectiveOp", "register_mesh", "registered_axes", "axis_vocabulary",
    "parse_collectives", "map_groups_to_axes", "map_pairs_to_axis",
    "inventory", "inventory_dict", "publish_static", "on_capture",
    "entry_summary", "summary", "reset", "COLLECTIVE_OPCODES",
    "KNOWN_AXIS_TOKENS", "UNMAPPED",
]

logger = logging.getLogger("paddle_tpu.profiler")

# The low-level HLO text primitives live in ``analysis.hlo.parsing`` —
# the standalone hlo-lint package, which must not import the framework,
# so the dependency points THIS way. Re-exported under their historic
# names: profiler callers and tests keep one import surface.
COLLECTIVE_OPCODES = _hloparse.COLLECTIVE_OPCODES
_DONE_OPCODES = _hloparse.DONE_OPCODES
_DTYPE_BYTES = _hloparse.DTYPE_BYTES
_NAME_RE = _hloparse.NAME_RE
_shape_bytes = _hloparse.shape_bytes
_parse_group_sets = _hloparse.parse_group_sets
_parse_pairs = _hloparse.parse_pairs
_opcode_and_type = _hloparse.opcode_and_type

# the framework's registered axis vocabulary (mesh_utils docstring +
# fleet engine ctor args) plus the eager process-level "world" and the
# honest "unmapped" degrade — the closed set the schema gate enforces
KNOWN_AXIS_TOKENS = ("dp", "mp", "tp", "pp", "sp", "sharding", "world")
UNMAPPED = _hloaxes.UNMAPPED


# -- mesh registry ------------------------------------------------------------

_mesh_lock = threading.Lock()
_mesh_axes: "Dict[str, int]" = {}  # insertion order == mesh axis order


def register_mesh(mesh_or_axes) -> None:
    """Register the live mesh's named axes (a ``jax.sharding.Mesh`` or an
    ordered ``{axis_name: size}`` dict). Partition ids are assumed
    row-major over the axis order — jax's own device-array layout. The
    LAST registered mesh wins: engines construct their mesh at build
    time and the programs compiled afterwards are the ones a capture
    attributes."""
    global _mesh_axes
    axes: Dict[str, int] = {}
    if hasattr(mesh_or_axes, "axis_names"):
        for name in mesh_or_axes.axis_names:
            axes[str(name)] = int(mesh_or_axes.shape[name])
    else:
        for name, size in dict(mesh_or_axes).items():
            axes[str(name)] = int(size)
    with _mesh_lock:
        _mesh_axes = axes
    _invalidate_inventory()


def registered_axes() -> Dict[str, int]:
    with _mesh_lock:
        return dict(_mesh_axes)


def axis_vocabulary() -> Tuple[str, ...]:
    """Every axis label this process may publish: the registered axis
    names (falling back to the known framework set when no mesh is
    registered yet) plus "world" and "unmapped"."""
    axes = tuple(registered_axes()) or KNOWN_AXIS_TOKENS
    out = list(axes)
    for extra in ("world", UNMAPPED):
        if extra not in out:
            out.append(extra)
    return tuple(out)


# the group/pair → axis math itself lives in analysis.hlo.axes (pure,
# mesh passed explicitly, shared with hlo-lint's H5/H6); these wrappers
# add the framework default — the live registered mesh
_strides = _hloaxes.strides
_expected_groups = _hloaxes.expected_groups


def map_groups_to_axes(groups: List[Tuple[int, ...]],
                       axes: Optional[Dict[str, int]] = None) -> str:
    """The axis label of a replica-group set: the MINIMAL subset of
    registered mesh axes whose expected grouping matches exactly
    ("dp", or "dp+tp" for a flattened multi-axis group), else
    ``unmapped``. Matching is exact set equality — attribution never
    guesses."""
    return _hloaxes.map_groups_to_axes(
        groups, registered_axes() if axes is None else dict(axes))


def map_pairs_to_axis(pairs: List[Tuple[int, int]],
                      axes: Optional[Dict[str, int]] = None) -> str:
    """The axis of a ``collective-permute``: every (source, target) pair
    must differ along exactly one non-trivial mesh axis — the ring axis
    of PR 8's sp rotation. Anything else is ``unmapped``."""
    return _hloaxes.map_pairs_to_axis(
        pairs, registered_axes() if axes is None else dict(axes))


# -- the inventory ------------------------------------------------------------

@dataclasses.dataclass
class CollectiveOp:
    """One collective instruction of one compiled entry."""

    name: str            # HLO instruction name (joins against trace events)
    opcode: str
    axis: str            # mapped axis label ("dp", "dp+tp", "unmapped")
    bytes: float         # output-payload bytes per execution
    group_count: int = 0
    group_size: int = 0


def parse_collectives(text: str,
                      axes: Optional[Dict[str, int]] = None
                      ) -> List[CollectiveOp]:
    """Every collective instruction of one optimized-HLO text, mapped
    onto the mesh axes. The ``*-done`` halves of async collectives are
    skipped (the start half owns the instance)."""
    out: List[CollectiveOp] = []
    for line in text.splitlines():
        m = _NAME_RE.match(line.strip())
        if not m:
            continue
        name, body = m.group(1), m.group(2)
        opcode, type_text = _opcode_and_type(body)
        if opcode in _DONE_OPCODES:
            continue
        if opcode not in COLLECTIVE_OPCODES:
            continue
        nbytes = _shape_bytes(type_text)
        if opcode.startswith("collective-permute"):
            pairs = _parse_pairs(body)
            axis = map_pairs_to_axis(pairs or [], axes)
            gc, gs = len(pairs or []), 2
        else:
            groups = _parse_group_sets(body)
            if groups == []:
                # XLA's `replica_groups={}` is shorthand for ONE group of
                # ALL devices — the most common global reduction; expand
                # it against the registered mesh so it maps to the full
                # axis product instead of degrading to unmapped
                use_axes = registered_axes() if axes is None else axes
                world = 1
                for size in (use_axes or {}).values():
                    world *= size
                if use_axes:
                    groups = [tuple(range(world))]
            axis = map_groups_to_axes(groups or [], axes)
            gc = len(groups or [])
            gs = len(groups[0]) if groups else 0
        out.append(CollectiveOp(name=name, opcode=opcode, axis=axis,
                                bytes=nbytes, group_count=gc, group_size=gs))
    return out


_inv_lock = threading.Lock()
# entry -> (text_hash, [CollectiveOp]) — parsing is cheap but walking a
# 32-entry registry per publish isn't free, and texts rarely change
_inv_cache: Dict[str, Tuple[int, List[CollectiveOp]]] = {}


def _invalidate_inventory() -> None:
    with _inv_lock:
        _inv_cache.clear()


def inventory(entries: Optional[List[str]] = None
              ) -> Dict[str, List[CollectiveOp]]:
    """``{entry: [CollectiveOp]}`` over the compiled-HLO registry.
    Note: in the default cost-analysis mode the registry stores lowered
    programs and compiles text on demand (counted ``profile/
    hlo_compiles``) — call this from explicitly-requested paths (bench
    columns, captures, ``/debug/collectives``), not per-step loops."""
    from . import xla_cost

    texts = xla_cost.hlo_texts(entries)
    out: Dict[str, List[CollectiveOp]] = {}
    axes = registered_axes()
    with _inv_lock:
        for entry, text in texts.items():
            h = hash(text)
            cached = _inv_cache.get(entry)
            if cached is not None and cached[0] == h:
                out[entry] = cached[1]
                continue
            ops = parse_collectives(text, axes or None)
            _inv_cache[entry] = (h, ops)
            out[entry] = ops
    return out


def inventory_dict(entries: Optional[List[str]] = None) -> Dict[str, list]:
    """JSON-ready inventory (the ``/debug/collectives`` payload)."""
    return {entry: [dataclasses.asdict(op) for op in ops]
            for entry, ops in inventory(entries).items()}


def _gauge_axis(axis: str) -> str:
    """The axis label as published into the TELEMETRY namespace: labels
    whose every "+"-component is in the framework's registered-axis
    vocabulary pass through; a custom mesh axis name ("data", "model")
    publishes as ``unmapped`` so it can never fail the schema gate's
    closed-vocabulary contract — the REAL name stays visible in the
    inventory/summary surfaces (``/debug/collectives``, bench columns)."""
    if axis == UNMAPPED:
        return axis
    parts = axis.split("+")
    if parts and all(p in KNOWN_AXIS_TOKENS for p in parts):
        return axis
    return UNMAPPED


def _per_axis(ops: List[CollectiveOp]) -> Dict[str, Dict[str, float]]:
    table: Dict[str, Dict[str, float]] = {}
    for op in ops:
        row = table.setdefault(op.axis, {"bytes": 0.0, "count": 0.0})
        row["bytes"] += op.bytes
        row["count"] += 1.0
    return table


def publish_static(telemetry: Optional[Telemetry] = None,
                   entries: Optional[List[str]] = None
                   ) -> Dict[str, Dict[str, Dict[str, float]]]:
    """Publish the static per-axis inventory as
    ``gauge/collective/<axis>/{bytes,count}.<entry>`` (per STEP —
    windowed entries divide by their registered steps-per-call) and
    return ``{entry: {axis: {bytes, count}}}``. Works with no capture
    and no device lanes — the laneless-CPU degrade path ROADMAP-3
    prices layouts from."""
    from . import xla_cost

    tel = telemetry or get_telemetry()
    out: Dict[str, Dict[str, Dict[str, float]]] = {}
    for entry, ops in inventory(entries).items():
        if not ops:
            continue
        spc = max(xla_cost.cost_registry().steps_per_call(entry), 1)
        table = _per_axis(ops)
        for axis, row in table.items():
            scaled = {"bytes": row["bytes"] / spc, "count": row["count"] / spc}
            ga = _gauge_axis(axis)
            tel.gauge(f"collective/{ga}/bytes.{entry}", scaled["bytes"])
            tel.gauge(f"collective/{ga}/count.{entry}", scaled["count"])
            out.setdefault(entry, {})[axis] = scaled
    return out


# entry -> {axis: measured window-total ms} from the latest capture join
_measured_lock = threading.Lock()
_measured_ms: Dict[str, Dict[str, float]] = {}


def on_capture(report, telemetry: Optional[Telemetry] = None
               ) -> Dict[str, Dict[str, float]]:
    """Join a fresh ``AttributionReport`` (device_profile just finished
    a capture) against the inventory: per-op device ms land on their
    mapped axis, published as ``gauge/collective/<axis>/ms.<entry>``
    (window-total ms, so the schema gate can hold sum-per-entry ≤ the
    same record's ``gauge/profile/device_total_ms``). Returns
    ``{entry: {axis: ms}}``. Best-effort like every attribution hook."""
    tel = telemetry or get_telemetry()
    # retract the PREVIOUS capture's measured ms first: a fresh (maybe
    # shorter, different-entry) window overwrites the global
    # profile/device_total_ms, and a stale per-entry ms gauge from a
    # dead window would break the schema's "comm ms <= device total"
    # cross-field on a healthy multi-capture run. The cumulative .eager
    # gauges are process totals, not window state — kept.
    try:
        tel.remove_gauges(lambda n: n.startswith("collective/")
                          and "/ms." in n and not n.endswith(".eager"))
    except AttributeError:
        pass  # a bare Telemetry-like test double without the API
    inv = inventory(list(getattr(report, "entries", {}) or {}))
    joined: Dict[str, Dict[str, float]] = {}
    for entry, att in (getattr(report, "entries", {}) or {}).items():
        by_axis: Dict[str, float] = {}
        axis_of = {op.name: op.axis for op in inv.get(entry, [])}
        for op_name, ms in getattr(att, "by_op", {}).items():
            axis = axis_of.get(op_name)
            if axis is None:
                # unattributed-but-collective trace rows (runtime ops the
                # HLO never names) stay honest: unmapped, not invented
                meta = getattr(att, "op_meta", {}).get(op_name)
                if meta is not None and meta[2] == "collective":
                    axis = UNMAPPED
                else:
                    continue
            by_axis[axis] = by_axis.get(axis, 0.0) + float(ms)
        if not by_axis:
            continue
        joined[entry] = by_axis
        for axis, ms in by_axis.items():
            tel.gauge(f"collective/{_gauge_axis(axis)}/ms.{entry}", ms)
    with _measured_lock:
        _measured_ms.clear()
        _measured_ms.update(joined)
    # static bytes/count ride along so one capture leaves the complete
    # per-axis picture in the same record
    try:
        publish_static(tel, entries=list(inv))
    except Exception:  # noqa: BLE001 — attribution must never kill a run
        pass
    return joined


def measured_ms() -> Dict[str, Dict[str, float]]:
    with _measured_lock:
        return {e: dict(t) for e, t in _measured_ms.items()}


def dominant_axis(entry: str) -> Optional[Tuple[str, float]]:
    """(axis, window ms) of the entry's biggest measured collective
    axis, else (axis, bytes) from the static inventory, else None — the
    evidence behind the ``comm_bound:<axis>`` verdict refinement."""
    ms = measured_ms().get(entry)
    if ms:
        axis = max(ms, key=ms.get)
        return axis, ms[axis]
    try:
        table = _per_axis(inventory([entry]).get(entry, []))
    except Exception:  # noqa: BLE001
        return None
    if not table:
        return None
    axis = max(table, key=lambda a: table[a]["bytes"])
    return axis, table[axis]["bytes"]


def entry_summary(entry: str) -> Dict[str, Dict[str, float]]:
    """``{axis: {bytes, count[, ms]}}`` for one entry (the bench_all
    per-axis column source): static inventory per step plus the latest
    capture's measured ms when one exists."""
    out: Dict[str, Dict[str, float]] = {}
    try:
        from . import xla_cost

        ops = inventory([entry]).get(entry, [])
        spc = max(xla_cost.cost_registry().steps_per_call(entry), 1)
        for axis, row in _per_axis(ops).items():
            out[axis] = {"bytes": row["bytes"] / spc,
                         "count": row["count"] / spc}
    except Exception:  # noqa: BLE001
        return out
    for axis, ms in measured_ms().get(entry, {}).items():
        out.setdefault(axis, {"bytes": 0.0, "count": 0.0})["ms"] = ms
    return out


def summary() -> Dict[str, Dict[str, Dict[str, float]]]:
    """``{entry: {axis: {bytes, count[, ms]}}}`` over every inventoried
    entry (the ``/debug/collectives`` summary table)."""
    out: Dict[str, Dict[str, Dict[str, float]]] = {}
    for entry in inventory():
        table = entry_summary(entry)
        if table:
            out[entry] = table
    return out


def reset() -> None:
    """Forget the mesh registration, inventory cache, and measured join
    (test isolation; hooked from ``xla_cost.reset`` alongside the HLO
    registry both describe)."""
    global _mesh_axes
    with _mesh_lock:
        _mesh_axes = {}
    _invalidate_inventory()
    with _measured_lock:
        _measured_ms.clear()
