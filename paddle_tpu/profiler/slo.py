"""SLO monitor — declarative objectives, sliding windows, burn-rate alerts.

Nine PRs of recorded signal (latency histograms, serve counters, TTFT/
TPOT) still left "are we meeting our promises RIGHT NOW?" as a human
judgment over dashboards. This module makes it a computation:

- **Objectives** are declarative: an availability target over the serve
  terminal counters, or a latency bound at a percentile over any
  telemetry histogram (``ttft_ms:p99<500`` reads "99% of requests get
  their first token within 500 ms").
- **Burn rate** is the SRE-book quantity: ``bad_fraction / error_budget``
  where the error budget is ``1 - target``. Burn 1.0 spends the budget
  exactly at the objective's horizon; burn 14 spends a 30-day budget in
  ~2 days. Each objective is evaluated over TWO sliding windows — a fast
  one (catches a cliff in minutes) and a slow one (arms the fast one:
  a single bad batch cannot page) — and the alert fires only when BOTH
  exceed their thresholds, the standard multi-window guard against both
  slow-burn blindness and single-spike flapping.
- **Alerts are telemetry**: each firing bumps ``alert/<objective>``
  through the schema-gated funnel (``tools/check_telemetry_schema.py``
  pins ``counter/alert/* >= 0``), live burn rates publish as
  ``gauge/slo/<objective>/burn_{fast,slow}``, and ``tools/telemetry_agg``
  folds ``alert/*`` into SLO-BURN findings next to DEAD-RANK/straggler/
  SUSPECT-CHIP. An active alert also degrades the ops plane's
  ``/healthz`` (the monitor registers as a health source), so a load
  balancer ejects a replica that is burning budget before users notice.

Event accounting: counter objectives difference monotone counters, so
windows are exact. Histogram objectives estimate newly-observed bad
events from the histogram's bounded sample window
(``Histogram.recent_above``) — exact while ticks outpace window
overflow, a proportional estimate beyond (the monitor's tick default of
1 s against the 1024-sample window makes overflow the overload case,
where the estimate saturates toward "all bad" anyway).

Env grammar (``PADDLE_TPU_SLO``, ';'-separated)::

    PADDLE_TPU_SLO="availability:0.999;ttft_ms:p99<500;latency_ms:p95<200"

- ``availability:<target>`` — good = ``serve/completed``, bad =
  ``serve/errors`` + ``serve/deadline_exceeded`` (admission rejects are
  load shedding by design, surfaced by their own counters).
- ``<hist>:p<QQ><<bound_ms>`` — histogram ``serve/<hist>`` (or any fully
  qualified histogram name containing '/'), target ``QQ/100``: "QQ% of
  observations at or under bound_ms".

Window/threshold knobs: ``PADDLE_TPU_SLO_FAST_S`` (default 60),
``PADDLE_TPU_SLO_SLOW_S`` (default 300), ``PADDLE_TPU_SLO_FAST_BURN``
(default 14.4), ``PADDLE_TPU_SLO_SLOW_BURN`` (default 6.0),
``PADDLE_TPU_SLO_TICK_S`` (default 1.0).
"""
from __future__ import annotations

import os
import re
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from .telemetry import (Telemetry, _IntervalService, env_float,
                        get_telemetry)

__all__ = ["SLOObjective", "SLOMonitor", "parse_slos",
           "install_slo_monitor", "get_slo_monitor", "clear_slo_monitor",
           "maybe_start_from_env"]


class SLOObjective:
    """One declarative objective.

    Args:
        name: alert key — fires as ``counter/alert/<name>``.
        target: good-event fraction promised (0 < target < 1], e.g.
            0.999 availability or 0.99 for a p99 latency bound.
        good / bad: counter names (availability mode) — totals are
            differenced over the windows. ``total = good + bad``.
        hist / bound_ms: histogram mode — an observation past
            ``bound_ms`` is a bad event.
    """

    def __init__(self, name: str, target: float,
                 good: Sequence[str] = (), bad: Sequence[str] = (),
                 hist: Optional[str] = None,
                 bound_ms: Optional[float] = None):
        if not (0.0 < float(target) <= 1.0):
            raise ValueError(f"target must be in (0, 1], got {target}")
        if (hist is None) == (not good and not bad):
            raise ValueError(
                f"objective {name!r} needs counters (good/bad) XOR a "
                f"histogram (hist + bound_ms)")
        if hist is not None and bound_ms is None:
            raise ValueError(f"objective {name!r}: hist without bound_ms")
        self.name = str(name)
        self.target = float(target)
        self.good = tuple(good)
        self.bad = tuple(bad)
        self.hist = hist
        self.bound_ms = None if bound_ms is None else float(bound_ms)

    @property
    def budget(self) -> float:
        """Error budget: the bad fraction the objective tolerates."""
        return 1.0 - self.target

    def __repr__(self):
        what = (f"hist={self.hist} p<={self.bound_ms}ms" if self.hist
                else f"good={self.good} bad={self.bad}")
        return f"SLOObjective({self.name}, target={self.target}, {what})"


_SLO_HIST_RE = re.compile(r"^\s*([\w./-]+)\s*:\s*p(\d{1,2}(?:\.\d+)?)\s*"
                          r"<\s*([0-9.]+)\s*$")
_SLO_AVAIL_RE = re.compile(r"^\s*availability\s*:\s*(0?\.\d+|1(?:\.0*)?)\s*$")


def parse_slos(spec: str) -> List[SLOObjective]:
    """Objectives from the PADDLE_TPU_SLO grammar (see module docstring).
    A malformed clause raises — a silently dropped objective is an SLO
    that LOOKS monitored."""
    out: List[SLOObjective] = []
    for clause in (spec or "").split(";"):
        clause = clause.strip()
        if not clause:
            continue
        m = _SLO_AVAIL_RE.match(clause)
        if m:
            out.append(SLOObjective(
                "availability", float(m.group(1)),
                good=("serve/completed",),
                bad=("serve/errors", "serve/deadline_exceeded")))
            continue
        m = _SLO_HIST_RE.match(clause)
        if m:
            hist, pct, bound = m.group(1), float(m.group(2)), \
                float(m.group(3))
            if not (0 < pct < 100):
                raise ValueError(f"SLO percentile out of range: {clause!r}")
            full = hist if "/" in hist else f"serve/{hist}"
            out.append(SLOObjective(
                f"{hist.rsplit('/', 1)[-1]}_p{m.group(2).replace('.', '_')}",
                pct / 100.0, hist=full, bound_ms=bound))
            continue
        raise ValueError(f"unparsable SLO clause: {clause!r} "
                         f"(grammar: 'availability:0.999' or "
                         f"'ttft_ms:p99<500')")
    return out


class _ObjectiveState:
    """Per-objective cumulative (total, bad) event accounting plus the
    timestamped snapshot ring the windowed rates difference."""

    def __init__(self, objective: SLOObjective):
        self.obj = objective
        self.snaps: deque = deque()  # (ts, total, bad)
        self.alerting = False
        # histogram mode: cumulative estimates folded from recent_above
        self._hist_count = 0
        self._bad_cum = 0.0

    def observe(self, tel: Telemetry, now: float) -> Tuple[float, float]:
        obj = self.obj
        if obj.hist is None:
            bad = float(sum(tel.counter_value(c) for c in obj.bad))
            total = bad + float(sum(tel.counter_value(c)
                                    for c in obj.good))
        else:
            h = tel._hists.get(obj.hist)  # peek, never create
            if h is None:
                total, bad = 0.0, 0.0
            else:
                count = h.count
                new = count - self._hist_count
                if new > 0:
                    above, considered = h.recent_above(obj.bound_ms, new)
                    frac = above / considered if considered else 0.0
                    self._bad_cum += frac * new
                    self._hist_count = count
                total, bad = float(self._hist_count), self._bad_cum
        self.snaps.append((now, total, bad))
        return total, bad

    def window_burn(self, window_s: float, now: float) -> float:
        """Burn rate over the trailing window: bad-fraction of the events
        that happened in it, divided by the error budget. No events in
        the window → burn 0 (an idle replica is not failing anyone)."""
        if not self.snaps:
            return 0.0
        now_ts, now_total, now_bad = self.snaps[-1]
        # newest snapshot at or before the window's left edge (fall back
        # to the oldest we have: early in a run the window is the run)
        then_total, then_bad = self.snaps[0][1], self.snaps[0][2]
        for ts, total, bad in reversed(self.snaps):
            if now - ts >= window_s:
                then_total, then_bad = total, bad
                break
        d_total = now_total - then_total
        d_bad = now_bad - then_bad
        if d_total <= 0:
            return 0.0
        bad_rate = min(max(d_bad / d_total, 0.0), 1.0)
        budget = self.obj.budget
        if budget <= 0:
            return float("inf") if bad_rate > 0 else 0.0
        return bad_rate / budget

    def prune(self, keep_s: float, now: float) -> None:
        while len(self.snaps) > 2 and now - self.snaps[0][0] > keep_s:
            self.snaps.popleft()


class SLOMonitor:
    """Evaluates objectives over fast/slow sliding windows on each
    ``evaluate()`` tick (or continuously via ``start()``'s daemon
    thread), publishing burn gauges and ``alert/*`` counters."""

    def __init__(self, objectives: Sequence[SLOObjective],
                 telemetry: Optional[Telemetry] = None,
                 fast_window_s: Optional[float] = None,
                 slow_window_s: Optional[float] = None,
                 fast_burn: Optional[float] = None,
                 slow_burn: Optional[float] = None):
        self._tel = telemetry or get_telemetry()
        self._states = [_ObjectiveState(o) for o in objectives]
        self.fast_window_s = (fast_window_s if fast_window_s is not None
                              else env_float("PADDLE_TPU_SLO_FAST_S", 60.0))
        self.slow_window_s = (slow_window_s if slow_window_s is not None
                              else env_float("PADDLE_TPU_SLO_SLOW_S", 300.0))
        self.fast_burn = (fast_burn if fast_burn is not None
                          else env_float("PADDLE_TPU_SLO_FAST_BURN", 14.4))
        self.slow_burn = (slow_burn if slow_burn is not None
                          else env_float("PADDLE_TPU_SLO_SLOW_BURN", 6.0))
        self._lock = threading.Lock()
        # loop lifecycle via the shared service helper: each started
        # thread owns its own stop event, so a stop whose join timed out
        # (evaluate blocked on a contended lock) can never be revived by
        # a later start into a second evaluator double-counting episodes
        self._ticker = _IntervalService("SLOMonitor")

    @property
    def objectives(self) -> List[SLOObjective]:
        return [s.obj for s in self._states]

    def evaluate(self, now: Optional[float] = None) -> Dict[str, dict]:
        """One tick: snapshot every objective, compute both window burns,
        latch/unlatch alerts. Returns {objective: {burn_fast, burn_slow,
        alerting}}."""
        now = time.monotonic() if now is None else now
        tel = self._tel
        out: Dict[str, dict] = {}
        with self._lock:
            for st in self._states:
                st.observe(tel, now)
                burn_fast = st.window_burn(self.fast_window_s, now)
                burn_slow = st.window_burn(self.slow_window_s, now)
                firing = (burn_fast >= self.fast_burn
                          and burn_slow >= self.slow_burn)
                if firing and not st.alerting:
                    # rising edge: ONE alert event per episode — the
                    # counter counts episodes, the gauge shows state
                    tel.counter(f"alert/{st.obj.name}")
                st.alerting = firing
                name = st.obj.name
                tel.gauge(f"slo/{name}/burn_fast", burn_fast)
                tel.gauge(f"slo/{name}/burn_slow", burn_slow)
                tel.gauge(f"slo/{name}/alerting", 1.0 if firing else 0.0)
                st.prune(2.0 * self.slow_window_s, now)
                out[name] = {"burn_fast": burn_fast,
                             "burn_slow": burn_slow,
                             "alerting": firing,
                             "target": st.obj.target}
            tel.gauge("slo/alerts_active",
                      float(sum(1 for s in self._states if s.alerting)))
        return out

    def active_alerts(self) -> List[str]:
        with self._lock:
            return [s.obj.name for s in self._states if s.alerting]

    # -- background evaluation --------------------------------------------
    def start(self, tick_s: Optional[float] = None) -> "SLOMonitor":
        tick = tick_s if tick_s is not None else env_float(
            "PADDLE_TPU_SLO_TICK_S", 1.0)
        self._ticker.start(tick, self.evaluate)
        return self

    def stop(self, timeout: float = 2.0) -> None:
        self._ticker.stop(timeout)


_monitor: Optional[SLOMonitor] = None
_monitor_lock = threading.Lock()


def install_slo_monitor(monitor: Optional[SLOMonitor]) -> None:
    """Register the process-wide monitor (the ops server's /healthz
    consults it). Stops and replaces any previous one."""
    global _monitor
    with _monitor_lock:
        if _monitor is not None and _monitor is not monitor:
            _monitor.stop()
        _monitor = monitor


def get_slo_monitor() -> Optional[SLOMonitor]:
    return _monitor


def clear_slo_monitor() -> None:
    install_slo_monitor(None)


def maybe_start_from_env(telemetry: Optional[Telemetry] = None
                         ) -> Optional[SLOMonitor]:
    """PADDLE_TPU_SLO set → parse it, build the monitor, start its tick
    thread, install it process-wide. Unset/empty → None. Idempotent: an
    installed monitor is returned as-is. A malformed spec must not kill
    the workload, but it must be LOUD: a warning plus a
    ``slo/spec_parse_failures`` counter — a swallowed parse error would
    be an SLO that looks monitored and never alerts."""
    existing = get_slo_monitor()
    if existing is not None:
        return existing
    spec = os.environ.get("PADDLE_TPU_SLO", "")
    if not spec.strip():
        return None
    try:
        objectives = parse_slos(spec)
    except ValueError as e:
        import warnings

        (telemetry or get_telemetry()).counter("slo/spec_parse_failures")
        warnings.warn(f"PADDLE_TPU_SLO ignored — {e}; NO SLO objectives "
                      f"are being monitored", stacklevel=2)
        return None
    monitor = SLOMonitor(objectives, telemetry=telemetry).start()
    install_slo_monitor(monitor)
    return monitor
