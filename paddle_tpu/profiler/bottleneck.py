"""Automated bottleneck verdicts: one word per entry on why the step
takes as long as it does.

Folds the device-profile decomposition (``profile/*_frac.<entry>`` — when
a capture ran) with the always-on roofline/MFU attribution
(``gauge/roofline/<entry>``, ``gauge/mfu/<entry>`` from ``xla_cost``)
into ``gauge/bottleneck/<entry>`` over a CLOSED vocabulary:

======== ================ ====================================================
 id       verdict          meaning / dominant evidence
======== ================ ====================================================
 0        compute_bound    device busy, arithmetic intensity above the
                           machine balance point — you are spending MXU
 1        memory_bound     device busy, intensity below balance — HBM
                           bandwidth is the wall
 2        comm_bound       collectives dominate the device time
 3        input_bound      the device waits on data — large host gap with
                           significant h2d/d2h transfer share
 4        host_bound       the device waits on Python — large host gap
                           with no transfer signal (dispatch/feed overhead,
                           the static-executor 16.7%-vs-52.2% class)
======== ================ ====================================================

Verdicts publish as gauge VALUES (the id) so they ride /metrics, the
JSONL schema gate, and telemetry_agg untouched; :data:`VERDICT_NAMES`
maps back. Without a capture the decomposition half is absent and the
verdict degrades honestly to the roofline's compute/memory split — a
capture upgrades it to the full five-way call with the dominating
numbers attached (returned per entry, surfaced as bench columns).
"""
from __future__ import annotations

from typing import Dict, Optional

from .telemetry import Telemetry, get_telemetry

__all__ = ["VERDICT_IDS", "VERDICT_NAMES", "verdicts", "publish",
           "COMM_FRAC_THRESHOLD", "HOST_GAP_THRESHOLD",
           "TRANSFER_FRAC_THRESHOLD"]

VERDICT_IDS = {
    "compute_bound": 0,
    "memory_bound": 1,
    "comm_bound": 2,
    "input_bound": 3,
    "host_bound": 4,
}
VERDICT_NAMES = {v: k for k, v in VERDICT_IDS.items()}

# collectives past this fraction of wall dominate the step
COMM_FRAC_THRESHOLD = 0.35
# the device idling past this fraction of wall makes the host the story
HOST_GAP_THRESHOLD = 0.40
# within a host-gapped step, this much transfer implicates the input
# pipeline rather than Python dispatch
TRANSFER_FRAC_THRESHOLD = 0.05


def _entry_fractions(scalars: Dict[str, float]) -> Dict[str, Dict[str, float]]:
    """Group ``gauge/profile/<cat>_frac.<entry>`` scalars per entry."""
    out: Dict[str, Dict[str, float]] = {}
    for name, v in scalars.items():
        if not name.startswith("gauge/profile/"):
            continue
        rest = name[len("gauge/profile/"):]
        if "_frac." not in rest:
            continue
        cat, entry = rest.split("_frac.", 1)
        out.setdefault(entry, {})[cat] = float(v)
    return out


def _judge(fracs: Optional[Dict[str, float]],
           roofline: Optional[float],
           mfu: Optional[float]) -> Optional[dict]:
    """One entry's verdict from whatever evidence exists."""
    if fracs:
        comm = fracs.get("collective", 0.0)
        gap = fracs.get("host_gap", 0.0)
        transfer = fracs.get("transfer", 0.0)
        compute = fracs.get("compute", 0.0)
        if comm >= COMM_FRAC_THRESHOLD and comm >= compute:
            return {"verdict": "comm_bound",
                    "evidence": {"collective_frac": comm,
                                 "compute_frac": compute}}
        if gap >= HOST_GAP_THRESHOLD and gap >= compute:
            if transfer >= TRANSFER_FRAC_THRESHOLD:
                return {"verdict": "input_bound",
                        "evidence": {"host_gap_frac": gap,
                                     "transfer_frac": transfer}}
            return {"verdict": "host_bound",
                    "evidence": {"host_gap_frac": gap,
                                 "compute_frac": compute}}
        # device-dominated: the roofline decides compute vs memory
        if roofline is not None:
            name = "compute_bound" if roofline >= 0.5 else "memory_bound"
            ev = {"compute_frac": compute, "roofline": roofline}
            if mfu is not None:
                ev["mfu_pct"] = mfu
            return {"verdict": name, "evidence": ev}
        return {"verdict": "compute_bound",
                "evidence": {"compute_frac": compute}}
    if roofline is not None:
        name = "compute_bound" if roofline >= 0.5 else "memory_bound"
        ev = {"roofline": roofline}
        if mfu is not None:
            ev["mfu_pct"] = mfu
        return {"verdict": name, "evidence": ev}
    return None


def verdicts(telemetry: Optional[Telemetry] = None) -> Dict[str, dict]:
    """``{entry: {"verdict", "id", "evidence"}}`` for every entry with
    any attribution signal (a profile decomposition, or a roofline
    verdict from the compile-time cost model)."""
    tel = telemetry or get_telemetry()
    snap = tel.snapshot()
    gauges = snap["gauges"]
    scalars = {f"gauge/{k}": v for k, v in gauges.items()}
    per_entry = _entry_fractions(scalars)
    entries = set(per_entry)
    for name in gauges:
        if name.startswith("roofline/"):
            entries.add(name[len("roofline/"):])
    out: Dict[str, dict] = {}
    for entry in sorted(entries):
        row = _judge(per_entry.get(entry),
                     gauges.get(f"roofline/{entry}"),
                     gauges.get(f"mfu/{entry}"))
        if row is not None:
            row["id"] = VERDICT_IDS[row["verdict"]]
            _refine_comm_axis(entry, row, gauges)
            out[entry] = row
    return out


def _refine_comm_axis(entry: str, row: dict, gauges: Dict[str, float]
                      ) -> None:
    """Refine a ``comm_bound`` verdict into ``comm_bound:<axis>`` from
    the per-axis collective gauges (``collective/<axis>/ms.<entry>``,
    measured by the last capture join; bytes as the static fallback).
    The numeric ``id`` stays 2 — the closed vocabulary is untouched; the
    axis rides the string verdict and the evidence, the same place
    telemetry_agg and the bench columns read it."""
    if row.get("verdict") != "comm_bound":
        return
    best = None
    for field in ("ms", "bytes"):
        per_axis = {}
        prefix = "collective/"
        suffix = f"/{field}.{entry}"
        for name, v in gauges.items():
            if name.startswith(prefix) and name.endswith(suffix):
                axis = name[len(prefix):-len(suffix)]
                if "/" not in axis:
                    per_axis[axis] = float(v)
        if per_axis:
            axis = max(per_axis, key=per_axis.get)
            best = (axis, field, per_axis[axis])
            break
    if best is None:
        return
    axis, field, value = best
    row["verdict"] = f"comm_bound:{axis}"
    row["evidence"]["axis"] = axis
    row["evidence"][f"axis_collective_{field}"] = value


def publish(telemetry: Optional[Telemetry] = None) -> Dict[str, dict]:
    """Evaluate and publish ``gauge/bottleneck/<entry>`` for every
    judged entry (hooked from ``Telemetry.to_jsonl`` so each exported
    record carries current verdicts; also the seam ``bench_all.py`` and
    the ops plane read)."""
    tel = telemetry or get_telemetry()
    out = verdicts(tel)
    for entry, row in out.items():
        tel.gauge(f"bottleneck/{entry}", row["id"])
    return out
