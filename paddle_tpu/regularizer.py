"""Regularizers — parity with python/paddle/regularizer.py (L1Decay/L2Decay
appended to gradients by the optimizer, reference fluid/regularizer.py)."""
from __future__ import annotations

__all__ = ["L1Decay", "L2Decay"]


class L1Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)
        self._coeff = self.coeff
        self._l1 = True


class L2Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)
        self._coeff = self.coeff
        self._l1 = False
