"""paddle_tpu.jit — staging, export, and compiled execution.

Parity with python/paddle/jit (to_static/save/load, fluid/dygraph/jit.py) —
implemented by JAX tracing instead of AST rewriting (see functionalize.py).
"""
from __future__ import annotations

import os
import pickle
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn.layer_base import Layer
from .functionalize import (
    TracedLayer,
    cast_floats,
    functionalize,
    get_buffers,
    get_params,
    set_buffers,
    set_params,
    _unwrap_tree,
    _wrap_tree,
)
from .train_step import EvalStep, TrainStep
from . import dy2static  # noqa: F401

__all__ = [
    "to_static", "save", "load", "not_to_static", "TracedLayer", "TrainStep",
    "EvalStep", "functionalize", "InputSpec", "dy2static",
]


class InputSpec:
    """Parity with paddle.static.InputSpec."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = list(shape)
        self.dtype = dtype
        self.name = name

    def to_shape_dtype_struct(self, batch=1):
        from ..core import dtype as dtype_mod

        shape = tuple(batch if (s is None or s == -1) else int(s) for s in self.shape)
        return jax.ShapeDtypeStruct(shape, dtype_mod.convert_dtype(self.dtype))


class StaticFunction:
    """jit-compiling wrapper for a python function or Layer method."""

    def __init__(self, fn: Callable, input_spec=None, layer: Optional[Layer] = None):
        self._fn = fn
        self._layer = layer
        self._input_spec = input_spec
        self._cache = {}

    def __call__(self, *args, **kwargs):
        if self._layer is not None:
            apply = functionalize(self._layer, training=self._layer.training)
            params = get_params(self._layer)
            buffers = get_buffers(self._layer)
            key = ("layer", tuple(_sig(a) for a in args))
            if key not in self._cache:
                self._cache[key] = jax.jit(apply)
            raw_args = [a._value if isinstance(a, Tensor) else a for a in args]
            out, new_b = self._cache[key](params, buffers, *raw_args)
            set_buffers(self._layer, new_b)
            return _wrap_tree(out)
        key = tuple(_sig(a) for a in args)
        if key not in self._cache:
            def pure(*raw):
                from ..core.tensor import no_grad

                with no_grad():
                    wrapped = [Tensor(r) if hasattr(r, "dtype") else r for r in raw]
                    out = self._fn(*wrapped, **kwargs)
                return _unwrap_tree(out)

            self._cache[key] = jax.jit(pure)
        raw_args = [a._value if isinstance(a, Tensor) else a for a in args]
        return _wrap_tree(self._cache[key](*raw_args))

    @property
    def concrete_program(self):
        return self


def _sig(a):
    if isinstance(a, Tensor):
        return ("T", tuple(a.shape), str(a.dtype))
    if hasattr(a, "shape") and hasattr(a, "dtype"):
        return ("A", tuple(a.shape), str(a.dtype))
    return ("v", a if isinstance(a, (int, float, str, bool, type(None))) else id(a))


def to_static(function=None, input_spec=None, build_strategy=None, **kwargs):
    """Decorator staging a function/Layer.forward into a compiled callable.

    Data-dependent python control flow (``if``/``while``/``and``/``or`` over
    tensors) is first rewritten by the AST converter (dy2static.py — the
    ProgramTranslator equivalent) into lax-compatible ops, then the result is
    traced and jit-compiled.
    """
    from .dy2static import convert_to_static

    def decorate(fn):
        if isinstance(fn, Layer):
            fwd = fn.forward
            raw = getattr(fwd, "__func__", None)
            if raw is not None:
                conv = convert_to_static(raw)
                if getattr(conv, "_dy2static_converted", False):
                    fn.forward = conv.__get__(fn)
            return StaticFunction(fn.forward, input_spec, layer=fn)
        return StaticFunction(convert_to_static(fn), input_spec)

    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn):
    fn._not_to_static = True
    return fn


def save(layer, path, input_spec=None, **configs):
    """jit.save parity: persist state + a deployable AOT artifact.

    Layout (reference: save_inference_model's program+params pair,
    fluid/io.py:1199):
    - ``<path>.pdiparams`` — pickled state_dict (always written).
    - ``<path>.pdmodel``  — metadata (class name, input specs, StableHLO
      text for inspection).
    - ``<path>.pdexport`` — with ``input_spec``: jax.export serialization of
      the jitted forward with the weights baked in as constants. This is the
      self-contained serving artifact paddle_tpu.inference loads — no model
      code needed at serving time.
    """
    from ..framework.io import save as _save_state

    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    if isinstance(layer, StaticFunction):
        layer = layer._layer
    state = layer.state_dict()
    encrypt_key = configs.get("encrypt_key")
    # validate OUTSIDE the best-effort export block: a typo'd precision
    # must be a hard error, not a silently-f32 artifact + export_error
    precision = configs.get("precision")
    if precision and precision not in ("float32", "bfloat16", "float16"):
        raise ValueError(f"unsupported export precision {precision!r} "
                         "(float32, bfloat16, float16)")
    # with a key, EVERY artifact that reveals the model is protected:
    # weights (.pdiparams), compiled program (.pdexport), and the StableHLO
    # text is withheld from the plaintext metadata below
    _save_state(state, path + ".pdiparams", cipher_key=encrypt_key)
    meta = {"class": type(layer).__name__}
    if input_spec:
        try:
            from ..core import dtype as dtype_mod
            from ..inference._export import export_fn, write_pdexport

            apply = functionalize(layer, training=False)
            params = get_params(layer)
            buffers = get_buffers(layer)

            # precision="bfloat16"/"float16": bake CAST weights into the
            # artifact (serving-dtype export — inference.PrecisionType).
            # Compute runs in that dtype; outputs return as float32 so
            # the client contract is precision-independent. The blob
            # records the dtype so loaders can verify Config precision.
            cast_dtype = None
            if precision and precision != "float32":
                cast_dtype = jnp.dtype(precision)
                params = cast_floats(params, cast_dtype)
                buffers = cast_floats(buffers, cast_dtype)

            def closed(*xs):
                if cast_dtype is not None:
                    xs = cast_floats(tuple(xs), cast_dtype)
                out = apply(params, buffers, *xs)[0]
                if cast_dtype is not None:
                    out = cast_floats(out, jnp.float32)
                return out

            shapes_dtypes = []
            for s in input_spec:
                if isinstance(s, InputSpec):
                    shapes_dtypes.append(
                        (list(s.shape), dtype_mod.convert_dtype(s.dtype)))
                else:  # a ShapeDtypeStruct / array-like
                    shapes_dtypes.append((list(s.shape), s.dtype))
            # dynamic (None/-1) dims export symbolically: the artifact
            # accepts any size there (variable batch)
            exported, pinned = export_fn(closed, shapes_dtypes)
            input_names = [
                (s.name or f"x{i}") if isinstance(s, InputSpec) else f"x{i}"
                for i, s in enumerate(input_spec)
            ]
            n_out = len(jax.tree_util.tree_leaves(exported.out_avals))
            in_specs = [
                ([None if not isinstance(d, int) else d for d in shape],
                 str(dt)) for shape, dt in shapes_dtypes
            ]
            blob = write_pdexport(
                path, exported, input_names,
                [f"output{i}" for i in range(n_out)], in_specs,
                pinned_dynamic_dims=pinned,
                encrypt_key=encrypt_key,
                dtype=precision or "float32",
            )
            if encrypt_key is None:
                meta["stablehlo"] = exported.mlir_module()
            meta["in_specs"] = blob["in_specs"]
        except Exception as e:  # export is best-effort; state always saved
            meta["export_error"] = repr(e)
    with open(path + ".pdmodel", "wb") as f:
        pickle.dump(meta, f)


def load(path, **configs):
    """Load a jit-saved model for inference: returns a predictor-like object
    exposing the saved state; pair with the original Layer class via
    set_state_dict, or run through paddle_tpu.inference.
    ``configs['cipher_key']``: key for artifacts saved with encrypt_key."""
    from ..framework.io import load as _load_state

    state = _load_state(path + ".pdiparams",
                        cipher_key=configs.get("cipher_key"))
    meta = {}
    model_f = path + ".pdmodel"
    if os.path.exists(model_f):
        with open(model_f, "rb") as f:
            meta = pickle.load(f)

    class _Loaded:
        def __init__(self):
            self.state_dict_data = state
            self.meta = meta

        def state_dict(self):
            return self.state_dict_data

    return _Loaded()
