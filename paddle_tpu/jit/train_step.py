"""Compiled training step.

The TPU-native equivalent of the reference's executor hot loop
(framework/executor.cc:292 per-op interpretation): the ENTIRE training step —
forward, backward, optimizer update, metric — is one jitted XLA program.
hapi.Model, the fleet data-parallel engine, and bench.py all build on this.
"""
from __future__ import annotations

import contextlib
import os
import time
from functools import partial
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..nn.layer_base import Layer
from ..optimizer.optimizer import Optimizer
from ..profiler import device_profile as _device_profile
from ..profiler import goodput as _goodput
from ..profiler import spans as _spans
from ..profiler.retrace import tracked_jit
from ..profiler.telemetry import get_telemetry
from ..resilience.guard import copy_tree as _copy_tree
from ..resilience.watchdog import heartbeat as _watchdog_heartbeat
from .functionalize import functionalize, get_buffers, get_params, set_buffers, set_params

__all__ = ["TrainStep", "EvalStep"]


class TrainStep:
    """Stages layer+loss+optimizer into one jitted update.

    ``step(inputs, labels)`` keeps parameters and optimizer state on-device
    across iterations and writes them back into the Layer lazily (on demand /
    at checkpoint time), so the hot loop never leaves XLA.
    """

    def __init__(self, layer: Layer, loss_fn: Callable, optimizer: Optimizer,
                 donate: bool = True, mesh=None, in_shardings=None,
                 check_finite: Optional[bool] = None,
                 guard_updates: bool = False, remat="off",
                 fingerprint_every: Optional[int] = None):
        self._layer = layer
        self._optimizer = optimizer
        self._loss_fn = loss_fn
        self._apply = functionalize(layer, training=True)
        self._params = get_params(layer)
        self._buffers = get_buffers(layer)
        self._named_params = dict(layer.named_parameters())
        self._opt_state = {
            name: optimizer._init_state_for(p)
            for name, p in self._params.items()
        }
        self._dirty = True

        opt = optimizer
        from ..core.sanitizer import finite_flags, jit_check_enabled

        # ``guard_updates`` (resilience.StepGuard contract): the compiled
        # step selects between the updated and the incoming state on its
        # own finite sweep, so a NaN/Inf step never applies its optimizer
        # update; the guard reads the flags host-side instead of raising.
        self._guard_updates = bool(guard_updates)
        self._check_nan = (jit_check_enabled() if check_finite is None
                           else bool(check_finite)) or self._guard_updates
        self._nan_names: list = []
        self._last_flags = None

        # ``fingerprint_every`` (resilience.integrity contract): every N
        # steps the compiled step folds params+opt-state+buffers into 3
        # scalars (sum / abs-sum / bit-exact XOR) INSIDE the jit, gated
        # by a TRACED bool argument — the gate is decided at build time,
        # the due-ness per step at run time, so the retrace budget is
        # untouched and off-interval steps skip the reduces at runtime.
        from ..resilience.integrity import fingerprint_every_from_env

        if fingerprint_every is None:
            fingerprint_every = fingerprint_every_from_env()
        self._fp_every = max(0, int(fingerprint_every))
        import collections

        self._fp_history: collections.deque = collections.deque(
            maxlen=int(os.environ.get("PADDLE_TPU_FP_HISTORY", "64") or 64))

        # ``remat``: 'off' (default) | 'auto' (roofline-driven selective
        # rematerialization — ops.remat_policy measures the compiled
        # step's peak HBM against the chip's capacity at the first call
        # and escalates dots→nothing→offload only as needed) | an
        # explicit jax.checkpoint policy ('full'/'dots'/'dots_no_batch'/
        # 'nothing'/'offload').
        from ..ops import remat_policy as _remat_policy

        self._remat = _remat_policy.normalize(remat)

        def forward_loss(p, buffers, inputs, labels):
            out, new_b = self._apply(p, buffers, *inputs)
            loss = self._loss_fn(out, *labels)
            if isinstance(loss, Tensor):
                loss = loss._value
            return loss, new_b

        self._forward_loss_base = forward_loss

        def step_fn_of(fwd):
            if self._fp_every:
                def step_fn(params, buffers, opt_state, lr, batch, fp_due):
                    inputs, labels = batch
                    (loss, new_buffers), grads = jax.value_and_grad(
                        fwd, has_aux=True)(params, buffers, inputs, labels)
                    return self._finish_step(params, buffers, opt_state, lr,
                                             loss, new_buffers, grads,
                                             fp_due=fp_due)
            else:
                def step_fn(params, buffers, opt_state, lr, batch):
                    inputs, labels = batch
                    (loss, new_buffers), grads = jax.value_and_grad(
                        fwd, has_aux=True)(params, buffers, inputs, labels)
                    return self._finish_step(params, buffers, opt_state, lr,
                                             loss, new_buffers, grads)

            return step_fn

        self._step_fn_of = step_fn_of
        self._donate = donate
        if self._remat == "auto":
            self._jitted = None  # resolved (and built) at the first call
        else:
            self._build_jitted(
                _remat_policy.apply_policy(forward_loss, self._remat))
        self._last_step_t = None  # inter-call interval ⇒ steady-state step time

    def _build_jitted(self, fwd):
        self._jitted = tracked_jit(
            self._step_fn_of(fwd), name="jit.train_step",
            sig_argnums=(3, 4),
            donate_argnums=(0, 2) if self._donate else ())

    def _candidate_jit(self, policy):
        """A plain-jit twin of the step under remat ``policy`` with the
        real donation, so XLA's aliasing accounting matches the step that
        will actually run (never tracked — probe compiles must not
        pollute the attribution registry)."""
        from ..ops import remat_policy

        fn = self._step_fn_of(
            remat_policy.apply_policy(self._forward_loss_base, policy))
        return jax.jit(fn, donate_argnums=(0, 2) if self._donate else ())

    def lower_cost(self, policy, inputs, labels):
        """XLA's own cost accounting — exact peak HBM, flops, bytes — for
        this step compiled under remat ``policy`` (the measurement
        ``remat='auto'`` ladders on); None when infeasible."""
        from ..ops import remat_policy

        batch = jax.device_put((
            tuple(a._value if isinstance(a, Tensor) else jnp.asarray(a)
                  for a in inputs),
            tuple(a._value if isinstance(a, Tensor) else jnp.asarray(a)
                  for a in labels)))
        args = (self._params, self._buffers, self._opt_state,
                self._optimizer.lr_device_scalar(), batch) \
            + self._fp_args()
        return remat_policy.program_cost(self._candidate_jit(policy), args)

    def _fp_args(self):
        """The trailing traced fingerprint-due argument (probe compiles
        pass False — due-ness never changes the program signature)."""
        return (jnp.asarray(False),) if self._fp_every else ()

    def _resolve_remat(self, lr, batch):
        """remat='auto': measure candidate policies' peak HBM on this
        call's avals (ops.remat_policy ladder) and build the jitted step
        with the winner. Runs once, before the first compile."""
        from ..ops import remat_policy

        args = (self._params, self._buffers, self._opt_state, lr, batch) \
            + self._fp_args()
        chosen = remat_policy.resolve(
            "jit.train_step",
            lambda policy: remat_policy.program_cost(
                self._candidate_jit(policy), args))
        self._build_jitted(
            remat_policy.apply_policy(self._forward_loss_base, chosen))

    def _finish_step(self, params, buffers, opt_state, lr, loss,
                     new_buffers, grads, fp_due=None):
        """Traced tail of the step: clip, optimizer update, finite sweep,
        guarded select, optional state fingerprint. Shared by every
        remat variant of the forward."""
        from ..core.sanitizer import finite_flags

        opt = self._optimizer
        if opt._grad_clip is not None:
            from ..nn.clip import ClipGradByGlobalNorm, clip_grads_global_norm_raw

            if isinstance(opt._grad_clip, ClipGradByGlobalNorm):
                grads = clip_grads_global_norm_raw(grads, opt._grad_clip.clip_norm)
        new_params = {}
        new_opt_state = {}
        for name, p in params.items():
            st = opt_state[name]
            # multi_precision: all pre-update math (L2 fold, AdamW
            # decay) runs on the f32 master, like apply_optimizer_update
            master = (st.get("master")
                      if isinstance(st, dict) else None)
            p_eff = master if master is not None else p
            g = grads[name].astype(p_eff.dtype)
            wd = opt._decay_coeff(self._named_params[name])
            if wd and type(opt).__name__ != "AdamW":
                g = g + wd * p_eff
            if type(opt).__name__ == "AdamW" and getattr(opt, "_coeff", 0.0):
                decay = True
                if opt._apply_decay_param_fun is not None:
                    decay = opt._apply_decay_param_fun(name)
                if decay:
                    p_eff = p_eff * (1.0 - lr * opt._coeff)
            if master is not None:
                sub = {k: v for k, v in st.items() if k != "master"}
                new_master, ns = opt._update(p_eff, g, sub, lr)
                ns["master"] = new_master
                np_ = new_master.astype(p.dtype)
            else:
                np_, ns = opt._update(p_eff, g, st, lr)
            new_params[name] = np_
            new_opt_state[name] = ns
        flags = (finite_flags(self._nan_names, loss=loss, grad=grads,
                              param=new_params)
                 if self._check_nan else None)
        if self._guard_updates and flags is not None:
            from ..core.sanitizer import select_if_finite

            new_params, new_buffers, new_opt_state = select_if_finite(
                flags, (new_params, new_buffers, new_opt_state),
                (params, buffers, opt_state))
        if self._fp_every:
            from ..core.sanitizer import tree_fingerprint, zero_fingerprint

            # fingerprint the state the step RETURNS (post-update,
            # post-guarded-select — what the next step will carry); the
            # runtime cond skips the reduces on off-interval steps
            fp = jax.lax.cond(
                fp_due,
                lambda: tree_fingerprint(new_params, new_opt_state,
                                         new_buffers),
                zero_fingerprint)
            return new_params, new_buffers, new_opt_state, loss, flags, fp
        return new_params, new_buffers, new_opt_state, loss, flags

    def prefetch(self, batches, depth=2, buckets=None):
        """Wrap a ``(inputs, labels)`` batch iterator in a background
        ``DevicePrefetcher`` (pad/bucket + one async pytree device_put per
        batch, ``depth`` batches ahead) so H2D overlaps the in-flight
        step. See ``paddle_tpu.io.DevicePrefetcher``."""
        from ..io.prefetch import DevicePrefetcher

        return DevicePrefetcher(batches, depth=depth, buckets=buckets)

    def __call__(self, inputs, labels):
        _watchdog_heartbeat()
        # on-demand device profiling: a no-op global check unless a
        # windowed capture is armed (env cadence or POST /debug/profile)
        _device_profile.step_boundary("jit.train_step")
        # goodput: the whole call is productive_step wall time; a
        # compile triggered inside claims its own category (nested),
        # and the helper split keeps the body at its original indent
        with _goodput.activity("productive_step"):
            return self._call_in_claim(inputs, labels)

    def _call_in_claim(self, inputs, labels):
        with contextlib.ExitStack() as _stk:
            if not _spans.in_category("step"):
                # hapi fit (or another loop-level owner) may already hold
                # the step span — h2d/compute then nest under it directly
                _stk.enter_context(_spans.span(
                    "step", cat="step", step=self._optimizer._global_step))
            with _spans.span("h2d", cat="h2d"):
                # ONE pytree transfer for the whole batch (single
                # dispatch; a device-resident batch — e.g. from
                # ``prefetch`` — passes through)
                raw_inputs, raw_labels = jax.device_put((
                    tuple(a._value if isinstance(a, Tensor) else jnp.asarray(a)
                          for a in inputs),
                    tuple(a._value if isinstance(a, Tensor) else jnp.asarray(a)
                          for a in labels),
                ))
            lr = self._optimizer.lr_device_scalar()
            if self._jitted is None:  # remat='auto': first batch's avals
                self._resolve_remat(lr, (raw_inputs, raw_labels))
            compiles_before = self._jitted.tracker.compiles
            fp_due = bool(self._fp_every) and \
                self._optimizer._global_step % self._fp_every == 0
            with _spans.span("compute", cat="compute"):
                if self._fp_every:
                    (self._params, self._buffers, self._opt_state, loss,
                     flags, fp) = self._jitted(
                        self._params, self._buffers, self._opt_state, lr,
                        (raw_inputs, raw_labels), jnp.asarray(fp_due))
                else:
                    (self._params, self._buffers, self._opt_state, loss,
                     flags) = self._jitted(
                        self._params, self._buffers, self._opt_state, lr,
                        (raw_inputs, raw_labels),
                    )
        if self._fp_every and fp_due:
            from ..resilience.integrity import publish_fingerprint

            publish_fingerprint(self._fp_history,
                                self._optimizer._global_step, fp,
                                self._fp_every)
        if self._check_nan:
            self._last_flags = flags
            if not self._guard_updates:
                from ..core.sanitizer import raise_if_nonfinite

                raise_if_nonfinite(self._nan_names, flags)
        self._optimizer._global_step += 1
        self._dirty = True
        # steady-state step time from the inter-call interval (dispatch
        # is async — same rationale as engine/step_ms); the interval
        # containing a (re)compile is dropped, and the shared pause
        # filter in observe_interval rejects checkpoint/eval gaps. This
        # histogram is the MFU denominator for the jit.train_step entry.
        tel = get_telemetry()
        if tel.enabled:
            now = time.perf_counter()
            last = self._last_step_t
            if last is not None and now > last \
                    and self._jitted.tracker.compiles == compiles_before:
                tel.observe_interval("jit/step_ms", (now - last) * 1e3)
            self._last_step_t = now
        return Tensor(loss)

    # -- resilience (StepGuard engine contract) ------------------------
    def last_step_finite(self):
        """(ok, bad_leaf_names) of the most recent step's finite sweep."""
        from ..resilience.guard import finite_report

        return finite_report(self._nan_names, self._last_flags)

    @property
    def fingerprint_every(self) -> int:
        """The in-jit fingerprint interval (0 = off)."""
        return self._fp_every

    def last_fingerprint(self):
        """The newest in-jit state fingerprint as ``(step, {"sum",
        "abs_sum", "xor"})`` with host-fetched scalars (bit-preserving
        ``np.asarray`` — this is the sync point the divergence monitor
        pays once per interval), or None before the first one."""
        if not self._fp_history:
            return None
        step, fp = self._fp_history[-1]
        return step, {k: np.asarray(v) for k, v in fp.items()}

    def fingerprint_history(self):
        """Bounded per-rank history of (step, fingerprint) pairs, oldest
        first (device scalars — fetch lazily)."""
        return list(self._fp_history)

    def snapshot_state(self):
        """Deep on-device copy of params/buffers/opt-state. A copy, not a
        reference: the jitted step donates its inputs, so snapshot
        buffers held by reference would be deleted on the next call."""
        return {"params": _copy_tree(self._params),
                "buffers": _copy_tree(self._buffers),
                "opt_state": _copy_tree(self._opt_state)}

    def restore_state(self, snap):
        """Install a snapshot (from ``snapshot_state`` or a restored
        checkpoint). Installs COPIES so a snapshot survives being
        restored more than once (the engine will donate what it holds)."""
        self._params = _copy_tree(snap["params"])
        self._buffers = _copy_tree(snap["buffers"])
        self._opt_state = _copy_tree(snap["opt_state"])
        self._dirty = True

    def sync_to_layer(self):
        """Write staged params/buffers back into the imperative Layer."""
        if self._dirty:
            set_params(self._layer, self._params)
            set_buffers(self._layer, self._buffers)
            # restore optimizer accumulator mapping
            for name, p in self._named_params.items():
                self._optimizer._accumulators[id(p)] = self._opt_state[name]
            self._dirty = False

    def refresh_from_layer(self):
        self._params = get_params(self._layer)
        self._buffers = get_buffers(self._layer)


class EvalStep:
    def __init__(self, layer: Layer, loss_fn: Optional[Callable] = None):
        self._layer = layer
        self._apply = functionalize(layer, training=False)
        self._loss_fn = loss_fn

        def eval_fn(params, buffers, *inputs):
            out, _ = self._apply(params, buffers, *inputs)
            return out

        self._jitted = tracked_jit(eval_fn, name="jit.eval_step",
                                   sig_argnums=slice(2, None))

    def prefetch(self, batches, depth=2, buckets=None):
        """Background device prefetch for eval input batches (see
        ``TrainStep.prefetch``)."""
        from ..io.prefetch import DevicePrefetcher

        return DevicePrefetcher(batches, depth=depth, buckets=buckets)

    def __call__(self, *inputs):
        # goodput: eval wall time is its own ledger category (an eval
        # pass inside a training loop nests under the loop's claims)
        with _goodput.activity("eval"):
            # one pytree transfer instead of one implicit put per array
            raw = jax.device_put(tuple(
                a._value if isinstance(a, Tensor) else jnp.asarray(a)
                for a in inputs))
            out = self._jitted(get_params(self._layer),
                               get_buffers(self._layer), *raw)
        from .functionalize import _wrap_tree

        return _wrap_tree(out)
