"""Functionalize imperative Layers for XLA compilation.

This is the TPU-native replacement for the reference's dygraph→static AST
transpiler (fluid/dygraph/dygraph_to_static/program_translator.py + 24 AST
transformers): instead of rewriting Python source into ProgramDesc, we trace
the Layer's forward with JAX tracers threaded through the same eager ops.
Parameters/buffers are lifted into pytrees, so the result is a pure function
``apply(params, buffers, *args)`` that jax.jit/pjit compiles — no per-op
dispatch at runtime, full XLA fusion.
"""
from __future__ import annotations

import contextlib
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from ..core.tensor import Parameter, Tensor, no_grad
from ..nn.layer_base import Layer

__all__ = ["functionalize", "get_params", "get_buffers", "set_params",
           "cast_floats", "TracedLayer"]


def cast_floats(tree, dtype):
    """Cast the FLOAT leaves of a pytree to ``dtype`` (everything else
    passes through untouched). The serving-precision primitive shared by
    ``jit.save(precision=...)`` (bake cast weights into the artifact)
    and ``inference.Predictor`` (cast a live layer at load, cast inputs
    in / outputs back out) — one definition so the two paths cannot
    silently diverge on what "cast the floats" means."""
    dtype = jnp.dtype(dtype)
    return jax.tree_util.tree_map(
        lambda p: p.astype(dtype)
        if jnp.issubdtype(jnp.asarray(p).dtype, jnp.floating) else p, tree)


def get_params(layer: Layer) -> Dict[str, Any]:
    """Named parameter pytree (raw jax arrays)."""
    return {name: p._value for name, p in layer.named_parameters()}


def get_buffers(layer: Layer) -> Dict[str, Any]:
    return {name: b._value for name, b in layer.named_buffers()}


def set_params(layer: Layer, params: Dict[str, Any]):
    named = dict(layer.named_parameters())
    for name, v in params.items():
        named[name]._value = v


def set_buffers(layer: Layer, buffers: Dict[str, Any]):
    named = dict(layer.named_buffers())
    for name, v in buffers.items():
        named[name]._value = v


@contextlib.contextmanager
def _swapped_state(layer: Layer, params, buffers):
    named_p = dict(layer.named_parameters())
    named_b = dict(layer.named_buffers())
    saved_p = {n: p._value for n, p in named_p.items()}
    saved_b = {n: b._value for n, b in named_b.items()}
    try:
        for n, v in params.items():
            if n in named_p:
                named_p[n]._value = v
        for n, v in (buffers or {}).items():
            if n in named_b:
                named_b[n]._value = v
        yield named_b
    finally:
        for n, v in saved_p.items():
            named_p[n]._value = v
        for n, v in saved_b.items():
            named_b[n]._value = v


def functionalize(layer: Layer, with_buffers: bool = True, training: bool | None = None):
    """Return ``apply(params, buffers, *raw_args) -> (raw_out, new_buffers)``.

    The returned function is pure: it swaps the pytree leaves into the layer,
    runs forward under no-grad (JAX handles differentiation outside), and
    restores. Buffer mutations (e.g. BN running stats) are captured and
    returned functionally so the caller can carry them through a jitted loop.
    """

    def apply(params, buffers, *raw_args, **raw_kwargs):
        with _swapped_state(layer, params, buffers or {}) as named_b:
            prev_training = layer.training
            if training is not None:
                layer.training = training
                for l in layer.sublayers():
                    l.training = training
            try:
                with no_grad():
                    args = [
                        Tensor(a) if not isinstance(a, Tensor) and hasattr(a, "dtype") else a
                        for a in raw_args
                    ]
                    kwargs = {
                        k: Tensor(v) if not isinstance(v, Tensor) and hasattr(v, "dtype") else v
                        for k, v in raw_kwargs.items()
                    }
                    out = layer(*args, **kwargs)
                new_buffers = {n: b._value for n, b in named_b.items()}
            finally:
                layer.training = prev_training
                for l in layer.sublayers():
                    l.training = prev_training
        return _unwrap_tree(out), new_buffers

    return apply


def _unwrap_tree(out):
    if isinstance(out, Tensor):
        return out._value
    if isinstance(out, (list, tuple)):
        return type(out)(_unwrap_tree(o) for o in out)
    if isinstance(out, dict):
        return {k: _unwrap_tree(v) for k, v in out.items()}
    return out


def _wrap_tree(out):
    if isinstance(out, (list, tuple)):
        return type(out)(_wrap_tree(o) for o in out)
    if isinstance(out, dict):
        return {k: _wrap_tree(v) for k, v in out.items()}
    if hasattr(out, "dtype") and hasattr(out, "shape"):
        return Tensor(out)
    return out


class TracedLayer:
    """jit-compiled inference wrapper over a Layer (parity with
    fluid/dygraph/jit.py TracedLayer)."""

    def __init__(self, layer: Layer, training=False, donate_buffers=False):
        self._layer = layer
        self._apply = functionalize(layer, training=training)
        self._jitted = jax.jit(self._apply)

    def __call__(self, *args):
        params = get_params(self._layer)
        buffers = get_buffers(self._layer)
        raw_args = [a._value if isinstance(a, Tensor) else a for a in args]
        out, new_buffers = self._jitted(params, buffers, *raw_args)
        set_buffers(self._layer, new_buffers)
        return _wrap_tree(out)
