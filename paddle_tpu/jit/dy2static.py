"""dygraph→static conversion: AST rewriting of data-dependent Python control
flow into traceable ops.

Parity with the reference's ProgramTranslator + AST transformer stack
(/root/reference/python/paddle/fluid/dygraph/dygraph_to_static/
program_translator.py, ifelse_transformer.py, loop_transformer.py,
logical_transformer.py — 24 transformer files). The reference rewrites
``if``/``while``/``and``/``or``/``not`` over Variables into
cond/while_loop/logical_* layer calls so the same Python runs as a static
program; here the rewrite targets ``paddle_tpu.static.cond/while_loop``,
which already dispatch three ways (eager python, ``lax.cond/while_loop``
under jit tracing, composite op under Program recording) — so one converted
function serves dygraph, ``jax.jit``, and the Program facade.

Supported rewrites (the reference's core set):
- ``if``/``elif``/``else`` whose test involves a Tensor → ``convert_ifelse``
  with branch closures returning the variables either branch assigns.
- ``while`` whose test involves a Tensor → ``convert_while`` over the loop
  variables assigned in the body.
- ``and`` / ``or`` / ``not`` over Tensors → short-circuit-free
  ``convert_logical_*`` (lax-compatible).
Statements a branch cannot stage (``return``/``break``/``continue`` inside a
converted block) keep their Python form — identical to eager semantics, and
an error only if actually traced with a tracer predicate, matching the
reference's partial-support behavior.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
from typing import Callable, List, Optional

from ..core.tensor import Tensor, _is_tracer

__all__ = [
    "convert_to_static",
    "convert_ifelse",
    "convert_while",
    "convert_logical_and",
    "convert_logical_or",
    "convert_logical_not",
    "convert_bool",
]


# ---------------------------------------------------------------------------
# runtime conversion helpers (reference: dygraph_to_static/convert_operators.py)
# ---------------------------------------------------------------------------
def _is_dynamic(x) -> bool:
    if isinstance(x, Tensor):
        return _is_tracer(x._value) or _recording()
    return _is_tracer(x)


def _recording() -> bool:
    from ..core import tensor as tensor_mod

    return tensor_mod._op_recorder is not None


class _Undefined:
    """Sentinel for names unbound before a converted block (the reference's
    UndefinedVar, dygraph_to_static/utils.py). Any USE raises the NameError
    python would have raised — only threading it through the branch plumbing
    untouched is allowed."""

    __slots__ = ()

    def __repr__(self):
        return "<undefined before converted control flow>"

    def _raise(self, *a, **k):
        raise NameError(
            "variable used before assignment: it was unbound before a "
            "converted if/while and the taken branch did not assign it")

    __getattr__ = _raise
    __bool__ = _raise
    __float__ = _raise
    __int__ = _raise
    __iter__ = _raise
    __call__ = _raise
    __add__ = __radd__ = __sub__ = __rsub__ = _raise
    __mul__ = __rmul__ = __truediv__ = __rtruediv__ = _raise
    __getitem__ = __len__ = _raise


UNDEF = _Undefined()


def convert_ifelse(pred, true_fn: Callable, false_fn: Callable, args=()):
    """Dispatch an ``if``: python branch for concrete predicates, staged
    select/cond for tracers/recorded programs (ifelse_transformer.py
    semantics).

    Staged under jit, both branches are traced and the assigned-variable
    tuple is combined leafwise with ``where`` — a name bound in only ONE
    branch arrives as UNDEF from the other and is filled with a typed zero
    (the documented created-undefined-var deviation, matching
    convert_while's zero-trip staging); a name unbound in BOTH stays UNDEF."""
    if _recording():
        from ..static.control_flow import cond

        return cond(pred, lambda: true_fn(*args), lambda: false_fn(*args))
    if _is_dynamic(pred):
        import jax
        import jax.numpy as jnp

        from ..core.tensor import wrap_raw

        t_out = true_fn(*args)
        f_out = false_fn(*args)
        is_leaf = lambda x: isinstance(x, (_Undefined, Tensor))
        flat_t, tdef = jax.tree_util.tree_flatten(t_out, is_leaf=is_leaf)
        flat_f, fdef = jax.tree_util.tree_flatten(f_out, is_leaf=is_leaf)
        if tdef != fdef or len(flat_t) != len(flat_f):
            raise ValueError(
                "converted if/else branches produced different structures")
        praw = pred._value if isinstance(pred, Tensor) else pred

        def pick(a, b):
            if isinstance(a, _Undefined) and isinstance(b, _Undefined):
                return a
            if isinstance(a, _Undefined) or isinstance(b, _Undefined):
                bound = b if isinstance(a, _Undefined) else a
                braw = bound._value if isinstance(bound, Tensor) else \
                    jnp.asarray(bound)
                zero = jnp.zeros(jnp.shape(braw), braw.dtype)
                a_, b_ = (zero, braw) if isinstance(a, _Undefined) \
                    else (braw, zero)
                return wrap_raw(jnp.where(praw, a_, b_))
            araw = a._value if isinstance(a, Tensor) else jnp.asarray(a)
            braw = b._value if isinstance(b, Tensor) else jnp.asarray(b)
            return wrap_raw(jnp.where(praw, araw, braw))

        out = [pick(a, b) for a, b in zip(flat_t, flat_f)]
        return jax.tree_util.tree_unflatten(tdef, out)
    taken = bool(pred.numpy()) if isinstance(pred, Tensor) else bool(pred)
    return true_fn(*args) if taken else false_fn(*args)


def convert_while(cond_fn: Callable, body_fn: Callable, loop_vars: tuple):
    """Dispatch a ``while`` (loop_transformer.py semantics).

    Loop vars first assigned INSIDE the body arrive as the UNDEF sentinel.
    Eagerly that is python-exact (zero-trip leaves them undefined; one trip
    overwrites them). Staged, lax.while_loop needs typed carries, so the
    body is traced once on the inits — write-before-read slots produce real
    values — and the UNDEF inits are replaced by typed zeros (a zero-trip
    traced loop then yields zeros: the documented deviation, matching the
    reference's created-undefined-var behavior)."""
    first = cond_fn(*loop_vars)
    if _is_dynamic(first) or any(_is_dynamic(v) for v in loop_vars
                                 if not isinstance(v, _Undefined)):
        from ..static.control_flow import while_loop

        if any(isinstance(v, _Undefined) for v in loop_vars):
            import jax.numpy as jnp

            from ..core.tensor import wrap_raw

            template = tuple(body_fn(*loop_vars))

            def zero_like(t):
                if isinstance(t, Tensor):
                    return wrap_raw(jnp.zeros(t.shape, t._value.dtype))
                if hasattr(t, "dtype"):
                    return jnp.zeros(jnp.shape(t), t.dtype)
                return type(t)() if t is not None else None

            loop_vars = tuple(
                zero_like(tp) if isinstance(v, _Undefined) else v
                for v, tp in zip(loop_vars, template))
            first = cond_fn(*loop_vars)
        out = while_loop(cond_fn, lambda *vs: tuple(body_fn(*vs)),
                         list(loop_vars))
        return tuple(out)
    vars_ = tuple(loop_vars)
    cur = first
    while bool(cur.numpy()) if isinstance(cur, Tensor) else bool(cur):
        vars_ = tuple(body_fn(*vars_))
        cur = cond_fn(*vars_)
    return vars_


def convert_logical_and(lhs, rhs_fn: Callable):
    """``a and b`` — short-circuits for python values, elementwise logical
    for Tensors (logical_transformer.py)."""
    if isinstance(lhs, Tensor) and _is_dynamic(lhs):
        return lhs & rhs_fn()
    if isinstance(lhs, Tensor):
        if not bool(lhs.numpy().all() if lhs.ndim else lhs.numpy()):
            return lhs
        return rhs_fn()
    return lhs and rhs_fn()


def convert_logical_or(lhs, rhs_fn: Callable):
    if isinstance(lhs, Tensor) and _is_dynamic(lhs):
        return lhs | rhs_fn()
    if isinstance(lhs, Tensor):
        if bool(lhs.numpy().all() if lhs.ndim else lhs.numpy()):
            return lhs
        return rhs_fn()
    return lhs or rhs_fn()


def convert_range_check(i, stop, step):
    """Loop-continue test for a converted ``for _ in range(...)`` —
    sign-aware so negative steps work, tensor-safe so it stages. A concrete
    zero step raises like python's range()."""
    if not isinstance(step, Tensor) and step == 0:
        raise ValueError("range() arg 3 must not be zero")
    if isinstance(step, Tensor) or _is_dynamic(i) or _is_dynamic(stop):
        import jax.numpy as jnp

        from ..core.tensor import wrap_raw

        iv = i._value if isinstance(i, Tensor) else jnp.asarray(i)
        sv = stop._value if isinstance(stop, Tensor) else jnp.asarray(stop)
        st = step._value if isinstance(step, Tensor) else jnp.asarray(step)
        return wrap_raw((st > 0) & (iv < sv) | (st < 0) & (iv > sv))
    return (step > 0 and i < stop) or (step < 0 and i > stop)


def convert_logical_not(x):
    if isinstance(x, Tensor):
        return x.logical_not() if hasattr(x, "logical_not") else ~x
    return not x


def convert_bool(x):
    """bool(x) in a converted test position."""
    if isinstance(x, Tensor) and _is_dynamic(x):
        return x
    if isinstance(x, Tensor):
        return bool(x.numpy().all() if x.ndim else x.numpy())
    return x


# ---------------------------------------------------------------------------
# AST analysis
# ---------------------------------------------------------------------------
import re as _re

_GENERATED_NAME = _re.compile(
    r"^__(true_fn|false_fn|loop_cond|loop_body|range_it|range_stop|"
    r"range_step)_\d+$")


def _is_generated_name(name: str) -> bool:
    return bool(_GENERATED_NAME.match(name))


def _assigned_names(nodes: List[ast.stmt]) -> List[str]:
    out: List[str] = []

    class V(ast.NodeVisitor):
        def visit_Assign(self, n):
            for t in n.targets:
                self._target(t)
            self.generic_visit(n)

        def visit_AugAssign(self, n):
            self._target(n.target)
            self.generic_visit(n)

        def visit_AnnAssign(self, n):
            if n.value is not None:
                self._target(n.target)
            self.generic_visit(n)

        def visit_For(self, n):
            self._target(n.target)
            self.generic_visit(n)

        def _target(self, t):
            if isinstance(t, ast.Name):
                if t.id not in out and not _is_generated_name(t.id):
                    out.append(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    self._target(e)

        # do not descend into nested function defs; record USER def names
        # (they thread through branches eagerly like any assignment) but
        # never the converter's own generated helpers (__true_fn_N, …) —
        # those leaking into loop/branch vars breaks staging
        def visit_FunctionDef(self, n):
            if not _is_generated_name(n.name) and n.name not in out:
                out.append(n.name)

        visit_AsyncFunctionDef = visit_FunctionDef

    v = V()
    for n in nodes:
        v.visit(n)
    return out


def _read_value_names(node) -> set:
    """Names read as VALUES — excluding names whose only appearance is as
    the callee base of a Call (``paddle`` in ``paddle.sum(x)``): those are
    module/function bindings, and threading them through a
    ``lax.while_loop`` carry fails under jit staging."""
    callee_bases = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            f = n.func
            while isinstance(f, ast.Attribute):
                f = f.value
            if isinstance(f, ast.Name):
                callee_bases.add(id(f))
    names = set()
    for n in ast.walk(node):
        if (isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
                and id(n) not in callee_bases):
            names.add(n.id)
    return names


def _has_scope_decl(nodes: List[ast.stmt]) -> bool:
    """global/nonlocal in the block: declared names cannot also be branch-fn
    parameters, so such blocks keep their python form."""
    for n in nodes:
        for sub in ast.walk(n):
            if isinstance(sub, (ast.Global, ast.Nonlocal)):
                return True
    return False


def _has_escape(nodes: List[ast.stmt]) -> bool:
    """return/break/continue/yield at this block's level (not in nested defs
    or nested loops for break/continue)."""

    class V(ast.NodeVisitor):
        found = False
        loop_depth = 0

        def visit_Return(self, n):
            self.found = True

        def visit_Yield(self, n):
            self.found = True

        def visit_YieldFrom(self, n):
            self.found = True

        def visit_Break(self, n):
            if self.loop_depth == 0:
                self.found = True

        visit_Continue = visit_Break

        def visit_While(self, n):
            self.loop_depth += 1
            self.generic_visit(n)
            self.loop_depth -= 1

        visit_For = visit_While

        def visit_FunctionDef(self, n):
            pass  # don't descend

        visit_AsyncFunctionDef = visit_FunctionDef
        visit_Lambda = visit_FunctionDef

    v = V()
    for n in nodes:
        v.visit(n)
    return v.found


_HELPER = "_jst"


# ---------------------------------------------------------------------------
# escape rewriting (reference: return_transformer.py,
# break_continue_transformer.py)
# ---------------------------------------------------------------------------
def _has_return(nodes) -> bool:
    class V(ast.NodeVisitor):
        found = False

        def visit_Return(self, n):
            self.found = True

        def visit_FunctionDef(self, n):
            pass

        visit_AsyncFunctionDef = visit_FunctionDef
        visit_Lambda = visit_FunctionDef

    v = V()
    for n in nodes:
        v.visit(n)
    return v.found


def _return_inside_loop(nodes) -> bool:
    """A Return whose nearest enclosing loop within this subtree is a loop
    (we cannot elseify those)."""
    class V(ast.NodeVisitor):
        found = False
        depth = 0

        def visit_Return(self, n):
            if self.depth > 0:
                self.found = True

        def visit_While(self, n):
            self.depth += 1
            self.generic_visit(n)
            self.depth -= 1

        visit_For = visit_While

        def visit_FunctionDef(self, n):
            pass

        visit_AsyncFunctionDef = visit_FunctionDef
        visit_Lambda = visit_FunctionDef

    v = V()
    for n in nodes:
        v.visit(n)
    return v.found


def _has_loop_escape(nodes, kinds) -> bool:
    """break/continue at THIS loop's level (not inside nested loops/defs)."""
    class V(ast.NodeVisitor):
        found = False

        def generic_visit(self, n):
            if isinstance(n, kinds):
                self.found = True
            if not isinstance(n, (ast.While, ast.For, ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Lambda)):
                super().generic_visit(n)

    v = V()
    for n in nodes:
        v.visit(n)
    return v.found


def _assign(name, value_node):
    return ast.Assign(targets=[ast.Name(id=name, ctx=ast.Store())],
                      value=value_node)


class _EscapeRewriter(ast.NodeTransformer):
    """Rewrites ``return``/``break``/``continue`` inside convertible control
    flow into value/flag threading, so the staging transformers see
    escape-free blocks (the reference's return_transformer.py and
    break_continue_transformer.py).

    - ``return`` inside ``if``: the function is ELSE-IFIED — the statements
      after an early-return guard move into its other branch, so every path
      ends assigning one return slot and falls to a single tail ``return``.
      Exact python semantics (including types) and, staged, both
      ``lax.cond`` branches produce the path's own value. Returns inside
      loops keep python form (as in eager).
    - ``break``/``continue``: lowered to boolean flags — the loop test
      gains ``not <brk>``, the statements following the escape are guarded
      by ``if not <flag>:``, and the flags thread through the loop carry.
    """

    _n = 0

    @classmethod
    def _name(cls, base):
        _EscapeRewriter._n += 1
        return f"__dy2s_{base}{cls._n}"

    # -- return elseification ----------------------------------------------
    def visit_FunctionDef(self, node):
        self.generic_visit(node)  # nested defs / loops first
        if not _needs_elseify(node.body) or _return_inside_loop(node.body):
            return node
        ret = self._name("ret")
        ok, new_body = _elseify(list(node.body), ret)
        if not ok:
            return node
        node.body = new_body + [ast.Return(value=ast.Name(id=ret,
                                                          ctx=ast.Load()))]
        ast.fix_missing_locations(node)
        return node

    # -- break/continue flags ----------------------------------------------
    def visit_While(self, node):
        self.generic_visit(node)
        return self._rewrite_loop(node)

    def visit_For(self, node):
        self.generic_visit(node)
        return self._rewrite_loop(node)

    def _rewrite_loop(self, node):
        has_b = _has_loop_escape(node.body, ast.Break)
        has_c = _has_loop_escape(node.body, ast.Continue)
        if not (has_b or has_c):
            return node
        if node.orelse or _has_return(node.body):
            return node  # loop-else interplay / returns: keep python form
        if isinstance(node, ast.For):
            # only range() for-loops lower to convert_while and consume the
            # break flag; other iterables keep python form — rewriting their
            # break would silently disable it
            it = node.iter
            if not (isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
                    and it.func.id == "range" and not it.keywords
                    and 1 <= len(it.args) <= 3
                    and isinstance(node.target, ast.Name)):
                return node
        flags = []
        pre = []
        brk = cont = None
        if has_b:
            brk = self._name("brk")
            flags.append(brk)
            pre.append(_assign(brk, ast.Constant(value=False)))
        if has_c:
            cont = self._name("cont")
            flags.append(cont)
        body, _ = _rewrite_escapes(list(node.body), brk, cont, flags)
        if has_c:
            body = [_assign(cont, ast.Constant(value=False))] + body
        node.body = body
        if has_b:
            if isinstance(node, ast.While):
                node.test = ast.BoolOp(
                    op=ast.And(),
                    values=[ast.UnaryOp(op=ast.Not(),
                                        operand=ast.Name(id=brk,
                                                         ctx=ast.Load())),
                            node.test])
            else:
                node._dy2s_brk = brk  # for-range lowering ANDs it in
        ast.fix_missing_locations(node)
        if pre:
            for p in pre:
                ast.copy_location(p, node)
                ast.fix_missing_locations(p)
            return pre + [node]
        return node


_ELSEIFY_MAX_DEPTH = 5  # ≤ 2^5 tail copies; deeper keeps python form


def _needs_elseify(stmts) -> bool:
    """A Return that is NOT a top-level statement of the function body."""
    for st in stmts:
        if isinstance(st, ast.Return):
            continue
        if _has_return([st]):
            return True
    return False


def _elseify(stmts, ret, depth=0):
    """Rewrite so every path ends with ``<ret> = value``; returns (ok, new).
    Statements after a return-containing ``if`` are duplicated into the
    branch continuations (each staged branch then yields its own path's
    value — the only structure lax.cond can type; a single return-done
    flag, the reference's approach, cannot type the first guard's branches
    when the early value and the unset slot differ). Duplication doubles
    per sequential guard, so conversion bails past ``_ELSEIFY_MAX_DEPTH``
    guards (the function keeps python form, as before)."""
    import copy

    if depth > _ELSEIFY_MAX_DEPTH:
        return False, stmts
    out = []
    for i, st in enumerate(stmts):
        if isinstance(st, ast.Return):
            out.append(_assign(ret, st.value if st.value is not None
                               else ast.Constant(value=None)))
            return True, out  # rest unreachable
        if isinstance(st, ast.If) and (_has_return(st.body)
                                       or _has_return(st.orelse)):
            if _return_inside_loop(st.body) or _return_inside_loop(st.orelse):
                return False, stmts
            cont = stmts[i + 1:]
            okb, nb = _elseify(list(st.body) + copy.deepcopy(cont), ret,
                               depth + 1)
            oke, ne = _elseify(list(st.orelse) + cont, ret, depth + 1)
            if not (okb and oke):
                return False, stmts
            new_if = ast.If(test=st.test, body=nb, orelse=ne)
            ast.copy_location(new_if, st)
            out.append(new_if)
            return True, out
        out.append(st)
    out.append(_assign(ret, ast.Constant(value=None)))
    return True, out


def _rewrite_escapes(stmts, brk, cont, flags):
    """Replace break/continue with flag sets; guard the statements that
    follow a potentially-escaping statement with ``if not <flags>:``.
    Returns (new_stmts, may_escape). Does not descend into nested loops or
    function defs (their escapes are their own)."""
    out = []
    for i, st in enumerate(stmts):
        if isinstance(st, ast.Break):
            out.append(_assign(brk, ast.Constant(value=True)))
            return out, True
        if isinstance(st, ast.Continue):
            out.append(_assign(cont, ast.Constant(value=True)))
            return out, True
        if isinstance(st, ast.If):
            nb, sb = _rewrite_escapes(list(st.body), brk, cont, flags)
            ne, se = _rewrite_escapes(list(st.orelse), brk, cont, flags)
            new_if = ast.If(test=st.test, body=nb or [ast.Pass()], orelse=ne)
            ast.copy_location(new_if, st)
            out.append(new_if)
            if sb or se:
                rest, _ = _rewrite_escapes(stmts[i + 1:], brk, cont, flags)
                if rest:
                    test = None
                    for f in flags:
                        notf = ast.UnaryOp(op=ast.Not(),
                                           operand=ast.Name(id=f,
                                                            ctx=ast.Load()))
                        test = notf if test is None else ast.BoolOp(
                            op=ast.And(), values=[test, notf])
                    guard = ast.If(test=test, body=rest, orelse=[])
                    ast.copy_location(guard, st)
                    out.append(guard)
                return out, True
            continue
        out.append(st)
    return out, False


def _undef_guards(names: List[str]) -> List[ast.stmt]:
    """Per name: ``try: <name>\nexcept NameError: <name> = _jst.UNDEF`` so a
    converted block can thread names that were unbound before it (the
    reference pre-assigns UndefinedVar the same way)."""
    out = []
    for n in names:
        out.append(ast.Try(
            body=[ast.Expr(value=ast.Name(id=n, ctx=ast.Load()))],
            handlers=[ast.ExceptHandler(
                type=ast.Name(id="NameError", ctx=ast.Load()),
                name=None,
                body=[ast.Assign(
                    targets=[ast.Name(id=n, ctx=ast.Store())],
                    value=ast.Attribute(
                        value=ast.Name(id=_HELPER, ctx=ast.Load()),
                        attr="UNDEF", ctx=ast.Load()))])],
            orelse=[], finalbody=[]))
    return out


class _LoopLowering:
    """Shared while/for lowering: builds the cond_fn/body_fn pair and the
    ``convert_while`` call over a loop-var tuple (one implementation so the
    two visitors cannot drift)."""

    def _lower_loop(self, node, loop_vars, cond_expr, body_stmts,
                    guard_vars=None):
        params = ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=n) for n in loop_vars],
            kwonlyargs=[], kw_defaults=[], defaults=[])
        cond_name = self._name("loop_cond")
        body_name = self._name("loop_body")
        cond_fn = ast.FunctionDef(
            name=cond_name, args=params,
            body=[ast.Return(value=cond_expr)], decorator_list=[])
        body_fn = ast.FunctionDef(
            name=body_name, args=params,
            body=list(body_stmts) + [ast.Return(value=ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Load()) for n in loop_vars],
                ctx=ast.Load()))],
            decorator_list=[])
        call = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Store()) for n in loop_vars],
                ctx=ast.Store())],
            value=ast.Call(
                func=ast.Attribute(value=ast.Name(id=_HELPER, ctx=ast.Load()),
                                   attr="convert_while", ctx=ast.Load()),
                args=[ast.Name(id=cond_name, ctx=ast.Load()),
                      ast.Name(id=body_name, ctx=ast.Load()),
                      ast.Tuple(elts=[ast.Name(id=n, ctx=ast.Load())
                                      for n in loop_vars], ctx=ast.Load())],
                keywords=[]))
        out = _undef_guards(guard_vars if guard_vars is not None
                            else loop_vars) + [cond_fn, body_fn, call]
        for n in out:
            ast.copy_location(n, node)
            ast.fix_missing_locations(n)
        return out


class _ForRangeTransformer(_LoopLowering):
    """Mixin for visit_For: ``for i in range(...)`` lowers to the while
    conversion (loop_transformer.py's for_range path); other iterables keep
    python form (they are host-side by construction).

    Design: a PRIVATE counter drives the iteration and assigns the user's
    loop variable at the top of each body — so body code reassigning ``i``
    cannot derail the iteration (python range semantics), and after the loop
    ``i`` holds the last yielded value, not last+step."""

    def visit_For(self, node):
        self.generic_visit(node)
        it = node.iter
        if not (isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
                and it.func.id == "range" and not it.keywords
                and 1 <= len(it.args) <= 3
                and isinstance(node.target, ast.Name)):
            return node
        if _has_escape(node.body) or node.orelse or _has_scope_decl(node.body):
            return node
        args = it.args
        start = args[0] if len(args) >= 2 else ast.Constant(value=0)
        stop = args[0] if len(args) == 1 else args[1]
        step = args[2] if len(args) == 3 else ast.Constant(value=1)
        ivar = node.target.id
        counter = self._name("range_it")
        stop_name = self._name("range_stop")
        step_name = self._name("range_step")

        def name_l(n):
            return ast.Name(id=n, ctx=ast.Load())

        def assign(n, value):
            return ast.Assign(targets=[ast.Name(id=n, ctx=ast.Store())],
                              value=value)

        pre = [
            assign(stop_name, stop),
            assign(step_name, step),
            assign(counter, start),
        ]
        # the user var is NOT pre-assigned: python's zero-trip range leaves
        # a prior binding untouched (and an unbound name unbound) — the
        # UNDEF guard + convert_while's typed-zeros staging handle both
        body_assigned = [n for n in _assigned_names(node.body) if n != ivar]
        loop_vars = [counter, ivar] + body_assigned
        cond_expr = ast.Call(
            func=ast.Attribute(value=ast.Name(id=_HELPER, ctx=ast.Load()),
                               attr="convert_range_check", ctx=ast.Load()),
            args=[name_l(counter), name_l(stop_name), name_l(step_name)],
            keywords=[])
        brk = getattr(node, "_dy2s_brk", None)
        if brk is not None:
            # break-rewritten body (escape rewriter): stop iterating once
            # the flag is set — convert_logical_and stages over tensors
            cond_expr = ast.Call(
                func=ast.Attribute(value=ast.Name(id=_HELPER, ctx=ast.Load()),
                                   attr="convert_logical_and",
                                   ctx=ast.Load()),
                args=[ast.Call(
                    func=ast.Attribute(
                        value=ast.Name(id=_HELPER, ctx=ast.Load()),
                        attr="convert_logical_not", ctx=ast.Load()),
                    args=[name_l(brk)], keywords=[]),
                    ast.Lambda(
                        args=ast.arguments(posonlyargs=[], args=[],
                                           kwonlyargs=[], kw_defaults=[],
                                           defaults=[]),
                        body=cond_expr)],
                keywords=[])
            if brk not in loop_vars:
                loop_vars.append(brk)
        body_stmts = (
            [assign(ivar, name_l(counter))] + list(node.body) +
            [assign(counter, ast.BinOp(left=name_l(counter), op=ast.Add(),
                                       right=name_l(step_name)))]
        )
        lowered = self._lower_loop(node, loop_vars, cond_expr, body_stmts,
                                   guard_vars=[ivar] + body_assigned)
        for n in pre:
            ast.copy_location(n, node)
            ast.fix_missing_locations(n)
        return pre + lowered



class _Dy2StaticTransformer(_ForRangeTransformer, ast.NodeTransformer):
    """Rewrites if/while/boolop into _jst.convert_* calls."""

    def __init__(self):
        self.counter = 0

    def _name(self, tag):
        self.counter += 1
        return f"__{tag}_{self.counter}"

    # -- boolean operators --------------------------------------------------
    def visit_BoolOp(self, node):
        self.generic_visit(node)
        fn = ("convert_logical_and" if isinstance(node.op, ast.And)
              else "convert_logical_or")
        expr = node.values[-1]
        for lhs in reversed(node.values[:-1]):
            expr = ast.Call(
                func=ast.Attribute(
                    value=ast.Name(id=_HELPER, ctx=ast.Load()),
                    attr=fn, ctx=ast.Load()),
                args=[lhs,
                      ast.Lambda(
                          args=ast.arguments(posonlyargs=[], args=[],
                                             kwonlyargs=[], kw_defaults=[],
                                             defaults=[]),
                          body=expr)],
                keywords=[])
        return ast.copy_location(expr, node)

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return ast.copy_location(
                ast.Call(
                    func=ast.Attribute(
                        value=ast.Name(id=_HELPER, ctx=ast.Load()),
                        attr="convert_logical_not", ctx=ast.Load()),
                    args=[node.operand], keywords=[]),
                node)
        return node

    # -- if -----------------------------------------------------------------
    def visit_If(self, node):
        self.generic_visit(node)
        if (_has_escape(node.body) or _has_escape(node.orelse)
                or _has_scope_decl(node.body + node.orelse)):
            return node  # python semantics preserved (partial support)
        assigned = _assigned_names(node.body + node.orelse)
        if not assigned:
            # branches are pure side-effect python (e.g. appends): keep as-is
            return node

        true_name = self._name("true_fn")
        false_name = self._name("false_fn")
        ret = ast.Return(value=ast.Tuple(
            elts=[ast.Name(id=n, ctx=ast.Load()) for n in assigned],
            ctx=ast.Load()))
        # the assigned names are branch-fn PARAMETERS (reads see the outer
        # value, writes stay branch-local) — the reference's true_fn/false_fn
        # argument threading, ifelse_transformer.py
        params = ast.arguments(
            posonlyargs=[],
            args=[ast.arg(arg=n) for n in assigned],
            kwonlyargs=[], kw_defaults=[], defaults=[])

        def mk_fn(name, body):
            body = list(body) if body else [ast.Pass()]
            return ast.FunctionDef(name=name, args=params,
                                   body=body + [ret], decorator_list=[])

        call = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Store()) for n in assigned],
                ctx=ast.Store())],
            value=ast.Call(
                func=ast.Attribute(value=ast.Name(id=_HELPER, ctx=ast.Load()),
                                   attr="convert_ifelse", ctx=ast.Load()),
                args=[node.test,
                      ast.Name(id=true_name, ctx=ast.Load()),
                      ast.Name(id=false_name, ctx=ast.Load()),
                      ast.Tuple(elts=[ast.Name(id=n, ctx=ast.Load())
                                      for n in assigned], ctx=ast.Load())],
                keywords=[]))
        out = _undef_guards(assigned) + [
            mk_fn(true_name, node.body), mk_fn(false_name, node.orelse), call]
        for n in out:
            ast.copy_location(n, node)
            ast.fix_missing_locations(n)
        return out

    # -- while --------------------------------------------------------------
    def visit_While(self, node):
        self.generic_visit(node)
        if _has_escape(node.body) or node.orelse or _has_scope_decl(node.body):
            return node
        assigned = _assigned_names(node.body)
        loop_vars = [n for n in assigned] + [
            n for n in sorted(_read_value_names(node.test))
            if n not in assigned and n != _HELPER
        ]
        if not loop_vars:
            return node
        return self._lower_loop(node, loop_vars, node.test, node.body)


def convert_to_static(fn: Callable) -> Callable:
    """Rewrite ``fn``'s data-dependent control flow; returns the converted
    function (or ``fn`` unchanged when its source is unavailable — builtins,
    C extensions, REPL lambdas)."""
    if getattr(fn, "_not_to_static", False):
        return fn
    try:
        src = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError):
        return fn
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return fn
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn
    fdef.decorator_list = []  # strip @to_static etc. — we call the raw result
    tree = _EscapeRewriter().visit(tree)
    ast.fix_missing_locations(tree)
    new_tree = _Dy2StaticTransformer().visit(tree)
    ast.fix_missing_locations(new_tree)
    try:
        code = compile(new_tree, filename=f"<dy2static {fn.__qualname__}>",
                       mode="exec")
    except SyntaxError:
        return fn  # converted form invalid for this function: keep python
    from . import dy2static as _self

    import types

    glob = dict(fn.__globals__)
    glob[_HELPER] = _self
    if fn.__closure__:
        # Rebuild inside a wrapper that redeclares the free variables, then
        # swap in the ORIGINAL cells so later nonlocal mutation stays visible
        # (copying cell contents would freeze them at conversion time).
        freevars = fn.__code__.co_freevars
        wrapper_src = "def __outer__({}):\n".format(", ".join(freevars))
        wrapper_src += textwrap.indent(ast.unparse(new_tree.body[0]), "    ")
        wrapper_src += f"\n    return {fdef.name}"
        wglob = dict(glob)
        try:
            exec(compile(wrapper_src, f"<dy2static {fn.__qualname__}>",
                         "exec"), wglob)
        except SyntaxError:
            return fn
        snapshot = wglob["__outer__"](
            *[c.cell_contents for c in fn.__closure__])
        cellmap = dict(zip(freevars, fn.__closure__))
        try:
            live_cells = tuple(cellmap[n]
                               for n in snapshot.__code__.co_freevars)
            converted = types.FunctionType(
                snapshot.__code__, glob, fn.__name__, fn.__defaults__,
                live_cells)
        except KeyError:
            converted = snapshot  # new freevar we can't map: snapshot mode
    else:
        exec(code, glob)
        converted = glob[fdef.name]
    converted = functools.wraps(fn)(converted)
    converted._dy2static_converted = True
    return converted
