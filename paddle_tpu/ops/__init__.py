"""paddle_tpu.ops — TPU kernels (Pallas + lax): the counterpart of the
reference's operators/fused/ tier, built for the MXU instead of CUDA."""
from .attention import (  # noqa: F401
    blockwise_attention,
    dot_product_attention,
    flash_attention,
    ring_attention,
    set_attention_impl,
    set_ring_context,
    xla_attention,
)
from .fused import fused_adam_step, fused_layer_norm, fused_softmax_bias  # noqa: F401
from . import remat_policy, tier_policy  # noqa: F401
