"""Attention kernels — the TPU-native replacement for the reference's fused
attention CUDA kernels (operators/fused/multihead_matmul_op.cu,
fused_attention) plus net-new long-context support (ring/context parallelism,
absent in the reference — SURVEY.md §5 'Long-context: Absent').

Three tiers, one API:
- ``blockwise_attention``: online-softmax scan over K blocks (FlashAttention
  recurrence in pure lax) — O(seq) memory, differentiable, runs anywhere.
- ``flash_attention``: Pallas TPU kernel for the forward (MXU-tiled, VMEM
  blocked), custom_vjp whose backward recomputes via the blockwise path.
- ``ring_attention``: sequence-parallel attention inside shard_map — K/V
  shards rotate around the 'sp' mesh axis via ppermute (ICI neighbor
  transfers) while each device keeps running softmax stats for its Q shard.
"""
from __future__ import annotations

import functools
import logging
import math
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

logger = logging.getLogger("paddle_tpu.ops")

__all__ = [
    "blockwise_attention", "flash_attention", "ring_attention",
    "xla_attention", "dot_product_attention", "set_attention_impl",
    "set_ring_context", "paged_attention",
]

# Attention implementation selector. 'auto' (default) picks per context:
# ring for sp-sharded, the materialized XLA path on TPU up to a
# per-context length threshold — measured fastest end-to-end on v5e for
# GPT-2 345M (L=1024, d=64: the big batched einsums tile onto the MXU
# better than per-head Pallas kernel ops) AND, q-chunked, for causal
# unbiased sequences up to L=8192 (46.5k vs 27.5k tok/s on the longctx
# bench, r5) — then the repo's flash_tpu Mosaic kernel for longer causal
# sequences (the materialized scores exhaust HBM and blockwise is 8-10x
# slower). 'pallas' (the jax-shipped kernel) and 'flash_tpu' can
# also be forced explicitly. Rigs whose Mosaic compile service fails —
# plain XLA needs no such service — would die at jit-compile time on
# auto's long-sequence route: set PADDLE_TPU_ATTN_NO_MOSAIC=1 to keep
# auto on the streaming blockwise path instead.
_IMPL = os.environ.get("PADDLE_TPU_ATTENTION", "auto")
_NO_MOSAIC = os.environ.get("PADDLE_TPU_ATTN_NO_MOSAIC", "") == "1"
# beyond these lengths the materialized scores dominate HBM; stream
# instead. Two thresholds (r5): CAUSAL unbiased attention runs q-chunked
# (_causal_chunked_fwd_impl — fully-masked blocks never computed, ~0.53·L²
# footprint) and measured 46.5k tok/s at GPT-small L=8192 b=1 vs 27.5k on
# flash_tpu + recompute, so its auto threshold is 8192; everything else
# materializes the full [b,h,L,L] scores and keeps the stricter 4096.
_XLA_MAX_SEQ = int(os.environ.get("PADDLE_TPU_ATTENTION_MAX_SEQ", "4096"))
_XLA_MAX_SEQ_CAUSAL = int(os.environ.get(
    "PADDLE_TPU_ATTENTION_MAX_SEQ_CAUSAL", "8192"))


def set_attention_impl(impl: str):
    """impl ∈ {'auto', 'pallas', 'flash_tpu', 'xla', 'blockwise'}.

    'pallas' selects the jax-shipped Mosaic flash kernel; 'flash_tpu' the
    repo's layout-native Pallas kernel (ops/flash_tpu.py). The selector is
    read at TRACE time: functions already jitted keep the implementation
    they compiled with (jit cache). Call before building the train/eval
    step, or clear caches, for the change to take effect.
    """
    global _IMPL
    if impl not in ("auto", "pallas", "flash_tpu", "xla", "blockwise"):
        raise ValueError(f"unknown attention impl {impl!r}")
    _IMPL = impl

_NEG_INF = -1e30


# ---------------------------------------------------------------------------
# Blockwise online-softmax attention (lax-level flash recurrence)
# ---------------------------------------------------------------------------
def _block_scan_attention(q, k, v, causal, q_offset, kv_offset, block_k, bias=None):
    """q: [Lq, d]; k/v: [Lk, d]. Online softmax over k blocks.

    ``q_offset``/``kv_offset`` are global position offsets (for ring /
    sharded causal masking)."""
    Lq, d = q.shape
    Lk = k.shape[0]
    scale = 1.0 / math.sqrt(d)
    nblocks = max((Lk + block_k - 1) // block_k, 1)
    pad = nblocks * block_k - Lk
    if pad:
        k = jnp.pad(k, ((0, pad), (0, 0)))
        v = jnp.pad(v, ((0, pad), (0, 0)))
        if bias is not None:
            bias = jnp.pad(bias, ((0, 0), (0, pad)), constant_values=_NEG_INF)
    kb = k.reshape(nblocks, block_k, d)
    vb = v.reshape(nblocks, block_k, d)
    bb = bias.reshape(Lq, nblocks, block_k).swapaxes(0, 1) if bias is not None else None

    q_pos = q_offset + jnp.arange(Lq)

    def body(carry, blk):
        acc, m, l = carry
        if bb is not None:
            kblk, vblk, bblk, bi = blk
        else:
            kblk, vblk, bi = blk
            bblk = None
        s = (q.astype(jnp.float32) @ kblk.astype(jnp.float32).T) * scale  # [Lq, bk]
        k_pos = kv_offset + bi * block_k + jnp.arange(block_k)
        valid = k_pos < (kv_offset + Lk)
        mask = jnp.broadcast_to(valid[None, :], s.shape)
        if causal:
            mask = mask & (k_pos[None, :] <= q_pos[:, None])
        if bblk is not None:
            s = s + bblk
        s = jnp.where(mask, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, None])
        correction = jnp.exp(m - m_new)
        l_new = l * correction + p.sum(axis=-1)
        acc_new = acc * correction[:, None] + p @ vblk.astype(jnp.float32)
        return (acc_new, m_new, l_new), None

    acc0 = jnp.zeros((Lq, d), jnp.float32)
    m0 = jnp.full((Lq,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((Lq,), jnp.float32)
    idx = jnp.arange(nblocks)
    xs = (kb, vb, bb, idx) if bb is not None else (kb, vb, idx)
    (acc, m, l), _ = jax.lax.scan(body, (acc0, m0, l0), xs)
    out = acc / jnp.maximum(l, 1e-30)[:, None]
    return out.astype(q.dtype), m + jnp.log(jnp.maximum(l, 1e-30))


def blockwise_attention(q, k, v, causal=False, block_k=512, bias=None,
                        q_offset=0, kv_offset=0):
    """q,k,v: [batch, heads, len, dim]. Returns [batch, heads, len, dim]."""

    def per_head(qh, kh, vh, bh):
        out, _ = _block_scan_attention(qh, kh, vh, causal, q_offset, kv_offset,
                                       block_k, bh)
        return out

    if bias is not None:
        # bias broadcastable to [b, h, lq, lk]
        b_full = jnp.broadcast_to(bias, q.shape[:2] + (q.shape[2], k.shape[2]))
        fn = jax.vmap(jax.vmap(per_head))
        return fn(q, k, v, b_full)
    fn = jax.vmap(jax.vmap(lambda a, b, c: per_head(a, b, c, None)))
    return fn(q, k, v)


# ---------------------------------------------------------------------------
# Pallas TPU flash-attention forward
# ---------------------------------------------------------------------------
def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k, causal, sm_scale,
                      seq_len):
    from jax.experimental import pallas as pl

    # NOTE: all index math is pinned to int32 — with jax_enable_x64 on,
    # python-int promotion would inject int64 converts, which the Mosaic
    # lowering cannot handle (infinite recursion in convert_element_type).
    i32 = jnp.int32
    q = q_ref[0].astype(jnp.float32)  # [block_q, d]
    block_q, d = q.shape
    qi = pl.program_id(1).astype(i32)
    q_pos = qi * i32(block_q) + jax.lax.broadcasted_iota(
        i32, (block_q, block_k), 0)

    nk = seq_len // block_k

    def body(i, carry):
        acc, m, l = carry
        i = i.astype(i32)
        k = k_ref[0, pl.dslice(i * i32(block_k), block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.dslice(i * i32(block_k), block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            k_pos = i * i32(block_k) + jax.lax.broadcasted_iota(
                i32, (block_q, block_k), 1
            )
            s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1)
        acc_new = acc * corr[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return acc_new, m_new, l_new

    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    if causal:
        # only scan k blocks up to (and including) this q block's diagonal
        upper = jnp.minimum((qi + i32(1)) * i32(block_q) // i32(block_k)
                            + i32(1), i32(nk))
    else:
        upper = i32(nk)
    acc, m, l = jax.lax.fori_loop(i32(0), upper, body, (acc0, m0, l0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


def _flash_fwd_pallas(q, k, v, causal, block_q, block_k):
    from jax.experimental import pallas as pl

    b, h, L, d = q.shape
    sm_scale = 1.0 / math.sqrt(d)
    bh = b * h
    q3 = q.reshape(bh, L, d)
    k3 = k.reshape(bh, L, d)
    v3 = v.reshape(bh, L, d)
    grid = (bh, L // block_q)
    out = pl.pallas_call(
        functools.partial(_flash_fwd_kernel, block_k=block_k, causal=causal,
                          sm_scale=sm_scale, seq_len=L),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, L, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, L, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, L, d), q.dtype),
    )(q3, k3, v3)
    return out.reshape(b, h, L, d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q, k, v, causal=False, block_q=256, block_k=256):
    """Pallas-accelerated attention; falls back to blockwise when shapes or
    platform don't fit the kernel. [b, h, l, d] layout."""
    return _flash_attention_impl(q, k, v, causal, block_q, block_k)


def _flash_attention_impl(q, k, v, causal, block_q, block_k):
    L = q.shape[2]
    d = q.shape[3]
    on_tpu = jax.default_backend() == "tpu"
    fits = (L % block_q == 0 and L % block_k == 0 and d % 128 == 0
            and k.shape[2] == L)
    if on_tpu and fits:
        return _flash_fwd_pallas(q, k, v, causal, block_q, block_k)
    if on_tpu:
        # the kernel was on the table (TPU) and the SHAPE knocked it off:
        # that silent 8-10x drop must be counted and named (off-TPU the
        # blockwise path is the documented behavior, not a fallback)
        _count_fallback(
            "flash", q.shape,
            f"shape does not tile the Pallas forward (needs L % "
            f"{block_q}/{block_k} == 0, d % 128 == 0, Lq == Lk)")
    return blockwise_attention(q, k, v, causal=causal, block_k=block_k)


def jax_flash_attention(q, k, v, causal=False, block_q=None, block_k=None):
    """The jax-shipped Mosaic flash-attention kernel (fwd AND bwd kernels,
    [b, h, l, d]), with block sizes clamped to the shape. Falls back to the
    local ``flash_attention`` tier (→ blockwise) when the shape doesn't
    tile, or when TRACING fails (eager x64 issues etc.) — a Mosaic compile
    SERVICE failure under jit surfaces at jit-compile time instead; use the
    'auto'/'xla' impl on such rigs."""
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes, flash_attention as _fa)

    L, d = q.shape[2], q.shape[3]
    bq = min(block_q or 512, L)
    bk = min(block_k or 512, L)
    if L % bq != 0 or L % bk != 0 or k.shape[2] != L:
        if jax.default_backend() == "tpu":
            _count_fallback(
                "pallas", q.shape,
                f"shape does not tile the jax flash kernel "
                f"(L % {bq}/{bk} != 0 or Lq != Lk)")
        return flash_attention(q, k, v, causal)
    bs = BlockSizes(
        block_q=bq, block_k_major=bk, block_k=bk, block_b=1,
        block_q_major_dkv=bq, block_k_major_dkv=bk, block_k_dkv=bk,
        block_q_dkv=bq, block_k_major_dq=bk, block_k_dq=bk, block_q_dq=bq,
    )
    # the kernel's index math assumes 32-bit python-int promotion; this repo
    # enables x64 globally, so scope it off around the trace
    try:
        with jax.enable_x64(False):
            return _fa(q, k, v, causal=causal, block_sizes=bs,
                       sm_scale=1.0 / math.sqrt(d))
    except Exception:
        return flash_attention(q, k, v, causal)


def _flash_fwd_rule(q, k, v, causal, block_q, block_k):
    out = _flash_attention_impl(q, k, v, causal, block_q, block_k)
    return out, (q, k, v)


def _flash_bwd_rule(causal, block_q, block_k, res, g):
    q, k, v = res
    # recompute-based backward through the blockwise recurrence
    _, vjp = jax.vjp(
        lambda q_, k_, v_: blockwise_attention(q_, k_, v_, causal=causal,
                                               block_k=block_k),
        q, k, v,
    )
    return vjp(g)


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


# ---------------------------------------------------------------------------
# Ring attention (sequence/context parallelism over a mesh axis)
# ---------------------------------------------------------------------------
def _shard_map_fn():
    """shard_map across jax versions: ``jax.shard_map`` (new API,
    replication checking keyword ``check_vma``) or
    ``jax.experimental.shard_map.shard_map`` (0.4.x, ``check_rep``).
    Returns a ``fn(f, mesh, in_specs, out_specs)`` wrapper with
    replication checking disabled (ring's psums confuse the checker), or
    None when neither API exists (callers keep their single-device
    path)."""
    sm = getattr(jax, "shard_map", None)
    kw = "check_vma"
    if sm is None:
        try:
            from jax.experimental.shard_map import shard_map as sm
            kw = "check_rep"
        except Exception:
            return None

    def wrap(f, mesh, in_specs, out_specs):
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  **{kw: False})

    return wrap


def _ring_pass(q, k, v, axis_name, causal, fn, init):
    """One full rotation of K/V around ``axis_name``: ``fn(carry, kc, vc,
    q_off, kv_off)`` folds the resident shard into the carry, then K/V
    (plus any extra carried-with-K/V leaves ``fn`` returns) hop one
    neighbor (lax.ppermute → ICI point-to-point, overlapping the next
    step's compute). Shared by the forward and the recompute backward."""
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    L_local = q.shape[2]
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def step(carry, i):
        state, kc, vc, rotating = carry
        src_idx = (my_idx - i) % axis_size  # whose shard we currently hold
        state, rotating = fn(state, kc, vc, my_idx * L_local,
                             src_idx * L_local, rotating)
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        rotating = jax.tree_util.tree_map(
            lambda t: jax.lax.ppermute(t, axis_name, perm), rotating)
        return (state, kc, vc, rotating), None

    (state, _, _, rotating), _ = jax.lax.scan(
        step, (init[0], k, v, init[1]), jnp.arange(axis_size))
    return state, rotating


def _ring_fwd_impl(q, k, v, axis_name, causal):
    """Forward ring pass; returns (out, lse) with lse = m + log l per row
    ([b, h, L_local]) — the flash-style statistic the recompute backward
    normalizes against."""
    b, h, L_local, d = q.shape
    scale = 1.0 / math.sqrt(d)
    qf = q.astype(jnp.float32) * scale

    def fold(state, kc, vc, q_off, kv_off, _):
        acc, m, l = state
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kc.astype(jnp.float32))
        if causal:
            q_pos = q_off + jnp.arange(L_local)
            k_pos = kv_off + jnp.arange(kc.shape[2])
            s = jnp.where(k_pos[None, None, None, :]
                          <= q_pos[None, None, :, None], s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        acc = acc * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vc.astype(jnp.float32))
        l = l * corr + p.sum(axis=-1)
        return (acc, m_new, l), _

    acc0 = jnp.zeros((b, h, L_local, d), jnp.float32)
    m0 = jnp.full((b, h, L_local), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, L_local), jnp.float32)
    (acc, m, l), _ = _ring_pass(q, k, v, axis_name, causal, fold,
                                ((acc0, m0, l0), ()))
    l = jnp.maximum(l, 1e-30)
    out = (acc / l[..., None]).astype(q.dtype)
    return out, m + jnp.log(l)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def ring_attention(q, k, v, axis_name, causal=False, block_k=512):
    """Attention where q/k/v ([b, h, L_local, d]) are sequence-sharded
    over ``axis_name``.

    Must be called inside shard_map/pjit with ``axis_name`` in scope. Each
    step every device computes attention between its local Q shard and the
    K/V shard currently resident, folds the result into running
    online-softmax statistics, then rotates K/V one hop around the ring
    (lax.ppermute → ICI neighbor copy, overlapping with the next compute).

    The backward is a hand-written recompute pass (custom_vjp, like the
    flash/chunked tiers): the forward saves only the [b, h, L_local]
    logsumexp — never the O(L·L/ring) probability blocks autodiff-through-
    scan would stack per rotation — and the backward re-runs the ring,
    recomputing each block's probabilities from the saved statistic while
    dK/dV partial sums travel around the ring WITH the K/V shards they
    belong to (after the full rotation they land back home).
    ``block_k`` is accepted for tier-API compatibility; the local shard is
    one block."""
    out, _ = _ring_fwd_impl(q, k, v, axis_name, causal)
    return out


def _ring_fwd_rule(q, k, v, axis_name, causal, block_k):
    out, lse = _ring_fwd_impl(q, k, v, axis_name, causal)
    return out, (q, k, v, out, lse)


def _ring_bwd_rule(axis_name, causal, block_k, res, g):
    q, k, v, out, lse = res
    b, h, L_local, d = q.shape
    scale = 1.0 / math.sqrt(d)
    qf = q.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    # delta = rowsum(dO ⊙ O) — the softmax-backward row statistic,
    # computed once on the [.., d] output instead of any [.., L] block
    delta = jnp.einsum("bhqd,bhqd->bhq", gf, out.astype(jnp.float32))

    def fold(dq, kc, vc, q_off, kv_off, rotating):
        dk, dv = rotating
        s = jnp.einsum("bhqd,bhkd->bhqk", qf,
                       kc.astype(jnp.float32)) * scale
        if causal:
            q_pos = q_off + jnp.arange(L_local)
            k_pos = kv_off + jnp.arange(kc.shape[2])
            s = jnp.where(k_pos[None, None, None, :]
                          <= q_pos[None, None, :, None], s, _NEG_INF)
        p = jnp.exp(s - lse[..., None])  # normalized probs, recomputed
        dp = jnp.einsum("bhqd,bhkd->bhqk", gf, vc.astype(jnp.float32))
        ds = p * (dp - delta[..., None])
        dq = dq + jnp.einsum("bhqk,bhkd->bhqd", ds,
                             kc.astype(jnp.float32)) * scale
        # dK/dV partials for the shard CURRENTLY resident: they rotate
        # onward with it and are complete once it returns home
        dk = dk + jnp.einsum("bhqk,bhqd->bhkd", ds, qf) * scale
        dv = dv + jnp.einsum("bhqk,bhqd->bhkd", p, gf)
        return dq, (dk, dv)

    z = jnp.zeros((b, h, L_local, d), jnp.float32)
    dq, (dk, dv) = _ring_pass(q, k, v, axis_name, causal, fold,
                              (z, (z, z)))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


ring_attention.defvjp(_ring_fwd_rule, _ring_bwd_rule)


# -- ring auto-promotion (engine-provided mesh context) ---------------------
def _ring_min_seq() -> int:
    """Minimum GLOBAL sequence length for 'auto' to route through the ring
    (below it the per-hop latency beats the sharded-compute win). Read per
    dispatch — trace-time only, so tests and bench configs can flip it."""
    try:
        return int(os.environ.get("PADDLE_TPU_ATTN_RING_MIN_SEQ", "8192"))
    except ValueError:
        return 8192


_ring_ctx = {"mesh": None, "axis": None, "batch": None}


def set_ring_context(mesh, axis: Optional[str], batch_axis=None) -> None:
    """Engine hook (``fleet.ParallelTrainStep(sp_axis=...)``): register a
    mesh axis carrying sequence shards so 'auto' can promote long-context
    causal attention onto the ring. ``batch_axis`` names the mesh axis (or
    axis tuple) the BATCH dim is sharded over, so the ring's shard_map
    region keeps the engine's data parallelism instead of gathering the
    batch. Read at TRACE time, like ``set_attention_impl`` — call before
    building the step. ``axis=None`` clears."""
    _ring_ctx["mesh"] = mesh if axis else None
    _ring_ctx["axis"] = axis
    _ring_ctx["batch"] = batch_axis if axis else None


def _ring_auto_ok(L: int, causal: bool, bias) -> bool:
    from . import tier_policy

    mesh, axis = _ring_ctx["mesh"], _ring_ctx["axis"]
    if mesh is None or axis is None or not causal or bias is not None:
        return False
    if axis not in mesh.axis_names or mesh.shape[axis] <= 1:
        return False
    # an EXPLICIT policy override outranks promotion: a forced tier or a
    # pinned heuristic must measure exactly what it names (the bench
    # ablation legs depend on this); the unset default and 'bench' leave
    # the engine's sp_axis request in force
    forced = tier_policy.forced_mode()
    if forced in ("xla", "blockwise", "flash_tpu", "pallas", "heuristic"):
        return False
    size = mesh.shape[axis]
    if L % size != 0 or (L < _ring_min_seq() and forced != "ring"):
        return False
    return _shard_map_fn() is not None


def _ring_unavailable_reason(L: int, causal: bool, bias) -> str:
    """Why ``_ring_auto_ok`` said no, for the forced-ring fallback
    warning — the operator gets the ACTUAL blocker, not a generic hint
    (the usual failure is not a missing context at all)."""
    mesh, axis = _ring_ctx["mesh"], _ring_ctx["axis"]
    if mesh is None or axis is None:
        return ("no ring mesh context is registered "
                "(fleet.ParallelTrainStep(sp_axis=) / "
                "ops.attention.set_ring_context)")
    if not causal:
        return "the ring path only supports causal attention"
    if bias is not None:
        return "the ring path does not support an attention bias"
    if axis not in mesh.axis_names or mesh.shape[axis] <= 1:
        return (f"registered axis {axis!r} is not a multi-device axis of "
                f"the mesh {dict(mesh.shape)}")
    if L % mesh.shape[axis] != 0:
        return (f"sequence length {L} does not divide the ring size "
                f"{mesh.shape[axis]}")
    if _shard_map_fn() is None:
        return "this jax has no shard_map API"
    return "the ring context was cleared by a later engine"


def _ring_sharded(q, k, v, causal, blhd):
    """Manually-partitioned ring region nested inside the engine's jitted
    GSPMD program: shard_map over the registered mesh with the sequence
    dim sharded on the ring axis — Q/K/V enter pre-rotated (the engine's
    batch sharding already lands them sequence-sharded, so no resharding
    happens at this boundary)."""
    from jax.sharding import PartitionSpec as P

    mesh, axis = _ring_ctx["mesh"], _ring_ctx["axis"]
    ba = _ring_ctx["batch"]  # keep the engine's dp sharding on the batch dim
    sm = _shard_map_fn()
    spec = P(ba, axis, None, None) if blhd else P(ba, None, axis, None)

    def local(q_, k_, v_):
        if blhd:  # local transpose to the ring's [b, h, l, d] layout
            tr = lambda t: t.transpose(0, 2, 1, 3)
            return tr(ring_attention(tr(q_), tr(k_), tr(v_), axis,
                                     causal, 512))
        return ring_attention(q_, k_, v_, axis, causal, 512)

    return sm(local, mesh, (spec, spec, spec), spec)(q, k, v)


# ---------------------------------------------------------------------------
# Paged attention (decode over the serving KV-cache pool)
# ---------------------------------------------------------------------------
# The token-level serving runtime (inference.serving.decode) keeps K/V in
# a blocked pool: pages [N, block_size, H, D] plus per-sequence block
# tables. Decode-time attention gathers a sequence's pages by table and
# attends the query chunk (T=1 for plain decode, T=k+1 for speculative
# verify, T=chunk for prefill) against them. Two XLA-level tiers with
# genuinely different memory/compute profiles, selected by
# tier_policy.select_paged (micro-benched + verdict-cached like every
# training tier):
# - 'paged_gather': one gather of the whole context then one fused
#   masked softmax — fastest while the context is score-tensor-small;
# - 'paged_scan': lax.scan over pages with online softmax — O(block)
#   live memory, int8 pages dequantize one page at a time (the actual
#   HBM win of int8 storage).
# Positions are logical: token p of a sequence lives in table slot
# p // block_size at offset p % block_size, so slot index IS position.


def _paged_widen(x, scale, compute_dtype):
    """Pages (possibly int8 + scales) -> compute dtype."""
    if scale is None:
        return x.astype(compute_dtype)
    from ..quant import dequantize_kv

    return dequantize_kv(x, scale, compute_dtype)


def _paged_mask(k_pos, q_positions, kv_lens):
    """[B, T, K] bool: causal (k_pos <= q_pos) AND within the written
    prefix (k_pos < kv_len) — the second clause keeps padded table slots
    and stale post-eviction entries unreadable."""
    return ((k_pos[None, None, :] <= q_positions[:, :, None])
            & (k_pos[None, None, :] < kv_lens[:, None, None]))


def _paged_gather_impl(q, k_pages, v_pages, block_tables, q_positions,
                       kv_lens, k_scale=None, v_scale=None):
    """q: [B, T, H, D]; k_pages/v_pages: [N, bs, H, D] (+ [N, bs, H]
    scales for int8 pools); block_tables: [B, M] int32; q_positions:
    [B, T] int32 global positions; kv_lens: [B] int32 valid prefix."""
    B, T, H, D = q.shape
    bs = k_pages.shape[1]
    M = block_tables.shape[1]
    scale = 1.0 / math.sqrt(D)
    k = _paged_widen(k_pages[block_tables],
                     None if k_scale is None else k_scale[block_tables],
                     jnp.float32).reshape(B, M * bs, H, D)
    v = _paged_widen(v_pages[block_tables],
                     None if v_scale is None else v_scale[block_tables],
                     jnp.float32).reshape(B, M * bs, H, D)
    s = jnp.einsum("bthd,bkhd->bhtk", q.astype(jnp.float32) * scale, k)
    k_pos = jnp.arange(M * bs, dtype=jnp.int32)
    mask = _paged_mask(k_pos, q_positions, kv_lens)
    s = jnp.where(mask[:, None], s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    e = jnp.exp(s - m)
    p = e / jnp.maximum(e.sum(axis=-1, keepdims=True), 1e-30)
    out = jnp.einsum("bhtk,bkhd->bthd", p, v)
    return out.astype(q.dtype)


def _paged_scan_impl(q, k_pages, v_pages, block_tables, q_positions,
                     kv_lens, k_scale=None, v_scale=None):
    """Online-softmax scan over table slots — the flash recurrence over
    pages. Only one [B, bs, H, D] page pair is live (and, for int8
    pools, dequantized) per step."""
    B, T, H, D = q.shape
    bs = k_pages.shape[1]
    M = block_tables.shape[1]
    scale = 1.0 / math.sqrt(D)
    qf = q.astype(jnp.float32) * scale

    def body(carry, i):
        acc, m, l = carry
        pids = block_tables[:, i]  # [B]
        kc = _paged_widen(k_pages[pids],
                          None if k_scale is None else k_scale[pids],
                          jnp.float32)  # [B, bs, H, D]
        vc = _paged_widen(v_pages[pids],
                          None if v_scale is None else v_scale[pids],
                          jnp.float32)
        s = jnp.einsum("bthd,bshd->bhts", qf, kc)
        k_pos = i * bs + jnp.arange(bs, dtype=jnp.int32)
        mask = _paged_mask(k_pos, q_positions, kv_lens)
        s = jnp.where(mask[:, None], s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        acc = acc * corr[..., None] + jnp.einsum("bhts,bshd->bhtd", p, vc)
        l = l * corr + p.sum(axis=-1)
        return (acc, m_new, l), None

    acc0 = jnp.zeros((B, H, T, D), jnp.float32)
    m0 = jnp.full((B, H, T), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, T), jnp.float32)
    (acc, m, l), _ = jax.lax.scan(
        body, (acc0, m0, l0), jnp.arange(M, dtype=jnp.int32))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def paged_attention(q, k_pages, v_pages, block_tables, q_positions,
                    kv_lens, k_scale=None, v_scale=None):
    """Attention of a query chunk against a paged KV cache.

    Args:
        q: [B, T, H, D] query chunk (T=1 plain decode; T=k+1 speculative
            verify; T=chunk_size chunked prefill).
        k_pages/v_pages: one layer's pool pages [N, bs, H, D] (int8 or
            float storage).
        block_tables: [B, M] int32 page ids (scratch-padded).
        q_positions: [B, T] int32 global position of each query token.
        kv_lens: [B] int32 — number of valid cache positions (tokens of
            the sequence INCLUDING this chunk's writes).
        k_scale/v_scale: [N, bs, H] float32 per-token-head scales when
            the pool stores int8 (``quant.quantize_kv``), else None.

    Tier selection happens at TRACE time via
    ``tier_policy.select_paged`` — micro-benched on TPU, verdict-cached,
    zero per-step work — and every dispatch publishes its verdict to
    ``gauge/attn/tier.paged.*`` (the attribution tier gate covers decode
    records like every other attention-bearing record)."""
    from ..profiler.telemetry import get_telemetry
    from . import tier_policy

    get_telemetry().counter("attn/calls")
    B, T, H, D = q.shape
    bs = k_pages.shape[1]
    M = block_tables.shape[1]
    tier = tier_policy.select_paged(T, H, D, M, bs, q.dtype,
                                    k_scale is not None)
    get_telemetry().gauge(f"attn/tier.paged.t{T}.d{D}",
                          tier_policy.TIER_IDS.get(tier, -1))
    impl = (_paged_gather_impl if tier == "paged_gather"
            else _paged_scan_impl)
    return impl(q, k_pages, v_pages, block_tables, q_positions, kv_lens,
                k_scale, v_scale)


# ---------------------------------------------------------------------------
# Materialized XLA attention (TPU fast path for moderate sequence lengths)
# ---------------------------------------------------------------------------
# minimum causal q-chunk rows (sweepable; 128 measured optimum on v5e)
_CAUSAL_CHUNK = int(os.environ.get("PADDLE_TPU_ATTN_MIN_CHUNK", "128"))
# max causal q-chunks (sweepable: more chunks skip more upper-triangle work
# but emit more ops). Together with the 128-row minimum the default of 32
# gives the measured v5e optima at both ends: L=1024 -> c=128 (8 chunks;
# c=256 measured -6%) and L=8192 -> c=256 (32 chunks; +27% over the old
# 16-chunk default — 47.0k -> 60.0k tok/s on the longctx config; c=128
# and c=64 both measured worse there)
_CAUSAL_MAX_CHUNKS = int(os.environ.get("PADDLE_TPU_ATTN_CHUNKS", "32"))
# sweep knob (bench tuning): force the [b,h,l,d] layout path
_FORCE_BHLD = os.environ.get("PADDLE_TPU_ATTN_LAYOUT", "") == "bhld"
# bf16 score STORAGE, default ON for bf16/f16 inputs: the centered logits
# already round-trip through bf16 before exp, and softmax cancels the max
# shift exactly (m only guards overflow), so bf16-stored scores are
# numerically ~equivalent (~1 ulp of bf16 either way) while halving the
# O(L²) tensor's bytes. Set =0 for f32 score storage.
_SCORE_BF16 = os.environ.get("PADDLE_TPU_ATTN_SCORE_BF16", "1") == "1"
# sweep knob: hand-written chunked-attention backward (custom_vjp) vs
# autodiff of the same forward. Default OFF — measured end-to-end on v5e
# GPT-2 345M the manual rule is ~3% SLOWER (52.4k vs 53.9k tok/s/chip):
# its per-chunk dk/dv pad+sum accumulation costs more than autodiff's
# cotangent accumulation saves, and the backward's contract-q dots hit the
# same ~43 TFLOP/s emitter ceiling either way (every orientation rewrite —
# 'bhdk' outputs, pre-transposed operands, optimization barriers — was
# canonicalized by XLA to the identical dot and measured identical).
# Kept as an opt-in: it halves residual memory bookkeeping for long-L
# sweeps and documents the measured negative result.
_MANUAL_ATTN_VJP = os.environ.get("PADDLE_TPU_ATTN_MANUAL_VJP", "0") == "1"


def _einsum_eqs(blhd: bool):
    return (("bqhd,bkhd->bhqk", "bhqk,bkhd->bqhd") if blhd
            else ("bhqd,bhkd->bhqk", "bhqk,bhkd->bhqd"))


def _attention_core(q, k, v, mask, bias=None, blhd=False):
    """One materialized softmax(QKᵀ)V block.

    ``blhd``: q/k/v are [b, l, h, d] (einsum contracts without pre-transposed
    operands — the [b,h,l,d] transposes are real HBM copies the model can
    skip); otherwise [b, h, l, d]. ``mask`` is [Lq, Lk] bool or None. For
    bf16/f16 inputs the centered logits and probabilities round-trip through
    the input dtype — the exp input IS materialized, and halving that O(L²)
    tensor's bytes is a real HBM saving (see xla_attention docstring)."""
    d = q.shape[-1]
    eq = _einsum_eqs(blhd)
    s = jnp.einsum(eq[0], q, k,
                   preferred_element_type=jnp.float32) * (1.0 / math.sqrt(d))
    if bias is not None:
        s = s + bias
    if mask is not None:
        s = jnp.where(mask, s, _NEG_INF)
    m = jax.lax.stop_gradient(jnp.max(s, axis=-1, keepdims=True))
    if jnp.issubdtype(q.dtype, jnp.floating) and q.dtype != jnp.float32:
        e = jnp.exp((s - m).astype(q.dtype).astype(jnp.float32))
    else:
        e = jnp.exp(s - m)
    p = (e / jnp.maximum(e.sum(axis=-1, keepdims=True), 1e-30)).astype(q.dtype)
    return jnp.einsum(eq[1], p, v)


def _causal_chunk_size(Lq: int):
    """Chunk size for causal q-chunking, or None when no exact chunking
    exists (c must divide Lq — a truncated concat would silently drop query
    rows)."""
    c = max(_CAUSAL_CHUNK, Lq // max(_CAUSAL_MAX_CHUNKS, 1))
    if Lq % c != 0 or Lq // c < 2:
        return None
    return c


# backward einsum equations per layout: dP ('dO,V->P-shape'), dq
# ('dS,K->q-shape'), dk ('dS,Q->k-shape'), dv ('E,dO->v-shape'), delta
# ('dO,O->rows')
_BWD_EQS = {
    True: ("bqhd,bkhd->bhqk", "bhqk,bkhd->bqhd", "bhqk,bqhd->bkhd",
           "bhqk,bqhd->bkhd", "bqhd,bqhd->bhq"),
    False: ("bhqd,bhkd->bhqk", "bhqk,bhkd->bhqd", "bhqk,bhqd->bhkd",
            "bhqk,bhqd->bhkd", "bhqd,bhqd->bhq"),
}


def _inv_rows(inv, blhd):
    """Broadcast a [b,h,q] row statistic against [.., q-axis, .., d]."""
    return inv.transpose(0, 2, 1)[..., None] if blhd else inv[..., None]


def _chunk_e(q, k, i, c, blhd, m=None):
    """exp weights of causal chunk i: e = exp(s − max(s)), s = scaled QKᵀ
    under the chunk's static tril mask. Shared by forward and (remat mode)
    backward — with the saved per-chunk max passed as ``m`` the recomputed
    values are BITWISE the forward's (same ops, same operands). Returns
    (e, m, used_sdt)."""
    axis_l = 1 if blhd else 2
    sl = functools.partial(jax.lax.slice_in_dim, axis=axis_l)
    d = q.shape[-1]
    scale = 1.0 / math.sqrt(d)
    eq = _einsum_eqs(blhd)
    bf = (jnp.issubdtype(q.dtype, jnp.floating) and q.dtype != jnp.float32)
    sdt = q.dtype if (_SCORE_BF16 and bf) else jnp.float32
    neg = jnp.asarray(_NEG_INF if sdt == jnp.float32 else -3e38, sdt)
    ub = (i + 1) * c
    qi = sl(q, i * c, ub) * jnp.asarray(scale, q.dtype)
    ki = sl(k, 0, ub)
    s = jnp.einsum(eq[0], qi, ki, preferred_element_type=sdt)
    mask = jnp.tril(jnp.ones((c, ub), bool), k=ub - c)
    s = jnp.where(mask, s, neg)
    if m is None:
        m = jax.lax.stop_gradient(jnp.max(s, axis=-1, keepdims=True))
    # the UNNORMALIZED probabilities are MATERIALIZED in the input dtype
    # (exp computed in f32 per-element, rounded on store): for bf16
    # models this halves the O(L²) exp tensor's bytes in fwd AND in the
    # saved residual the backward re-reads — values in (0, 1], safe in
    # bf16, and the f32-accumulated row sum below normalizes the same
    # bf16 weights the PV einsum consumes (profiled: the f32 exp store
    # was 25 ms/step of divide_subtract fusions)
    if sdt != jnp.float32:  # honors the PADDLE_TPU_ATTN_SCORE_BF16 opt-out
        e = jnp.exp((s - m).astype(q.dtype).astype(jnp.float32)
                    ).astype(q.dtype)
    else:
        e = jnp.exp(s - m)
    return e, m


def _remat_e() -> bool:
    """Backward recomputes the exp weights instead of saving them (default
    ON). The saved-e residuals are the single largest non-matmul cost of
    the GPT-2 345M step: ~148 MB/layer of bf16 written in fwd, re-read in
    bwd, PLUS ~5 ms/step of relayout copies XLA inserts moving them across
    the custom_vjp boundary (profiled shapes bf16[8,16,128,ub]). Recompute
    costs one extra QK einsum + exp per chunk (~0.2 ms/layer) — flash
    attention's trade, expressed at the XLA level."""
    return os.environ.get("PADDLE_TPU_ATTN_REMAT_E", "1") == "1"


def _causal_chunked_fwd_impl(q, k, v, blhd: bool):
    """Forward pass; returns (out, residuals per chunk). Residual slot 4
    holds the exp weights (save-e mode) or their per-chunk row maxima
    (remat mode, `_remat_e`)."""
    axis_l = 1 if blhd else 2
    Lq = q.shape[axis_l]
    c = _causal_chunk_size(Lq)
    n = Lq // c
    sl = functools.partial(jax.lax.slice_in_dim, axis=axis_l)
    eq = _einsum_eqs(blhd)
    remat = _remat_e()
    outs, aux, invs = [], [], []
    for i in range(n):
        e, m = _chunk_e(q, k, i, c, blhd)
        vi = sl(v, 0, (i + 1) * c)
        l_sum = jnp.maximum(e.sum(axis=-1, dtype=jnp.float32), 1e-30)
        o = jnp.einsum(eq[1], e.astype(q.dtype), vi)
        inv = (1.0 / l_sum).astype(q.dtype)
        outs.append(o * _inv_rows(inv, blhd))
        aux.append(m if remat else e)
        invs.append(inv)
    out = jnp.concatenate(outs, axis=axis_l)
    return out, (q, k, v, out, tuple(aux), tuple(invs))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _causal_chunked(q, k, v, blhd: bool):
    """Causal self-attention, q-chunked: chunk i attends to keys [0, (i+1)·c)
    under a static top-left tril mask — upper-triangle blocks are never
    computed (~45% of attention compute+bandwidth at 8 chunks).

    TPU-first structure (profile-driven, v5e):
    - the softmax NORMALIZATION is deferred until after the PV matmul: the
      unnormalized exp weights feed the MXU and the divide runs on the
      [.., c, d] output instead of the [.., c, L] score tensor — one full
      O(L²) elementwise pass (read+write) removed per chunk (flash's trick,
      expressed at the XLA level);
    - the 1/sqrt(d) scale folds into the [.., c, d] query chunk, not the
      score tensor;
    - einsums contract the native [b, l, h, d] layout directly (blhd=True):
      no [b,h,l,d] transpose copies;
    - the BACKWARD is hand-written (custom_vjp, `_causal_chunked_bwd`):
      autodiff's transposed einsums pick degenerate per-head layouts on TPU
      (profiled 18 ms/step of ~1%-MFU dots + 13 ms of relayout copies at
      GPT-2 345M). The manual rule keeps every backward contraction in the
      same layout family as the forward and folds the 1/l normalization
      into the [.., c, d] dO chunk (flash's backward trick at the XLA
      level), so no O(L²) divide pass exists in either direction.
    """
    out, _ = _causal_chunked_fwd_impl(q, k, v, blhd)
    return out


def _causal_chunked_fwd(q, k, v, blhd):
    return _causal_chunked_fwd_impl(q, k, v, blhd)


def _causal_chunked_bwd(blhd, res, g):
    q, k, v, out, aux, invs = res
    axis_l = 1 if blhd else 2
    Lq = q.shape[axis_l]
    c = _causal_chunk_size(Lq)
    n = Lq // c
    sl = functools.partial(jax.lax.slice_in_dim, axis=axis_l)
    d = q.shape[-1]
    scale = jnp.asarray(1.0 / math.sqrt(d), q.dtype)
    dP_eq, dq_eq, dk_eq, dv_eq, delta_eq = _BWD_EQS[blhd]
    remat = _remat_e()

    dqs, dks, dvs = [], [], []
    for i in range(n):
        ub = (i + 1) * c
        qi = sl(q, i * c, ub)
        ki, vi = sl(k, 0, ub), sl(v, 0, ub)
        gi = sl(g, i * c, ub)
        oi = sl(out, i * c, ub)
        if remat:  # aux holds the chunk maxima; e recomputed bitwise
            e, _ = _chunk_e(q, k, i, c, blhd, m=aux[i])
        else:
            e = aux[i]
        inv = invs[i]
        # softmax backward with the normalization folded into dO:
        #   P = e·inv;  dS = P ⊙ (dP − rowsum(dP ⊙ P))
        #             = e ⊙ (dP·inv − rowsum(dO ⊙ O)·inv)
        # rowsum(dP ⊙ P) collapses to rowsum(dO ⊙ O) — computed on the
        # [.., c, d] output, never touching the [.., c, L] score tensor
        g_inv = (gi * _inv_rows(inv, blhd)).astype(q.dtype)
        delta = jnp.einsum(delta_eq, gi, oi,
                           preferred_element_type=jnp.float32)
        dP = jnp.einsum(dP_eq, g_inv, vi, preferred_element_type=jnp.float32)
        dS = (e.astype(jnp.float32)
              * (dP - (delta * inv.astype(jnp.float32))[..., None])
              ).astype(q.dtype)
        # masked positions need no re-masking: e is exactly 0 there
        dqs.append(jnp.einsum(dq_eq, dS, ki) * scale)
        # pad-to-L and tree-sum: measured BEST of three accumulation
        # shapes for the ragged dk/dv chunk contributions on v5e (ragged
        # per-block slice+sum+concat re-lowered to 2.8× the
        # dynamic-update-slice traffic; see r5_gpt.txt)
        pad = [(0, 0)] * q.ndim
        pad[axis_l] = (0, Lq - ub)
        dks.append(jnp.pad(jnp.einsum(dk_eq, dS, qi) * scale, pad))
        dvs.append(jnp.pad(jnp.einsum(dv_eq, e.astype(q.dtype), g_inv), pad))
    dq = jnp.concatenate(dqs, axis=axis_l)
    dk = sum(dks[1:], dks[0])
    dv = sum(dvs[1:], dvs[0])
    return dq, dk, dv


_causal_chunked.defvjp(_causal_chunked_fwd, _causal_chunked_bwd)


def xla_attention(q, k, v, causal=False, bias=None, layout="bhld"):
    """softmax(QKᵀ)V with the [Lq, Lk] scores materialized (XLA-level).

    TPU-first details (profile-driven on v5e / GPT-2 345M, 12.9k→53k
    tok/s/chip end-to-end vs the scan-based blockwise path):
    - scores ACCUMULATE in f32 on the MXU regardless of storage dtype; for
      bf16/f16 inputs the stored scores, centered logits, and unnormalized
      probabilities round-trip through the input dtype by default
      (``PADDLE_TPU_ATTN_SCORE_BF16=0`` opts back into f32 storage) —
      softmax cancels the max shift exactly, so this is numerically ~1 ulp
      of bf16 either way while halving the O(L²) HBM bytes;
    - **causal** self-attention runs q-chunked (``_causal_chunked``): chunk
      i only matmuls keys ≤ its diagonal, skipping the fully-masked
      upper-triangle blocks (~45% of attention compute/bandwidth at 8
      chunks), and softmax normalization is deferred until after the PV
      matmul;
    - ``layout='blhd'`` contracts [b, l, h, d] operands directly, letting
      the model skip the four [b,h,l,d] transpose copies per layer.
    """
    blhd = layout == "blhd"
    axis_l = 1 if blhd else 2
    Lq, Lk = q.shape[axis_l], k.shape[axis_l]
    if (causal and bias is None and Lq == Lk
            and _causal_chunk_size(Lq) is not None):
        # chunk-count cap keeps the emitted program small (some TPU compile
        # services reject huge ones)
        if _MANUAL_ATTN_VJP:
            return _causal_chunked(q, k, v, blhd)
        return _causal_chunked_fwd_impl(q, k, v, blhd)[0]
    mask = jnp.tril(jnp.ones((Lq, Lk), bool)) if causal else None
    # causal mask is top-left aligned (k_pos <= q_pos), matching
    # blockwise/flash so the dispatch tiers agree for Lq != Lk
    if blhd and bias is not None:
        raise NotImplementedError("bias requires layout='bhld'")
    return _attention_core(q, k, v, mask, bias, blhd)


# ---------------------------------------------------------------------------
# Public dispatch
# ---------------------------------------------------------------------------
# one-shot fallback warnings, keyed (tier, shape, reason)
_fallback_warned: set = set()


def _count_fallback(tier: str, shape, reason: str) -> None:
    """A dispatch decision silently rerouted off a fast tier: count it
    (``counter/attn/tier_fallbacks`` — gated to ZERO over bench records
    by tools/check_attribution.py) and warn once per (tier, shape). A
    10x slowdown must never be invisible."""
    from ..profiler.telemetry import get_telemetry

    get_telemetry().counter("attn/tier_fallbacks")
    key = (tier, tuple(shape), reason)
    if key not in _fallback_warned:
        _fallback_warned.add(key)
        logger.warning(
            "attention: %s tier fell back for shape %s — %s (counted in "
            "counter/attn/tier_fallbacks; warned once per shape)",
            tier, tuple(shape), reason)


# impl-name → tier-policy name (the kernel impls split per backend)
_TIER_OF_IMPL = {"jax_flash": "pallas", "flash": "pallas"}


def dot_product_attention(q, k, v, causal=False, bias=None, sp_axis=None,
                          use_flash=True, layout="bhld"):
    """Attention dispatch by context, measurement, and
    ``set_attention_impl``: ring (sp sharded, or auto-promoted when an
    engine registered a ring mesh via ``set_ring_context`` and the
    sequence is long enough) > the benchmarked tier policy
    (``ops.tier_policy``, consulted by ``impl='auto'``) > the threshold
    heuristic > blockwise fallback.

    ``layout='blhd'`` passes [b, l, h, d] operands straight into the XLA
    and flash_tpu paths (no transpose copies); impls that need
    [b, h, l, d] get a transposed view and transpose back. All selection
    happens at TRACE time: the chosen tier is baked into the compiled
    program (zero per-step work, zero extra retraces)."""
    from ..profiler.telemetry import get_telemetry
    from . import tier_policy

    blhd = layout == "blhd"
    # trace-time fact: how many attention dispatches the compiled entry
    # contains (marks a bench record "attention-bearing" for the tier
    # gate); in eager mode it counts calls, which is equally true
    get_telemetry().counter("attn/calls")
    tr = lambda t: t.transpose(0, 2, 1, 3)
    L = q.shape[1] if blhd else q.shape[2]
    d = q.shape[-1]
    if sp_axis is not None:
        # explicit sequence-sharded call (L here is the LOCAL shard):
        # the verdict gauge must still land — the tier gate requires one
        # on every attention-bearing record
        tier_policy.publish_tier(L, d, causal, "ring")
        if blhd:
            return tr(ring_attention(tr(q), tr(k), tr(v), sp_axis,
                                     causal, 512))
        return ring_attention(q, k, v, sp_axis, causal, 512)
    if _IMPL == "auto" and _ring_auto_ok(L, causal, bias):
        tier_policy.publish_tier(L, d, causal, "ring")
        return _ring_sharded(q, k, v, causal, blhd)
    impl = _select_impl(q, k, bias, use_flash, causal, blhd)
    tier_policy.publish_tier(L, d, causal, _TIER_OF_IMPL.get(impl, impl))
    if blhd:
        if not _FORCE_BHLD:
            if impl == "flash_tpu":
                from .flash_tpu import flash_attention_blhd

                return flash_attention_blhd(q, k, v, causal)
            if impl == "xla" and bias is None:
                return xla_attention(q, k, v, causal=causal, layout="blhd")
        return tr(_apply_impl(impl, tr(q), tr(k), tr(v), causal, bias))
    return _apply_impl(impl, q, k, v, causal, bias)


def _apply_impl(impl, q, k, v, causal, bias):
    """Run one resolved impl on [b, h, l, d] operands."""
    if impl == "flash_tpu":
        from .flash_tpu import flash_attention_blhd

        tr = lambda t: t.transpose(0, 2, 1, 3)
        return tr(flash_attention_blhd(tr(q), tr(k), tr(v), causal))
    if impl == "jax_flash":
        return jax_flash_attention(q, k, v, causal=causal)
    if impl == "flash":
        return flash_attention(q, k, v, causal)
    if impl == "xla":
        return xla_attention(q, k, v, causal=causal, bias=bias)
    return blockwise_attention(q, k, v, causal=causal, bias=bias)


def _select_impl(q, k, bias, use_flash, causal, blhd):
    """The impl this dispatch will take, both layouts agreeing: the
    benchmarked tier policy when it has jurisdiction (``impl='auto'``,
    unbiased, ``use_flash``), the measured-threshold heuristic
    (``_resolve_impl``) otherwise."""
    from . import tier_policy

    L = q.shape[1] if blhd else q.shape[2]
    if _IMPL == "auto" and bias is None and use_flash:
        mode = tier_policy.policy_mode()
        choice = None
        if mode in ("xla", "blockwise", "flash_tpu", "pallas"):
            choice = mode  # PADDLE_TPU_ATTN_POLICY forced tier wins
        elif mode == "ring":
            _count_fallback(
                "ring", q.shape,
                "PADDLE_TPU_ATTN_POLICY=ring but "
                + _ring_unavailable_reason(L, causal, bias))
        elif mode == "bench":
            h = q.shape[2] if blhd else q.shape[1]
            choice = tier_policy.select(
                h, L, q.shape[-1], q.dtype, causal,
                _tier_candidates(q, k, causal, blhd))
        if choice is not None:
            return _impl_of_tier(choice, q, k, causal, blhd)
    impl = _resolve_impl(L, bias, use_flash, causal)
    if impl == "flash_tpu" and not _flash_tpu_fits(q, k, blhd=blhd):
        # the heuristic picked the kernel but the shape doesn't tile: keep
        # the MEMORY-SAFE streaming path (the kernel's own fallback is the
        # materialized O(L²) form — wrong for long L)
        _count_fallback(
            "flash_tpu", q.shape,
            "shape does not tile onto the flash_tpu kernel (needs "
            "Lq == Lk, L % 256 == 0, heads*dim % 128 == 0) — streaming "
            "via blockwise instead, ~8-10x slower at long L")
        impl = "blockwise"
    return impl


def _impl_of_tier(tier, q, k, causal, blhd):
    """Map a tier-policy verdict onto a dispatchable impl name, with the
    same shape safety net the heuristic path has."""
    if tier == "flash_tpu":
        if _flash_tpu_fits(q, k, blhd=blhd) and causal:
            return "flash_tpu"
        _count_fallback("flash_tpu", q.shape,
                        "cached tier verdict no longer tiles this call — "
                        "streaming via blockwise")
        return "blockwise"
    if tier == "pallas":
        return "jax_flash" if jax.default_backend() == "tpu" else "flash"
    return tier  # xla | blockwise


def _tier_candidates(q, k, causal, blhd):
    """Feasible tiers for the micro-bench: shape/backend gates only —
    never preferences (preference is exactly what gets measured). The
    xla candidate is capped at 2x its heuristic threshold so the bench
    itself cannot OOM materializing scores for extreme L."""
    if blhd:
        L, H = q.shape[1], q.shape[2]
        Lk = k.shape[1]
    else:
        H, L = q.shape[1], q.shape[2]
        Lk = k.shape[2]
    on_tpu = jax.default_backend() == "tpu"
    cands = []
    xla_cap = 2 * (_XLA_MAX_SEQ_CAUSAL if causal else _XLA_MAX_SEQ)
    if Lk == L and L <= xla_cap:
        cands.append("xla")
    if (on_tpu and causal and not _NO_MOSAIC
            and _flash_tpu_fits(q, k, blhd=blhd)):
        cands.append("flash_tpu")
    # mirror jax_flash_attention's own dispatch gate (L must tile its
    # min(512, L) default blocks) — a candidate the kernel would bounce
    # back off would time the FALLBACK under the 'pallas' label and could
    # persist that mislabel to the verdict cache
    if on_tpu and Lk == L and L % min(512, L) == 0:
        cands.append("pallas")
    cands.append("blockwise")
    return cands


def _flash_tpu_fits(q, k, blhd):
    """Shape gate for routing AUTO dispatch into the flash_tpu kernel:
    self-attention only (Lq == Lk — the kernel reshapes k to q's length)
    and the kernel's own tiling constraints."""
    from .flash_tpu import _fits

    if blhd:
        b, L, H, d = q.shape
        Lk = k.shape[1]
    else:
        b, H, L, d = q.shape
        Lk = k.shape[2]
    return Lk == L and _fits(b, L, H, d, 256)


def _resolve_impl(L, bias, use_flash, causal=True):
    """Single source of truth for the impl a [b,h,l,d] dispatch will take
    (the blhd fast path consults it too, so both layouts always agree).

    auto: ``use_flash=False`` keeps the exact f32 blockwise recurrence (the
    model-level flag selects numerics, not just a kernel); on TPU short/mid
    sequences take the materialized XLA path (measured fastest at GPT-class
    shapes — L=1024/d=64: 53k vs 40k for the kernels). CAUSAL unbiased
    sequences stay on the q-chunked XLA tier up to _XLA_MAX_SEQ_CAUSAL
    (r5: its fully-masked blocks are skipped and its residuals fit HBM at
    the longctx bench shape — GPT-small L=8192 measured 46.5k tok/s vs
    27.5k on flash_tpu + recompute); NON-causal or biased calls keep the
    stricter _XLA_MAX_SEQ=4096 guard — their [b,h,L,L] score tensor has
    no masked blocks to skip and exhausts HBM well before 8k at real
    batch sizes. Past the threshold, causal goes to the repo's Pallas
    flash kernel (flash_tpu.py) and the rest to the blockwise recurrence
    (the scan path is 8-10x slower — measured L=8192 f+b: 100ms vs 13ms —
    but O(L) in memory). Off-TPU flash_attention safely degrades to
    blockwise. The kernel tiers gate on SHAPE at trace time; a rig whose
    Mosaic compile service itself fails surfaces that at jit-compile
    time — select 'xla'/'blockwise' there."""
    on_tpu = jax.default_backend() == "tpu"
    if _IMPL == "flash_tpu":
        return "flash_tpu" if (on_tpu and bias is None and causal) else "xla"
    if _IMPL == "pallas":
        if bias is not None:
            return "blockwise"
        return "jax_flash" if on_tpu else "flash"
    if _IMPL == "xla":
        return "xla"
    if _IMPL == "blockwise":
        return "blockwise"
    if not use_flash:
        return "blockwise"
    if on_tpu:
        xla_max = (_XLA_MAX_SEQ_CAUSAL if (causal and bias is None)
                   else _XLA_MAX_SEQ)
        if L <= xla_max:
            return "xla"
        if causal and bias is None and not _NO_MOSAIC:
            return "flash_tpu"
        return "blockwise"
    return "blockwise" if bias is not None else "flash"
