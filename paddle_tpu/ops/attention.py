"""Attention kernels — the TPU-native replacement for the reference's fused
attention CUDA kernels (operators/fused/multihead_matmul_op.cu,
fused_attention) plus net-new long-context support (ring/context parallelism,
absent in the reference — SURVEY.md §5 'Long-context: Absent').

Three tiers, one API:
- ``blockwise_attention``: online-softmax scan over K blocks (FlashAttention
  recurrence in pure lax) — O(seq) memory, differentiable, runs anywhere.
- ``flash_attention``: Pallas TPU kernel for the forward (MXU-tiled, VMEM
  blocked), custom_vjp whose backward recomputes via the blockwise path.
- ``ring_attention``: sequence-parallel attention inside shard_map — K/V
  shards rotate around the 'sp' mesh axis via ppermute (ICI neighbor
  transfers) while each device keeps running softmax stats for its Q shard.
"""
from __future__ import annotations

import functools
import math
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "blockwise_attention", "flash_attention", "ring_attention",
    "xla_attention", "dot_product_attention", "set_attention_impl",
]

# Attention implementation selector. 'auto' (default) picks per context:
# ring for sp-sharded, the materialized XLA path on TPU up to a
# per-context length threshold — measured fastest end-to-end on v5e for
# GPT-2 345M (L=1024, d=64: the big batched einsums tile onto the MXU
# better than per-head Pallas kernel ops) AND, q-chunked, for causal
# unbiased sequences up to L=8192 (46.5k vs 27.5k tok/s on the longctx
# bench, r5) — then the repo's flash_tpu Mosaic kernel for longer causal
# sequences (the materialized scores exhaust HBM and blockwise is 8-10x
# slower). 'pallas' (the jax-shipped kernel) and 'flash_tpu' can
# also be forced explicitly. Rigs whose Mosaic compile service fails —
# plain XLA needs no such service — would die at jit-compile time on
# auto's long-sequence route: set PADDLE_TPU_ATTN_NO_MOSAIC=1 to keep
# auto on the streaming blockwise path instead.
_IMPL = os.environ.get("PADDLE_TPU_ATTENTION", "auto")
_NO_MOSAIC = os.environ.get("PADDLE_TPU_ATTN_NO_MOSAIC", "") == "1"
# beyond these lengths the materialized scores dominate HBM; stream
# instead. Two thresholds (r5): CAUSAL unbiased attention runs q-chunked
# (_causal_chunked_fwd_impl — fully-masked blocks never computed, ~0.53·L²
# footprint) and measured 46.5k tok/s at GPT-small L=8192 b=1 vs 27.5k on
# flash_tpu + recompute, so its auto threshold is 8192; everything else
# materializes the full [b,h,L,L] scores and keeps the stricter 4096.
_XLA_MAX_SEQ = int(os.environ.get("PADDLE_TPU_ATTENTION_MAX_SEQ", "4096"))
_XLA_MAX_SEQ_CAUSAL = int(os.environ.get(
    "PADDLE_TPU_ATTENTION_MAX_SEQ_CAUSAL", "8192"))


def set_attention_impl(impl: str):
    """impl ∈ {'auto', 'pallas', 'flash_tpu', 'xla', 'blockwise'}.

    'pallas' selects the jax-shipped Mosaic flash kernel; 'flash_tpu' the
    repo's layout-native Pallas kernel (ops/flash_tpu.py). The selector is
    read at TRACE time: functions already jitted keep the implementation
    they compiled with (jit cache). Call before building the train/eval
    step, or clear caches, for the change to take effect.
    """
    global _IMPL
    if impl not in ("auto", "pallas", "flash_tpu", "xla", "blockwise"):
        raise ValueError(f"unknown attention impl {impl!r}")
    _IMPL = impl

_NEG_INF = -1e30


# ---------------------------------------------------------------------------
# Blockwise online-softmax attention (lax-level flash recurrence)
# ---------------------------------------------------------------------------
def _block_scan_attention(q, k, v, causal, q_offset, kv_offset, block_k, bias=None):
    """q: [Lq, d]; k/v: [Lk, d]. Online softmax over k blocks.

    ``q_offset``/``kv_offset`` are global position offsets (for ring /
    sharded causal masking)."""
    Lq, d = q.shape
    Lk = k.shape[0]
    scale = 1.0 / math.sqrt(d)
    nblocks = max((Lk + block_k - 1) // block_k, 1)
    pad = nblocks * block_k - Lk
    if pad:
        k = jnp.pad(k, ((0, pad), (0, 0)))
        v = jnp.pad(v, ((0, pad), (0, 0)))
        if bias is not None:
            bias = jnp.pad(bias, ((0, 0), (0, pad)), constant_values=_NEG_INF)
    kb = k.reshape(nblocks, block_k, d)
    vb = v.reshape(nblocks, block_k, d)
    bb = bias.reshape(Lq, nblocks, block_k).swapaxes(0, 1) if bias is not None else None

    q_pos = q_offset + jnp.arange(Lq)

    def body(carry, blk):
        acc, m, l = carry
        if bb is not None:
            kblk, vblk, bblk, bi = blk
        else:
            kblk, vblk, bi = blk
            bblk = None
        s = (q.astype(jnp.float32) @ kblk.astype(jnp.float32).T) * scale  # [Lq, bk]
        k_pos = kv_offset + bi * block_k + jnp.arange(block_k)
        valid = k_pos < (kv_offset + Lk)
        mask = jnp.broadcast_to(valid[None, :], s.shape)
        if causal:
            mask = mask & (k_pos[None, :] <= q_pos[:, None])
        if bblk is not None:
            s = s + bblk
        s = jnp.where(mask, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, None])
        correction = jnp.exp(m - m_new)
        l_new = l * correction + p.sum(axis=-1)
        acc_new = acc * correction[:, None] + p @ vblk.astype(jnp.float32)
        return (acc_new, m_new, l_new), None

    acc0 = jnp.zeros((Lq, d), jnp.float32)
    m0 = jnp.full((Lq,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((Lq,), jnp.float32)
    idx = jnp.arange(nblocks)
    xs = (kb, vb, bb, idx) if bb is not None else (kb, vb, idx)
    (acc, m, l), _ = jax.lax.scan(body, (acc0, m0, l0), xs)
    out = acc / jnp.maximum(l, 1e-30)[:, None]
    return out.astype(q.dtype), m + jnp.log(jnp.maximum(l, 1e-30))


def blockwise_attention(q, k, v, causal=False, block_k=512, bias=None,
                        q_offset=0, kv_offset=0):
    """q,k,v: [batch, heads, len, dim]. Returns [batch, heads, len, dim]."""

    def per_head(qh, kh, vh, bh):
        out, _ = _block_scan_attention(qh, kh, vh, causal, q_offset, kv_offset,
                                       block_k, bh)
        return out

    if bias is not None:
        # bias broadcastable to [b, h, lq, lk]
        b_full = jnp.broadcast_to(bias, q.shape[:2] + (q.shape[2], k.shape[2]))
        fn = jax.vmap(jax.vmap(per_head))
        return fn(q, k, v, b_full)
    fn = jax.vmap(jax.vmap(lambda a, b, c: per_head(a, b, c, None)))
    return fn(q, k, v)


# ---------------------------------------------------------------------------
# Pallas TPU flash-attention forward
# ---------------------------------------------------------------------------
def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k, causal, sm_scale,
                      seq_len):
    from jax.experimental import pallas as pl

    # NOTE: all index math is pinned to int32 — with jax_enable_x64 on,
    # python-int promotion would inject int64 converts, which the Mosaic
    # lowering cannot handle (infinite recursion in convert_element_type).
    i32 = jnp.int32
    q = q_ref[0].astype(jnp.float32)  # [block_q, d]
    block_q, d = q.shape
    qi = pl.program_id(1).astype(i32)
    q_pos = qi * i32(block_q) + jax.lax.broadcasted_iota(
        i32, (block_q, block_k), 0)

    nk = seq_len // block_k

    def body(i, carry):
        acc, m, l = carry
        i = i.astype(i32)
        k = k_ref[0, pl.dslice(i * i32(block_k), block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.dslice(i * i32(block_k), block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            k_pos = i * i32(block_k) + jax.lax.broadcasted_iota(
                i32, (block_q, block_k), 1
            )
            s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1)
        acc_new = acc * corr[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return acc_new, m_new, l_new

    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    if causal:
        # only scan k blocks up to (and including) this q block's diagonal
        upper = jnp.minimum((qi + i32(1)) * i32(block_q) // i32(block_k)
                            + i32(1), i32(nk))
    else:
        upper = i32(nk)
    acc, m, l = jax.lax.fori_loop(i32(0), upper, body, (acc0, m0, l0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


def _flash_fwd_pallas(q, k, v, causal, block_q, block_k):
    from jax.experimental import pallas as pl

    b, h, L, d = q.shape
    sm_scale = 1.0 / math.sqrt(d)
    bh = b * h
    q3 = q.reshape(bh, L, d)
    k3 = k.reshape(bh, L, d)
    v3 = v.reshape(bh, L, d)
    grid = (bh, L // block_q)
    out = pl.pallas_call(
        functools.partial(_flash_fwd_kernel, block_k=block_k, causal=causal,
                          sm_scale=sm_scale, seq_len=L),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, L, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, L, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, L, d), q.dtype),
    )(q3, k3, v3)
    return out.reshape(b, h, L, d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q, k, v, causal=False, block_q=256, block_k=256):
    """Pallas-accelerated attention; falls back to blockwise when shapes or
    platform don't fit the kernel. [b, h, l, d] layout."""
    return _flash_attention_impl(q, k, v, causal, block_q, block_k)


def _flash_attention_impl(q, k, v, causal, block_q, block_k):
    L = q.shape[2]
    d = q.shape[3]
    on_tpu = jax.default_backend() == "tpu"
    fits = (L % block_q == 0 and L % block_k == 0 and d % 128 == 0
            and k.shape[2] == L)
    if on_tpu and fits:
        return _flash_fwd_pallas(q, k, v, causal, block_q, block_k)
    return blockwise_attention(q, k, v, causal=causal, block_k=block_k)


def jax_flash_attention(q, k, v, causal=False, block_q=None, block_k=None):
    """The jax-shipped Mosaic flash-attention kernel (fwd AND bwd kernels,
    [b, h, l, d]), with block sizes clamped to the shape. Falls back to the
    local ``flash_attention`` tier (→ blockwise) when the shape doesn't
    tile, or when TRACING fails (eager x64 issues etc.) — a Mosaic compile
    SERVICE failure under jit surfaces at jit-compile time instead; use the
    'auto'/'xla' impl on such rigs."""
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes, flash_attention as _fa)

    L, d = q.shape[2], q.shape[3]
    bq = min(block_q or 512, L)
    bk = min(block_k or 512, L)
    if L % bq != 0 or L % bk != 0 or k.shape[2] != L:
        return flash_attention(q, k, v, causal)
    bs = BlockSizes(
        block_q=bq, block_k_major=bk, block_k=bk, block_b=1,
        block_q_major_dkv=bq, block_k_major_dkv=bk, block_k_dkv=bk,
        block_q_dkv=bq, block_k_major_dq=bk, block_k_dq=bk, block_q_dq=bq,
    )
    # the kernel's index math assumes 32-bit python-int promotion; this repo
    # enables x64 globally, so scope it off around the trace
    try:
        with jax.enable_x64(False):
            return _fa(q, k, v, causal=causal, block_sizes=bs,
                       sm_scale=1.0 / math.sqrt(d))
    except Exception:
        return flash_attention(q, k, v, causal)


def _flash_fwd_rule(q, k, v, causal, block_q, block_k):
    out = _flash_attention_impl(q, k, v, causal, block_q, block_k)
    return out, (q, k, v)


def _flash_bwd_rule(causal, block_q, block_k, res, g):
    q, k, v = res
    # recompute-based backward through the blockwise recurrence
    _, vjp = jax.vjp(
        lambda q_, k_, v_: blockwise_attention(q_, k_, v_, causal=causal,
                                               block_k=block_k),
        q, k, v,
    )
    return vjp(g)


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


# ---------------------------------------------------------------------------
# Ring attention (sequence/context parallelism over a mesh axis)
# ---------------------------------------------------------------------------
def ring_attention(q, k, v, axis_name, causal=False, block_k=512):
    """Attention where q/k/v are sequence-sharded over ``axis_name``.

    Must be called inside shard_map/pjit with ``axis_name`` in scope. Each
    step every device computes blockwise attention between its local Q shard
    and the K/V shard currently resident, folds the result into running
    online-softmax statistics, then rotates K/V one hop around the ring
    (lax.ppermute → ICI neighbor copy, overlapping with the next compute).
    Differentiable end-to-end: jax reverses the permutes in the backward.
    """
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, h, L_local, d = q.shape
    scale = 1.0 / math.sqrt(d)

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def local_block(qh, kh, vh, q_off, kv_off):
        # returns (unnormalized acc, m, l) for one head
        Lq = qh.shape[0]
        Lk = kh.shape[0]
        s = (qh.astype(jnp.float32) @ kh.astype(jnp.float32).T) * scale
        q_pos = q_off + jnp.arange(Lq)
        k_pos = kv_off + jnp.arange(Lk)
        if causal:
            mask = k_pos[None, :] <= q_pos[:, None]
            s = jnp.where(mask, s, _NEG_INF)
        m = s.max(axis=-1)
        p = jnp.exp(s - m[:, None])
        l = p.sum(axis=-1)
        acc = p @ vh.astype(jnp.float32)
        return acc, m, l

    vblock = jax.vmap(jax.vmap(local_block, in_axes=(0, 0, 0, None, None)),
                      in_axes=(0, 0, 0, None, None))

    def step(carry, i):
        acc, m, l, kc, vc = carry
        src_idx = (my_idx - i) % axis_size  # whose shard we currently hold
        a_i, m_i, l_i = vblock(q, kc, vc, my_idx * L_local, src_idx * L_local)
        m_new = jnp.maximum(m, m_i)
        c_old = jnp.exp(m - m_new)
        c_new = jnp.exp(m_i - m_new)
        acc = acc * c_old[..., None] + a_i * c_new[..., None]
        l = l * c_old + l_i * c_new
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        return (acc, m_new, l, kc, vc), None

    acc0 = jnp.zeros((b, h, L_local, d), jnp.float32)
    m0 = jnp.full((b, h, L_local), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, L_local), jnp.float32)
    (acc, m, l, _, _), _ = jax.lax.scan(
        step, (acc0, m0, l0, k, v), jnp.arange(axis_size)
    )
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Materialized XLA attention (TPU fast path for moderate sequence lengths)
# ---------------------------------------------------------------------------
# minimum causal q-chunk rows (sweepable; 128 measured optimum on v5e)
_CAUSAL_CHUNK = int(os.environ.get("PADDLE_TPU_ATTN_MIN_CHUNK", "128"))
# max causal q-chunks (sweepable: more chunks skip more upper-triangle work
# but emit more ops). Together with the 128-row minimum the default of 32
# gives the measured v5e optima at both ends: L=1024 -> c=128 (8 chunks;
# c=256 measured -6%) and L=8192 -> c=256 (32 chunks; +27% over the old
# 16-chunk default — 47.0k -> 60.0k tok/s on the longctx config; c=128
# and c=64 both measured worse there)
_CAUSAL_MAX_CHUNKS = int(os.environ.get("PADDLE_TPU_ATTN_CHUNKS", "32"))
# sweep knob (bench tuning): force the [b,h,l,d] layout path
_FORCE_BHLD = os.environ.get("PADDLE_TPU_ATTN_LAYOUT", "") == "bhld"
# bf16 score STORAGE, default ON for bf16/f16 inputs: the centered logits
# already round-trip through bf16 before exp, and softmax cancels the max
# shift exactly (m only guards overflow), so bf16-stored scores are
# numerically ~equivalent (~1 ulp of bf16 either way) while halving the
# O(L²) tensor's bytes. Set =0 for f32 score storage.
_SCORE_BF16 = os.environ.get("PADDLE_TPU_ATTN_SCORE_BF16", "1") == "1"
# sweep knob: hand-written chunked-attention backward (custom_vjp) vs
# autodiff of the same forward. Default OFF — measured end-to-end on v5e
# GPT-2 345M the manual rule is ~3% SLOWER (52.4k vs 53.9k tok/s/chip):
# its per-chunk dk/dv pad+sum accumulation costs more than autodiff's
# cotangent accumulation saves, and the backward's contract-q dots hit the
# same ~43 TFLOP/s emitter ceiling either way (every orientation rewrite —
# 'bhdk' outputs, pre-transposed operands, optimization barriers — was
# canonicalized by XLA to the identical dot and measured identical).
# Kept as an opt-in: it halves residual memory bookkeeping for long-L
# sweeps and documents the measured negative result.
_MANUAL_ATTN_VJP = os.environ.get("PADDLE_TPU_ATTN_MANUAL_VJP", "0") == "1"


def _einsum_eqs(blhd: bool):
    return (("bqhd,bkhd->bhqk", "bhqk,bkhd->bqhd") if blhd
            else ("bhqd,bhkd->bhqk", "bhqk,bhkd->bhqd"))


def _attention_core(q, k, v, mask, bias=None, blhd=False):
    """One materialized softmax(QKᵀ)V block.

    ``blhd``: q/k/v are [b, l, h, d] (einsum contracts without pre-transposed
    operands — the [b,h,l,d] transposes are real HBM copies the model can
    skip); otherwise [b, h, l, d]. ``mask`` is [Lq, Lk] bool or None. For
    bf16/f16 inputs the centered logits and probabilities round-trip through
    the input dtype — the exp input IS materialized, and halving that O(L²)
    tensor's bytes is a real HBM saving (see xla_attention docstring)."""
    d = q.shape[-1]
    eq = _einsum_eqs(blhd)
    s = jnp.einsum(eq[0], q, k,
                   preferred_element_type=jnp.float32) * (1.0 / math.sqrt(d))
    if bias is not None:
        s = s + bias
    if mask is not None:
        s = jnp.where(mask, s, _NEG_INF)
    m = jax.lax.stop_gradient(jnp.max(s, axis=-1, keepdims=True))
    if jnp.issubdtype(q.dtype, jnp.floating) and q.dtype != jnp.float32:
        e = jnp.exp((s - m).astype(q.dtype).astype(jnp.float32))
    else:
        e = jnp.exp(s - m)
    p = (e / jnp.maximum(e.sum(axis=-1, keepdims=True), 1e-30)).astype(q.dtype)
    return jnp.einsum(eq[1], p, v)


def _causal_chunk_size(Lq: int):
    """Chunk size for causal q-chunking, or None when no exact chunking
    exists (c must divide Lq — a truncated concat would silently drop query
    rows)."""
    c = max(_CAUSAL_CHUNK, Lq // max(_CAUSAL_MAX_CHUNKS, 1))
    if Lq % c != 0 or Lq // c < 2:
        return None
    return c


# backward einsum equations per layout: dP ('dO,V->P-shape'), dq
# ('dS,K->q-shape'), dk ('dS,Q->k-shape'), dv ('E,dO->v-shape'), delta
# ('dO,O->rows')
_BWD_EQS = {
    True: ("bqhd,bkhd->bhqk", "bhqk,bkhd->bqhd", "bhqk,bqhd->bkhd",
           "bhqk,bqhd->bkhd", "bqhd,bqhd->bhq"),
    False: ("bhqd,bhkd->bhqk", "bhqk,bhkd->bhqd", "bhqk,bhqd->bhkd",
            "bhqk,bhqd->bhkd", "bhqd,bhqd->bhq"),
}


def _inv_rows(inv, blhd):
    """Broadcast a [b,h,q] row statistic against [.., q-axis, .., d]."""
    return inv.transpose(0, 2, 1)[..., None] if blhd else inv[..., None]


def _chunk_e(q, k, i, c, blhd, m=None):
    """exp weights of causal chunk i: e = exp(s − max(s)), s = scaled QKᵀ
    under the chunk's static tril mask. Shared by forward and (remat mode)
    backward — with the saved per-chunk max passed as ``m`` the recomputed
    values are BITWISE the forward's (same ops, same operands). Returns
    (e, m, used_sdt)."""
    axis_l = 1 if blhd else 2
    sl = functools.partial(jax.lax.slice_in_dim, axis=axis_l)
    d = q.shape[-1]
    scale = 1.0 / math.sqrt(d)
    eq = _einsum_eqs(blhd)
    bf = (jnp.issubdtype(q.dtype, jnp.floating) and q.dtype != jnp.float32)
    sdt = q.dtype if (_SCORE_BF16 and bf) else jnp.float32
    neg = jnp.asarray(_NEG_INF if sdt == jnp.float32 else -3e38, sdt)
    ub = (i + 1) * c
    qi = sl(q, i * c, ub) * jnp.asarray(scale, q.dtype)
    ki = sl(k, 0, ub)
    s = jnp.einsum(eq[0], qi, ki, preferred_element_type=sdt)
    mask = jnp.tril(jnp.ones((c, ub), bool), k=ub - c)
    s = jnp.where(mask, s, neg)
    if m is None:
        m = jax.lax.stop_gradient(jnp.max(s, axis=-1, keepdims=True))
    # the UNNORMALIZED probabilities are MATERIALIZED in the input dtype
    # (exp computed in f32 per-element, rounded on store): for bf16
    # models this halves the O(L²) exp tensor's bytes in fwd AND in the
    # saved residual the backward re-reads — values in (0, 1], safe in
    # bf16, and the f32-accumulated row sum below normalizes the same
    # bf16 weights the PV einsum consumes (profiled: the f32 exp store
    # was 25 ms/step of divide_subtract fusions)
    if sdt != jnp.float32:  # honors the PADDLE_TPU_ATTN_SCORE_BF16 opt-out
        e = jnp.exp((s - m).astype(q.dtype).astype(jnp.float32)
                    ).astype(q.dtype)
    else:
        e = jnp.exp(s - m)
    return e, m


def _remat_e() -> bool:
    """Backward recomputes the exp weights instead of saving them (default
    ON). The saved-e residuals are the single largest non-matmul cost of
    the GPT-2 345M step: ~148 MB/layer of bf16 written in fwd, re-read in
    bwd, PLUS ~5 ms/step of relayout copies XLA inserts moving them across
    the custom_vjp boundary (profiled shapes bf16[8,16,128,ub]). Recompute
    costs one extra QK einsum + exp per chunk (~0.2 ms/layer) — flash
    attention's trade, expressed at the XLA level."""
    return os.environ.get("PADDLE_TPU_ATTN_REMAT_E", "1") == "1"


def _causal_chunked_fwd_impl(q, k, v, blhd: bool):
    """Forward pass; returns (out, residuals per chunk). Residual slot 4
    holds the exp weights (save-e mode) or their per-chunk row maxima
    (remat mode, `_remat_e`)."""
    axis_l = 1 if blhd else 2
    Lq = q.shape[axis_l]
    c = _causal_chunk_size(Lq)
    n = Lq // c
    sl = functools.partial(jax.lax.slice_in_dim, axis=axis_l)
    eq = _einsum_eqs(blhd)
    remat = _remat_e()
    outs, aux, invs = [], [], []
    for i in range(n):
        e, m = _chunk_e(q, k, i, c, blhd)
        vi = sl(v, 0, (i + 1) * c)
        l_sum = jnp.maximum(e.sum(axis=-1, dtype=jnp.float32), 1e-30)
        o = jnp.einsum(eq[1], e.astype(q.dtype), vi)
        inv = (1.0 / l_sum).astype(q.dtype)
        outs.append(o * _inv_rows(inv, blhd))
        aux.append(m if remat else e)
        invs.append(inv)
    out = jnp.concatenate(outs, axis=axis_l)
    return out, (q, k, v, out, tuple(aux), tuple(invs))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _causal_chunked(q, k, v, blhd: bool):
    """Causal self-attention, q-chunked: chunk i attends to keys [0, (i+1)·c)
    under a static top-left tril mask — upper-triangle blocks are never
    computed (~45% of attention compute+bandwidth at 8 chunks).

    TPU-first structure (profile-driven, v5e):
    - the softmax NORMALIZATION is deferred until after the PV matmul: the
      unnormalized exp weights feed the MXU and the divide runs on the
      [.., c, d] output instead of the [.., c, L] score tensor — one full
      O(L²) elementwise pass (read+write) removed per chunk (flash's trick,
      expressed at the XLA level);
    - the 1/sqrt(d) scale folds into the [.., c, d] query chunk, not the
      score tensor;
    - einsums contract the native [b, l, h, d] layout directly (blhd=True):
      no [b,h,l,d] transpose copies;
    - the BACKWARD is hand-written (custom_vjp, `_causal_chunked_bwd`):
      autodiff's transposed einsums pick degenerate per-head layouts on TPU
      (profiled 18 ms/step of ~1%-MFU dots + 13 ms of relayout copies at
      GPT-2 345M). The manual rule keeps every backward contraction in the
      same layout family as the forward and folds the 1/l normalization
      into the [.., c, d] dO chunk (flash's backward trick at the XLA
      level), so no O(L²) divide pass exists in either direction.
    """
    out, _ = _causal_chunked_fwd_impl(q, k, v, blhd)
    return out


def _causal_chunked_fwd(q, k, v, blhd):
    return _causal_chunked_fwd_impl(q, k, v, blhd)


def _causal_chunked_bwd(blhd, res, g):
    q, k, v, out, aux, invs = res
    axis_l = 1 if blhd else 2
    Lq = q.shape[axis_l]
    c = _causal_chunk_size(Lq)
    n = Lq // c
    sl = functools.partial(jax.lax.slice_in_dim, axis=axis_l)
    d = q.shape[-1]
    scale = jnp.asarray(1.0 / math.sqrt(d), q.dtype)
    dP_eq, dq_eq, dk_eq, dv_eq, delta_eq = _BWD_EQS[blhd]
    remat = _remat_e()

    dqs, dks, dvs = [], [], []
    for i in range(n):
        ub = (i + 1) * c
        qi = sl(q, i * c, ub)
        ki, vi = sl(k, 0, ub), sl(v, 0, ub)
        gi = sl(g, i * c, ub)
        oi = sl(out, i * c, ub)
        if remat:  # aux holds the chunk maxima; e recomputed bitwise
            e, _ = _chunk_e(q, k, i, c, blhd, m=aux[i])
        else:
            e = aux[i]
        inv = invs[i]
        # softmax backward with the normalization folded into dO:
        #   P = e·inv;  dS = P ⊙ (dP − rowsum(dP ⊙ P))
        #             = e ⊙ (dP·inv − rowsum(dO ⊙ O)·inv)
        # rowsum(dP ⊙ P) collapses to rowsum(dO ⊙ O) — computed on the
        # [.., c, d] output, never touching the [.., c, L] score tensor
        g_inv = (gi * _inv_rows(inv, blhd)).astype(q.dtype)
        delta = jnp.einsum(delta_eq, gi, oi,
                           preferred_element_type=jnp.float32)
        dP = jnp.einsum(dP_eq, g_inv, vi, preferred_element_type=jnp.float32)
        dS = (e.astype(jnp.float32)
              * (dP - (delta * inv.astype(jnp.float32))[..., None])
              ).astype(q.dtype)
        # masked positions need no re-masking: e is exactly 0 there
        dqs.append(jnp.einsum(dq_eq, dS, ki) * scale)
        # pad-to-L and tree-sum: measured BEST of three accumulation
        # shapes for the ragged dk/dv chunk contributions on v5e (ragged
        # per-block slice+sum+concat re-lowered to 2.8× the
        # dynamic-update-slice traffic; see r5_gpt.txt)
        pad = [(0, 0)] * q.ndim
        pad[axis_l] = (0, Lq - ub)
        dks.append(jnp.pad(jnp.einsum(dk_eq, dS, qi) * scale, pad))
        dvs.append(jnp.pad(jnp.einsum(dv_eq, e.astype(q.dtype), g_inv), pad))
    dq = jnp.concatenate(dqs, axis=axis_l)
    dk = sum(dks[1:], dks[0])
    dv = sum(dvs[1:], dvs[0])
    return dq, dk, dv


_causal_chunked.defvjp(_causal_chunked_fwd, _causal_chunked_bwd)


def xla_attention(q, k, v, causal=False, bias=None, layout="bhld"):
    """softmax(QKᵀ)V with the [Lq, Lk] scores materialized (XLA-level).

    TPU-first details (profile-driven on v5e / GPT-2 345M, 12.9k→53k
    tok/s/chip end-to-end vs the scan-based blockwise path):
    - scores ACCUMULATE in f32 on the MXU regardless of storage dtype; for
      bf16/f16 inputs the stored scores, centered logits, and unnormalized
      probabilities round-trip through the input dtype by default
      (``PADDLE_TPU_ATTN_SCORE_BF16=0`` opts back into f32 storage) —
      softmax cancels the max shift exactly, so this is numerically ~1 ulp
      of bf16 either way while halving the O(L²) HBM bytes;
    - **causal** self-attention runs q-chunked (``_causal_chunked``): chunk
      i only matmuls keys ≤ its diagonal, skipping the fully-masked
      upper-triangle blocks (~45% of attention compute/bandwidth at 8
      chunks), and softmax normalization is deferred until after the PV
      matmul;
    - ``layout='blhd'`` contracts [b, l, h, d] operands directly, letting
      the model skip the four [b,h,l,d] transpose copies per layer.
    """
    blhd = layout == "blhd"
    axis_l = 1 if blhd else 2
    Lq, Lk = q.shape[axis_l], k.shape[axis_l]
    if (causal and bias is None and Lq == Lk
            and _causal_chunk_size(Lq) is not None):
        # chunk-count cap keeps the emitted program small (some TPU compile
        # services reject huge ones)
        if _MANUAL_ATTN_VJP:
            return _causal_chunked(q, k, v, blhd)
        return _causal_chunked_fwd_impl(q, k, v, blhd)[0]
    mask = jnp.tril(jnp.ones((Lq, Lk), bool)) if causal else None
    # causal mask is top-left aligned (k_pos <= q_pos), matching
    # blockwise/flash so the dispatch tiers agree for Lq != Lk
    if blhd and bias is not None:
        raise NotImplementedError("bias requires layout='bhld'")
    return _attention_core(q, k, v, mask, bias, blhd)


# ---------------------------------------------------------------------------
# Public dispatch
# ---------------------------------------------------------------------------
def dot_product_attention(q, k, v, causal=False, bias=None, sp_axis=None,
                          use_flash=True, layout="bhld"):
    """Attention dispatch by context and ``set_attention_impl``:
    ring (sp sharded) > selected impl > blockwise fallback.

    ``layout='blhd'`` passes [b, l, h, d] operands straight into the XLA
    path (no transpose copies); impls that need [b, h, l, d] get a
    transposed view and transpose back."""
    if layout == "blhd":
        if sp_axis is None and bias is None and not _FORCE_BHLD:
            impl = _resolve_impl(q.shape[1], bias, use_flash, causal)
            if impl == "flash_tpu" and not _flash_tpu_fits(q, k, blhd=True):
                # auto picked the kernel but the shape doesn't tile: keep
                # the MEMORY-SAFE streaming path (the kernel's own fallback
                # is the materialized O(L²) form — wrong for long L)
                impl = "blockwise"
            if impl == "flash_tpu":
                from .flash_tpu import flash_attention_blhd

                return flash_attention_blhd(q, k, v, causal)
            if impl == "xla":
                return xla_attention(q, k, v, causal=causal, layout="blhd")
        tr = lambda t: t.transpose(0, 2, 1, 3)
        out = dot_product_attention(tr(q), tr(k), tr(v), causal=causal,
                                    bias=bias, sp_axis=sp_axis,
                                    use_flash=use_flash)
        return tr(out)
    if sp_axis is not None:
        return ring_attention(q, k, v, sp_axis, causal=causal)
    impl = _resolve_impl(q.shape[2], bias, use_flash, causal)
    if impl == "flash_tpu" and not _flash_tpu_fits(q, k, blhd=False):
        impl = "blockwise"
    if impl == "flash_tpu":
        from .flash_tpu import flash_attention_blhd

        tr = lambda t: t.transpose(0, 2, 1, 3)
        return tr(flash_attention_blhd(tr(q), tr(k), tr(v), causal))
    if impl == "jax_flash":
        return jax_flash_attention(q, k, v, causal=causal)
    if impl == "flash":
        return flash_attention(q, k, v, causal)
    if impl == "xla":
        return xla_attention(q, k, v, causal=causal, bias=bias)
    return blockwise_attention(q, k, v, causal=causal, bias=bias)


def _flash_tpu_fits(q, k, blhd):
    """Shape gate for routing AUTO dispatch into the flash_tpu kernel:
    self-attention only (Lq == Lk — the kernel reshapes k to q's length)
    and the kernel's own tiling constraints."""
    from .flash_tpu import _fits

    if blhd:
        b, L, H, d = q.shape
        Lk = k.shape[1]
    else:
        b, H, L, d = q.shape
        Lk = k.shape[2]
    return Lk == L and _fits(b, L, H, d, 256)


def _resolve_impl(L, bias, use_flash, causal=True):
    """Single source of truth for the impl a [b,h,l,d] dispatch will take
    (the blhd fast path consults it too, so both layouts always agree).

    auto: ``use_flash=False`` keeps the exact f32 blockwise recurrence (the
    model-level flag selects numerics, not just a kernel); on TPU short/mid
    sequences take the materialized XLA path (measured fastest at GPT-class
    shapes — L=1024/d=64: 53k vs 40k for the kernels). CAUSAL unbiased
    sequences stay on the q-chunked XLA tier up to _XLA_MAX_SEQ_CAUSAL
    (r5: its fully-masked blocks are skipped and its residuals fit HBM at
    the longctx bench shape — GPT-small L=8192 measured 46.5k tok/s vs
    27.5k on flash_tpu + recompute); NON-causal or biased calls keep the
    stricter _XLA_MAX_SEQ=4096 guard — their [b,h,L,L] score tensor has
    no masked blocks to skip and exhausts HBM well before 8k at real
    batch sizes. Past the threshold, causal goes to the repo's Pallas
    flash kernel (flash_tpu.py) and the rest to the blockwise recurrence
    (the scan path is 8-10x slower — measured L=8192 f+b: 100ms vs 13ms —
    but O(L) in memory). Off-TPU flash_attention safely degrades to
    blockwise. The kernel tiers gate on SHAPE at trace time; a rig whose
    Mosaic compile service itself fails surfaces that at jit-compile
    time — select 'xla'/'blockwise' there."""
    on_tpu = jax.default_backend() == "tpu"
    if _IMPL == "flash_tpu":
        return "flash_tpu" if (on_tpu and bias is None and causal) else "xla"
    if _IMPL == "pallas":
        if bias is not None:
            return "blockwise"
        return "jax_flash" if on_tpu else "flash"
    if _IMPL == "xla":
        return "xla"
    if _IMPL == "blockwise":
        return "blockwise"
    if not use_flash:
        return "blockwise"
    if on_tpu:
        xla_max = (_XLA_MAX_SEQ_CAUSAL if (causal and bias is None)
                   else _XLA_MAX_SEQ)
        if L <= xla_max:
            return "xla"
        if causal and bias is None and not _NO_MOSAIC:
            return "flash_tpu"
        return "blockwise"
    return "blockwise" if bias is not None else "flash"
