"""Benchmarked attention tier selection — measurement over heuristics.

``ops/attention.py`` carries four interchangeable tiers (the materialized
``xla`` path, the repo's ``flash_tpu`` Pallas kernel, the jax-shipped
``pallas`` kernel, the streaming ``blockwise`` recurrence) whose relative
speed depends on shape, dtype, AND the rig (r4/r5 bench notes: the same
L=8192 causal shape measured 46.5k tok/s on the chunked XLA tier vs 27.5k
on flash_tpu on a rig whose Mosaic compile service is ~7x off the pace —
a hardcoded threshold is wrong somewhere for someone). This module makes
``impl='auto'`` consult a *measured* verdict instead:

- **One micro-bench per (backend, device_kind, heads, L, d, dtype,
  causal)**: the first trace that dispatches an unseen attention shape
  times every feasible tier — forward+backward, AOT-compiled
  (``jit -> lower -> compile``; the executable call path is immune to
  the ambient trace the selection usually runs under) — and the fastest
  wins. ``counter/attn/tier_bench`` counts benches run.
- **Persistent verdicts**: results land in a JSON cache file
  (``PADDLE_TPU_ATTN_TIER_CACHE``, defaulting next to the persistent XLA
  compile cache when ``PADDLE_TPU_COMPILE_CACHE_DIR`` is set), committed
  via ``framework.io.atomic_replace``, so a process restart re-selects
  without re-measuring — the same restart-warm contract as the compile
  cache whose key scheme (backend + device_kind + abstract shape) this
  mirrors. A corrupted cache file is NEVER deleted or overwritten: the
  policy re-measures in memory, warns once, and leaves the bytes on disk
  for inspection.
- **Override**: ``PADDLE_TPU_ATTN_POLICY`` forces a tier
  (``xla``/``flash_tpu``/``pallas``/``blockwise``/``ring``), pins the old
  threshold heuristic (``heuristic``), or forces measurement (``bench``).
  Unset, 'auto' measures on TPU and keeps the heuristic off-TPU (CPU
  timings would enshrine host quirks into the cache; CI opts in
  explicitly).

Telemetry (all trace-time facts — one event per compiled program, not
per step): ``gauge/attn/tier.<key>`` (the tier id in effect for a shape,
published by every dispatch in every mode), ``counter/attn/calls``,
``counter/attn/tier_bench`` (micro-benches run),
``counter/attn/tier_fallbacks`` (silent reroutes — gated to zero by
``tools/check_attribution.py``).
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional

logger = logging.getLogger("paddle_tpu.ops")

__all__ = [
    "TIER_IDS", "PAGED_TIERS", "policy_mode", "forced_mode", "cache_path",
    "select", "select_paged", "publish_tier", "registry", "TierRegistry",
    "reset",
]

# stable numeric ids for the gauge/attn/tier.* telemetry (schema: >= 0).
# paged_gather / paged_scan are the DECODE tiers (attention over the
# serving KV-cache pool — ops.attention.paged_attention); they join the
# same id space so one gauge family covers train and serve dispatch.
TIER_IDS = {"xla": 0, "flash_tpu": 1, "pallas": 2, "blockwise": 3, "ring": 4,
            "paged_gather": 5, "paged_scan": 6}

_FORCIBLE = ("xla", "flash_tpu", "pallas", "blockwise", "ring")

# decode-path tiers: both are always feasible (pure-XLA gather/scan), so
# selection is purely a measurement or heuristic question, never a gate
PAGED_TIERS = ("paged_gather", "paged_scan")

# micro-bench shape: batch is pinned to 1 (every tier scales ~linearly in
# batch, so the ranking is batch-invariant and the bench stays cheap);
# heads/L/d/dtype come from the real call — they drive tiling feasibility
# and the compute/bandwidth balance the tiers differ on.
_BENCH_BATCH = 1
_BENCH_REPS = 2

_warned_unknown_policy = None  # one-shot per distinct bad env value


def forced_mode() -> Optional[str]:
    """The EXPLICIT ``PADDLE_TPU_ATTN_POLICY`` value when one is set and
    valid, else None. Distinct from ``policy_mode`` so overrides can
    outrank decisions (ring auto-promotion) that the unset default must
    not suppress."""
    v = os.environ.get("PADDLE_TPU_ATTN_POLICY", "").strip().lower()
    if v in _FORCIBLE or v in ("bench", "heuristic"):
        return v
    return None


def policy_mode() -> str:
    """'bench' | 'heuristic' | a forced tier name.

    ``PADDLE_TPU_ATTN_POLICY`` wins; unset defaults to measured selection
    on TPU and the threshold heuristic elsewhere (read per call so tests
    and bench configs can flip it without reloads)."""
    global _warned_unknown_policy
    forced = forced_mode()
    if forced is not None:
        return forced
    if os.environ.get("PADDLE_TPU_ATTN_POLICY", "").strip():
        if os.environ["PADDLE_TPU_ATTN_POLICY"] != _warned_unknown_policy:
            _warned_unknown_policy = os.environ["PADDLE_TPU_ATTN_POLICY"]
            logger.warning("tier_policy: unknown PADDLE_TPU_ATTN_POLICY=%r "
                           "— falling back to the heuristic (warned once "
                           "per value)",
                           os.environ["PADDLE_TPU_ATTN_POLICY"])
        return "heuristic"
    import jax

    return "bench" if jax.default_backend() == "tpu" else "heuristic"


def cache_path() -> Optional[str]:
    """Verdict cache file, or None (memory-only). Keyed like the XLA
    compile cache: ``PADDLE_TPU_ATTN_TIER_CACHE`` wins, else
    ``<PADDLE_TPU_COMPILE_CACHE_DIR>/attn_tiers.json``."""
    p = os.environ.get("PADDLE_TPU_ATTN_TIER_CACHE")
    if p:
        return p
    d = os.environ.get("PADDLE_TPU_COMPILE_CACHE_DIR")
    return os.path.join(d, "attn_tiers.json") if d else None


def _backend_key() -> str:
    import jax

    kind = "unknown"
    try:
        kind = str(jax.devices()[0].device_kind).replace(" ", "_")
    except Exception:
        pass
    return f"{jax.default_backend()}:{kind}"


def make_key(h: int, L: int, d: int, dtype, causal: bool) -> str:
    return (f"{_backend_key()}:h{h}:L{L}:d{d}:{dtype}:"
            f"{'causal' if causal else 'full'}")


def gauge_key(L: int, d: int, causal: bool) -> str:
    """Short per-shape suffix for ``gauge/attn/tier.<key>``."""
    return f"L{L}.d{d}.{'c' if causal else 'f'}"


def publish_tier(L: int, d: int, causal: bool, tier: str) -> None:
    """Record the tier in effect for a shape — every dispatch publishes,
    whatever mode chose it, so bench records always carry the verdict
    (``tools/check_attribution.py`` gates on its presence)."""
    from ..profiler.telemetry import get_telemetry

    tel = get_telemetry()
    tel.gauge(f"attn/tier.{gauge_key(L, d, causal)}",
              TIER_IDS.get(tier, -1))


class TierRegistry:
    """In-memory verdicts + the persistent JSON cache behind them."""

    def __init__(self):
        self._lock = threading.RLock()
        self._verdicts: Dict[str, dict] = {}
        self._loaded_path: Optional[str] = None
        self._poisoned = False   # cache file unreadable: never write to it

    # -- persistence -------------------------------------------------------
    def _load(self, path: str) -> None:
        if self._loaded_path == path:
            return
        self._loaded_path = path
        self._poisoned = False
        if not os.path.exists(path):
            return
        try:
            with open(path) as f:
                data = json.load(f)
            if not isinstance(data, dict):
                raise ValueError(f"expected a JSON object, got "
                                 f"{type(data).__name__}")
        except Exception as e:
            # a corrupt cache is left EXACTLY as found (it may be the only
            # evidence of what corrupted it); verdicts re-measure in
            # memory and nothing further is written to this path
            self._poisoned = True
            logger.warning(
                "tier_policy: attention tier cache %s is unreadable (%s) — "
                "re-measuring in memory; the file is left untouched, "
                "remove it to re-enable persistence", path, e)
            return
        for k, v in data.items():
            if isinstance(v, dict) and v.get("tier") in TIER_IDS:
                self._verdicts.setdefault(k, v)

    def _persist(self, path: str) -> None:
        if self._poisoned:
            return
        from ..framework.io import atomic_replace

        persistable = {k: v for k, v in self._verdicts.items()
                       if not v.get("volatile")}
        # merge-on-write: re-read the file so verdicts another process
        # persisted since OUR load survive this atomic_replace (ours win
        # on key collision — we just measured; except volatile keys,
        # where the disk's full-candidate-set verdict is the keeper)
        try:
            with open(path) as f:
                data = json.load(f)
            if isinstance(data, dict):
                for k, v in data.items():
                    if isinstance(v, dict) and v.get("tier") in TIER_IDS:
                        self._verdicts.setdefault(k, v)
                        persistable.setdefault(k, v)
        except Exception:
            pass  # absent, or corrupted since load: poisoning is _load's call
        payload = json.dumps(persistable, indent=1, sort_keys=True)

        def write(tmp):
            with open(tmp, "w") as f:
                f.write(payload)

        try:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            atomic_replace(path, write)
        except OSError as e:
            logger.warning("tier_policy: could not persist tier cache to "
                           "%s: %s", path, e)

    # -- selection ---------------------------------------------------------
    def verdict(self, key: str) -> Optional[dict]:
        with self._lock:
            path = cache_path()
            if path:
                self._load(path)
            return self._verdicts.get(key)

    def record(self, key: str, verdict: dict, persist: bool = True) -> None:
        """Store a verdict; ``persist=False`` keeps it process-local
        (marked volatile — never written to disk, even as a bystander of
        a later persist) so a measurement taken under an env-restricted
        candidate set cannot clobber the full-set verdict on disk."""
        with self._lock:
            if not persist:
                verdict = dict(verdict, volatile=True)
            self._verdicts[key] = verdict
            path = cache_path()
            if path:
                self._load(path)   # no-op unless the cache path changed
                if persist:
                    self._persist(path)

    def reset(self) -> None:
        with self._lock:
            self._verdicts.clear()
            self._loaded_path = None
            self._poisoned = False


_registry = TierRegistry()


def registry() -> TierRegistry:
    return _registry


def reset() -> None:
    """Forget every in-memory verdict (tests; the disk cache persists)."""
    _registry.reset()


# -- the micro-bench -------------------------------------------------------

def _tier_callable(tier: str, causal: bool):
    """A [b, h, L, d] -> [b, h, L, d] callable for one tier."""
    from . import attention as att

    if tier == "xla":
        return lambda q, k, v: att.xla_attention(q, k, v, causal=causal)
    if tier == "blockwise":
        return lambda q, k, v: att.blockwise_attention(q, k, v, causal=causal)
    if tier == "flash_tpu":
        from .flash_tpu import flash_attention_blhd

        def _ft(q, k, v):
            tr = lambda t: t.transpose(0, 2, 1, 3)
            return tr(flash_attention_blhd(tr(q), tr(k), tr(v), causal))

        return _ft
    if tier == "pallas":
        return lambda q, k, v: att.jax_flash_attention(q, k, v, causal=causal)
    raise ValueError(f"unknown tier {tier!r}")


def _time_tier(tier: str, q, k, v, causal: bool) -> Optional[float]:
    """Median seconds of one fwd+bwd step, or None if the tier fails to
    compile/run for this shape on this rig (a Mosaic compile-service
    failure is data, not an error: the verdict routes around it).

    The step is AOT-compiled (``jit -> lower -> compile``) and the
    EXECUTABLE is what the clock times: a selection usually triggered
    mid-trace of the train step must neither be lifted into the ambient
    trace nor degrade into eager op-by-op dispatch — the compiled
    executable's call path is immune to both."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    fn = _tier_callable(tier, causal)

    def loss(q_, k_, v_):
        return fn(q_, k_, v_).astype(jnp.float32).sum()

    step = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2)))
    try:
        compiled = step.lower(q, k, v).compile()
        out = compiled(q, k, v)
        np.asarray(out[0])  # drain the device queue before the clock
        times = []
        for _ in range(_BENCH_REPS):
            t0 = time.perf_counter()
            out = compiled(q, k, v)
            np.asarray(out[0])
            times.append(time.perf_counter() - t0)
        # min, not mean/median: host noise (GC, scheduler) only ever ADDS
        # time, and a verdict poisoned by one blip persists restart-warm
        # where no gate can catch it — the fastest rep is the estimate
        # closest to the kernel's true cost
        return min(times)
    except Exception as e:
        logger.info("tier_policy: tier %r infeasible for this shape/rig "
                    "(%s: %s)", tier, type(e).__name__, e)
        return None


def bench(key: str, h: int, L: int, d: int, dtype, causal: bool,
          candidates: List[str], persist: bool = True) -> Optional[dict]:
    """Time ``candidates`` at [1, h, L, d] and record the winner.

    The first unseen shape is usually dispatched while TRACING the train
    step — ``jax.ensure_compile_time_eval()`` keeps the whole bench
    eagerly evaluated at trace time instead of being lifted into the
    ambient trace (where the timed steps would become tracers and the
    clock would measure nothing)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..profiler.telemetry import get_telemetry

    rng = np.random.RandomState(0)
    timings = {}
    with jax.ensure_compile_time_eval():
        # input CREATION only: jnp ops on host data must evaluate rather
        # than lift into the ambient trace; the timing itself runs AOT
        # executables, which need no escape hatch (and compile-time eval
        # would break scan transposes inside lower())
        mk = lambda: jnp.asarray(
            rng.randn(_BENCH_BATCH, h, L, d).astype(np.float32), dtype)
        q, k, v = mk(), mk(), mk()
    for tier in candidates:
        t = _time_tier(tier, q, k, v, causal)
        if t is not None:
            timings[tier] = t
    if not timings:
        return None
    best = min(timings, key=timings.get)
    verdict = {
        "tier": best,
        "timings_ms": {t: round(s * 1e3, 3) for t, s in timings.items()},
        "ts": time.time(),
    }
    _registry.record(key, verdict, persist=persist)
    get_telemetry().counter("attn/tier_bench")
    logger.info("tier_policy: %s -> %s (%s)", key, best,
                ", ".join(f"{t}={ms:.2f}ms"
                          for t, ms in verdict["timings_ms"].items()))
    return verdict


def select(h: int, L: int, d: int, dtype, causal: bool,
           candidates: List[str]) -> Optional[str]:
    """The measured tier for this shape, benching once per key if needed.
    Returns None when no candidate is feasible (caller keeps its
    heuristic). Pure cache hits are one dict lookup — selection happens
    at trace time and must never add per-step work (the verdict is baked
    into the compiled program; retrace budget unchanged)."""
    if not candidates:
        return None
    key = make_key(h, L, d, dtype, causal)
    verdict = _registry.verdict(key)
    if verdict is None:
        verdict = bench(key, h, L, d, dtype, causal, candidates)
    elif verdict.get("tier") not in candidates:
        # the cached winner is infeasible for THIS call's candidate set —
        # which, for an identical key, can only mean an env knob shrank
        # the set (e.g. PADDLE_TPU_ATTN_NO_MOSAIC). Re-measure for this
        # process but never overwrite the full-set verdict on disk.
        verdict = bench(key, h, L, d, dtype, causal, candidates,
                        persist=False)
    if verdict is None:
        return None
    return verdict["tier"]


# -- paged (decode) tier selection -----------------------------------------
# The KV-cache decode path has its own pair of tiers
# (ops.attention.paged_attention): 'paged_gather' materializes the whole
# gathered context per step (one big fused softmax — wins while the
# context fits comfortably), 'paged_scan' streams page-by-page with
# online softmax (O(block) live memory — wins for long contexts and is
# the only safe choice near HBM capacity). Their crossover depends on
# rig and shape exactly like the training tiers, so the same machinery
# applies: measure once per shape key, persist the verdict, zero
# per-step cost (selection happens at trace time of the decode step).

def paged_policy_mode() -> str:
    """'bench' | 'heuristic' | a forced paged tier.

    ``PADDLE_TPU_ATTN_PAGED_POLICY`` wins (``paged_gather`` /
    ``paged_scan`` / ``bench`` / ``heuristic``); unset follows the same
    default as the training tiers — measure on TPU, heuristic off-TPU
    (host timings never poison the shared verdict cache)."""
    v = os.environ.get("PADDLE_TPU_ATTN_PAGED_POLICY", "").strip().lower()
    if v in PAGED_TIERS or v in ("bench", "heuristic"):
        return v
    if v:
        global _warned_unknown_policy
        if v != _warned_unknown_policy:
            _warned_unknown_policy = v
            logger.warning("tier_policy: unknown "
                           "PADDLE_TPU_ATTN_PAGED_POLICY=%r — falling back "
                           "to the heuristic (warned once per value)", v)
        return "heuristic"
    import jax

    return "bench" if jax.default_backend() == "tpu" else "heuristic"


def make_paged_key(t: int, h: int, d: int, m: int, bs: int, dtype,
                   quantized: bool) -> str:
    """Decode-shape verdict key: query chunk length, heads, head_dim,
    table width x block size (the gathered-context geometry), storage
    dtype. Batch is deliberately absent — like the training bench's
    pinned batch, both tiers scale ~linearly in B, so the ranking is
    batch-invariant and one verdict covers every decode bucket."""
    q = "int8" if quantized else str(dtype)
    return f"{_backend_key()}:paged:t{t}:h{h}:d{d}:m{m}x{bs}:{q}"


def _paged_heuristic(m: int, bs: int) -> str:
    # materialized gather is profitable while the gathered context is
    # score-tensor-small; past that the page-streaming scan bounds live
    # memory (same 4096 knee the xla/blockwise training split uses)
    return "paged_gather" if m * bs <= 4096 else "paged_scan"


def bench_paged(key: str, t: int, h: int, d: int, m: int, bs: int, dtype,
                quantized: bool, persist: bool = True) -> Optional[dict]:
    """Time both paged tiers at [1, t, h, d] queries over an [m*bs]-token
    paged context and record the winner — forward only (decode is
    inference; there is no backward to weigh in)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..profiler.telemetry import get_telemetry
    from . import attention as att

    rng = np.random.RandomState(0)
    with jax.ensure_compile_time_eval():
        q = jnp.asarray(rng.randn(1, t, h, d).astype(np.float32), dtype)
        if quantized:
            k_pages = jnp.asarray(
                rng.randint(-127, 127, (m + 1, bs, h, d)), jnp.int8)
            v_pages = jnp.asarray(
                rng.randint(-127, 127, (m + 1, bs, h, d)), jnp.int8)
            k_scale = jnp.asarray(
                rng.rand(m + 1, bs, h).astype(np.float32)) * 0.01
            v_scale = jnp.asarray(
                rng.rand(m + 1, bs, h).astype(np.float32)) * 0.01
        else:
            k_pages = jnp.asarray(
                rng.randn(m + 1, bs, h, d).astype(np.float32), dtype)
            v_pages = jnp.asarray(
                rng.randn(m + 1, bs, h, d).astype(np.float32), dtype)
            k_scale = v_scale = None
        tables = jnp.asarray(np.arange(1, m + 1, dtype=np.int32)[None, :])
        q_pos = jnp.asarray(
            np.arange(m * bs - t, m * bs, dtype=np.int32)[None, :])
        kv_lens = jnp.asarray(np.asarray([m * bs], np.int32))
    timings = {}
    for tier in PAGED_TIERS:
        impl = (att._paged_gather_impl if tier == "paged_gather"
                else att._paged_scan_impl)

        def fn(q_, kp, vp, bt, qp, kl, ks=k_scale, vs=v_scale, impl=impl):
            return impl(q_, kp, vp, bt, qp, kl, ks, vs)

        try:
            compiled = jax.jit(fn).lower(
                q, k_pages, v_pages, tables, q_pos, kv_lens).compile()
            out = compiled(q, k_pages, v_pages, tables, q_pos, kv_lens)
            np.asarray(out)  # drain before the clock
            times = []
            for _ in range(_BENCH_REPS):
                t0 = time.perf_counter()
                out = compiled(q, k_pages, v_pages, tables, q_pos, kv_lens)
                np.asarray(out)
                times.append(time.perf_counter() - t0)
            timings[tier] = min(times)  # min: host noise only adds time
        except Exception as e:
            logger.info("tier_policy: paged tier %r infeasible (%s: %s)",
                        tier, type(e).__name__, e)
    if not timings:
        return None
    best = min(timings, key=timings.get)
    verdict = {"tier": best,
               "timings_ms": {k2: round(s * 1e3, 3)
                              for k2, s in timings.items()},
               "ts": time.time()}
    _registry.record(key, verdict, persist=persist)
    get_telemetry().counter("attn/tier_bench")
    logger.info("tier_policy: %s -> %s (%s)", key, best,
                ", ".join(f"{k2}={ms:.2f}ms"
                          for k2, ms in verdict["timings_ms"].items()))
    return verdict


def select_paged(t: int, h: int, d: int, m: int, bs: int, dtype,
                 quantized: bool) -> str:
    """The paged tier for this decode shape. Forced > cached verdict >
    fresh micro-bench (bench mode) > heuristic. Like ``select``, a pure
    cache hit is one dict lookup at trace time — the verdict bakes into
    the compiled decode step."""
    mode = paged_policy_mode()
    if mode in PAGED_TIERS:
        return mode
    if mode == "bench":
        key = make_paged_key(t, h, d, m, bs, dtype, quantized)
        verdict = _registry.verdict(key)
        if verdict is None or verdict.get("tier") not in PAGED_TIERS:
            verdict = bench_paged(key, t, h, d, m, bs, dtype, quantized)
        if verdict is not None:
            return verdict["tier"]
    return _paged_heuristic(m, bs)
