"""Fused elementwise kernels (Pallas) — counterpart of the reference's
operators/fused/ CUDA tier (fused_bn_activation_op.cu, fused_adam, layer-norm
kernels). XLA already fuses most elementwise chains into matmul epilogues;
these Pallas versions exist for the cases XLA splits (multi-tensor adam over
a flat buffer, layernorm over very wide rows) and as the template for future
custom kernels. All have jnp fallbacks and are numerically interchangeable.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["fused_layer_norm", "fused_softmax_bias", "fused_adam_step"]


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


# ---------------------------------------------------------------------------
# layer norm
# ---------------------------------------------------------------------------
def _ln_kernel(x_ref, w_ref, b_ref, o_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean((x - mean) ** 2, axis=-1, keepdims=True)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    o_ref[...] = (y * w_ref[...].astype(jnp.float32)
                  + b_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


def _ln_bwd_kernel(x_ref, w_ref, g_ref, dx_ref, dw_ref, db_ref, *, eps):
    """One row-block of the LayerNorm backward.

    μ/σ are recomputed from x (one extra read of a tile already in VMEM
    beats materializing per-row stats in HBM); dγ/dβ accumulate into a
    VMEM-resident (8, hidden) block across the sequential grid (constant
    index_map), row 0 carrying the sum.
        dx = σ⁻¹ · (g·w − mean(g·w) − x̂ · mean(g·w·x̂))
    """
    from jax.experimental import pallas as pl

    x = x_ref[...].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean((x - mean) ** 2, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = (x - mean) * rstd
    gw = g * w
    m1 = jnp.mean(gw, axis=-1, keepdims=True)
    m2 = jnp.mean(gw * xhat, axis=-1, keepdims=True)
    dx_ref[...] = (rstd * (gw - m1 - xhat * m2)).astype(dx_ref.dtype)

    @pl.when(pl.program_id(0) == 0)
    def _init():
        dw_ref[...] = jnp.zeros_like(dw_ref)
        db_ref[...] = jnp.zeros_like(db_ref)

    dw_ref[0, :] += jnp.sum(g * xhat, axis=0)
    db_ref[0, :] += jnp.sum(g, axis=0)


def _ln_shapes_fit(x, block_rows):
    hidden = x.shape[-1]
    rows = 1
    for s in x.shape[:-1]:
        rows *= s
    return (_on_tpu() and rows % block_rows == 0 and hidden % 128 == 0,
            rows, hidden)


def _ln_reference(x, weight, bias, eps):
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return ((x - mean) * jax.lax.rsqrt(var + eps) * weight + bias).astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def fused_layer_norm(x, weight, bias, eps=1e-5, block_rows=256):
    """x: [..., hidden]; weight/bias: [hidden]. Pallas forward AND backward
    kernels on TPU (one pass each over the activation tensor — XLA emits
    LayerNorm backward as several memory-bound fusions, measured ~3x the
    bytes); jnp fallback elsewhere."""
    return _fused_ln_fwd_impl(x, weight, bias, eps, block_rows)


def _fused_ln_fwd_impl(x, weight, bias, eps, block_rows):
    fits, rows, hidden = _ln_shapes_fit(x, block_rows)
    if not fits:
        return _ln_reference(x, weight, bias, eps)

    from jax.experimental import pallas as pl

    x2 = x.reshape(rows, hidden)
    # pin the trace to 32-bit inside the kernel call: the repo enables x64
    # globally, and Mosaic cannot legalize the i64 grid scalars it injects
    with jax.enable_x64(False):
        out = pl.pallas_call(
            functools.partial(_ln_kernel, eps=eps),
            grid=(rows // block_rows,),
            in_specs=[
                pl.BlockSpec((block_rows, hidden), lambda i: (i, 0)),
                pl.BlockSpec((hidden,), lambda i: (0,)),
                pl.BlockSpec((hidden,), lambda i: (0,)),
            ],
            out_specs=pl.BlockSpec((block_rows, hidden), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((rows, hidden), x.dtype),
        )(x2, weight, bias)
    return out.reshape(x.shape)


def _fused_ln_fwd(x, weight, bias, eps, block_rows):
    return _fused_ln_fwd_impl(x, weight, bias, eps, block_rows), (x, weight, bias)


def _fused_ln_bwd(eps, block_rows, res, g):
    x, weight, bias = res
    fits, rows, hidden = _ln_shapes_fit(x, block_rows)
    if not fits:
        _, vjp = jax.vjp(lambda a, w, b: _ln_reference(a, w, b, eps),
                         x, weight, bias)
        return vjp(g)

    from jax.experimental import pallas as pl

    nblocks = rows // block_rows
    x2 = x.reshape(rows, hidden)
    g2 = g.reshape(rows, hidden)
    with jax.enable_x64(False):
        dx, dw_p, db_p = pl.pallas_call(
            functools.partial(_ln_bwd_kernel, eps=eps),
            grid=(nblocks,),
            in_specs=[
                pl.BlockSpec((block_rows, hidden), lambda i: (i, 0)),
                pl.BlockSpec((hidden,), lambda i: (0,)),
                pl.BlockSpec((block_rows, hidden), lambda i: (i, 0)),
            ],
            out_specs=[
                pl.BlockSpec((block_rows, hidden), lambda i: (i, 0)),
                pl.BlockSpec((8, hidden), lambda i: (0, 0)),
                pl.BlockSpec((8, hidden), lambda i: (0, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((rows, hidden), x.dtype),
                jax.ShapeDtypeStruct((8, hidden), jnp.float32),
                jax.ShapeDtypeStruct((8, hidden), jnp.float32),
            ],
        )(x2, weight, g2)
    dw = dw_p[0].astype(weight.dtype)
    db = db_p[0].astype(bias.dtype)
    return dx.reshape(x.shape), dw, db


fused_layer_norm.defvjp(_fused_ln_fwd, _fused_ln_bwd)


# ---------------------------------------------------------------------------
# softmax(+bias) over the last axis
# ---------------------------------------------------------------------------
def fused_softmax_bias(x, bias=None, axis=-1):
    if bias is not None:
        x = x + bias
    return jax.nn.softmax(x, axis=axis)


# ---------------------------------------------------------------------------
# multi-tensor adam over a flat parameter buffer
# ---------------------------------------------------------------------------
def _adam_kernel(p_ref, g_ref, m_ref, v_ref, lr_ref, t_ref,
                 po_ref, mo_ref, vo_ref, *, b1, b2, eps):
    p = p_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    m = m_ref[...]
    v = v_ref[...]
    lr = lr_ref[0]
    t = t_ref[0]
    m_new = b1 * m + (1.0 - b1) * g
    v_new = b2 * v + (1.0 - b2) * g * g
    lr_t = lr * jnp.sqrt(1.0 - b2**t) / (1.0 - b1**t)
    po_ref[...] = (p - lr_t * m_new / (jnp.sqrt(v_new) + eps)).astype(po_ref.dtype)
    mo_ref[...] = m_new
    vo_ref[...] = v_new


def fused_adam_step(param_flat, grad_flat, m_flat, v_flat, lr, step,
                    beta1=0.9, beta2=0.999, eps=1e-8, block=1 << 16):
    """Single fused pass over flat (concatenated) param/grad/state buffers —
    the multi-tensor-apply pattern of the reference's fused adam."""
    n = param_flat.shape[0]
    if not _on_tpu() or n % block != 0:
        m_new = beta1 * m_flat + (1 - beta1) * grad_flat
        v_new = beta2 * v_flat + (1 - beta2) * grad_flat * grad_flat
        lr_t = lr * jnp.sqrt(1 - beta2**step) / (1 - beta1**step)
        p_new = param_flat - lr_t * m_new / (jnp.sqrt(v_new) + eps)
        return p_new.astype(param_flat.dtype), m_new, v_new

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    grid = (n // block,)
    p_new, m_new, v_new = pl.pallas_call(
        functools.partial(_adam_kernel, b1=beta1, b2=beta2, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block,), lambda i: (i,)),
            pl.BlockSpec((block,), lambda i: (i,)),
            pl.BlockSpec((block,), lambda i: (i,)),
            pl.BlockSpec((block,), lambda i: (i,)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((block,), lambda i: (i,)),
            pl.BlockSpec((block,), lambda i: (i,)),
            pl.BlockSpec((block,), lambda i: (i,)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n,), param_flat.dtype),
            jax.ShapeDtypeStruct((n,), jnp.float32),
            jax.ShapeDtypeStruct((n,), jnp.float32),
        ],
    )(param_flat, grad_flat, m_flat, v_flat,
      jnp.asarray([lr], jnp.float32), jnp.asarray([step], jnp.float32))
    return p_new, m_new, v_new
