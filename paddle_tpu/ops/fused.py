"""Fused elementwise kernels (Pallas) — counterpart of the reference's
operators/fused/ CUDA tier (fused_bn_activation_op.cu, fused_adam, layer-norm
kernels). XLA already fuses most elementwise chains into matmul epilogues;
these Pallas versions exist for the cases XLA splits (multi-tensor adam over
a flat buffer, layernorm over very wide rows) and as the template for future
custom kernels. All have jnp fallbacks and are numerically interchangeable.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["fused_layer_norm", "fused_softmax_bias", "fused_adam_step"]


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


# ---------------------------------------------------------------------------
# layer norm
# ---------------------------------------------------------------------------
def _ln_kernel(x_ref, w_ref, b_ref, o_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean((x - mean) ** 2, axis=-1, keepdims=True)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    o_ref[...] = (y * w_ref[...].astype(jnp.float32)
                  + b_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


def fused_layer_norm(x, weight, bias, eps=1e-5, block_rows=256):
    """x: [..., hidden]; weight/bias: [hidden]."""
    hidden = x.shape[-1]
    lead = x.shape[:-1]
    rows = 1
    for s in lead:
        rows *= s
    if not _on_tpu() or rows % block_rows != 0 or hidden % 128 != 0:
        mean = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        return ((x - mean) * jax.lax.rsqrt(var + eps) * weight + bias).astype(x.dtype)

    from jax.experimental import pallas as pl

    x2 = x.reshape(rows, hidden)
    out = pl.pallas_call(
        functools.partial(_ln_kernel, eps=eps),
        grid=(rows // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, hidden), lambda i: (i, 0)),
            pl.BlockSpec((hidden,), lambda i: (0,)),
            pl.BlockSpec((hidden,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block_rows, hidden), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, hidden), x.dtype),
    )(x2, weight, bias)
    return out.reshape(x.shape)


# ---------------------------------------------------------------------------
# softmax(+bias) over the last axis
# ---------------------------------------------------------------------------
def fused_softmax_bias(x, bias=None, axis=-1):
    if bias is not None:
        x = x + bias
    return jax.nn.softmax(x, axis=axis)


# ---------------------------------------------------------------------------
# multi-tensor adam over a flat parameter buffer
# ---------------------------------------------------------------------------
def _adam_kernel(p_ref, g_ref, m_ref, v_ref, lr_ref, t_ref,
                 po_ref, mo_ref, vo_ref, *, b1, b2, eps):
    p = p_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    m = m_ref[...]
    v = v_ref[...]
    lr = lr_ref[0]
    t = t_ref[0]
    m_new = b1 * m + (1.0 - b1) * g
    v_new = b2 * v + (1.0 - b2) * g * g
    lr_t = lr * jnp.sqrt(1.0 - b2**t) / (1.0 - b1**t)
    po_ref[...] = (p - lr_t * m_new / (jnp.sqrt(v_new) + eps)).astype(po_ref.dtype)
    mo_ref[...] = m_new
    vo_ref[...] = v_new


def fused_adam_step(param_flat, grad_flat, m_flat, v_flat, lr, step,
                    beta1=0.9, beta2=0.999, eps=1e-8, block=1 << 16):
    """Single fused pass over flat (concatenated) param/grad/state buffers —
    the multi-tensor-apply pattern of the reference's fused adam."""
    n = param_flat.shape[0]
    if not _on_tpu() or n % block != 0:
        m_new = beta1 * m_flat + (1 - beta1) * grad_flat
        v_new = beta2 * v_flat + (1 - beta2) * grad_flat * grad_flat
        lr_t = lr * jnp.sqrt(1 - beta2**step) / (1 - beta1**step)
        p_new = param_flat - lr_t * m_new / (jnp.sqrt(v_new) + eps)
        return p_new.astype(param_flat.dtype), m_new, v_new

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    grid = (n // block,)
    p_new, m_new, v_new = pl.pallas_call(
        functools.partial(_adam_kernel, b1=beta1, b2=beta2, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block,), lambda i: (i,)),
            pl.BlockSpec((block,), lambda i: (i,)),
            pl.BlockSpec((block,), lambda i: (i,)),
            pl.BlockSpec((block,), lambda i: (i,)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((block,), lambda i: (i,)),
            pl.BlockSpec((block,), lambda i: (i,)),
            pl.BlockSpec((block,), lambda i: (i,)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n,), param_flat.dtype),
            jax.ShapeDtypeStruct((n,), jnp.float32),
            jax.ShapeDtypeStruct((n,), jnp.float32),
        ],
    )(param_flat, grad_flat, m_flat, v_flat,
      jnp.asarray([lr], jnp.float32), jnp.asarray([step], jnp.float32))
    return p_new, m_new, v_new
