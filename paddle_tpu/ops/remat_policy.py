"""Roofline-driven selective rematerialization.

The engines' ``recompute`` flag was all-or-nothing: checkpoint everything
(r5 longctx: −25% throughput paid whether or not the memory was needed)
or nothing (OOM one batch-size later). This module turns the PR 5
attribution layer from a dashboard into a control loop: ``remat='auto'``
on ``jit.TrainStep`` / ``fleet.ParallelTrainStep`` *measures* the
compiled step's peak HBM (``lowered.compile().memory_analysis()`` — the
exact argument+output+temp−alias number behind
``gauge/compile/peak_hbm_bytes``) against the chip's capacity
(``profiler.xla_cost.hbm_capacity_bytes``) and escalates through
``jax.checkpoint`` policies only as far as needed:

- fits → **no remat** (fastest; recompute buys nothing you have room for);
- over budget and the roofline verdict (``gauge/roofline/<entry>``; the
  lowered program's own arithmetic intensity when no prior compile
  exists) says **memory-bound** → jump straight to ``nothing_saveable``
  (the recompute FLOPs are free under the roofline — the step is waiting
  on HBM anyway);
- over budget and **compute-bound** → try ``dots_saveable`` first (keep
  the matmul outputs whose recompute would cost real MXU time, re-derive
  the elementwise/norm/softmax tissue), then ``nothing_saveable``;
- still over → **offload** (``offload_dot_with_no_batch_dims`` to
  pinned_host, where this jax exposes it).

Resolution happens ONCE, at the first step, by lowering+compiling the
candidate programs (the persistent XLA compile cache absorbs the repeat
compiles across restarts; ``PADDLE_TPU_COST_ANALYSIS=0`` disables
measurement and resolves to no-remat with a warning). The chosen policy
is published as ``gauge/remat/<entry>`` (policy id) and
``gauge/remat/peak_hbm/<entry>`` so bench records prove what the control
loop chose and what it cost.

The attention tiers keep their own finer-grained residual knob
(``PADDLE_TPU_ATTN_REMAT_E``, exp-weight recompute inside the chunked
tier) — that one is about O(L²) attention residuals specifically and is
already measurement-backed; this module decides the transformer-block
level question the engines used to answer with a blanket flag.
"""
from __future__ import annotations

import logging
import os
from typing import Callable, Dict, Optional

import jax

logger = logging.getLogger("paddle_tpu.ops")

__all__ = ["POLICY_IDS", "apply_policy", "program_cost", "resolve",
           "normalize"]

# stable ids for gauge/remat/<entry> (schema: >= 0)
POLICY_IDS = {"off": 0, "dots": 1, "dots_no_batch": 2, "nothing": 3,
              "offload": 4, "full": 5}

_warned_off = False


def normalize(remat) -> str:
    """Engine ctor values -> canonical policy name. Accepts the legacy
    ``recompute`` vocabulary (False/True/'dots'/'dots_no_batch'/
    'nothing') plus 'off'/'full'/'offload'/'auto'."""
    if remat in (None, False, "off", ""):
        return "off"
    if remat is True or remat == "full":
        return "full"
    name = str(remat)
    if name in POLICY_IDS or name == "auto":
        return name
    raise ValueError(f"unknown remat policy {remat!r}; expected one of "
                     f"{sorted(POLICY_IDS)} or 'auto'")


def _checkpoint_policy(name: str):
    cp = jax.checkpoint_policies
    if name == "dots":
        return cp.checkpoint_dots
    if name == "dots_no_batch":
        return cp.checkpoint_dots_with_no_batch_dims
    if name == "nothing":
        return cp.nothing_saveable
    if name == "offload":
        return cp.offload_dot_with_no_batch_dims("device", "pinned_host")
    raise ValueError(f"no jax.checkpoint policy for {name!r}")


def apply_policy(fn: Callable, policy: str) -> Callable:
    """Wrap a forward-loss callable in the named checkpoint policy
    ('off' returns it untouched, 'full' is plain jax.checkpoint)."""
    policy = normalize(policy)
    if policy == "off":
        return fn
    if policy == "full":
        return jax.checkpoint(fn, static_argnums=())
    return jax.checkpoint(fn, static_argnums=(),
                          policy=_checkpoint_policy(policy))


def program_cost(jitted, args) -> Optional[Dict[str, float]]:
    """Compile a candidate step and read XLA's own accounting: exact peak
    HBM (argument+output+temp−alias) + flops/bytes for the roofline.
    None when lowering/compilation fails (an infeasible candidate — e.g.
    offload on a backend without pinned_host — is skipped, not fatal)."""
    try:
        lowered = jitted.lower(*args)
        compiled = lowered.compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        ca = dict(ca or {})
        mem = compiled.memory_analysis()
        peak = max(
            float(getattr(mem, "argument_size_in_bytes", 0))
            + float(getattr(mem, "output_size_in_bytes", 0))
            + float(getattr(mem, "temp_size_in_bytes", 0))
            - float(getattr(mem, "alias_size_in_bytes", 0)), 0.0)
        return {"peak_hbm_bytes": peak,
                "flops": float(ca.get("flops", 0.0)),
                "bytes_accessed": float(ca.get("bytes accessed", 0.0))}
    except Exception as e:
        logger.info("remat_policy: candidate failed to lower/compile "
                    "(%s: %s)", type(e).__name__, str(e)[:200])
        return None


def budget_bytes() -> float:
    """The peak-HBM budget a step must fit: chip capacity scaled by
    ``PADDLE_TPU_REMAT_BUDGET_FRAC`` (default 0.9 — headroom for the
    allocator, collectives scratch, and prefetched batches)."""
    from ..profiler.xla_cost import hbm_capacity_bytes

    try:
        frac = float(os.environ.get("PADDLE_TPU_REMAT_BUDGET_FRAC", "0.9"))
    except ValueError:
        frac = 0.9
    return hbm_capacity_bytes() * min(max(frac, 0.05), 1.0)


def _verdict_for(entry: str, base_cost: Dict[str, float]) -> str:
    """'compute-bound' | 'memory-bound': a prior compile's registry
    verdict for this entry when one exists (the gauge/roofline/<entry>
    fact), else the candidate program's own intensity vs the machine
    balance point."""
    from ..profiler import xla_cost

    rec = xla_cost.cost_registry().latest().get(entry)
    if rec is not None:
        v = xla_cost.roofline_verdict(rec)
        if v is not None:
            return v
    peaks = xla_cost.chip_peaks()
    if base_cost["bytes_accessed"] <= 0 or peaks["bytes_per_s"] <= 0:
        return "compute-bound"
    intensity = base_cost["flops"] / base_cost["bytes_accessed"]
    return ("compute-bound"
            if intensity >= peaks["flops"] / peaks["bytes_per_s"]
            else "memory-bound")


def resolve(entry: str, lower_cost: Callable[[str], Optional[Dict]],
            telemetry=None) -> str:
    """Pick the cheapest policy whose measured peak HBM fits the budget.

    ``lower_cost(policy)`` must return ``program_cost`` of the step built
    with that policy (or None if infeasible). Returns the chosen policy
    name and publishes ``gauge/remat/<entry>`` +
    ``gauge/remat/peak_hbm/<entry>``."""
    from ..profiler.telemetry import get_telemetry
    from ..profiler.xla_cost import cost_analysis_mode

    global _warned_off
    tel = telemetry or get_telemetry()

    def publish(policy: str, peak: Optional[float]) -> str:
        tel.gauge(f"remat/{entry}", POLICY_IDS[policy])
        if peak is not None:
            tel.gauge(f"remat/peak_hbm/{entry}", peak)
        return policy

    if cost_analysis_mode() == "off":
        if not _warned_off:
            _warned_off = True
            logger.warning(
                "remat_policy: PADDLE_TPU_COST_ANALYSIS=0 — remat='auto' "
                "cannot measure peak HBM and resolves to no remat; set a "
                "policy explicitly if this OOMs")
        return publish("off", None)
    budget = budget_bytes()
    base = lower_cost("off")
    if base is None:
        logger.warning("remat_policy: could not cost the no-remat step for "
                       "%s — resolving to no remat", entry)
        return publish("off", None)
    if base["peak_hbm_bytes"] <= budget:
        logger.info("remat_policy: %s peak %.2f GB fits budget %.2f GB — "
                    "no remat", entry, base["peak_hbm_bytes"] / 1e9,
                    budget / 1e9)
        return publish("off", base["peak_hbm_bytes"])
    verdict = _verdict_for(entry, base)
    ladder = (["nothing", "offload"] if verdict == "memory-bound"
              else ["dots", "nothing", "offload"])
    best_policy, best_peak = "off", base["peak_hbm_bytes"]
    for policy in ladder:
        try:
            cost = lower_cost(policy)
        except Exception as e:
            # apply_policy/_checkpoint_policy can raise BEFORE program_cost's
            # own try (e.g. a jax without offload_dot_with_no_batch_dims) —
            # an unavailable candidate is skipped, never fatal
            logger.info("remat_policy: candidate %r unavailable on this "
                        "jax (%s: %s)", policy, type(e).__name__,
                        str(e)[:200])
            cost = None
        if cost is None:
            continue
        peak = cost["peak_hbm_bytes"]
        if peak < best_peak:
            best_policy, best_peak = policy, peak
        if peak <= budget:
            logger.info(
                "remat_policy: %s (%s) over budget at %.2f GB — policy "
                "%r fits at %.2f GB (budget %.2f GB)", entry, verdict,
                base["peak_hbm_bytes"] / 1e9, policy, peak / 1e9,
                budget / 1e9)
            return publish(policy, peak)
    logger.warning(
        "remat_policy: %s (%s): no policy fits the %.2f GB budget — "
        "taking the smallest measured peak (%r at %.2f GB); expect "
        "allocator pressure", entry, verdict, budget / 1e9, best_policy,
        best_peak / 1e9)
    return publish(best_policy, best_peak)
